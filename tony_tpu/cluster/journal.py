"""Crash-safe control-plane journals (work-preserving restart substrate).

The AM and the pool service are processes that can die at any instruction
(SIGKILL — the chaos ``am-crash`` / ``pool-crash`` faults are exactly that),
yet their *recoverable* state must survive into a successor process that
adopts the live work instead of rebuilding it (docs/fault-tolerance.md
"Control-plane failures"). The carrier is an append-only JSONL journal:

- every record is one line, written with ``flush`` + ``fsync`` before the
  state transition is considered durable — a successor never replays a
  transition the predecessor had not fully persisted;
- a SIGKILL mid-append can only tear the FINAL line (appends are sequential
  within one process, and a killed process appends nothing further), so the
  reader tolerates exactly that: an unparseable last record is dropped as an
  expected torn tail, while garbage anywhere *before* the tail means the
  file is not a journal we wrote — :class:`JournalError`, and the caller
  degrades loudly (the AM falls back to a full gang restart, the pool starts
  empty) instead of adopting fiction.

Record shape: ``{"t": "<type>", ...fields}``. The record vocabulary is owned
by the writer (appmaster.py / pool.py); this module only knows lines — with
ONE mechanical exception, incremental compaction (docs/performance.md
"Control-plane scalability"): :meth:`Journal.compact` folds the caller's
live state into a single ``{"t": "snapshot", "records": [...]}`` record and
rotates the file down to it, so restart replay is O(live state), not
O(everything that ever happened). The snapshot's embedded records use the
writer's own vocabulary, and the writer's replay resets its accumulated
state when it meets one — replay-with-snapshot is therefore equivalent to
replay-without by the writer's own folding rules (asserted property-style in
tests). A reader that predates snapshots fails loudly on the unknown record
type and degrades, exactly the contract for any journal written by a newer
tony.
"""

from __future__ import annotations

import json
import os
from typing import Any, Iterator

from tony_tpu.obs import locktrace
from tony_tpu.obs import metrics as _metrics

#: the one record type this module owns: compaction's folded-state carrier
SNAPSHOT_RECORD = "snapshot"

_COMPACTIONS = _metrics.counter(
    "tony_journal_compactions_total",
    "journal snapshot+rotate compactions (pool and AM takeover journals)")


class JournalError(RuntimeError):
    """The journal is missing, empty, or corrupt before its final record —
    the caller must degrade to its journal-less recovery path (loudly)."""


class Journal:
    """Append-only fsync'd JSONL writer.

    Appends are best-effort after open: a full disk must degrade the NEXT
    takeover (the reader sees a torn/stale journal), never take down the
    control plane that is still serving the live gang.
    """

    def __init__(self, path: str):
        self.path = path
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")
        self._lock = locktrace.make_lock("journal.Journal._lock")
        self._failed = False
        #: appends since the last :meth:`compact` (or open) — the writer's
        #: compaction trigger (``tony.{pool,am}.journal.compact-every``)
        self.appends_since_compact = 0
        #: lifetime successful appends — :meth:`compact`'s optimistic
        #: concurrency token for writers whose appends are NOT all serialized
        #: under one state lock (the AM)
        self.total_appends = 0
        #: serialized-but-unflushed lines (:meth:`enqueue`) — the pool's
        #: under-its-lock half of a journaled transition; durability comes
        #: from the caller's :meth:`flush_pending` outside its lock
        self._pending: list[str] = []
        #: lifetime enqueues — :meth:`compact`'s token for enqueue-path
        #: writers (mirror of :attr:`total_appends` for the append path)
        self.total_enqueued = 0

    def append(self, t: str, **fields: Any) -> None:
        line = json.dumps({"t": t, **fields}, sort_keys=True)
        with self._lock:
            # pending enqueues were accepted first — keep file order FIFO
            self._flush_pending_locked()
            if self._write_lines_locked([line]):
                self.appends_since_compact += 1
                self.total_appends += 1

    def enqueue(self, t: str, **fields: Any) -> None:
        """Stage one record without touching the disk — O(json.dumps), no
        fsync, safe to call while holding a hot state lock (the pool's).
        The record becomes durable at the caller's next
        :meth:`flush_pending` (or any :meth:`append`/:meth:`compact`/
        :meth:`close`), which the caller runs OUTSIDE its lock and before
        acking the transition — same durability contract as append, the
        fsync latency just stops serializing unrelated threads."""
        line = json.dumps({"t": t, **fields}, sort_keys=True)
        with self._lock:
            self._pending.append(line)
            self.total_enqueued += 1

    def flush_pending(self) -> bool:
        """Drain every staged record with ONE batched write+fsync. Any
        thread's flush drains the whole shared queue, so a caller returns
        knowing its own enqueues are durable regardless of which thread
        paid the fsync."""
        with self._lock:
            return self._flush_pending_locked()

    def _flush_pending_locked(self) -> bool:
        if not self._pending:
            return True
        lines = self._pending
        self._pending = []
        if self._write_lines_locked(lines):
            self.appends_since_compact += len(lines)
            self.total_appends += len(lines)
            return True
        return False  # best-effort like append: records dropped, warned once

    def _write_lines_locked(self, lines: list[str]) -> bool:
        try:
            # one write + one fsync however many records — the batch costs
            # what a single append used to
            self._f.write("".join(ln + "\n" for ln in lines))  # lint: disable=blocking-under-lock — the journal lock IS the fsync serializer (leaf lock, nothing acquired under it)
            self._f.flush()  # lint: disable=blocking-under-lock — see above
            os.fsync(self._f.fileno())  # lint: disable=blocking-under-lock — see above
            self._failed = False
            return True
        except (OSError, ValueError):
            # ValueError: closed file (late append during teardown races)
            if not self._failed:
                # once per failure streak — a full disk must be VISIBLE
                # (the next takeover will degrade on this journal)
                from tony_tpu.obs import logging as obs_logging

                obs_logging.warning(
                    f"[tony-journal] append to {self.path} failed — a "
                    "successor's recovery from this journal may degrade")
            self._failed = True
            return False

    def compact(self, records: list[dict[str, Any]],
                expected_total: int | None = None,
                expected_enqueued: int | None = None) -> bool:
        """Fold the caller's live state into one durable snapshot record,
        then rotate the file down to just that record.

        ``expected_total`` is the optimistic-concurrency token for writers
        whose appends are not all serialized under one state lock (the AM:
        RPC handlers journal without the monitor loop's locks): pass
        :attr:`total_appends` as read BEFORE building ``records``, and the
        compaction is skipped (returns False, nothing written) if any append
        landed since — an interleaved record would otherwise sort before the
        stale snapshot and be silently discarded by the replay barrier. The
        caller simply retries on a later tick. ``expected_enqueued`` is the
        same token for the :meth:`enqueue` path (pass :attr:`total_enqueued`
        as read together with the state ``records`` capture): an enqueue
        that races the snapshot build would be drained below, sort before a
        snapshot that does NOT fold it, and be discarded by the replay
        barrier — the token turns that into a skipped compaction instead.
        Writers that hold their state lock across build+compact pass None.

        Two-phase, each safe to die in:

        1. APPEND ``{"t": "snapshot", "records": [...]}`` with the same
           flush+fsync contract as any record. From this instant replay
           resets at the snapshot; a SIGKILL tearing this very append
           leaves a torn FINAL line the reader silently drops — recovery
           falls back to the intact pre-snapshot tail, never a
           half-applied snapshot.
        2. Rewrite the file to only that line (write-tmp → fsync → atomic
           replace) and swap the append handle. A crash anywhere here
           leaves either the old file (snapshot appended at its tail) or
           the rotated one — both replay to the identical state; failure
           only costs disk space, so it is best-effort like append.

        Holds the journal lock throughout: records appended concurrently
        land strictly before the snapshot (folded into the caller's state
        it captured under its own lock) or strictly after rotation.
        """
        line = json.dumps(
            {"t": SNAPSHOT_RECORD, "records": records}, sort_keys=True)
        with self._lock:
            if expected_total is not None and self.total_appends != expected_total:
                return False  # an append raced the snapshot build: stale
            if expected_enqueued is not None and self.total_enqueued != expected_enqueued:
                return False  # an enqueue raced the snapshot build: stale
            # records staged before the token read are folded into the
            # snapshot state; drain them first so nothing pending can land
            # AFTER the snapshot line it is already part of
            self._flush_pending_locked()
            if not self._write_lines_locked([line]):
                # degraded sink (disk full): re-arm the cadence instead of
                # leaving the trigger latched — otherwise EVERY subsequent
                # journaled transition would rebuild + serialize the whole
                # live state under the writer's lock, turning the exact
                # failure mode the best-effort journal is meant to ride out
                # cheaply into an O(state)-per-append stall
                self.appends_since_compact = 0
                return False
            # replay is O(live) from here even if rotation fails below
            self.appends_since_compact = 0
            _COMPACTIONS.inc()
            tmp = self.path + ".compact.tmp"
            try:
                with open(tmp, "w", encoding="utf-8") as tf:  # lint: disable=blocking-under-lock — rotation must exclude concurrent appends; the journal lock is a leaf
                    tf.write(line + "\n")
                    tf.flush()
                    os.fsync(tf.fileno())  # lint: disable=blocking-under-lock — see above
                os.replace(tmp, self.path)  # lint: disable=blocking-under-lock — see above
            except OSError:
                return True  # snapshot durable; rotation skipped (space only)
            try:
                self._f.close()
            except OSError:
                pass
            try:
                self._f = open(self.path, "a", encoding="utf-8")  # lint: disable=blocking-under-lock — handle swap must exclude concurrent appends; leaf lock
            except OSError:
                self._failed = True  # further appends will warn + no-op
            return True

    def close(self) -> None:
        with self._lock:
            self._flush_pending_locked()  # staged records must not die with us
            try:
                self._f.close()
            except OSError:
                pass


def _parse_record(lineno: int, line: str, path: str, final: bool) -> dict[str, Any] | None:
    try:
        rec = json.loads(line)
        if not isinstance(rec, dict) or "t" not in rec:
            raise ValueError("not a journal record")
    except ValueError as e:
        if final:
            return None  # torn tail: the crash interrupted this very append
        raise JournalError(
            f"corrupt journal record at line {lineno} of {path}: {e}"
        ) from None
    return rec


def iter_journal(path: str) -> Iterator[dict[str, Any]]:
    """Every intact record, in append order, streamed one line at a time —
    memory stays flat however long the history (the pool/AM replay loops
    fold 100k-record journals without materializing them).

    Same contract as :func:`read_journal`, raised lazily during iteration:
    :class:`JournalError` when the journal is missing/empty or has an
    unparseable record anywhere before the final line; an unparseable FINAL
    record (the predecessor was SIGKILLed mid-append) is silently dropped —
    its transition never became durable. Consumers folding incrementally
    must treat ANY raise as a degraded journal (both replay paths already
    rebuild from scratch on any fault).
    """
    if not os.path.exists(path):
        raise JournalError(f"journal missing: {path}")
    try:
        f = open(path, encoding="utf-8", errors="replace")
    except OSError as e:
        raise JournalError(f"journal unreadable: {e}") from e
    yielded = False
    with f:
        prev: tuple[int, str] | None = None
        try:
            for lineno, line in enumerate(f, start=1):
                if not line.strip():
                    continue
                if prev is not None:
                    yield _parse_record(prev[0], prev[1], path, final=False)  # type: ignore[misc]
                    yielded = True
                prev = (lineno, line)
        except OSError as e:
            raise JournalError(f"journal unreadable: {e}") from e
        if prev is not None:
            rec = _parse_record(prev[0], prev[1], path, final=True)
            if rec is not None:
                yield rec
                yielded = True
    if not yielded:
        raise JournalError(f"journal empty: {path}")


def read_journal(path: str) -> list[dict[str, Any]]:
    """Every intact record, in append order, as one list (thin wrapper over
    :func:`iter_journal` for callers that want the whole history; the
    replay loops stream instead)."""
    return list(iter_journal(path))
