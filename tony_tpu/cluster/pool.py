"""Multi-host pool service: the ResourceManager daemon and its AM-side client.

This supplies the reference's defining process split (SURVEY.md §2.1, §3.1
process boundary #2): a cluster-wide RM daemon that host agents
(cluster/agent.py, the NM analog) register with and heartbeat to, and that
per-job Application Masters allocate containers from. Container *launch* goes
AM → agent directly (the NMClient analog); the RM only arbitrates inventory
and liveness — exactly YARN's split.

TPU twist on the YARN resource model: a node's inventory is memory + vcores +
the TPU chips it owns *within an ICI slice* (a v5e host owns 4 chips of its
slice's 2D grid). A container's chip ask is satisfied from ONE node — on real
TPU pods a training task is one process per host — so multi-host jobs are
expressed as gangs of per-host tasks, and the pool keeps a gang's chips inside
as few slices as possible so mesh axes ride ICI, not DCN.

Node death is detected by missed agent heartbeats; containers on a dead node
are surfaced to their AM through the normal ``poll_exited`` path with
``EXIT_NODE_LOST`` — the AM's existing failure machinery (fail-fast or
whole-gang restart from checkpoint) takes it from there.

Deployments of the same protocol:
  - in-process:  LocalResourceManager / MultiSliceResourceManager drive a
    ``ContainerLauncher`` directly (resources.py) — the MiniCluster analog;
  - distributed: this RM daemon + one NodeAgent per host, the AM holding a
    ``RemoteResourceManager``. Same scheduler, same launcher, same env
    contract; only the transport differs.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import signal
import sys
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any

from tony_tpu import constants
from tony_tpu.cluster.resources import (
    AllocationError,
    Container,
    ResourceManager,
    Resources,
    SliceSpec,
)
from tony_tpu.cluster.rpc import RpcClient, RpcError, RpcServer

POOL_RPC_METHODS = [
    "register_node",
    "node_heartbeat",
    "allocate",
    "release",
    "release_all",
    "poll_exited",
    "request_kill",
    "pool_status",
]

_RUNNING, _EXITED, _RELEASED = "RUNNING", "EXITED", "RELEASED"


@dataclass(eq=False)
class _Node:
    """One registered host agent and its live accounting."""

    name: str
    host: str
    port: int
    memory_bytes: int
    vcores: int
    slice_id: int                       # -1 → CPU-only node
    slice_spec: str                     # e.g. "v5e-16": the WHOLE slice's shape
    chips: tuple[tuple[int, int], ...]  # slice-grid coords this host owns
    used_memory: int = 0
    used_vcores: int = 0
    used_chips: set[tuple[int, int]] = field(default_factory=set)
    alive: bool = True
    last_heartbeat: float = field(default_factory=time.monotonic)
    pending_kills: list[str] = field(default_factory=list)

    @property
    def free_chips(self) -> set[tuple[int, int]]:
        return set(self.chips) - self.used_chips


def _rect_from(free: set[tuple[int, int]], n: int) -> tuple[tuple[int, int], ...] | None:
    """A contiguous axis-aligned n-chip rectangle from a host's free chips,
    most-square shape first (the per-node analog of ChipGrid.allocate_chips)."""
    if n <= 0:
        return ()
    if len(free) < n:
        return None
    rows = [r for r, _ in free]
    cols = [c for _, c in free]
    shapes = sorted(
        {(r, n // r) for r in range(1, n + 1) if n % r == 0},
        key=lambda rc: abs(rc[0] - rc[1]),
    )
    for r, c in shapes:
        for r0 in range(min(rows), max(rows) - r + 2):
            for c0 in range(min(cols), max(cols) - c + 2):
                coords = tuple(
                    (r0 + i, c0 + j) for i, j in itertools.product(range(r), range(c))
                )
                if free.issuperset(coords):
                    return coords
    return None


class PoolService:
    """The RM daemon: node registry, slice-aware inventory, per-app exits."""

    def __init__(
        self,
        bind_host: str = "127.0.0.1",
        port: int = 0,
        secret: str = "",
        heartbeat_interval_ms: int = 1000,
        max_missed_heartbeats: int = 10,
    ):
        self.heartbeat_interval_ms = heartbeat_interval_ms
        self.max_missed = max_missed_heartbeats
        self._nodes: dict[str, _Node] = {}
        self._containers: dict[str, dict[str, Any]] = {}   # cid → record
        self._app_exits: dict[str, dict[str, int]] = {}    # app → {cid: rc}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.rpc = RpcServer(host=bind_host, port=port, secret=secret)
        self.rpc.register_object(self, POOL_RPC_METHODS)
        self._monitor = threading.Thread(target=self._liveness_loop, name="pool-liveness", daemon=True)

    # ------------------------------------------------------------- lifecycle
    def start(self) -> None:
        self.rpc.start()
        self._monitor.start()

    def stop(self) -> None:
        self._stop.set()
        self.rpc.stop()

    @property
    def address(self) -> tuple[str, int]:
        return self.rpc.address

    # ------------------------------------------------------------ agent side
    def register_node(
        self,
        name: str,
        host: str,
        port: int,
        memory_bytes: int,
        vcores: int,
        slice_id: int = -1,
        slice_spec: str = "",
        chips: list[list[int]] | None = None,
    ) -> dict[str, Any]:
        coords = tuple((int(r), int(c)) for r, c in (chips or []))
        with self._lock:
            # validate FIRST: a rejected registration must not disturb a
            # healthy node's bookkeeping (same-name check excluded — a valid
            # re-registration replaces the old incarnation below)
            if coords:
                spec = SliceSpec.parse(slice_spec)
                rows, cols = spec.topology
                for r, c in coords:
                    if not (0 <= r < rows and 0 <= c < cols):
                        raise ValueError(f"chip {r},{c} outside slice grid {rows}x{cols}")
                for other in self._nodes.values():
                    if (
                        other.name != name
                        and other.alive
                        and other.slice_id == slice_id
                        and set(other.chips) & set(coords)
                    ):
                        raise ValueError(
                            f"chips of {name} collide with {other.name} in slice {slice_id}"
                        )
            old = self._nodes.get(name)
            if old is not None:
                # agent restart: everything it was running is gone
                self._mark_node_lost_locked(old, reason="re-registered")
            self._nodes[name] = _Node(
                name=name, host=host, port=port,
                memory_bytes=int(memory_bytes), vcores=int(vcores),
                slice_id=int(slice_id), slice_spec=slice_spec, chips=coords,
            )
        return {"ack": True, "heartbeat_interval_ms": self.heartbeat_interval_ms}

    def node_heartbeat(
        self, name: str, exited: dict[str, int] | None = None, live: list[str] | None = None
    ) -> dict[str, Any]:
        with self._lock:
            node = self._nodes.get(name)
            if node is None or not node.alive:
                # we never met this agent, or declared it dead while it was
                # partitioned — its containers were already written off
                return {"unknown_node": True}
            now = time.monotonic()
            node.last_heartbeat = now
            for cid, rc in (exited or {}).items():
                self._record_exit_locked(cid, int(rc))
            if live is not None:
                # reconcile: a container the agent once reported live but is
                # no longer tracking (and didn't just report exited) is gone —
                # e.g. its exit report was lost across an agent hiccup. Gated
                # on seen_live so a container allocated-but-not-yet-launched
                # (the AM launches after the whole gang allocates) is immune.
                live_set = set(live)
                for cid, rec in list(self._containers.items()):
                    if rec["node"] != name or rec["state"] != _RUNNING:
                        continue
                    if cid in live_set:
                        rec["seen_live"] = True
                    elif rec.get("seen_live") and cid not in (exited or {}):
                        self._record_exit_locked(cid, constants.EXIT_NODE_LOST)
            kills, node.pending_kills = node.pending_kills, []
        return {"ack": True, "kill": kills}

    # --------------------------------------------------------------- AM side
    def allocate(
        self,
        app_id: str,
        job_type: str,
        task_index: int,
        memory_bytes: int,
        vcores: int,
        chips: int = 0,
    ) -> dict[str, Any]:
        with self._lock:
            alive = [n for n in self._nodes.values() if n.alive]
            if chips > 0:
                biggest = max((len(n.chips) for n in alive), default=0)
                if chips > biggest:
                    raise AllocationError(
                        f"{job_type}:{task_index} asks {chips} chips but the largest "
                        f"host owns {biggest}: a container runs on one host — shard "
                        f"the job into per-host tasks (one process per TPU VM)"
                    )
                # pack the gang's chips into as few slices as possible: prefer
                # slices this app already occupies, then fullest host first
                app_slices = {
                    rec["slice_id"]
                    for rec in self._containers.values()
                    if rec["app_id"] == app_id and rec["state"] == _RUNNING and rec["slice_id"] >= 0
                }
                candidates = sorted(
                    (n for n in alive if n.slice_id >= 0),
                    key=lambda n: (n.slice_id not in app_slices, len(n.free_chips)),
                )
            else:
                # chipless tasks spread by free memory (headroom-first)
                candidates = sorted(
                    alive, key=lambda n: n.memory_bytes - n.used_memory, reverse=True
                )
            for node in candidates:
                if (
                    node.used_memory + memory_bytes > node.memory_bytes
                    or node.used_vcores + vcores > node.vcores
                ):
                    continue
                coords = _rect_from(node.free_chips, chips)
                if coords is None:
                    continue
                node.used_memory += memory_bytes
                node.used_vcores += vcores
                node.used_chips.update(coords)
                cid = f"container_{uuid.uuid4().hex[:12]}"
                rec = {
                    "id": cid, "app_id": app_id, "job_type": job_type,
                    "task_index": int(task_index), "node": node.name,
                    "memory_bytes": int(memory_bytes), "vcores": int(vcores),
                    "chips": [list(c) for c in coords], "slice_id": node.slice_id,
                    "state": _RUNNING,
                }
                self._containers[cid] = rec
                return {
                    **rec,
                    "agent_host": node.host, "agent_port": node.port,
                    "slice_spec": node.slice_spec,
                }
            raise AllocationError(
                f"no node can host {job_type}:{task_index} "
                f"(ask: {memory_bytes}B/{vcores}vc/{chips}ch; nodes: "
                + ", ".join(
                    f"{n.name}[{n.memory_bytes - n.used_memory}B free"
                    + (f", {len(n.free_chips)}ch]" if n.chips else "]")
                    for n in alive
                )
                + ")"
            )

    def release(self, app_id: str, container_id: str) -> dict[str, Any]:
        with self._lock:
            self._release_locked(container_id)
        return {"ack": True}

    def release_all(self, app_id: str) -> dict[str, Any]:
        with self._lock:
            for cid, rec in list(self._containers.items()):
                if rec["app_id"] == app_id:
                    self._request_kill_locked(rec)
                    self._release_locked(cid)
            self._app_exits.pop(app_id, None)
        return {"ack": True}

    def poll_exited(self, app_id: str) -> dict[str, int]:
        with self._lock:
            return self._app_exits.pop(app_id, {})

    def request_kill(self, container_id: str) -> dict[str, Any]:
        """Backstop kill path when the AM cannot reach the agent directly:
        the order rides the agent's next heartbeat response."""
        with self._lock:
            rec = self._containers.get(container_id)
            if rec is not None:
                self._request_kill_locked(rec)
        return {"ack": True}

    def pool_status(self) -> dict[str, Any]:
        with self._lock:
            return {
                "nodes": [
                    {
                        "name": n.name, "alive": n.alive, "slice_id": n.slice_id,
                        "chips_total": len(n.chips), "chips_free": len(n.free_chips),
                        "memory_free": n.memory_bytes - n.used_memory,
                        "vcores_free": n.vcores - n.used_vcores,
                    }
                    for n in self._nodes.values()
                ],
                "containers_running": sum(
                    1 for r in self._containers.values() if r["state"] == _RUNNING
                ),
            }

    # -------------------------------------------------------------- internal
    def _request_kill_locked(self, rec: dict[str, Any]) -> None:
        node = self._nodes.get(rec["node"])
        if node is not None and node.alive and rec["state"] == _RUNNING:
            node.pending_kills.append(rec["id"])

    def _free_locked(self, rec: dict[str, Any]) -> None:
        node = self._nodes.get(rec["node"])
        if node is not None:
            node.used_memory -= rec["memory_bytes"]
            node.used_vcores -= rec["vcores"]
            node.used_chips.difference_update(tuple(c) for c in rec["chips"])

    def _record_exit_locked(self, cid: str, rc: int) -> None:
        rec = self._containers.get(cid)
        if rec is None or rec["state"] != _RUNNING:
            return
        rec["state"] = _EXITED
        self._free_locked(rec)
        self._app_exits.setdefault(rec["app_id"], {})[cid] = rc

    def _release_locked(self, cid: str) -> None:
        rec = self._containers.pop(cid, None)
        if rec is not None and rec["state"] == _RUNNING:
            self._free_locked(rec)

    def _mark_node_lost_locked(self, node: _Node, reason: str) -> None:
        node.alive = False
        for cid, rec in self._containers.items():
            if rec["node"] == node.name and rec["state"] == _RUNNING:
                self._record_exit_locked(cid, constants.EXIT_NODE_LOST)

    def _liveness_loop(self) -> None:
        timeout_s = self.heartbeat_interval_ms * self.max_missed / 1000
        while not self._stop.wait(self.heartbeat_interval_ms / 1000 / 2):
            now = time.monotonic()
            with self._lock:
                for node in self._nodes.values():
                    if node.alive and now - node.last_heartbeat > timeout_s:
                        self._mark_node_lost_locked(node, reason="missed heartbeats")


class RemoteResourceManager(ResourceManager):
    """AM-side adapter speaking to a PoolService + its agents.

    allocate/release/poll ride the RM; launch/kill go straight to the owning
    node's agent (the NMClient analog). Satisfies the same ``ResourceManager``
    interface the in-process pools do, so the AM, scheduler, and every E2E
    behavior are unchanged.
    """

    def __init__(self, rm_host: str, rm_port: int, secret: str = "", app_id: str = ""):
        self.app_id = app_id or f"app_{uuid.uuid4().hex[:8]}"
        self.rm = RpcClient(rm_host, rm_port, secret=secret)
        self.secret = secret
        self._agents: dict[tuple[str, int], RpcClient] = {}
        self._containers: dict[str, tuple[Container, tuple[str, int], int]] = {}
        self._span: list[int] | None = None
        self._lock = threading.Lock()

    def _agent(self, addr: tuple[str, int]) -> RpcClient:
        with self._lock:
            cli = self._agents.get(addr)
            if cli is None:
                cli = self._agents[addr] = RpcClient(addr[0], addr[1], secret=self.secret)
            return cli

    def allocate(self, job_type: str, task_index: int, resources: Resources) -> Container:
        try:
            got = self.rm.call(
                "allocate",
                app_id=self.app_id,
                job_type=job_type,
                task_index=task_index,
                memory_bytes=resources.memory_bytes,
                vcores=resources.vcores,
                chips=resources.chips,
            )
        except RpcError as e:
            if "AllocationError" in str(e):
                raise AllocationError(str(e)) from e
            raise
        coords = tuple((r, c) for r, c in got["chips"])
        spec = SliceSpec.parse(got["slice_spec"]) if got.get("slice_spec") else None
        container = Container(
            id=got["id"],
            host=got["node"],
            resources=resources,
            chip_coords=coords,
            slice_name=spec.name if spec else "",
            slice_topology=spec.topology if spec else (0, 0),
            job_type=job_type,
            task_index=task_index,
        )
        with self._lock:
            self._containers[container.id] = (
                container,
                (got["agent_host"], got["agent_port"]),
                got["slice_id"],
            )
        return container

    def release(self, container: Container) -> None:
        with self._lock:
            self._containers.pop(container.id, None)
            if not self._containers:
                self._span = None  # gang fully released: next gang re-snapshots
        try:
            self.rm.call("release", app_id=self.app_id, container_id=container.id)
        except (RpcError, OSError):
            pass  # RM unreachable at teardown: release_all in shutdown retries

    def _gang_span(self) -> list[int]:
        """Gang DCN span, append-only across launch waves (same contract as
        MultiSliceResourceManager.gang_slice_span): one wave's tasks all see
        the same span; a later dependency-gated wave appends new slices so
        earlier tasks' TPU_SLICE_ID indices stay valid."""
        with self._lock:
            current = {sid for _, _, sid in self._containers.values() if sid >= 0}
            if self._span is None:
                self._span = sorted(current)
            else:
                self._span.extend(sorted(current - set(self._span)))
            return self._span

    def start_container(
        self, container: Container, command: list[str], env: dict[str, str], log_dir: str
    ) -> None:
        with self._lock:
            entry = self._containers.get(container.id)
        if entry is None:
            raise AllocationError(f"start of unknown container {container.id}")
        _, addr, slice_id = entry
        # ship the job-facing env, not the AM's machine baseline: keys the
        # framework contract owns (TONY_/JAX_/TPU_/... prefixes, same
        # whitelist the docker runtime forwards) plus anything the AM
        # changed relative to its inherited environment. Baseline keys the
        # AM merely inherited (PATH, HOME, ...) come from the REMOTE node's
        # environ, which the agent merges under the shipped delta.
        from tony_tpu.cluster.resources import _DOCKER_ENV_PREFIXES

        delta = {
            k: v
            for k, v in env.items()
            if any(k.startswith(p) for p in _DOCKER_ENV_PREFIXES)
            or os.environ.get(k) != v
        }
        if slice_id >= 0:
            span = self._gang_span()
            delta[constants.ENV_TPU_SLICE_ID] = str(span.index(slice_id))
            delta[constants.ENV_TPU_NUM_SLICES] = str(len(span))
        self._agent(addr).call(
            "launch_container",
            container_id=container.id,
            command=command,
            env=delta,
            log_dir=log_dir,
        )

    def poll_exited(self) -> dict[str, int]:
        try:
            return {cid: int(rc) for cid, rc in self.rm.call("poll_exited", app_id=self.app_id).items()}
        except (RpcError, OSError):
            return {}

    def kill_container(self, container: Container) -> None:
        with self._lock:
            entry = self._containers.get(container.id)
        if entry is None:
            return
        _, addr, _ = entry
        try:
            self._agent(addr).call("kill_container", container_id=container.id)
        except (RpcError, OSError):
            # agent unreachable (dead node?) — backstop via the RM
            try:
                self.rm.call("request_kill", container_id=container.id)
            except (RpcError, OSError):
                pass

    def shutdown(self) -> None:
        try:
            self.rm.call("release_all", app_id=self.app_id)
        except (RpcError, OSError):
            pass
        with self._lock:
            self._containers.clear()
            agents = list(self._agents.values())
            self._agents.clear()
        for cli in agents:
            cli.close()
        self.rm.close()


def main(argv: list[str] | None = None) -> int:
    from tony_tpu.config import TonyConfig, keys

    p = argparse.ArgumentParser(prog="tony-pool", description="tony-tpu pool service (RM analog)")
    p.add_argument("--bind-host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--secret", default=os.environ.get(constants.ENV_POOL_SECRET, ""))
    p.add_argument("--conf_file", default=None, help="site config supplying tony.node.* liveness keys")
    p.add_argument("--conf", action="append", default=[], help="key=value override (repeatable)")
    p.add_argument("--heartbeat-ms", type=int, default=None,
                   help="overrides tony.node.heartbeat-interval-ms")
    p.add_argument("--max-missed", type=int, default=None,
                   help="overrides tony.node.max-missed-heartbeats")
    p.add_argument("--info-file", default="", help="write host/port JSON here once serving")
    args = p.parse_args(argv)
    config = TonyConfig.from_layers(conf_file=args.conf_file, conf_args=args.conf)
    svc = PoolService(
        bind_host=args.bind_host,
        port=args.port,
        secret=args.secret,
        heartbeat_interval_ms=args.heartbeat_ms
        if args.heartbeat_ms is not None
        else config.get_time_ms(keys.NODE_HEARTBEAT_INTERVAL_MS, 1000),
        max_missed_heartbeats=args.max_missed
        if args.max_missed is not None
        else config.get_int(keys.NODE_MAX_MISSED_HEARTBEATS, 10),
    )
    svc.start()
    host, port = svc.address
    if args.info_file:
        tmp = args.info_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"host": host, "port": port}, f)
        os.replace(tmp, args.info_file)
    print(f"[tony-pool] serving on {host}:{port}", flush=True)
    done = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: done.set())
    signal.signal(signal.SIGINT, lambda *_: done.set())
    done.wait()
    svc.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
