"""Multi-host pool service: the ResourceManager daemon and its AM-side client.

This supplies the reference's defining process split (SURVEY.md §2.1, §3.1
process boundary #2): a cluster-wide RM daemon that host agents
(cluster/agent.py, the NM analog) register with and heartbeat to, and that
per-job Application Masters allocate containers from. Container *launch* goes
AM → agent directly (the NMClient analog); the RM only arbitrates inventory
and liveness — exactly YARN's split.

TPU twist on the YARN resource model: a node's inventory is memory + vcores +
the TPU chips it owns *within an ICI slice* (a v5e host owns 4 chips of its
slice's 2D grid). A container's chip ask is satisfied from ONE node — on real
TPU pods a training task is one process per host — so multi-host jobs are
expressed as gangs of per-host tasks, and the pool keeps a gang's chips inside
as few slices as possible so mesh axes ride ICI, not DCN.

Node death is detected by missed agent heartbeats; containers on a dead node
are surfaced to their AM through the normal ``poll_exited`` path with
``EXIT_NODE_LOST`` — the AM's existing failure machinery (fail-fast or
whole-gang restart from checkpoint) takes it from there.

Deployments of the same protocol:
  - in-process:  LocalResourceManager / MultiSliceResourceManager drive a
    ``ContainerLauncher`` directly (resources.py) — the MiniCluster analog;
  - distributed: this RM daemon + one NodeAgent per host, the AM holding a
    ``RemoteResourceManager``. Same scheduler, same launcher, same env
    contract; only the transport differs.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import signal
import sys
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any

from tony_tpu import constants
from tony_tpu.obs import logging as obs_logging
from tony_tpu.cluster.journal import Journal, JournalError, read_journal
from tony_tpu.cluster.resources import (
    AllocationError,
    AllocationPending,
    Container,
    ResourceManager,
    Resources,
    SliceSpec,
    container_from_record,
    container_to_record,
)
from tony_tpu.cluster.rpc import RpcClient, RpcError, RpcServer
from tony_tpu.obs import metrics as obs_metrics

POOL_RPC_METHODS = [
    "register_node",
    "node_heartbeat",
    "register_app",
    "allocate",
    "release",
    "release_all",
    "poll_exited",
    "request_kill",
    "pool_status",
    "cluster_capacity",
    "pool_metrics",
]

_POOL_ADMISSIONS = obs_metrics.counter(
    "tony_pool_admissions_total", "apps admitted by the capacity scheduler", labelnames=("queue",))
_POOL_EVICTIONS = obs_metrics.counter(
    "tony_pool_evictions_total", "apps preempted back to waiting", labelnames=("queue",))
_POOL_ALLOCATE_QUEUED = obs_metrics.counter(
    "tony_pool_allocate_queued_total", "allocate() calls answered with wait (queued)")

_RUNNING, _EXITED, _RELEASED = "RUNNING", "EXITED", "RELEASED"


def parse_queue_spec(spec: str) -> dict[str, float]:
    """``"prod=0.7,dev=0.3"`` → {"prod": 0.7, "dev": 0.3}. Shares are each
    queue's guaranteed fraction of the pool's primary capacity dimension
    (chips when the pool has chips, memory otherwise); a queue may borrow
    beyond its share while no other queue has waiting apps (elastic, the
    capacity-scheduler behavior)."""
    queues: dict[str, float] = {}
    for part in (spec or "default=1.0").split(","):
        part = part.strip()
        if not part:
            continue
        name, _, share = part.partition("=")
        try:
            f = float(share) if share else 1.0
        except ValueError:
            raise ValueError(f"bad queue share in {part!r}: expected name=fraction") from None
        if not 0 < f <= 1:
            raise ValueError(f"queue {name!r} share must be in (0, 1], got {f}")
        queues[name.strip()] = f
    if not queues:
        raise ValueError(f"no queues in spec {spec!r}")
    _validate_queue_shares(queues)
    return queues


def _validate_queue_shares(queues: dict[str, float]) -> None:
    """Shares are GUARANTEES — they cannot oversubscribe the pool. YARN's
    capacity scheduler rejects capacities that don't fit 100% for the same
    reason: with prod=0.9,dev=0.9 the over-share gate almost never fires and
    the operator's 'guarantee' silently degrades to FIFO."""
    bad = [(q, f) for q, f in queues.items() if not 0 < f <= 1]
    if bad:
        raise ValueError(f"queue shares must each be in (0, 1]: {bad}")
    total = sum(queues.values())
    if total > 1.0 + 1e-9:
        raise ValueError(
            f"queue shares sum to {total:g} > 1 — guarantees would "
            f"oversubscribe the pool: {queues}"
        )


@dataclass(eq=False)
class _App:
    """One tenant application and its queue/admission state.

    ``admitted`` apps hold a capacity CLAIM of elementwise
    max(demand, held) — reserved even while their containers are being
    (re)allocated, so an app mid-gang-restart keeps its capacity and two
    half-allocated gangs can never deadlock each other. Waiting apps hold
    nothing and retry through ``allocate`` until the scheduler admits them.
    """

    app_id: str
    queue: str
    priority: int = 0
    demand_memory: int = 0
    demand_vcores: int = 0
    demand_chips: int = 0
    seq: int = 0
    admitted: bool = False
    preempted: bool = False    # demoted by preemption; re-queues via allocate
    # when this app last STARTED waiting (registration or eviction) — the
    # cross-queue reclaim grace is measured from here
    wait_since: float = field(default_factory=time.monotonic)

    @property
    def sort_key(self) -> tuple[int, int]:
        return (-self.priority, self.seq)  # higher priority first, then FIFO


@dataclass(eq=False)
class _Node:
    """One registered host agent and its live accounting."""

    name: str
    host: str
    port: int
    memory_bytes: int
    vcores: int
    slice_id: int                       # -1 → CPU-only node
    slice_spec: str                     # e.g. "v5e-16": the WHOLE slice's shape
    chips: tuple[tuple[int, int], ...]  # slice-grid coords this host owns
    used_memory: int = 0
    used_vcores: int = 0
    used_chips: set[tuple[int, int]] = field(default_factory=set)
    alive: bool = True
    last_heartbeat: float = field(default_factory=time.monotonic)
    pending_kills: list[str] = field(default_factory=list)

    @property
    def free_chips(self) -> set[tuple[int, int]]:
        return set(self.chips) - self.used_chips


def _rect_from(free: set[tuple[int, int]], n: int) -> tuple[tuple[int, int], ...] | None:
    """A contiguous axis-aligned n-chip rectangle from a host's free chips,
    most-square shape first (the per-node analog of ChipGrid.allocate_chips)."""
    if n <= 0:
        return ()
    if len(free) < n:
        return None
    rows = [r for r, _ in free]
    cols = [c for _, c in free]
    shapes = sorted(
        {(r, n // r) for r in range(1, n + 1) if n % r == 0},
        key=lambda rc: abs(rc[0] - rc[1]),
    )
    for r, c in shapes:
        for r0 in range(min(rows), max(rows) - r + 2):
            for c0 in range(min(cols), max(cols) - c + 2):
                coords = tuple(
                    (r0 + i, c0 + j) for i, j in itertools.product(range(r), range(c))
                )
                if free.issuperset(coords):
                    return coords
    return None


class PoolService:
    """The RM daemon: node registry, slice-aware inventory, per-app exits."""

    def __init__(
        self,
        bind_host: str = "127.0.0.1",
        port: int = 0,
        secret: str = "",
        heartbeat_interval_ms: int = 1000,
        max_missed_heartbeats: int = 10,
        queues: dict[str, float] | None = None,
        preemption: bool = False,
        preemption_grace_ms: int = 0,
        journal_path: str | None = None,
        chaos=None,
    ):
        self.heartbeat_interval_ms = heartbeat_interval_ms
        self.max_missed = max_missed_heartbeats
        self.queues = dict(queues) if queues else {"default": 1.0}
        _validate_queue_shares(self.queues)
        self.preemption = preemption
        # cross-queue reclaim fires only for heads waiting at least this
        # long (tony.pool.preemption.grace-ms): transient waits — an app
        # about to finish, a gang mid-restart — don't trigger kills in
        # other queues
        self.preemption_grace_ms = preemption_grace_ms
        #: optional fault-injection context (pool-crash); None in production
        self.chaos = chaos
        self._nodes: dict[str, _Node] = {}
        self._containers: dict[str, dict[str, Any]] = {}   # cid → record
        self._app_exits: dict[str, dict[str, int]] = {}    # app → {cid: rc}
        self._apps: dict[str, _App] = {}                   # app → queue state
        self._app_seq = itertools.count()
        self._preempt_cids: set[str] = set()               # kills we initiated
        self._all_dead_since: float | None = None          # allocate() saw 0 alive
        self._lock = threading.Lock()
        self._stop = threading.Event()
        # work-preserving restart (tony.pool.journal.file): registrations,
        # admissions, and allocations are journaled so a restarted pool
        # rebuilds its queue state and re-adopts live containers from agent
        # re-registration instead of forgetting every admitted app
        self._journal: Journal | None = None
        if journal_path:
            if os.path.exists(journal_path):
                try:
                    with self._lock:
                        self._recover_from_journal_locked(read_journal(journal_path))
                    obs_logging.info(
                        f"[tony-pool] recovered from journal: "
                        f"{len(self._apps)} app(s), "
                        f"{sum(1 for r in self._containers.values() if r['state'] == _RUNNING)} "
                        "live container record(s) awaiting agent re-registration")
                except Exception as e:  # noqa: BLE001 — ANY replay fault degrades, never refuses to start
                    # loud degrade to EMPTY state (a half-replayed journal is
                    # fiction — an agent could get its orphans re-adopted
                    # against it): agents re-register and kill the orphans,
                    # the pre-journal behavior
                    obs_logging.error(f"[tony-pool] journal unusable — starting empty: {e}")
                    with self._lock:
                        self._apps = {}
                        self._containers = {}
                        self._app_exits = {}
                        self._app_seq = itertools.count()
            self._journal = Journal(journal_path)
        self.rpc = RpcServer(host=bind_host, port=port, secret=secret)
        self.rpc.register_object(self, POOL_RPC_METHODS)
        self._monitor = threading.Thread(target=self._liveness_loop, name="pool-liveness", daemon=True)

    # ------------------------------------------------------ recovery journal
    def _jlog_locked(self, t: str, **fields: Any) -> None:
        if self._journal is not None:
            self._journal.append(t, **fields)

    def _journal_app_locked(self, app: _App) -> None:
        """Full app row (last record wins on replay) — written on every
        registration/admission/eviction state change."""
        self._jlog_locked(
            "app", app_id=app.app_id, queue=app.queue, priority=app.priority,
            seq=app.seq, admitted=app.admitted, preempted=app.preempted,
            demand_memory=app.demand_memory, demand_vcores=app.demand_vcores,
            demand_chips=app.demand_chips,
        )

    def _recover_from_journal_locked(self, records: list[dict[str, Any]]) -> None:
        """Rebuild apps/containers/undelivered-exits from the journal. Nodes
        are runtime state: they re-register on their next heartbeat (the
        agent's ``unknown_node`` path) carrying their live container ids, and
        ``register_node`` re-applies the accounting for records replayed
        here. A waiting app admitted pre-crash stays admitted (never
        double-admitted); a running app keeps its claim and is not evicted."""
        max_seq = -1
        for rec in records:
            t = rec.get("t")
            if t == "app":
                app = _App(
                    app_id=str(rec["app_id"]),
                    queue=str(rec["queue"]),
                    priority=int(rec.get("priority", 0)),
                    seq=int(rec.get("seq", 0)),
                    admitted=bool(rec.get("admitted")),
                    preempted=bool(rec.get("preempted")),
                    demand_memory=int(rec.get("demand_memory", 0)),
                    demand_vcores=int(rec.get("demand_vcores", 0)),
                    demand_chips=int(rec.get("demand_chips", 0)),
                )
                if app.queue not in self.queues:
                    # queue config changed across the restart: park the app in
                    # the first declared queue rather than refusing recovery
                    app.queue = "default" if "default" in self.queues else next(iter(self.queues))
                max_seq = max(max_seq, app.seq)
                self._apps[app.app_id] = app
            elif t == "app_removed":
                self._apps.pop(str(rec["app_id"]), None)
                self._app_exits.pop(str(rec["app_id"]), None)
            elif t == "container":
                crec = dict(rec["rec"])
                crec.pop("seen_live", None)  # must be re-observed by a live agent
                self._containers[crec["id"]] = crec
            elif t == "seen":
                crec = self._containers.get(str(rec["cid"]))
                if crec is not None:
                    crec["seen_live"] = True
            elif t == "kill_requested":
                crec = self._containers.get(str(rec["cid"]))
                if crec is not None:
                    crec["kill_requested"] = True
            elif t == "exited":
                crec = self._containers.get(str(rec["cid"]))
                if crec is not None and crec["state"] == _RUNNING:
                    crec["state"] = _EXITED
                    self._app_exits.setdefault(crec["app_id"], {})[crec["id"]] = int(rec["rc"])
            elif t == "released":
                self._containers.pop(str(rec["cid"]), None)
            elif t == "polled":
                self._app_exits.pop(str(rec["app_id"]), None)
            else:
                raise JournalError(f"unknown pool journal record type {t!r}")
        self._app_seq = itertools.count(max_seq + 1)

    # ------------------------------------------------------------- lifecycle
    def start(self) -> None:
        self.rpc.start()
        self._monitor.start()

    def stop(self) -> None:
        self._stop.set()
        self.rpc.stop()
        if self._journal is not None:
            self._journal.close()

    @property
    def address(self) -> tuple[str, int]:
        return self.rpc.address

    # ------------------------------------------------------------ agent side
    def register_node(
        self,
        name: str,
        host: str,
        port: int,
        memory_bytes: int,
        vcores: int,
        slice_id: int = -1,
        slice_spec: str = "",
        chips: list[list[int]] | None = None,
        live: list[str] | None = None,
    ) -> dict[str, Any]:
        """Agent (re-)registration, now container-preserving: ``live`` names
        the container ids the agent is still running. Containers the pool
        recognizes (including ones replayed from the recovery journal after a
        pool restart) are RE-ADOPTED — their accounting is applied to the
        fresh node object and they keep running. Containers the pool does
        NOT recognize are orphans of a forgotten epoch and come back in the
        ``kill`` list; a pool with no journal therefore recognizes nothing
        and the agent kills everything — exactly the pre-journal behavior."""
        coords = tuple((int(r), int(c)) for r, c in (chips or []))
        live_set = set(live or [])
        with self._lock:
            # validate FIRST: a rejected registration must not disturb a
            # healthy node's bookkeeping (same-name check excluded — a valid
            # re-registration replaces the old incarnation below)
            if coords:
                spec = SliceSpec.parse(slice_spec)
                rows, cols = spec.topology
                for r, c in coords:
                    if not (0 <= r < rows and 0 <= c < cols):
                        raise ValueError(f"chip {r},{c} outside slice grid {rows}x{cols}")
                for other in self._nodes.values():
                    if (
                        other.name != name
                        and other.alive
                        and other.slice_id == slice_id
                        and set(other.chips) & set(coords)
                    ):
                        raise ValueError(
                            f"chips of {name} collide with {other.name} in slice {slice_id}"
                        )
            old = self._nodes.get(name)
            for cid, rec in list(self._containers.items()):
                if rec["node"] != name or rec["state"] != _RUNNING or cid in live_set:
                    continue
                # gone from the agent's live list: written off IF we knew the
                # node before (agent restart: its processes died with it) or
                # an agent once reported the container live (journal replay +
                # genuine death while the pool was down). A journaled record
                # never seen live is an allocated-not-yet-launched container
                # — the AM may still start it; leave it RUNNING.
                if old is not None or rec.get("seen_live"):
                    self._record_exit_locked(cid, constants.EXIT_NODE_LOST)
            # a live node clears the all-dead escalation clock — otherwise a
            # stale timestamp from a PAST outage would fail the next brief
            # blip instantly instead of granting its liveness-budget grace
            self._all_dead_since = None
            node = _Node(
                name=name, host=host, port=port,
                memory_bytes=int(memory_bytes), vcores=int(vcores),
                slice_id=int(slice_id), slice_spec=slice_spec, chips=coords,
            )
            self._nodes[name] = node
            if old is not None:
                # undelivered kill orders must survive the node-object swap:
                # with work-preserving re-adoption nothing else culls them
                node.pending_kills = list(old.pending_kills)
            kills: list[str] = []
            for cid, rec in self._containers.items():
                # re-account EVERY record still RUNNING on this node — both
                # the agent-confirmed live ones and allocated-not-yet-launched
                # ones (never seen live): their claim is real either way, or
                # allocate() would double-book the chips and the eventual
                # exit would drive the accounting negative
                if rec["state"] != _RUNNING or rec["node"] != name:
                    continue
                node.used_memory += rec["memory_bytes"]
                node.used_vcores += rec["vcores"]
                node.used_chips.update(tuple(c) for c in rec["chips"])
                if cid in live_set:
                    if not rec.get("seen_live"):
                        rec["seen_live"] = True
                        self._jlog_locked("seen", cid=cid)
                    if rec.get("kill_requested"):
                        # a backstop kill arrived while this node was away:
                        # deliver it now instead of resurrecting the victim
                        kills.append(cid)
            # live containers the pool has NO record of: orphans of an epoch
            # this pool never knew — the agent kills them
            kills.extend(
                cid for cid in sorted(live_set)
                if not (
                    (rec := self._containers.get(cid)) is not None
                    and rec["state"] == _RUNNING and rec["node"] == name
                )
            )
            self._schedule_locked()
        return {
            "ack": True,
            "heartbeat_interval_ms": self.heartbeat_interval_ms,
            "kill": kills,
        }

    def node_heartbeat(
        self, name: str, exited: dict[str, int] | None = None, live: list[str] | None = None
    ) -> dict[str, Any]:
        with self._lock:
            node = self._nodes.get(name)
            if node is None or not node.alive:
                # we never met this agent, or declared it dead while it was
                # partitioned — its containers were already written off
                return {"unknown_node": True}
            now = time.monotonic()
            node.last_heartbeat = now
            for cid, rc in (exited or {}).items():
                self._record_exit_locked(cid, int(rc))
            if live is not None:
                # reconcile: a container the agent once reported live but is
                # no longer tracking (and didn't just report exited) is gone —
                # e.g. its exit report was lost across an agent hiccup. Gated
                # on seen_live so a container allocated-but-not-yet-launched
                # (the AM launches after the whole gang allocates) is immune.
                live_set = set(live)
                for cid, rec in list(self._containers.items()):
                    if rec["node"] != name or rec["state"] != _RUNNING:
                        continue
                    if cid in live_set:
                        if not rec.get("seen_live"):
                            rec["seen_live"] = True
                            # durable: after a pool restart, only containers
                            # an agent once reported live may be written off
                            # when missing from a re-registration
                            self._jlog_locked("seen", cid=cid)
                    elif rec.get("seen_live") and cid not in (exited or {}):
                        self._record_exit_locked(cid, constants.EXIT_NODE_LOST)
            kills, node.pending_kills = node.pending_kills, []
        return {"ack": True, "kill": kills}

    # --------------------------------------------------------------- AM side
    def register_app(
        self,
        app_id: str,
        queue: str = "default",
        priority: int = 0,
        memory_bytes: int = 0,
        vcores: int = 0,
        chips: int = 0,
    ) -> dict[str, Any]:
        """ApplicationSubmissionContext analog: the AM announces its queue,
        priority, and TOTAL gang demand before allocating. Admission (the
        YARN capacity-queue behavior ``tony.application.queue`` configures)
        is decided from these demands: apps WAIT when the pool is busy
        instead of failing."""
        if queue not in self.queues:
            raise ValueError(
                f"unknown queue {queue!r}: pool queues are {sorted(self.queues)} "
                f"(tony.pool.queues)"
            )
        with self._lock:
            app = self._apps.get(app_id)
            if app is None:
                app = self._apps[app_id] = _App(
                    app_id=app_id, queue=queue, priority=int(priority),
                    seq=next(self._app_seq),
                )
            app.queue, app.priority = queue, int(priority)
            app.demand_memory = int(memory_bytes)
            app.demand_vcores = int(vcores)
            app.demand_chips = int(chips)
            self._schedule_locked()
            self._journal_app_locked(app)
            return {"ack": True, "queue": queue, "admitted": app.admitted}

    def allocate(
        self,
        app_id: str,
        job_type: str,
        task_index: int,
        memory_bytes: int,
        vcores: int,
        chips: int = 0,
    ) -> dict[str, Any]:
        with self._lock:
            alive = [n for n in self._nodes.values() if n.alive]
            if not alive:
                if not self._nodes:
                    # nothing EVER registered: a misconfigured pool — fail fast
                    raise AllocationError(
                        f"pool has no registered nodes to host {job_type}:{task_index}"
                    )
                # nodes exist but are all currently dead (agent blip/restart):
                # they re-register on their next heartbeat — wait, but only
                # for one more liveness budget: agents that stay gone past it
                # are permanently dead, and an unbounded wait would leave the
                # job queued forever with no escalation
                now = time.monotonic()
                if self._all_dead_since is None:
                    self._all_dead_since = now
                budget_s = self.heartbeat_interval_ms * self.max_missed / 1000
                waited = now - self._all_dead_since
                if waited > budget_s:
                    raise AllocationError(
                        f"all pool nodes unreachable for {waited:.1f}s (> liveness "
                        f"budget {budget_s:.1f}s) — pool agents look permanently "
                        f"dead; cannot host {job_type}:{task_index}"
                    )
                _POOL_ALLOCATE_QUEUED.inc()
                return {
                    "wait": True, "queue": "", "position": 0,
                    "reason": "all pool nodes currently unreachable",
                }
            self._all_dead_since = None
            if chips > 0:
                biggest = max((len(n.chips) for n in alive), default=0)
                if chips > biggest:
                    raise AllocationError(
                        f"{job_type}:{task_index} asks {chips} chips but the largest "
                        f"host owns {biggest}: a container runs on one host — shard "
                        f"the job into per-host tasks (one process per TPU VM)"
                    )
                # placeability-if-empty: an ask no host could satisfy even
                # with ZERO occupancy (e.g. a 2x2 rect on a host owning a
                # 1x4 strip) would otherwise wait forever as "fragmentation"
                if not any(_rect_from(set(n.chips), chips) for n in alive):
                    raise AllocationError(
                        f"{job_type}:{task_index} asks a {chips}-chip rectangle "
                        f"no host's chip layout can form even when empty"
                    )
            if memory_bytes > max(n.memory_bytes for n in alive):
                raise AllocationError(
                    f"{job_type}:{task_index} asks {memory_bytes}B memory but the "
                    f"largest host owns {max(n.memory_bytes for n in alive)}B"
                )
            if vcores > max(n.vcores for n in alive):
                raise AllocationError(
                    f"{job_type}:{task_index} asks {vcores} vcores but the largest "
                    f"host owns {max(n.vcores for n in alive)}"
                )
            app = self._apps.get(app_id)
            if app is None:
                # back-compat: an unregistered app enters the default queue
                # claiming only what it asks for (AMs register real demands)
                default_q = "default" if "default" in self.queues else next(iter(self.queues))
                app = self._apps[app_id] = _App(
                    app_id=app_id, queue=default_q, seq=next(self._app_seq),
                )
            # demand learns the observed gang size (auto-registered apps
            # under-claim; held+ask is exact once the gang allocates serially)
            held = self._held_locked(app_id)
            before = (app.demand_memory, app.demand_vcores, app.demand_chips)
            app.demand_memory = max(app.demand_memory, held[0] + memory_bytes)
            app.demand_vcores = max(app.demand_vcores, held[1] + vcores)
            app.demand_chips = max(app.demand_chips, held[2] + chips)
            if (app.demand_memory, app.demand_vcores, app.demand_chips) != before:
                self._journal_app_locked(app)
            if not app.admitted:
                self._schedule_locked()
            if not app.admitted:
                totals = self._totals_locked()
                if (
                    app.demand_memory > totals[0]
                    or app.demand_vcores > totals[1]
                    or app.demand_chips > totals[2]
                ):
                    raise AllocationError(
                        f"app {app_id} demand ({app.demand_memory}B/"
                        f"{app.demand_vcores}vc/{app.demand_chips}ch) exceeds the "
                        f"pool's total capacity ({totals[0]}B/{totals[1]}vc/"
                        f"{totals[2]}ch) — it can never be admitted"
                    )
                waiting = [
                    a for a in self._apps.values()
                    if a.queue == app.queue and not a.admitted
                ]
                waiting.sort(key=lambda a: a.sort_key)
                _POOL_ALLOCATE_QUEUED.inc()
                return {
                    "wait": True,
                    "queue": app.queue,
                    "position": waiting.index(app),
                    "reason": f"queued in {app.queue!r} at position "
                              f"{waiting.index(app)} of {len(waiting)}"
                              + (" (preempted)" if app.preempted else ""),
                }
            if chips > 0:
                # pack the gang's chips into as few slices as possible: prefer
                # slices this app already occupies, then fullest host first
                app_slices = {
                    rec["slice_id"]
                    for rec in self._containers.values()
                    if rec["app_id"] == app_id and rec["state"] == _RUNNING and rec["slice_id"] >= 0
                }
                candidates = sorted(
                    (n for n in alive if n.slice_id >= 0),
                    key=lambda n: (n.slice_id not in app_slices, len(n.free_chips)),
                )
            else:
                # chipless tasks spread by free memory (headroom-first)
                candidates = sorted(
                    alive, key=lambda n: n.memory_bytes - n.used_memory, reverse=True
                )
            for node in candidates:
                if (
                    node.used_memory + memory_bytes > node.memory_bytes
                    or node.used_vcores + vcores > node.vcores
                ):
                    continue
                coords = _rect_from(node.free_chips, chips)
                if coords is None:
                    continue
                node.used_memory += memory_bytes
                node.used_vcores += vcores
                node.used_chips.update(coords)
                cid = f"container_{uuid.uuid4().hex[:12]}"
                rec = {
                    "id": cid, "app_id": app_id, "job_type": job_type,
                    "task_index": int(task_index), "node": node.name,
                    "memory_bytes": int(memory_bytes), "vcores": int(vcores),
                    "chips": [list(c) for c in coords], "slice_id": node.slice_id,
                    "state": _RUNNING,
                }
                self._containers[cid] = rec
                self._jlog_locked("container", rec=dict(rec))
                return {
                    **rec,
                    "agent_host": node.host, "agent_port": node.port,
                    "slice_spec": node.slice_spec,
                }
            # ADMITTED but nothing fits right now (other tenants' containers
            # still draining, or fragmentation): transient — the app keeps
            # its claim and the AM retries. Never-fit asks were rejected above.
            _POOL_ALLOCATE_QUEUED.inc()
            return {
                "wait": True,
                "queue": app.queue,
                "position": 0,
                "reason": f"admitted; no node can host {job_type}:{task_index} yet "
                          f"(ask: {memory_bytes}B/{vcores}vc/{chips}ch; nodes: "
                          + ", ".join(
                              f"{n.name}[{n.memory_bytes - n.used_memory}B free"
                              + (f", {len(n.free_chips)}ch]" if n.chips else "]")
                              for n in alive
                          )
                          + ")",
            }

    def release(self, app_id: str, container_id: str) -> dict[str, Any]:
        with self._lock:
            self._release_locked(container_id)
            self._schedule_locked()
        return {"ack": True}

    def release_all(self, app_id: str) -> dict[str, Any]:
        with self._lock:
            for cid, rec in list(self._containers.items()):
                if rec["app_id"] == app_id:
                    self._request_kill_locked(rec)
                    self._release_locked(cid)
            self._app_exits.pop(app_id, None)
            self._apps.pop(app_id, None)  # app done: leave the queue entirely
            self._jlog_locked("app_removed", app_id=app_id)
            self._schedule_locked()
        return {"ack": True}

    def poll_exited(self, app_id: str) -> dict[str, int]:
        with self._lock:
            exits = self._app_exits.pop(app_id, {})
            if exits:
                # delivered: a restarted pool must not re-deliver these
                self._jlog_locked("polled", app_id=app_id)
            return exits

    def request_kill(self, container_id: str) -> dict[str, Any]:
        """Backstop kill path when the AM cannot reach the agent directly:
        the order rides the agent's next heartbeat response."""
        with self._lock:
            rec = self._containers.get(container_id)
            if rec is not None:
                self._request_kill_locked(rec)
        return {"ack": True}

    def pool_metrics(self) -> dict[str, Any]:
        """This pool-service process's metrics-registry snapshot
        (obs/metrics.py) — scrapeable through any RPC client, same shape as
        the AM's ``get_metrics``."""
        return {"identity": "pool", "metrics": obs_metrics.REGISTRY.snapshot()}

    def pool_status(self) -> dict[str, Any]:
        with self._lock:
            return {
                "nodes": [
                    {
                        "name": n.name, "alive": n.alive, "slice_id": n.slice_id,
                        "chips_total": len(n.chips), "chips_free": len(n.free_chips),
                        "memory_free": n.memory_bytes - n.used_memory,
                        "vcores_free": n.vcores - n.used_vcores,
                    }
                    for n in self._nodes.values()
                ],
                "containers_running": sum(
                    1 for r in self._containers.values() if r["state"] == _RUNNING
                ),
                "queues": {
                    q: {
                        "share": share,
                        "admitted": sorted(
                            (
                                {
                                    "app_id": a.app_id, "priority": a.priority,
                                    "held_chips": self._held_locked(a.app_id)[2],
                                    "held_memory": self._held_locked(a.app_id)[0],
                                }
                                for a in self._apps.values()
                                if a.queue == q and a.admitted
                            ),
                            key=lambda e: e["app_id"],
                        ),
                        "waiting": [
                            {
                                "app_id": a.app_id, "priority": a.priority,
                                "position": i, "preempted": a.preempted,
                            }
                            for i, a in enumerate(sorted(
                                (a for a in self._apps.values()
                                 if a.queue == q and not a.admitted),
                                key=lambda a: a.sort_key,
                            ))
                        ],
                    }
                    for q, share in self.queues.items()
                },
                "preemption": self.preemption,
            }

    def cluster_capacity(self) -> dict[str, int]:
        """TOTAL capacity of currently-alive nodes (the admission universe) —
        what the AM's elastic-downsize decision compares gang demand against
        after a node is permanently lost."""
        with self._lock:
            mem, vc, chips = self._totals_locked()
            return {
                "memory_bytes": mem, "vcores": vc, "chips": chips,
                "alive_nodes": sum(1 for n in self._nodes.values() if n.alive),
                "nodes": [
                    {
                        "memory_bytes": n.memory_bytes,
                        "vcores": n.vcores,
                        "chips": len(n.chips),
                    }
                    for n in self._nodes.values()
                    if n.alive
                ],
            }

    # ------------------------------------------------- admission scheduling
    def _totals_locked(self) -> tuple[int, int, int]:
        """(memory, vcores, chips) over alive nodes — the admission universe."""
        alive = [n for n in self._nodes.values() if n.alive]
        return (
            sum(n.memory_bytes for n in alive),
            sum(n.vcores for n in alive),
            sum(len(n.chips) for n in alive),
        )

    def _held_locked(self, app_id: str) -> tuple[int, int, int]:
        mem = vc = ch = 0
        for rec in self._containers.values():
            if rec["app_id"] == app_id and rec["state"] == _RUNNING:
                mem += rec["memory_bytes"]
                vc += rec["vcores"]
                ch += len(rec["chips"])
        return mem, vc, ch

    def _claim_locked(self, app: _App) -> tuple[int, int, int]:
        held = self._held_locked(app.app_id)
        return (
            max(app.demand_memory, held[0]),
            max(app.demand_vcores, held[1]),
            max(app.demand_chips, held[2]),
        )

    @staticmethod
    def _fits(free: list[int], demand: tuple[int, int, int]) -> bool:
        return all(f >= d for f, d in zip(free, demand))

    def _schedule_locked(self) -> None:
        """Admit waiting apps (the capacity-scheduler decision).

        Claims-based: each admitted app reserves max(demand, held), so
        admission is all-or-nothing at GANG granularity — two apps can never
        interleave half-gangs into a deadlock. Within a queue: priority desc,
        then FIFO. Across queues: least relative usage (claim/share) first.
        A queue may exceed its share while no other queue has waiters, and
        every queue may always run at least one app (no share-induced
        starvation). With preemption on, a waiting app may evict
        strictly-lower-priority admitted apps from its own queue.
        """
        totals = self._totals_locked()
        if not any(totals):
            return  # no capacity registered yet — everything waits
        primary = 2 if totals[2] > 0 else 0  # chips when the pool has chips
        demand_of = lambda a: (a.demand_memory, a.demand_vcores, a.demand_chips)  # noqa: E731
        claims = {a.app_id: self._claim_locked(a) for a in self._apps.values() if a.admitted}
        free = [t - sum(c[i] for c in claims.values()) for i, t in enumerate(totals)]
        queue_used: dict[str, int] = {q: 0 for q in self.queues}
        for a in self._apps.values():
            if a.admitted:
                queue_used[a.queue] = queue_used.get(a.queue, 0) + claims[a.app_id][primary]

        def waiting_in(q: str) -> list[_App]:
            return sorted(
                (a for a in self._apps.values() if a.queue == q and not a.admitted),
                key=lambda a: a.sort_key,
            )

        def admit(app: _App) -> None:
            app.admitted, app.preempted = True, False
            _POOL_ADMISSIONS.inc(queue=app.queue)
            self._journal_app_locked(app)
            d = demand_of(app)
            for i in range(3):
                free[i] -= d[i]
            queue_used[app.queue] = queue_used.get(app.queue, 0) + d[primary]

        while True:
            eligible: list[tuple[float, tuple[int, int], _App]] = []
            blocked_heads: list[_App] = []
            for q, share in self.queues.items():
                heads = waiting_in(q)
                if not heads:
                    continue
                head = heads[0]
                if not self._fits(free, demand_of(head)):
                    blocked_heads.append(head)
                    continue
                others_waiting = any(
                    a for a in self._apps.values() if not a.admitted and a.queue != q
                )
                cap = share * totals[primary]
                over_share = queue_used.get(q, 0) + demand_of(head)[primary] > cap
                if over_share and others_waiting and queue_used.get(q, 0) > 0:
                    # queue is over its share while others wait (elastic
                    # borrowing only applies to an otherwise-idle pool; a
                    # queue's FIRST app always may run)
                    blocked_heads.append(head)
                    continue
                eligible.append((queue_used.get(q, 0) / share, head.sort_key, head))
            if eligible:
                eligible.sort(key=lambda e: (e[0], e[1]))
                admit(eligible[0][2])
                continue
            if self.preemption and blocked_heads:
                blocked_heads.sort(key=lambda a: a.sort_key)
                if self._preempt_for_locked(
                    blocked_heads[0], free, claims, queue_used, primary, totals, admit
                ):
                    continue
                # same-queue priority preemption didn't help: try restoring
                # the CAPACITY GUARANTEE — an under-share head may reclaim
                # from queues that borrowed beyond their share
                if any(
                    self._reclaim_across_queues_locked(
                        h, free, claims, queue_used, primary, totals, admit
                    )
                    for h in blocked_heads
                ):
                    continue
            return

    def _preempt_for_locked(
        self,
        cand: _App,
        free: list[int],
        claims: dict[str, tuple[int, int, int]],
        queue_used: dict[str, int],
        primary: int,
        totals: tuple[int, int, int],
        admit,
    ) -> bool:
        """Evict strictly-lower-priority admitted apps from ``cand``'s own
        queue (lowest priority, newest first) and admit ``cand`` in the SAME
        action. The atomic evict+admit matters: if the freed claims went back
        to the general pool, the next admission pass could hand them to
        another queue's head and the eviction would cascade (or be wasted) —
        victims are evicted exactly for the app that takes their place.
        Kills ride the agents' heartbeats; the claim swap is immediate, so
        ``cand``'s allocations simply wait out the drain.

        Share gate: evicting same-queue victims cannot grow the queue's
        usage, but the part of ``cand``'s demand NOT covered by the victims'
        freed claims must pass the same over-share rule as normal admission
        — preemption overrides priority inside a queue, never the queue's
        capacity contract with other tenants."""
        victims = sorted(
            (a for a in self._apps.values()
             if a.admitted and a.queue == cand.queue and a.priority < cand.priority),
            key=lambda a: (a.priority, -a.seq),
        )
        demand = (cand.demand_memory, cand.demand_vcores, cand.demand_chips)
        chosen: list[_App] = []
        trial = list(free)
        freed_primary = 0
        for v in victims:
            if self._fits(trial, demand):
                break
            # canonical claim, not the pass-local dict: apps admitted earlier
            # in THIS scheduling pass (incl. by a prior preemption) are
            # missing from it, and their claim is simply their demand
            c = self._claim_locked(v)
            for i in range(3):
                trial[i] += c[i]
            freed_primary += c[primary]
            chosen.append(v)
        if not chosen or not self._fits(trial, demand):
            return False
        net_growth = demand[primary] - freed_primary
        if net_growth > 0:
            others_waiting = any(
                a for a in self._apps.values()
                if not a.admitted and a.queue != cand.queue
            )
            used_after = queue_used.get(cand.queue, 0) - freed_primary
            cap = self.queues.get(cand.queue, 1.0) * totals[primary]
            if others_waiting and used_after > 0 and used_after + demand[primary] > cap:
                return False
        for v in chosen:
            self._evict_locked(v, free, claims, queue_used, primary)
        admit(cand)
        return True

    def _evict_locked(
        self,
        v: _App,
        free: list[int],
        claims: dict[str, tuple[int, int, int]],
        queue_used: dict[str, int],
        primary: int,
    ) -> None:
        """Demote an admitted app back to waiting, return its claim to the
        pass-local pool, and kill its running containers (marked as
        preemption so the AM's failure budget is never charged)."""
        c = self._claim_locked(v)
        v.admitted, v.preempted = False, True
        _POOL_EVICTIONS.inc(queue=v.queue)
        self._journal_app_locked(v)
        v.wait_since = time.monotonic()
        claims.pop(v.app_id, None)
        for i in range(3):
            free[i] += c[i]
        queue_used[v.queue] -= c[primary]
        for cid, rec in self._containers.items():
            if rec["app_id"] == v.app_id and rec["state"] == _RUNNING:
                self._preempt_cids.add(cid)
                self._request_kill_locked(rec)

    def _reclaim_across_queues_locked(
        self,
        cand: _App,
        free: list[int],
        claims: dict[str, tuple[int, int, int]],
        queue_used: dict[str, int],
        primary: int,
        totals: tuple[int, int, int],
        admit,
    ) -> bool:
        """Cross-queue capacity reclaim (the YARN capacity-scheduler
        guarantee, VERDICT r4 #2): a waiting head whose queue is UNDER its
        share may evict apps from queues that borrowed BEYOND their share —
        otherwise a long borrower admitted on an idle pool locks the
        guaranteed queue out for its whole duration and the share is
        decorative exactly when it matters.

        Rules, all enforced on a trial copy before any eviction happens
        (all-or-nothing, same structure as ``_preempt_for_locked``):
        - reclaim only RESTORES the guarantee: admitting ``cand`` must keep
          its queue within its own share (borrowing beyond share rides free
          capacity only, never other queues' evictions);
        - victims come only from queues currently OVER their share, most
          over-share queue first, and eviction stops the moment a victim
          queue is no longer over its share — a queue AT or UNDER its share
          is never touched. Granularity is whole gangs, so the LAST
          eviction may land the borrower below its share (a 3 GB app over
          a 2 GB share evicts whole): that app only ever ran by borrowing,
          and it re-queues with under-share priority like any waiter;
        - within a victim queue: lowest priority first, newest first — the
          newest borrowers repay first;
        - grace (``tony.pool.preemption.grace-ms``): only heads waiting at
          least this long trigger cross-queue kills.
        """
        demand = (cand.demand_memory, cand.demand_vcores, cand.demand_chips)
        cap_cand = self.queues.get(cand.queue, 1.0) * totals[primary]
        if queue_used.get(cand.queue, 0) + demand[primary] > cap_cand:
            return False  # head would overshoot its own guarantee
        if time.monotonic() - cand.wait_since < self.preemption_grace_ms / 1000:
            return False
        trial = list(free)
        trial_used = dict(queue_used)
        chosen: list[_App] = []
        while not self._fits(trial, demand):
            # most over-share queue first (by primary-dimension excess)
            best: tuple[int, _App] | None = None
            for q, share in self.queues.items():
                if q == cand.queue:
                    continue
                excess = trial_used.get(q, 0) - share * totals[primary]
                if excess <= 0:
                    continue  # at or under share: protected from reclaim
                apps = sorted(
                    (a for a in self._apps.values()
                     if a.admitted and a.queue == q and a not in chosen),
                    key=lambda a: (a.priority, -a.seq),
                )
                if apps and (best is None or excess > best[0]):
                    best = (excess, apps[0])
            if best is None:
                return False  # no eligible borrower left and cand still unfit
            v = best[1]
            c = self._claim_locked(v)
            for i in range(3):
                trial[i] += c[i]
            trial_used[v.queue] -= c[primary]
            chosen.append(v)
        for v in chosen:
            self._evict_locked(v, free, claims, queue_used, primary)
        admit(cand)
        return True

    # -------------------------------------------------------------- internal
    def _request_kill_locked(self, rec: dict[str, Any]) -> None:
        if rec["state"] != _RUNNING:
            return
        node = self._nodes.get(rec["node"])
        if node is not None and node.alive:
            node.pending_kills.append(rec["id"])
        elif not rec.get("kill_requested"):
            # node currently away (pool mid-recovery, agent partitioned):
            # the order must not be silently dropped — with work-preserving
            # re-adoption nothing else would ever kill this container. Mark
            # the record (durably) and deliver at re-registration.
            rec["kill_requested"] = True
            self._jlog_locked("kill_requested", cid=rec["id"])

    def _free_locked(self, rec: dict[str, Any]) -> None:
        node = self._nodes.get(rec["node"])
        if node is not None:
            node.used_memory -= rec["memory_bytes"]
            node.used_vcores -= rec["vcores"]
            node.used_chips.difference_update(tuple(c) for c in rec["chips"])

    def _record_exit_locked(self, cid: str, rc: int) -> None:
        rec = self._containers.get(cid)
        if rec is None or rec["state"] != _RUNNING:
            return
        if cid in self._preempt_cids:
            # we killed it: report the cluster action, not the signal — AMs
            # exclude EXIT_PREEMPTED from restart budgets (YARN PREEMPTED)
            self._preempt_cids.discard(cid)
            rc = constants.EXIT_PREEMPTED
        rec["state"] = _EXITED
        self._free_locked(rec)
        self._app_exits.setdefault(rec["app_id"], {})[cid] = rc
        self._jlog_locked("exited", cid=cid, rc=rc)
        self._schedule_locked()

    def _release_locked(self, cid: str) -> None:
        rec = self._containers.pop(cid, None)
        if rec is not None:
            self._jlog_locked("released", cid=cid)
        if rec is not None and rec["state"] == _RUNNING:
            self._free_locked(rec)

    def _mark_node_lost_locked(self, node: _Node, reason: str) -> None:
        node.alive = False
        for cid, rec in self._containers.items():
            if rec["node"] == node.name and rec["state"] == _RUNNING:
                self._record_exit_locked(cid, constants.EXIT_NODE_LOST)

    def _liveness_loop(self) -> None:
        timeout_s = self.heartbeat_interval_ms * self.max_missed / 1000
        while not self._stop.wait(self.heartbeat_interval_ms / 1000 / 2):
            if self.chaos is not None and self.chaos.take("pool-crash") is not None:
                # control-plane death fidelity: SIGKILL, no drain, no final
                # journal record beyond what each transition already fsync'd
                os.kill(os.getpid(), signal.SIGKILL)
            now = time.monotonic()
            with self._lock:
                for node in self._nodes.values():
                    if node.alive and now - node.last_heartbeat > timeout_s:
                        self._mark_node_lost_locked(node, reason="missed heartbeats")


class RemoteResourceManager(ResourceManager):
    """AM-side adapter speaking to a PoolService + its agents.

    allocate/release/poll ride the RM; launch/kill go straight to the owning
    node's agent (the NMClient analog). Satisfies the same ``ResourceManager``
    interface the in-process pools do, so the AM, scheduler, and every E2E
    behavior are unchanged.
    """

    def __init__(self, rm_host: str, rm_port: int, secret: str = "", app_id: str = ""):
        self.app_id = app_id or f"app_{uuid.uuid4().hex[:8]}"
        self.rm = RpcClient(rm_host, rm_port, secret=secret)
        self.secret = secret
        self._agents: dict[tuple[str, int], RpcClient] = {}
        self._containers: dict[str, tuple[Container, tuple[str, int], int]] = {}
        self._span: list[int] | None = None
        self._lock = threading.Lock()

    def _agent(self, addr: tuple[str, int]) -> RpcClient:
        with self._lock:
            cli = self._agents.get(addr)
            if cli is None:
                cli = self._agents[addr] = RpcClient(addr[0], addr[1], secret=self.secret)
            return cli

    def register_app(self, queue: str, priority: int, demand: Resources) -> None:
        self.rm.call(
            "register_app",
            app_id=self.app_id,
            queue=queue,
            priority=priority,
            memory_bytes=demand.memory_bytes,
            vcores=demand.vcores,
            chips=demand.chips,
        )

    def total_capacity(self) -> Resources | None:
        try:
            got = self.rm.call("cluster_capacity")
        except (RpcError, OSError):
            return None  # RM unreachable: the AM skips the downsize decision
        return Resources(
            memory_bytes=int(got["memory_bytes"]),
            vcores=int(got["vcores"]),
            chips=int(got["chips"]),
        )

    def node_capacities(self) -> list[Resources] | None:
        try:
            got = self.rm.call("cluster_capacity")
        except (RpcError, OSError):
            return None
        return [
            Resources(
                memory_bytes=int(n["memory_bytes"]),
                vcores=int(n["vcores"]),
                chips=int(n["chips"]),
            )
            for n in got.get("nodes", [])
        ]

    def allocate(self, job_type: str, task_index: int, resources: Resources) -> Container:
        try:
            got = self.rm.call(
                "allocate",
                app_id=self.app_id,
                job_type=job_type,
                task_index=task_index,
                memory_bytes=resources.memory_bytes,
                vcores=resources.vcores,
                chips=resources.chips,
            )
        except RpcError as e:
            if "AllocationError" in str(e):
                raise AllocationError(str(e)) from e
            raise
        if got.get("wait"):
            raise AllocationPending(got.get("reason", "queued"))
        coords = tuple((r, c) for r, c in got["chips"])
        spec = SliceSpec.parse(got["slice_spec"]) if got.get("slice_spec") else None
        container = Container(
            id=got["id"],
            host=got["node"],
            resources=resources,
            chip_coords=coords,
            slice_name=spec.name if spec else "",
            slice_topology=spec.topology if spec else (0, 0),
            job_type=job_type,
            task_index=task_index,
        )
        with self._lock:
            self._containers[container.id] = (
                container,
                (got["agent_host"], got["agent_port"]),
                got["slice_id"],
            )
        return container

    def release(self, container: Container) -> None:
        with self._lock:
            self._containers.pop(container.id, None)
            if not self._containers:
                self._span = None  # gang fully released: next gang re-snapshots
        try:
            self.rm.call("release", app_id=self.app_id, container_id=container.id)
        except (RpcError, OSError):
            pass  # RM unreachable at teardown: release_all in shutdown retries

    def _gang_span(self) -> list[int]:
        """Gang DCN span, append-only across launch waves (same contract as
        MultiSliceResourceManager.gang_slice_span): one wave's tasks all see
        the same span; a later dependency-gated wave appends new slices so
        earlier tasks' TPU_SLICE_ID indices stay valid."""
        with self._lock:
            current = {sid for _, _, sid in self._containers.values() if sid >= 0}
            if self._span is None:
                self._span = sorted(current)
            else:
                self._span.extend(sorted(current - set(self._span)))
            return self._span

    def start_container(
        self, container: Container, command: list[str], env: dict[str, str], log_dir: str
    ) -> None:
        with self._lock:
            entry = self._containers.get(container.id)
        if entry is None:
            raise AllocationError(f"start of unknown container {container.id}")
        _, addr, slice_id = entry
        # ship the job-facing env, not the AM's machine baseline: keys the
        # framework contract owns (TONY_/JAX_/TPU_/... prefixes, same
        # whitelist the docker runtime forwards) plus anything the AM
        # changed relative to its inherited environment. Baseline keys the
        # AM merely inherited (PATH, HOME, ...) come from the REMOTE node's
        # environ, which the agent merges under the shipped delta.
        from tony_tpu.cluster.resources import _DOCKER_ENV_PREFIXES

        delta = {
            k: v
            for k, v in env.items()
            if any(k.startswith(p) for p in _DOCKER_ENV_PREFIXES)
            or os.environ.get(k) != v
        }
        if slice_id >= 0:
            span = self._gang_span()
            delta[constants.ENV_TPU_SLICE_ID] = str(span.index(slice_id))
            delta[constants.ENV_TPU_NUM_SLICES] = str(len(span))
        self._agent(addr).call(
            "launch_container",
            container_id=container.id,
            command=command,
            env=delta,
            log_dir=log_dir,
        )

    def _live_containers(self) -> list[Container]:
        with self._lock:
            return [c for c, _, _ in self._containers.values()]

    def journal_info(self, container: Container) -> dict | None:
        with self._lock:
            entry = self._containers.get(container.id)
        if entry is None:
            return None
        _, (agent_host, agent_port), slice_id = entry
        return {
            **container_to_record(container),
            "agent_host": agent_host, "agent_port": agent_port,
            "slice_id": slice_id,
        }

    def adopt_container(self, record: dict) -> Container | None:
        """Takeover adoption against a remote pool: the POOL survived and
        still holds the allocation under this app id — only this client-side
        tracking (container → owning agent) needs rebuilding."""
        agent_host, agent_port = record.get("agent_host"), record.get("agent_port")
        if not agent_host or not agent_port:
            return None
        c = container_from_record(record)
        with self._lock:
            self._containers[c.id] = (
                c, (str(agent_host), int(agent_port)), int(record.get("slice_id", -1)),
            )
        return c

    def reclaim_orphans(self) -> None:
        """Degraded takeover: release (and kill, via the agents' heartbeat
        kill orders) everything the pool still holds for this app id before
        the fresh gang allocates."""
        try:
            self.rm.call("release_all", app_id=self.app_id)
        except (RpcError, OSError):
            pass  # pool unreachable: allocation conflicts will surface loudly

    def poll_exited(self) -> dict[str, int]:
        try:
            exits = {cid: int(rc) for cid, rc in self.rm.call("poll_exited", app_id=self.app_id).items()}
        except (RpcError, OSError):
            return {}
        if self.chaos is not None:
            # chaos node-loss / preempt against a remote pool: the kill rides
            # the real AM→agent path, the exit code is synthesized here (the
            # same seam the in-process RMs use)
            exits = self.chaos.perturb_container_exits(self, exits)
        return exits

    def kill_container(self, container: Container) -> None:
        with self._lock:
            entry = self._containers.get(container.id)
        if entry is None:
            return
        _, addr, _ = entry
        try:
            self._agent(addr).call("kill_container", container_id=container.id)
        except (RpcError, OSError):
            # agent unreachable (dead node?) — backstop via the RM
            try:
                self.rm.call("request_kill", container_id=container.id)
            except (RpcError, OSError):
                pass

    def shutdown(self) -> None:
        try:
            self.rm.call("release_all", app_id=self.app_id)
        except (RpcError, OSError):
            pass
        with self._lock:
            self._containers.clear()
            agents = list(self._agents.values())
            self._agents.clear()
        for cli in agents:
            cli.close()
        self.rm.close()


def main(argv: list[str] | None = None) -> int:
    from tony_tpu.config import TonyConfig, keys

    p = argparse.ArgumentParser(prog="tony-pool", description="tony-tpu pool service (RM analog)")
    p.add_argument("--bind-host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--secret", default=os.environ.get(constants.ENV_POOL_SECRET, ""))
    p.add_argument("--conf_file", default=None, help="site config supplying tony.node.* liveness keys")
    p.add_argument("--conf", action="append", default=[], help="key=value override (repeatable)")
    p.add_argument("--heartbeat-ms", type=int, default=None,
                   help="overrides tony.node.heartbeat-interval-ms")
    p.add_argument("--max-missed", type=int, default=None,
                   help="overrides tony.node.max-missed-heartbeats")
    p.add_argument("--info-file", default="", help="write host/port JSON here once serving")
    p.add_argument("--journal-file", default=None,
                   help="recovery journal path (overrides tony.pool.journal.file); "
                        "a restarted pool replays it and re-adopts live work")
    args = p.parse_args(argv)
    config = TonyConfig.from_layers(conf_file=args.conf_file, conf_args=args.conf)
    from tony_tpu.chaos import ChaosContext

    svc = PoolService(
        bind_host=args.bind_host,
        port=args.port,
        secret=args.secret,
        heartbeat_interval_ms=args.heartbeat_ms
        if args.heartbeat_ms is not None
        else config.get_time_ms(keys.NODE_HEARTBEAT_INTERVAL_MS, 1000),
        max_missed_heartbeats=args.max_missed
        if args.max_missed is not None
        else config.get_int(keys.NODE_MAX_MISSED_HEARTBEATS, 10),
        queues=parse_queue_spec(config.get(keys.POOL_QUEUES) or "default=1.0"),
        preemption=config.get_bool(keys.POOL_PREEMPTION_ENABLED),
        preemption_grace_ms=config.get_time_ms(keys.POOL_PREEMPTION_GRACE_MS, 0),
        journal_path=args.journal_file
        if args.journal_file is not None
        else (config.get(keys.POOL_JOURNAL_FILE) or None),
        chaos=ChaosContext.from_config(config, identity="pool"),
    )
    svc.start()
    host, port = svc.address
    if args.info_file:
        tmp = args.info_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"host": host, "port": port}, f)
        os.replace(tmp, args.info_file)
    obs_logging.info(f"[tony-pool] serving on {host}:{port}")
    done = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: done.set())
    signal.signal(signal.SIGINT, lambda *_: done.set())
    done.wait()
    svc.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
