"""Multi-host pool service: the ResourceManager daemon and its AM-side client.

This supplies the reference's defining process split (SURVEY.md §2.1, §3.1
process boundary #2): a cluster-wide RM daemon that host agents
(cluster/agent.py, the NM analog) register with and heartbeat to, and that
per-job Application Masters allocate containers from. Container *launch* goes
AM → agent directly (the NMClient analog); the RM only arbitrates inventory
and liveness — exactly YARN's split.

TPU twist on the YARN resource model: a node's inventory is memory + vcores +
the TPU chips it owns *within an ICI slice* (a v5e host owns 4 chips of its
slice's 2D grid). A container's chip ask is satisfied from ONE node — on real
TPU pods a training task is one process per host — so multi-host jobs are
expressed as gangs of per-host tasks, and the pool keeps a gang's chips inside
as few slices as possible so mesh axes ride ICI, not DCN.

Node death is detected by missed agent heartbeats; containers on a dead node
are surfaced to their AM through the normal ``poll_exited`` path with
``EXIT_NODE_LOST`` — the AM's existing failure machinery (fail-fast or
whole-gang restart from checkpoint) takes it from there.

Deployments of the same protocol:
  - in-process:  LocalResourceManager / MultiSliceResourceManager drive a
    ``ContainerLauncher`` directly (resources.py) — the MiniCluster analog;
  - distributed: this RM daemon + one NodeAgent per host, the AM holding a
    ``RemoteResourceManager``. Same scheduler, same launcher, same env
    contract; only the transport differs.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import signal
import sys
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any

from tony_tpu import constants
from tony_tpu.obs import locktrace
from tony_tpu.obs import logging as obs_logging
from tony_tpu.cluster.journal import (
    SNAPSHOT_RECORD,
    Journal,
    JournalError,
    iter_journal,
)
from tony_tpu.cluster.policy import (
    AppView,
    PreemptionPolicy,
    WorldIndex,
    make_policy,
    validate_queue_shares as _validate_queue_shares,
)
from tony_tpu.cluster.recorder import (
    DecisionRecord,
    FlightRecorder,
    QueueTelemetry,
    window_line,
)
from tony_tpu.cluster.resources import (
    AllocationError,
    AllocationPending,
    Container,
    ResourceManager,
    Resources,
    SliceSpec,
    container_from_record,
    container_to_record,
)
from tony_tpu.cluster.rpc import RpcClient, RpcError, RpcServer
from tony_tpu.obs import metrics as obs_metrics

POOL_RPC_METHODS = [
    "register_node",
    "node_heartbeat",
    "register_app",
    "allocate",
    "release",
    "release_all",
    "poll_exited",
    "update_demand",
    "request_kill",
    "pool_status",
    "pool_explain",
    "cluster_capacity",
    "pool_metrics",
]

_POOL_ADMISSIONS = obs_metrics.counter(
    "tony_pool_admissions_total", "apps admitted by the capacity scheduler", labelnames=("queue",))
_POOL_EVICTIONS = obs_metrics.counter(
    "tony_pool_evictions_total", "apps preempted back to waiting", labelnames=("queue",))
_POOL_ALLOCATE_QUEUED = obs_metrics.counter(
    "tony_pool_allocate_queued_total", "allocate() calls answered with wait (queued)")
_POOL_PREEMPTIONS = obs_metrics.counter(
    "tony_pool_preemptions_total",
    "preemption outcomes by mode: drain (victim checkpointed and yielded "
    "inside the deadline), kill (immediate or escalated kill path), shrink "
    "(elastic victim shed workers instead of dying whole)",
    labelnames=("mode",))
_POOL_DRAIN_SECONDS = obs_metrics.histogram(
    "tony_pool_drain_duration_seconds",
    "eviction-to-resolution latency of cooperative drain/shrink episodes",
    buckets=obs_metrics.WAIT_BUCKETS)
# per-queue telemetry (tony.pool.recorder.*, docs/scheduling.md "Explaining
# decisions"): sampled on the liveness tick, primary capacity dimension
_POOL_QUEUE_USED = obs_metrics.gauge(
    "tony_pool_queue_used",
    "admitted claim per queue in the pool's primary capacity dimension",
    labelnames=("queue",))
_POOL_QUEUE_SHARE_CAPACITY = obs_metrics.gauge(
    "tony_pool_queue_share_capacity",
    "the queue's share GUARANTEE in the primary capacity dimension",
    labelnames=("queue",))
_POOL_QUEUE_DEMAND = obs_metrics.gauge(
    "tony_pool_queue_demand",
    "waiting (unadmitted) claim per queue in the primary capacity dimension",
    labelnames=("queue",))
_POOL_QUEUE_WAITING = obs_metrics.gauge(
    "tony_pool_queue_waiting", "apps waiting per queue", labelnames=("queue",))
_POOL_QUEUE_WAIT_AGE = obs_metrics.gauge(
    "tony_pool_queue_wait_age_seconds",
    "age of the queue's oldest waiter", labelnames=("queue",))
_POOL_QUEUE_DENIALS = obs_metrics.counter(
    "tony_pool_queue_denials_total",
    "blocked-head denials by binding rule (the flight recorder's deny "
    "records; docs/scheduling.md 'Explaining decisions')",
    labelnames=("queue", "rule"))
# the serve/train capacity market (docs/scheduling.md "Capacity market")
_POOL_QUEUE_PUBLISHED = obs_metrics.gauge(
    "tony_pool_queue_published_demand",
    "unmet demand admitted apps published via update_demand (capacity the "
    "market is asked to fund), primary capacity dimension",
    labelnames=("queue",))
_POOL_MARKET_FUNDED = obs_metrics.counter(
    "tony_pool_market_funded_workers_total",
    "elastic workers shed to fund published demand (recorder rule "
    "demand-spike), labeled by the shed borrower's queue",
    labelnames=("queue",))
_POOL_MARKET_GROWBACK = obs_metrics.counter(
    "tony_pool_market_growback_workers_total",
    "workers offered back to shrunken borrowers after demand ebbed "
    "(recorder rule grow-back), labeled by the borrower's queue",
    labelnames=("queue",))

_RUNNING, _EXITED, _RELEASED = "RUNNING", "EXITED", "RELEASED"


def parse_queue_spec(spec: str) -> dict[str, float]:
    """``"prod=0.7,dev=0.3"`` → {"prod": 0.7, "dev": 0.3}. Shares are each
    queue's guaranteed fraction of the pool's primary capacity dimension
    (chips when the pool has chips, memory otherwise); a queue may borrow
    beyond its share while no other queue has waiting apps (elastic, the
    capacity-scheduler behavior)."""
    queues: dict[str, float] = {}
    for part in (spec or "default=1.0").split(","):
        part = part.strip()
        if not part:
            continue
        name, _, share = part.partition("=")
        try:
            f = float(share) if share else 1.0
        except ValueError:
            raise ValueError(f"bad queue share in {part!r}: expected name=fraction") from None
        if not 0 < f <= 1:
            raise ValueError(f"queue {name!r} share must be in (0, 1], got {f}")
        queues[name.strip()] = f
    if not queues:
        raise ValueError(f"no queues in spec {spec!r}")
    _validate_queue_shares(queues)
    return queues


@dataclass(eq=False)
class _App:
    """One tenant application and its queue/admission state.

    ``admitted`` apps hold a capacity CLAIM of elementwise
    max(demand, held) — reserved even while their containers are being
    (re)allocated, so an app mid-gang-restart keeps its capacity and two
    half-allocated gangs can never deadlock each other. Waiting apps hold
    nothing and retry through ``allocate`` until the scheduler admits them.

    The admission/preemption DECISION over these records lives in
    cluster/policy.py (pure, clock-injectable, shared with ``tony sim``);
    this record only carries the state the policy views are built from.
    """

    app_id: str
    queue: str
    priority: int = 0
    demand_memory: int = 0
    demand_vcores: int = 0
    demand_chips: int = 0
    seq: int = 0
    admitted: bool = False
    preempted: bool = False    # demoted by preemption; re-queues via allocate
    # when this app last STARTED waiting (registration or eviction) — the
    # cross-queue reclaim grace is measured from here. wait_unix is the
    # wall-clock twin journaled so a pool restart preserves the waiting AGE
    # instead of silently restarting every waiter's grace clock.
    wait_since: float = field(default_factory=time.monotonic)
    wait_unix: float = field(default_factory=time.time)
    # when this app was last admitted — the minimum-runtime protection
    # (tony.pool.preemption.min-runtime-ms) is measured from here
    admitted_at: float = 0.0
    admitted_unix: float = 0.0
    # elastic partial-reclaim contract the AM registered: resources one shed
    # worker frees, and how many workers the app may shed (0 → not elastic)
    elastic_unit: tuple[int, int, int] = (0, 0, 0)
    elastic_slack: int = 0

    @property
    def sort_key(self) -> tuple[int, int]:
        return (-self.priority, self.seq)  # higher priority first, then FIFO


@dataclass(eq=False)
class _Node:
    """One registered host agent and its live accounting."""

    name: str
    host: str
    port: int
    memory_bytes: int
    vcores: int
    slice_id: int                       # -1 → CPU-only node
    slice_spec: str                     # e.g. "v5e-16": the WHOLE slice's shape
    chips: tuple[tuple[int, int], ...]  # slice-grid coords this host owns
    used_memory: int = 0
    used_vcores: int = 0
    used_chips: set[tuple[int, int]] = field(default_factory=set)
    alive: bool = True
    last_heartbeat: float = field(default_factory=time.monotonic)
    pending_kills: list[str] = field(default_factory=list)

    @property
    def free_chips(self) -> set[tuple[int, int]]:
        return set(self.chips) - self.used_chips


def _rect_from(free: set[tuple[int, int]], n: int) -> tuple[tuple[int, int], ...] | None:
    """A contiguous axis-aligned n-chip rectangle from a host's free chips,
    most-square shape first (the per-node analog of ChipGrid.allocate_chips)."""
    if n <= 0:
        return ()
    if len(free) < n:
        return None
    rows = [r for r, _ in free]
    cols = [c for _, c in free]
    shapes = sorted(
        {(r, n // r) for r in range(1, n + 1) if n % r == 0},
        key=lambda rc: abs(rc[0] - rc[1]),
    )
    for r, c in shapes:
        for r0 in range(min(rows), max(rows) - r + 2):
            for c0 in range(min(cols), max(cols) - c + 2):
                coords = tuple(
                    (r0 + i, c0 + j) for i, j in itertools.product(range(r), range(c))
                )
                if free.issuperset(coords):
                    return coords
    return None


class PoolService:
    """The RM daemon: node registry, slice-aware inventory, per-app exits."""

    def __init__(
        self,
        bind_host: str = "127.0.0.1",
        port: int = 0,
        secret: str = "",
        heartbeat_interval_ms: int = 1000,
        max_missed_heartbeats: int = 10,
        queues: dict[str, float] | None = None,
        preemption: bool = False,
        preemption_grace_ms: int = 0,
        preemption_drain_ms: int = 0,
        preemption_min_runtime_ms: int = 0,
        preemption_budget: int = 0,
        preemption_budget_window_ms: int = 60_000,
        demand_enabled: bool = True,
        demand_ttl_ms: int = 60_000,
        growback_ebb_ms: int = 30_000,
        growback_step: int = 0,
        journal_path: str | None = None,
        journal_compact_every: int = 0,
        scheduler_indexed: bool = True,
        recorder_enabled: bool = True,
        recorder_capacity: int = 2048,
        recorder_window_ms: int = 60_000,
        recorder_series_file: str | None = None,
        chaos=None,
    ):
        self.heartbeat_interval_ms = heartbeat_interval_ms
        self.max_missed = max_missed_heartbeats
        self.queues = dict(queues) if queues else {"default": 1.0}
        _validate_queue_shares(self.queues)
        self.preemption = preemption
        self.preemption_grace_ms = preemption_grace_ms
        # cooperative drain window (tony.pool.preemption.drain-ms): eviction
        # becomes two-phase — the victim learns it is DRAINING through its
        # poll path, urgent-checkpoints, and yields; kills fire only at this
        # deadline. 0 → the classic immediate kill path.
        self.preemption_drain_ms = preemption_drain_ms
        # the decision itself is the pure policy module — the same code
        # `tony sim` drives over thousands of synthetic arrivals. Default is
        # the indexed implementation over a delta-fed WorldIndex;
        # tony.pool.scheduler.indexed=false restores the reference pass
        # (identical semantics, full world rescan per pass)
        self._policy = make_policy(
            "indexed" if scheduler_indexed else "reference",
            self.queues,
            preemption=preemption,
            grace_ms=preemption_grace_ms,
            min_runtime_ms=preemption_min_runtime_ms,
            eviction_budget=preemption_budget,
            budget_window_ms=preemption_budget_window_ms,
        )
        # cross-pass incrementality (docs/performance.md "Scheduler pass"):
        # the index holds one persistent AppView per app, updated by deltas
        # at the same choke points that journal — a scheduling pass reads
        # maintained heads/counters/claim sums instead of rebuilding every
        # view, and a tick over an unchanged world is skipped outright
        self._world: WorldIndex | None = WorldIndex() if scheduler_indexed else None
        self._sched_seen_version = -1
        self._sched_last_empty = False
        self._sched_wake_at: float | None = None
        # flight recorder (tony.pool.recorder.*, docs/scheduling.md
        # "Explaining decisions"): the policy's decision-provenance sink —
        # admit/evict/shrink facts plus every blocked head's binding rule in
        # a bounded in-memory ring served by `pool_explain` / `tony explain`.
        # In-memory on purpose: provenance is diagnostics, not recoverable
        # state — a restarted pool re-derives current reasons in one pass.
        # Provenance needs the indexed pass (the default); the reference
        # oracle stays uninstrumented by design.
        self.recorder: FlightRecorder | None = None
        self._telemetry: QueueTelemetry | None = None
        self._series_file = recorder_series_file or None
        # the cluster_series source identity: the series file's stem, so two
        # pools feeding one history store through different files can never
        # clobber each other's (source, queue, metric, window) rows
        self._series_source = (
            os.path.splitext(os.path.basename(self._series_file))[0] or "pool"
            if self._series_file else "pool"
        )
        self._telemetry_next = 0.0
        if recorder_enabled:
            self.recorder = FlightRecorder(
                capacity=recorder_capacity,
                on_note=self._on_decision_record,
            )
            self._policy.sink = self.recorder
            self._telemetry = QueueTelemetry(window_ms=recorder_window_ms)
        # held resources per app over RUNNING containers, maintained at the
        # container create/exit/release transitions so neither the policy
        # views nor pool_status rescan every container record
        self._app_held: dict[str, list[int]] = {}
        #: optional fault-injection context (pool-crash); None in production
        self.chaos = chaos
        self._nodes: dict[str, _Node] = {}
        self._containers: dict[str, dict[str, Any]] = {}   # cid → record
        self._app_exits: dict[str, dict[str, int]] = {}    # app → {cid: rc}
        self._apps: dict[str, _App] = {}                   # app → queue state
        self._app_seq = itertools.count()
        self._preempt_cids: set[str] = set()               # kills we initiated
        self._all_dead_since: float | None = None          # allocate() saw 0 alive
        # in-flight drain/shrink episodes: app_id → {req_id, mode, workers,
        # deadline (monotonic), t0 (monotonic), escalated}
        self._drains: dict[str, dict[str, Any]] = {}
        # one-shot cancellation notices (drain victim re-admitted before it
        # yielded): app_id → req_id, delivered on the app's next poll
        self._cancelled: dict[str, str] = {}
        # ---- the serve/train capacity market (tony.pool.demand.*,
        # docs/scheduling.md "Capacity market"). All three ledgers are
        # journaled so a restart mid-spike keeps the published demand and
        # the debt owed to shrunken borrowers.
        self.demand_enabled = demand_enabled
        self.demand_ttl_ms = demand_ttl_ms
        self.growback_ebb_ms = growback_ebb_ms
        self.growback_step = growback_step
        # app_id → published unmet demand {workers, unit, unix, mono}
        self._demand: dict[str, dict[str, Any]] = {}
        # grow-back ledger (workers the market took and still owes):
        # app_id → {workers, unit, queue, since_unix}
        self._shrunk: dict[str, dict[str, Any]] = {}
        # in-flight grow offers awaiting the borrower's resize:
        # app_id → {req_id, workers, expected_primary, deadline (monotonic)}
        self._grows: dict[str, dict[str, Any]] = {}
        # anti-thrash shield: app_id → monotonic instant of its last accepted
        # grow-back (in-memory only: after a restart the budget still guards)
        self._grown_at: dict[str, float] = {}
        # when the LAST published deficit cleared (monotonic) — the grow-back
        # ebb hysteresis measures from here; None while any demand is live
        self._demand_quiet_since: float | None = time.monotonic()
        self._grow_seq = itertools.count(1)
        self._lock = locktrace.make_lock("pool.PoolService._lock")
        # leaf serializer for the cluster-series file only — held across the
        # append so concurrent flushers don't interleave lines, never while
        # holding (or taking) the state lock above
        self._series_lock = locktrace.make_lock("pool.PoolService._series_lock")
        self._stop = threading.Event()
        # work-preserving restart (tony.pool.journal.file): registrations,
        # admissions, and allocations are journaled so a restarted pool
        # rebuilds its queue state and re-adopts live containers from agent
        # re-registration instead of forgetting every admitted app
        # incremental compaction (tony.pool.journal.compact-every): once this
        # many records pile up past the last snapshot, the live state is
        # folded into one snapshot record and the file rotates — replay is
        # O(live state), not O(history). 0 keeps append-forever.
        self._journal_compact_every = max(int(journal_compact_every), 0)
        self._journal: Journal | None = None
        if journal_path:
            if os.path.exists(journal_path):
                try:
                    with self._lock:
                        # streamed: a 100k-record history folds record by
                        # record without ever materializing as a list
                        self._recover_from_journal_locked(iter_journal(journal_path))
                        self._rebuild_derived_locked()
                    obs_logging.info(
                        f"[tony-pool] recovered from journal: "
                        f"{len(self._apps)} app(s), "
                        f"{sum(1 for r in self._containers.values() if r['state'] == _RUNNING)} "
                        "live container record(s) awaiting agent re-registration")
                except Exception as e:  # noqa: BLE001 — ANY replay fault degrades, never refuses to start
                    # loud degrade to EMPTY state (a half-replayed journal is
                    # fiction — an agent could get its orphans re-adopted
                    # against it): agents re-register and kill the orphans,
                    # the pre-journal behavior
                    obs_logging.error(f"[tony-pool] journal unusable — starting empty: {e}")
                    with self._lock:
                        self._apps = {}
                        self._containers = {}
                        self._app_exits = {}
                        self._drains = {}
                        self._demand = {}
                        self._shrunk = {}
                        self._grows = {}
                        self._app_seq = itertools.count()
                        self._rebuild_derived_locked()
            self._journal = Journal(journal_path)
            # make the decision CONTEXT replayable (cluster/replay.py):
            # every process start records the config its scheduling
            # decisions run under, so `tony sim --from-history` replays a
            # journal under the shares/knobs that actually produced it —
            # not guesses. Capacity rides separate records at every node
            # join/loss (register_node / _mark_node_lost_locked).
            self._jlog_locked(
                "config",
                queues=dict(self.queues),
                preemption=bool(self.preemption),
                grace_ms=int(self.preemption_grace_ms),
                drain_ms=int(self.preemption_drain_ms),
                min_runtime_ms=int(self._policy.min_runtime_ms),
                budget=int(self._policy.eviction_budget),
                budget_window_ms=int(self._policy.budget_window_ms),
                unix=time.time(),
            )
            self._journal_sync()
        self.rpc = RpcServer(host=bind_host, port=port, secret=secret)
        self.rpc.register_object(self, POOL_RPC_METHODS)
        self._monitor = threading.Thread(target=self._liveness_loop, name="pool-liveness", daemon=True)

    # ------------------------------------------------------ recovery journal
    def _jlog_locked(self, t: str, **fields: Any) -> None:
        """Stage a journal record under the state lock — O(json.dumps),
        nothing touches the disk here. The caller's :meth:`_journal_sync`
        (run OUTSIDE the lock, before the RPC response returns) makes it
        durable. The old shape — append + fsync + inline compaction right
        here, under the state lock — serialized every RPC handler, the
        liveness tick, and telemetry behind each fsync; blocking-under-lock
        now flags exactly that."""
        if self._journal is None:
            return
        self._journal.enqueue(t, **fields)

    def _journal_sync(self) -> None:
        """Make every staged record durable, then compact on cadence — all
        OUTSIDE the state lock. Each journaling entry point calls this
        after releasing the lock and before acking its response: the
        transition is durable before anyone acts on the ack, same contract
        as the old inline append, but the fsync no longer serializes
        unrelated threads. Any thread's flush drains the whole shared
        queue, so concurrent entry points cover each other.

        Compaction folds the live state into one snapshot (docs/
        performance.md "Control-plane scalability"): the state lock is
        re-taken briefly to capture a consistent snapshot + the enqueue
        token, the two fsyncs happen after it is released, and a racing
        enqueue between capture and compact makes the token stale —
        :meth:`Journal.compact` skips, and a later sync retries."""
        j = self._journal
        if j is None:
            return
        j.flush_pending()
        if (
            self._journal_compact_every > 0
            and j.appends_since_compact >= self._journal_compact_every
        ):
            with self._lock:
                token = j.total_enqueued
                records = self._snapshot_records_locked()
            j.compact(records, expected_enqueued=token)

    def _snapshot_records_locked(self) -> list[dict[str, Any]]:
        """The live state as replayable records (the journal's own
        vocabulary): app rows, container records (+ their seen/kill flags),
        undelivered exits, in-flight drains. History that no longer matters
        — released containers, removed apps, delivered exits — is exactly
        what compaction sheds. Replaying [snapshot] is equivalent to
        replaying the full history it folds (asserted property-style in
        tests/test_pool.py)."""
        now_mono, now_unix = time.monotonic(), time.time()
        recs: list[dict[str, Any]] = []
        # the replay context survives compaction: a folded journal must
        # still say what config/capacity its surviving rows' decisions ran
        # under, or `tony sim --from-history` falls back to guessed shares
        recs.append({
            "t": "config", "queues": dict(self.queues),
            "preemption": bool(self.preemption),
            "grace_ms": int(self.preemption_grace_ms),
            "drain_ms": int(self.preemption_drain_ms),
            "min_runtime_ms": int(self._policy.min_runtime_ms),
            "budget": int(self._policy.eviction_budget),
            "budget_window_ms": int(self._policy.budget_window_ms),
            "unix": now_unix,
        })
        recs.append({
            "t": "capacity", "totals": list(self._totals_locked()),
            "unix": now_unix,
        })
        for app in self._apps.values():
            recs.append({
                "t": "app", "app_id": app.app_id, "queue": app.queue,
                "priority": app.priority, "seq": app.seq,
                "admitted": app.admitted, "preempted": app.preempted,
                "demand_memory": app.demand_memory,
                "demand_vcores": app.demand_vcores,
                "demand_chips": app.demand_chips,
                "wait_unix": app.wait_unix, "admitted_unix": app.admitted_unix,
                "elastic_unit": list(app.elastic_unit),
                "elastic_slack": app.elastic_slack,
            })
        for cid, rec in self._containers.items():
            pending = self._app_exits.get(rec["app_id"], {}).get(cid)
            body = {k: v for k, v in rec.items()
                    if k not in ("seen_live", "kill_requested")}
            if pending is not None:
                body["state"] = _RUNNING  # the exited record below re-applies it
            recs.append({"t": "container", "rec": body})
            if rec.get("seen_live"):
                recs.append({"t": "seen", "cid": cid})
            if rec.get("kill_requested"):
                recs.append({"t": "kill_requested", "cid": cid})
            if pending is not None:
                recs.append({"t": "exited", "cid": cid, "rc": int(pending)})
        # undelivered exits whose container was already released: replay
        # needs the container row to exist when the exit lands, then drops it
        for app_id, exits in self._app_exits.items():
            for cid, rc in exits.items():
                if cid in self._containers:
                    continue
                recs.append({"t": "container", "rec": {
                    "id": cid, "app_id": app_id, "job_type": "",
                    "task_index": 0, "node": "", "memory_bytes": 0,
                    "vcores": 0, "chips": [], "slice_id": -1,
                    "state": _RUNNING,
                }})
                recs.append({"t": "exited", "cid": cid, "rc": int(rc)})
                recs.append({"t": "released", "cid": cid})
        for app_id, entry in self._drains.items():
            rec = {
                "t": "drain", "app_id": app_id, "req_id": entry["req_id"],
                "mode": entry["mode"], "workers": entry["workers"],
                "target_primary": entry.get("target_primary", 0),
                "undo_demand": [int(x) for x in (entry.get("undo_demand") or (0, 0, 0))],
                "deadline_unix": now_unix + (entry["deadline"] - now_mono),
                "t0_unix": now_unix + (entry["t0"] - now_mono),
            }
            if entry.get("reduced_demand"):
                rec["reduced_demand"] = [int(x) for x in entry["reduced_demand"]]
            if entry.get("origin"):
                rec["origin"] = entry["origin"]
                rec["for_app"] = entry.get("for_app", "")
            recs.append(rec)
        for app_id, d in self._demand.items():
            recs.append({
                "t": "demand", "app_id": app_id, "workers": d["workers"],
                "unit": [int(x) for x in d["unit"]], "unix": d["unix"],
            })
        for app_id, s in self._shrunk.items():
            rec = {
                "t": "growback", "app_id": app_id, "workers": s["workers"],
                "unit": [int(x) for x in s["unit"]], "queue": s["queue"],
                "since_unix": s["since_unix"],
            }
            g = self._grows.get(app_id)
            if g is not None:
                rec["offer"] = {
                    "req_id": g["req_id"], "workers": g["workers"],
                    "expected_primary": g["expected_primary"],
                    "deadline_unix": now_unix + (g["deadline"] - now_mono),
                }
            recs.append(rec)
        return recs

    def _journal_app_locked(self, app: _App) -> None:
        """Full app row (last record wins on replay) — written on every
        registration/admission/eviction state change. Waiting/admitted ages
        are journaled as WALL-CLOCK instants so a restarted pool restores
        them (monotonic clocks don't survive the process): without this,
        every pool restart silently restarted the cross-queue reclaim grace
        for every waiting app."""
        self._jlog_locked(
            "app", app_id=app.app_id, queue=app.queue, priority=app.priority,
            seq=app.seq, admitted=app.admitted, preempted=app.preempted,
            demand_memory=app.demand_memory, demand_vcores=app.demand_vcores,
            demand_chips=app.demand_chips,
            wait_unix=app.wait_unix, admitted_unix=app.admitted_unix,
            elastic_unit=list(app.elastic_unit), elastic_slack=app.elastic_slack,
        )

    def _journal_demand_locked(self, app_id: str) -> None:
        """Full published-demand row (last record wins on replay; workers=0
        clears) — written whenever an app's published deficit CHANGES."""
        d = self._demand.get(app_id)
        if d is None:
            self._jlog_locked("demand", app_id=app_id, workers=0)
        else:
            self._jlog_locked(
                "demand", app_id=app_id, workers=d["workers"],
                unit=[int(x) for x in d["unit"]], unix=d["unix"],
            )

    def _journal_growback_locked(self, app_id: str) -> None:
        """Full grow-back ledger row for ``app_id`` — workers owed plus any
        in-flight grow offer (last record wins on replay; workers=0 settles
        the debt and drops the offer)."""
        s = self._shrunk.get(app_id)
        if s is None:
            self._jlog_locked("growback", app_id=app_id, workers=0)
            return
        rec: dict[str, Any] = dict(
            app_id=app_id, workers=s["workers"],
            unit=[int(x) for x in s["unit"]], queue=s["queue"],
            since_unix=s["since_unix"],
        )
        g = self._grows.get(app_id)
        if g is not None:
            now_mono, now_unix = time.monotonic(), time.time()
            rec["offer"] = {
                "req_id": g["req_id"], "workers": g["workers"],
                "expected_primary": g["expected_primary"],
                "deadline_unix": now_unix + (g["deadline"] - now_mono),
            }
        self._jlog_locked("growback", **rec)

    def _recover_from_journal_locked(self, records) -> None:
        """Rebuild apps/containers/undelivered-exits from the journal (any
        iterable — recovery streams it). Nodes are runtime state: they
        re-register on their next heartbeat (the agent's ``unknown_node``
        path) carrying their live container ids, and ``register_node``
        re-applies the accounting for records replayed here. A waiting app
        admitted pre-crash stays admitted (never double-admitted); a running
        app keeps its claim and is not evicted.

        A compaction ``snapshot`` record is a barrier: everything folded so
        far is superseded history — state resets and the embedded records
        (same vocabulary, written by ``_snapshot_records_locked``) fold in
        its place."""
        max_seq = -1
        now_mono, now_unix = time.monotonic(), time.time()

        def rebase(unix: float) -> float:
            """Wall-clock instant → this process's monotonic clock, so a
            journaled waiting/admitted AGE (or pending drain deadline)
            survives the restart. May be before this process started —
            negative offsets are fine, only differences are compared."""
            return now_mono + (unix - now_unix) if unix else 0.0

        for rec in self._expand_snapshots(records):
            t = rec.get("t")
            if t == SNAPSHOT_RECORD:
                # barrier emitted by _expand_snapshots BEFORE the embedded
                # records: drop everything folded so far
                self._apps.clear()
                self._containers.clear()
                self._app_exits.clear()
                self._drains.clear()
                self._demand.clear()
                self._shrunk.clear()
                self._grows.clear()
                max_seq = -1
            elif t == "app":
                wait_unix = float(rec.get("wait_unix") or now_unix)
                admitted_unix = float(rec.get("admitted_unix") or 0.0)
                app = _App(
                    app_id=str(rec["app_id"]),
                    queue=str(rec["queue"]),
                    priority=int(rec.get("priority", 0)),
                    seq=int(rec.get("seq", 0)),
                    admitted=bool(rec.get("admitted")),
                    preempted=bool(rec.get("preempted")),
                    demand_memory=int(rec.get("demand_memory", 0)),
                    demand_vcores=int(rec.get("demand_vcores", 0)),
                    demand_chips=int(rec.get("demand_chips", 0)),
                    wait_since=rebase(wait_unix) or now_mono,
                    wait_unix=wait_unix,
                    admitted_at=rebase(admitted_unix),
                    admitted_unix=admitted_unix,
                    elastic_unit=tuple(int(x) for x in (rec.get("elastic_unit") or (0, 0, 0))),
                    elastic_slack=int(rec.get("elastic_slack", 0)),
                )
                if app.queue not in self.queues:
                    # queue config changed across the restart: park the app in
                    # the first declared queue rather than refusing recovery
                    app.queue = "default" if "default" in self.queues else next(iter(self.queues))
                max_seq = max(max_seq, app.seq)
                self._apps[app.app_id] = app
            elif t == "app_removed":
                self._apps.pop(str(rec["app_id"]), None)
                self._app_exits.pop(str(rec["app_id"]), None)
                self._demand.pop(str(rec["app_id"]), None)
                self._shrunk.pop(str(rec["app_id"]), None)
                self._grows.pop(str(rec["app_id"]), None)
            elif t == "container":
                crec = dict(rec["rec"])
                crec.pop("seen_live", None)  # must be re-observed by a live agent
                self._containers[crec["id"]] = crec
            elif t == "seen":
                crec = self._containers.get(str(rec["cid"]))
                if crec is not None:
                    crec["seen_live"] = True
            elif t == "kill_requested":
                crec = self._containers.get(str(rec["cid"]))
                if crec is not None:
                    crec["kill_requested"] = True
            elif t == "exited":
                crec = self._containers.get(str(rec["cid"]))
                if crec is not None and crec["state"] == _RUNNING:
                    crec["state"] = _EXITED
                    self._app_exits.setdefault(crec["app_id"], {})[crec["id"]] = int(rec["rc"])
            elif t == "released":
                self._containers.pop(str(rec["cid"]), None)
            elif t == "polled":
                self._app_exits.pop(str(rec["app_id"]), None)
            elif t == "drain":
                # in-flight drain/shrink episode: rebase the deadline onto
                # this process's clock so the escalation still fires — a pool
                # restart mid-drain must not leave a demoted victim's
                # containers running forever
                self._drains[str(rec["app_id"])] = {
                    "req_id": str(rec["req_id"]),
                    "mode": str(rec.get("mode", "drain")),
                    "workers": int(rec.get("workers", 0)),
                    "target_primary": int(rec.get("target_primary", 0)),
                    "undo_demand": [int(x) for x in (rec.get("undo_demand") or (0, 0, 0))],
                    "reduced_demand": (
                        [int(x) for x in rec["reduced_demand"]]
                        if rec.get("reduced_demand") else None
                    ),
                    "deadline": rebase(float(rec.get("deadline_unix") or now_unix)),
                    "t0": rebase(float(rec.get("t0_unix") or now_unix)),
                    "escalated": False,
                    "origin": str(rec.get("origin", "sched")),
                    "for_app": str(rec.get("for_app", "")),
                }
            elif t == "drain_done":
                self._drains.pop(str(rec["app_id"]), None)
            elif t == "demand":
                # published unmet demand (capacity market): last record wins,
                # workers=0 clears. The publish instant is journaled as wall
                # clock and rebased so the TTL expiry survives the restart.
                app_id = str(rec["app_id"])
                workers = int(rec.get("workers", 0))
                if workers <= 0:
                    self._demand.pop(app_id, None)
                else:
                    unix = float(rec.get("unix") or now_unix)
                    self._demand[app_id] = {
                        "workers": workers,
                        "unit": tuple(int(x) for x in (rec.get("unit") or (0, 0, 0))),
                        "unix": unix,
                        "mono": rebase(unix) or now_mono,
                    }
            elif t == "growback":
                # grow-back ledger + any in-flight grow offer: last record
                # wins, workers=0 settles the debt. Offer deadlines rebase
                # like drain deadlines — retraction must still fire.
                app_id = str(rec["app_id"])
                workers = int(rec.get("workers", 0))
                if workers <= 0:
                    self._shrunk.pop(app_id, None)
                    self._grows.pop(app_id, None)
                else:
                    self._shrunk[app_id] = {
                        "workers": workers,
                        "unit": tuple(int(x) for x in (rec.get("unit") or (0, 0, 0))),
                        "queue": str(rec.get("queue", "")),
                        "since_unix": float(rec.get("since_unix") or now_unix),
                    }
                    offer = rec.get("offer")
                    if offer:
                        self._grows[app_id] = {
                            "req_id": str(offer.get("req_id", "")),
                            "workers": int(offer.get("workers", 0)),
                            "expected_primary": int(offer.get("expected_primary", 0)),
                            "deadline": rebase(float(offer.get("deadline_unix") or now_unix)),
                        }
                    else:
                        self._grows.pop(app_id, None)
            elif t in ("config", "capacity"):
                # replay-context records (cluster/replay.py): the config the
                # decisions ran under and the capacity timeline. Recovery
                # state comes from the constructor and re-registration — the
                # records exist for `tony sim --from-history`, not for us.
                pass
            else:
                raise JournalError(f"unknown pool journal record type {t!r}")
        self._app_seq = itertools.count(max_seq + 1)

    @staticmethod
    def _expand_snapshots(records):
        """Flatten compaction snapshots for the replay fold: each snapshot
        record is re-emitted as a bare barrier marker (the fold resets on
        it) followed by its embedded records. Nested or malformed snapshot
        contents are a corrupt journal — degrade, never half-replay."""
        for rec in records:
            if rec.get("t") == SNAPSHOT_RECORD:
                inner = rec.get("records")
                if not isinstance(inner, list):
                    raise JournalError("snapshot record carries no records")
                yield {"t": SNAPSHOT_RECORD}
                for r in inner:
                    if not isinstance(r, dict) or r.get("t") == SNAPSHOT_RECORD:
                        raise JournalError("malformed snapshot contents")
                    yield r
            else:
                yield rec

    # ------------------------------------------------------------- lifecycle
    def start(self) -> None:
        self.rpc.start()
        self._monitor.start()

    def stop(self) -> None:
        self._stop.set()
        self.rpc.stop()
        if self._telemetry is not None:
            # partial windows still carry signal: flush them marked by their
            # true end instant rather than losing the tail of the pool's life
            with self._lock:
                windows = self._telemetry.flush()
            self._write_series(windows)
        if self._journal is not None:
            self._journal.close()  # drains staged records before closing

    @property
    def address(self) -> tuple[str, int]:
        return self.rpc.address

    # ------------------------------------------------------------ agent side
    def register_node(
        self,
        name: str,
        host: str,
        port: int,
        memory_bytes: int,
        vcores: int,
        slice_id: int = -1,
        slice_spec: str = "",
        chips: list[list[int]] | None = None,
        live: list[str] | None = None,
    ) -> dict[str, Any]:
        """Agent (re-)registration, now container-preserving: ``live`` names
        the container ids the agent is still running. Containers the pool
        recognizes (including ones replayed from the recovery journal after a
        pool restart) are RE-ADOPTED — their accounting is applied to the
        fresh node object and they keep running. Containers the pool does
        NOT recognize are orphans of a forgotten epoch and come back in the
        ``kill`` list; a pool with no journal therefore recognizes nothing
        and the agent kills everything — exactly the pre-journal behavior."""
        coords = tuple((int(r), int(c)) for r, c in (chips or []))
        live_set = set(live or [])
        with self._lock:
            # validate FIRST: a rejected registration must not disturb a
            # healthy node's bookkeeping (same-name check excluded — a valid
            # re-registration replaces the old incarnation below)
            if coords:
                spec = SliceSpec.parse(slice_spec)
                rows, cols = spec.topology
                for r, c in coords:
                    if not (0 <= r < rows and 0 <= c < cols):
                        raise ValueError(f"chip {r},{c} outside slice grid {rows}x{cols}")
                for other in self._nodes.values():
                    if (
                        other.name != name
                        and other.alive
                        and other.slice_id == slice_id
                        and set(other.chips) & set(coords)
                    ):
                        raise ValueError(
                            f"chips of {name} collide with {other.name} in slice {slice_id}"
                        )
            old = self._nodes.get(name)
            for cid, rec in list(self._containers.items()):
                if rec["node"] != name or rec["state"] != _RUNNING or cid in live_set:
                    continue
                # gone from the agent's live list: written off IF we knew the
                # node before (agent restart: its processes died with it) or
                # an agent once reported the container live (journal replay +
                # genuine death while the pool was down). A journaled record
                # never seen live is an allocated-not-yet-launched container
                # — the AM may still start it; leave it RUNNING.
                if old is not None or rec.get("seen_live"):
                    self._record_exit_locked(cid, constants.EXIT_NODE_LOST)
            # a live node clears the all-dead escalation clock — otherwise a
            # stale timestamp from a PAST outage would fail the next brief
            # blip instantly instead of granting its liveness-budget grace
            self._all_dead_since = None
            node = _Node(
                name=name, host=host, port=port,
                memory_bytes=int(memory_bytes), vcores=int(vcores),
                slice_id=int(slice_id), slice_spec=slice_spec, chips=coords,
            )
            self._nodes[name] = node
            if old is not None:
                # undelivered kill orders must survive the node-object swap:
                # with work-preserving re-adoption nothing else culls them
                node.pending_kills = list(old.pending_kills)
            kills: list[str] = []
            for cid, rec in self._containers.items():
                # re-account EVERY record still RUNNING on this node — both
                # the agent-confirmed live ones and allocated-not-yet-launched
                # ones (never seen live): their claim is real either way, or
                # allocate() would double-book the chips and the eventual
                # exit would drive the accounting negative
                if rec["state"] != _RUNNING or rec["node"] != name:
                    continue
                node.used_memory += rec["memory_bytes"]
                node.used_vcores += rec["vcores"]
                node.used_chips.update(tuple(c) for c in rec["chips"])
                if cid in live_set:
                    if not rec.get("seen_live"):
                        rec["seen_live"] = True
                        self._jlog_locked("seen", cid=cid)
                    if rec.get("kill_requested"):
                        # a backstop kill arrived while this node was away:
                        # deliver it now instead of resurrecting the victim
                        kills.append(cid)
            # live containers the pool has NO record of: orphans of an epoch
            # this pool never knew — the agent kills them
            kills.extend(
                cid for cid in sorted(live_set)
                if not (
                    (rec := self._containers.get(cid)) is not None
                    and rec["state"] == _RUNNING and rec["node"] == name
                )
            )
            if self._world is not None:
                self._world.touch()  # pool totals moved with the node set
            self._jlog_locked(
                "capacity", totals=list(self._totals_locked()), unix=time.time())
            self._schedule_locked()
        self._journal_sync()  # seen/exit records durable before the agent acts
        return {
            "ack": True,
            "heartbeat_interval_ms": self.heartbeat_interval_ms,
            "kill": kills,
        }

    def node_heartbeat(
        self, name: str, exited: dict[str, int] | None = None, live: list[str] | None = None
    ) -> dict[str, Any]:
        with self._lock:
            node = self._nodes.get(name)
            if node is None or not node.alive:
                # we never met this agent, or declared it dead while it was
                # partitioned — its containers were already written off
                return {"unknown_node": True}
            now = time.monotonic()
            node.last_heartbeat = now
            for cid, rc in (exited or {}).items():
                self._record_exit_locked(cid, int(rc))
            if live is not None:
                # reconcile: a container the agent once reported live but is
                # no longer tracking (and didn't just report exited) is gone —
                # e.g. its exit report was lost across an agent hiccup. Gated
                # on seen_live so a container allocated-but-not-yet-launched
                # (the AM launches after the whole gang allocates) is immune.
                live_set = set(live)
                for cid, rec in list(self._containers.items()):
                    if rec["node"] != name or rec["state"] != _RUNNING:
                        continue
                    if cid in live_set:
                        if not rec.get("seen_live"):
                            rec["seen_live"] = True
                            # durable: after a pool restart, only containers
                            # an agent once reported live may be written off
                            # when missing from a re-registration
                            self._jlog_locked("seen", cid=cid)
                    elif rec.get("seen_live") and cid not in (exited or {}):
                        self._record_exit_locked(cid, constants.EXIT_NODE_LOST)
            kills, node.pending_kills = node.pending_kills, []
        self._journal_sync()  # exited/seen records durable before the ack
        return {"ack": True, "kill": kills}

    # --------------------------------------------------------------- AM side
    def register_app(
        self,
        app_id: str,
        queue: str = "default",
        priority: int = 0,
        memory_bytes: int = 0,
        vcores: int = 0,
        chips: int = 0,
        elastic_unit: list[int] | None = None,
        elastic_slack: int = 0,
    ) -> dict[str, Any]:
        """ApplicationSubmissionContext analog: the AM announces its queue,
        priority, and TOTAL gang demand before allocating. Admission (the
        YARN capacity-queue behavior ``tony.application.queue`` configures)
        is decided from these demands: apps WAIT when the pool is busy
        instead of failing.

        ``elastic_unit``/``elastic_slack`` advertise the partial-reclaim
        contract: the resources one shed worker of the app's elastic jobtype
        frees, and how many workers it may shed (current minus the elastic
        floor). A reclaiming under-share head can then ask this app to
        SHRINK instead of whole-gang-evicting it (docs/scheduling.md)."""
        if queue not in self.queues:
            raise ValueError(
                f"unknown queue {queue!r}: pool queues are {sorted(self.queues)} "
                f"(tony.pool.queues)"
            )
        with self._lock:
            app = self._apps.get(app_id)
            if app is None:
                app = self._apps[app_id] = _App(
                    app_id=app_id, queue=queue, priority=int(priority),
                    seq=next(self._app_seq),
                )
            app.queue, app.priority = queue, int(priority)
            app.demand_memory = int(memory_bytes)
            app.demand_vcores = int(vcores)
            app.demand_chips = int(chips)
            app.elastic_unit = tuple(int(x) for x in (elastic_unit or (0, 0, 0)))
            app.elastic_slack = max(int(elastic_slack), 0)
            grow = self._grows.get(app_id)
            if grow is not None and app.admitted:
                primary = 2 if self._totals_locked()[2] > 0 else 0
                new_primary = (app.demand_memory, app.demand_vcores,
                               app.demand_chips)[primary]
                if new_primary >= grow["expected_primary"]:
                    # the borrower ACCEPTED the grow offer by re-registering
                    # its grown demand: settle that much of the owed debt and
                    # shield it from the market for the min-runtime window
                    self._grows.pop(app_id, None)
                    self._grown_at[app_id] = time.monotonic()
                    owed = self._shrunk.get(app_id)
                    if owed is not None:
                        owed["workers"] -= grow["workers"]
                        if owed["workers"] <= 0:
                            self._shrunk.pop(app_id, None)
                    self._journal_growback_locked(app_id)
                    _POOL_MARKET_GROWBACK.inc(grow["workers"], queue=app.queue)
            self._world_upsert_locked(app)
            self._schedule_locked()
            self._journal_app_locked(app)
            out = {"ack": True, "queue": queue, "admitted": app.admitted}
        self._journal_sync()  # the app row is durable before the AM proceeds
        return out

    def allocate(
        self,
        app_id: str,
        job_type: str,
        task_index: int,
        memory_bytes: int,
        vcores: int,
        chips: int = 0,
    ) -> dict[str, Any]:
        try:
            return self._allocate_impl(
                app_id, job_type, task_index, memory_bytes, vcores, chips)
        finally:
            # the container record staged under the lock becomes durable
            # HERE — before the AM sees the allocation it would launch on
            self._journal_sync()

    def _allocate_impl(
        self,
        app_id: str,
        job_type: str,
        task_index: int,
        memory_bytes: int,
        vcores: int,
        chips: int,
    ) -> dict[str, Any]:
        with self._lock:
            alive = [n for n in self._nodes.values() if n.alive]
            if not alive:
                if not self._nodes:
                    # nothing EVER registered: a misconfigured pool — fail fast
                    raise AllocationError(
                        f"pool has no registered nodes to host {job_type}:{task_index}"
                    )
                # nodes exist but are all currently dead (agent blip/restart):
                # they re-register on their next heartbeat — wait, but only
                # for one more liveness budget: agents that stay gone past it
                # are permanently dead, and an unbounded wait would leave the
                # job queued forever with no escalation
                now = time.monotonic()
                if self._all_dead_since is None:
                    self._all_dead_since = now
                budget_s = self.heartbeat_interval_ms * self.max_missed / 1000
                waited = now - self._all_dead_since
                if waited > budget_s:
                    raise AllocationError(
                        f"all pool nodes unreachable for {waited:.1f}s (> liveness "
                        f"budget {budget_s:.1f}s) — pool agents look permanently "
                        f"dead; cannot host {job_type}:{task_index}"
                    )
                _POOL_ALLOCATE_QUEUED.inc()
                return {
                    "wait": True, "queue": "", "position": 0,
                    "reason": "all pool nodes currently unreachable",
                }
            self._all_dead_since = None
            if chips > 0:
                biggest = max((len(n.chips) for n in alive), default=0)
                if chips > biggest:
                    raise AllocationError(
                        f"{job_type}:{task_index} asks {chips} chips but the largest "
                        f"host owns {biggest}: a container runs on one host — shard "
                        f"the job into per-host tasks (one process per TPU VM)"
                    )
                # placeability-if-empty: an ask no host could satisfy even
                # with ZERO occupancy (e.g. a 2x2 rect on a host owning a
                # 1x4 strip) would otherwise wait forever as "fragmentation"
                if not any(_rect_from(set(n.chips), chips) for n in alive):
                    raise AllocationError(
                        f"{job_type}:{task_index} asks a {chips}-chip rectangle "
                        f"no host's chip layout can form even when empty"
                    )
            if memory_bytes > max(n.memory_bytes for n in alive):
                raise AllocationError(
                    f"{job_type}:{task_index} asks {memory_bytes}B memory but the "
                    f"largest host owns {max(n.memory_bytes for n in alive)}B"
                )
            if vcores > max(n.vcores for n in alive):
                raise AllocationError(
                    f"{job_type}:{task_index} asks {vcores} vcores but the largest "
                    f"host owns {max(n.vcores for n in alive)}"
                )
            app = self._apps.get(app_id)
            if app is None:
                # back-compat: an unregistered app enters the default queue
                # claiming only what it asks for (AMs register real demands)
                default_q = "default" if "default" in self.queues else next(iter(self.queues))
                app = self._apps[app_id] = _App(
                    app_id=app_id, queue=default_q, seq=next(self._app_seq),
                )
            # demand learns the observed gang size (auto-registered apps
            # under-claim; held+ask is exact once the gang allocates serially)
            held = self._held_locked(app_id)
            before = (app.demand_memory, app.demand_vcores, app.demand_chips)
            app.demand_memory = max(app.demand_memory, held[0] + memory_bytes)
            app.demand_vcores = max(app.demand_vcores, held[1] + vcores)
            app.demand_chips = max(app.demand_chips, held[2] + chips)
            if (app.demand_memory, app.demand_vcores, app.demand_chips) != before:
                self._journal_app_locked(app)
            self._world_upsert_locked(app)
            if not app.admitted:
                self._schedule_locked()
            if not app.admitted:
                totals = self._totals_locked()
                if (
                    app.demand_memory > totals[0]
                    or app.demand_vcores > totals[1]
                    or app.demand_chips > totals[2]
                ):
                    raise AllocationError(
                        f"app {app_id} demand ({app.demand_memory}B/"
                        f"{app.demand_vcores}vc/{app.demand_chips}ch) exceeds the "
                        f"pool's total capacity ({totals[0]}B/{totals[1]}vc/"
                        f"{totals[2]}ch) — it can never be admitted"
                    )
                waiting = self._waiting_sorted_locked(app.queue)
                position = waiting.index(app)
                blocked = self._blocked_reason_locked(app, position)
                _POOL_ALLOCATE_QUEUED.inc()
                return {
                    "wait": True,
                    "queue": app.queue,
                    "position": position,
                    "blocked_reason": blocked,
                    "reason": f"queued in {app.queue!r} at position "
                              f"{position} of {len(waiting)}"
                              + (" (preempted)" if app.preempted else "")
                              # the recorder's binding rule rides the wait
                              # answer: the AM's status (and `tony top`'s
                              # header) then say WHY, not just how long
                              + (f" — blocked: {blocked}" if blocked else ""),
                }
            if chips > 0:
                # pack the gang's chips into as few slices as possible: prefer
                # slices this app already occupies, then fullest host first
                app_slices = {
                    rec["slice_id"]
                    for rec in self._containers.values()
                    if rec["app_id"] == app_id and rec["state"] == _RUNNING and rec["slice_id"] >= 0
                }
                candidates = sorted(
                    (n for n in alive if n.slice_id >= 0),
                    key=lambda n: (n.slice_id not in app_slices, len(n.free_chips)),
                )
            else:
                # chipless tasks spread by free memory (headroom-first)
                candidates = sorted(
                    alive, key=lambda n: n.memory_bytes - n.used_memory, reverse=True
                )
            for node in candidates:
                if (
                    node.used_memory + memory_bytes > node.memory_bytes
                    or node.used_vcores + vcores > node.vcores
                ):
                    continue
                coords = _rect_from(node.free_chips, chips)
                if coords is None:
                    continue
                node.used_memory += memory_bytes
                node.used_vcores += vcores
                node.used_chips.update(coords)
                cid = f"container_{uuid.uuid4().hex[:12]}"
                rec = {
                    "id": cid, "app_id": app_id, "job_type": job_type,
                    "task_index": int(task_index), "node": node.name,
                    "memory_bytes": int(memory_bytes), "vcores": int(vcores),
                    "chips": [list(c) for c in coords], "slice_id": node.slice_id,
                    "state": _RUNNING,
                }
                self._containers[cid] = rec
                self._held_add_locked(app_id, int(memory_bytes), int(vcores), len(coords))
                self._jlog_locked("container", rec=dict(rec))
                return {
                    **rec,
                    "agent_host": node.host, "agent_port": node.port,
                    "slice_spec": node.slice_spec,
                }
            # ADMITTED but nothing fits right now (other tenants' containers
            # still draining, or fragmentation): transient — the app keeps
            # its claim and the AM retries. Never-fit asks were rejected above.
            if self.recorder is not None:
                # a pool-side fact the policy cannot see: the claim fits the
                # AGGREGATE but no single host can form the placement (chips
                # must be one contiguous rectangle on one host)
                self.recorder.note(
                    "deny", app_id, app.queue, "no-rect-placement",
                    ask_chips=chips, ask_memory=memory_bytes,
                    task=f"{job_type}:{task_index}")
            _POOL_ALLOCATE_QUEUED.inc()
            return {
                "wait": True,
                "queue": app.queue,
                "position": 0,
                "reason": f"admitted; no node can host {job_type}:{task_index} yet "
                          f"(ask: {memory_bytes}B/{vcores}vc/{chips}ch; nodes: "
                          + ", ".join(
                              f"{n.name}[{n.memory_bytes - n.used_memory}B free"
                              + (f", {len(n.free_chips)}ch]" if n.chips else "]")
                              for n in alive
                          )
                          + ")",
            }

    def release(self, app_id: str, container_id: str) -> dict[str, Any]:
        with self._lock:
            self._release_locked(container_id)
            self._schedule_locked()
        self._journal_sync()
        return {"ack": True}

    def release_all(self, app_id: str) -> dict[str, Any]:
        with self._lock:
            for cid, rec in list(self._containers.items()):
                if rec["app_id"] == app_id:
                    self._request_kill_locked(rec)
                    self._release_locked(cid)
            self._app_exits.pop(app_id, None)
            self._apps.pop(app_id, None)  # app done: leave the queue entirely
            if self._world is not None:
                self._world.remove(app_id)
            self._cancelled.pop(app_id, None)
            if self._drains.pop(app_id, None) is not None:
                # the app left the pool mid-drain (finished, or torn down):
                # the episode is over either way
                self._jlog_locked("drain_done", app_id=app_id)
            # the market forgets a departed app entirely: its published
            # demand, any debt owed to it, and any open grow offer
            if self._demand.pop(app_id, None) is not None:
                self._jlog_locked("demand", app_id=app_id, workers=0)
            if (self._shrunk.pop(app_id, None) is not None
                    or self._grows.pop(app_id, None) is not None):
                self._grows.pop(app_id, None)
                self._jlog_locked("growback", app_id=app_id, workers=0)
            self._grown_at.pop(app_id, None)
            self._jlog_locked("app_removed", app_id=app_id, unix=time.time())
            self._schedule_locked()
        self._journal_sync()  # removal durable before the AM tears down
        return {"ack": True}

    def poll_exited(self, app_id: str, with_preempt: bool = False) -> dict[str, Any]:
        """Undelivered container exits for ``app_id``. With ``with_preempt``
        (the RemoteResourceManager spelling) the response is
        ``{"exits": {...}, "preempt": notice|None}`` — the cooperative-drain
        notice rides the poll the AM already makes every monitor tick, so a
        victim learns it is DRAINING with no new RPC round-trip."""
        with self._lock:
            exits = self._app_exits.pop(app_id, {})
            if exits:
                # delivered: a restarted pool must not re-deliver these
                self._jlog_locked("polled", app_id=app_id)
            out: dict[str, Any] = exits if not with_preempt else {
                "exits": exits, "preempt": self._preempt_notice_locked(app_id)}
        self._journal_sync()  # "polled" durable before the AM consumes exits
        return out

    def update_demand(
        self,
        app_id: str,
        workers: int,
        unit: list[int] | None = None,
        reason: str = "",
    ) -> dict[str, Any]:
        """The capacity market's demand bridge (docs/scheduling.md "Capacity
        market"): an ADMITTED app publishes the replicas it wants but cannot
        place — ``workers`` each occupying ``unit`` — as live queue demand.
        ``workers=0`` clears. The deficit is journaled like every pool
        mutation (a restart mid-spike keeps it), folded into the queue's
        ``tony_pool_queue_demand`` series, and — with preemption on — funded
        immediately by shrinking over-share elastic borrowers
        (:meth:`_fund_demand_locked`, recorder rule ``demand-spike``); the
        liveness tick retries while the deficit persists and TTL-expires a
        publisher that went quiet (``tony.pool.demand.ttl-ms``)."""
        workers = max(int(workers), 0)
        u = tuple(int(x) for x in (unit or (0, 0, 0)))
        with self._lock:
            app = self._apps.get(app_id)
            if app is None:
                return {"ack": False, "unknown_app": True}
            if not self.demand_enabled:
                return {"ack": False, "disabled": True}
            funded = 0
            prev = self._demand.get(app_id)
            if workers <= 0:
                if prev is not None:
                    self._demand.pop(app_id, None)
                    self._journal_demand_locked(app_id)
            else:
                if (prev is None or prev["workers"] != workers
                        or tuple(prev["unit"]) != u):
                    self._demand[app_id] = {
                        "workers": workers, "unit": u,
                        "unix": time.time(), "mono": time.monotonic(),
                    }
                    self._journal_demand_locked(app_id)
                else:
                    # refreshed, not changed: bump the TTL clock without
                    # journal churn — the TTL already tolerates a restart
                    # restoring the older publish instant
                    prev["unix"], prev["mono"] = time.time(), time.monotonic()
                funded = self._fund_demand_locked(app_id)
            self._maintain_quiet_clock_locked()
            out = {"ack": True, "funded_workers": funded}
        self._journal_sync()  # the deficit is durable before the AM backs off
        return out

    def request_kill(self, container_id: str) -> dict[str, Any]:
        """Backstop kill path when the AM cannot reach the agent directly:
        the order rides the agent's next heartbeat response."""
        with self._lock:
            rec = self._containers.get(container_id)
            if rec is not None:
                self._request_kill_locked(rec)
        self._journal_sync()  # kill_requested durable before the ack
        return {"ack": True}

    def pool_metrics(self) -> dict[str, Any]:
        """This pool-service process's metrics-registry snapshot
        (obs/metrics.py) — scrapeable through any RPC client, same shape as
        the AM's ``get_metrics``."""
        return {"identity": "pool", "metrics": obs_metrics.REGISTRY.snapshot()}

    def pool_status(self) -> dict[str, Any]:
        with self._lock:
            totals = self._totals_locked()
            primary = 2 if totals[2] > 0 else 0
            now = time.monotonic()

            def queue_status(q: str, share: float) -> dict[str, Any]:
                used = sum(
                    self._claim_locked(a)[primary]
                    for a in self._apps.values()
                    if a.queue == q and a.admitted
                )
                return {
                    "share": share,
                    # used-vs-share in the primary capacity dimension: the
                    # portal's share-utilization bars and any "is my
                    # guarantee honored" question read straight off these
                    "used": used,
                    "share_capacity": int(share * totals[primary]),
                    "admitted": sorted(
                        (
                            {
                                "app_id": a.app_id, "priority": a.priority,
                                "held_chips": self._held_locked(a.app_id)[2],
                                "held_memory": self._held_locked(a.app_id)[0],
                                "draining": a.app_id in self._drains,
                            }
                            for a in self._apps.values()
                            if a.queue == q and a.admitted
                        ),
                        key=lambda e: e["app_id"],
                    ),
                    "waiting": [
                        {
                            "app_id": a.app_id, "priority": a.priority,
                            "position": i, "preempted": a.preempted,
                            "waiting_s": round(max(now - a.wait_since, 0.0), 3),
                            "draining": a.app_id in self._drains,
                            # the binding rule from the flight recorder's
                            # latest deny record — what `tony top`/portal
                            # show instead of bare waiting_s guesswork
                            "blocked_reason": self._blocked_reason_locked(a, i),
                        }
                        for i, a in enumerate(self._waiting_sorted_locked(q))
                    ],
                }

            return {
                "nodes": [
                    {
                        "name": n.name, "alive": n.alive, "slice_id": n.slice_id,
                        "chips_total": len(n.chips), "chips_free": len(n.free_chips),
                        "memory_free": n.memory_bytes - n.used_memory,
                        "vcores_free": n.vcores - n.used_vcores,
                    }
                    for n in self._nodes.values()
                ],
                "containers_running": sum(
                    1 for r in self._containers.values() if r["state"] == _RUNNING
                ),
                "primary_dimension": ("memory_bytes", "vcores", "chips")[primary],
                "queues": {
                    q: queue_status(q, share) for q, share in self.queues.items()
                },
                "preemption": self.preemption,
                "scheduler": "indexed" if self._world is not None else "reference",
                "drains_active": len(self._drains),
                # the capacity market's live ledgers (docs/scheduling.md
                # "Capacity market"): published deficits, debt owed to
                # shrunken borrowers, grow offers awaiting acceptance
                "market": {
                    "demand": {
                        a: {"workers": d["workers"], "unit": list(d["unit"]),
                            "age_s": round(max(now - d["mono"], 0.0), 3)}
                        for a, d in self._demand.items()
                    },
                    "shrunk": {
                        a: {"workers": s["workers"], "queue": s["queue"]}
                        for a, s in self._shrunk.items()
                    },
                    "grows": {
                        a: {"workers": g["workers"],
                            "deadline_s": round(g["deadline"] - now, 3)}
                        for a, g in self._grows.items()
                    },
                },
            }

    # --------------------------------------- flight recorder & telemetry
    def _on_decision_record(self, rec: DecisionRecord) -> None:
        """Recorder note hook: denials become the per-rule counter (the
        admit/evict/shrink instruments already exist)."""
        if rec.action == "deny":
            _POOL_QUEUE_DENIALS.inc(queue=rec.queue, rule=rec.rule)

    def _waiting_sorted_locked(self, q: str) -> list[_App]:
        return sorted(
            (a for a in self._apps.values() if a.queue == q and not a.admitted),
            key=lambda a: a.sort_key,
        )

    def _blocked_reason_locked(self, app: _App, position: int) -> str | None:
        """The binding rule currently blocking a waiting app: queue heads
        answer from their latest deny record; everyone behind the head is
        simply not at the front yet (their turn's rule would be fiction)."""
        if position > 0:
            return "behind-queue-head"
        if self.recorder is None:
            return None
        return self.recorder.blocked_reason(app.app_id)

    def _queue_sample_locked(
        self, now: float, totals: tuple[int, int, int], primary: int,
    ) -> dict[str, dict[str, float]]:
        """One tick's per-queue stats in the primary capacity dimension."""
        out: dict[str, dict[str, float]] = {}
        waiting_claims: dict[str, list[float]] = {}
        oldest: dict[str, float] = {}
        used: dict[str, float] = {}
        for a in self._apps.values():
            c = self._claim_locked(a)[primary]
            if a.admitted:
                used[a.queue] = used.get(a.queue, 0.0) + c
            else:
                waiting_claims.setdefault(a.queue, []).append(c)
                age = max(now - a.wait_since, 0.0)
                oldest[a.queue] = max(oldest.get(a.queue, 0.0), age)
        published: dict[str, float] = {}
        for app_id, d in self._demand.items():
            app = self._apps.get(app_id)
            if app is not None:
                published[app.queue] = (
                    published.get(app.queue, 0.0)
                    + d["workers"] * d["unit"][primary]
                )
        for q, share in self.queues.items():
            out[q] = {
                "used": used.get(q, 0.0),
                "share_capacity": float(int(share * totals[primary])),
                # published deficits ARE live queue demand (the capacity
                # market's bridge): folding them here makes a serve spike
                # visible in tony_pool_queue_demand and cluster_series even
                # though the demanding app is admitted, not waiting
                "demand": sum(waiting_claims.get(q, ())) + published.get(q, 0.0),
                "waiting": float(len(waiting_claims.get(q, ()))),
                "wait_age_s": round(oldest.get(q, 0.0), 3),
            }
        return out

    def _sample_telemetry_locked(self) -> list[dict[str, Any]]:
        """Feed the telemetry ring + the `tony_pool_queue_*` gauges, and
        return any finalized windows for the caller to write to the
        cluster-series file (:meth:`_write_series`) AFTER releasing the
        state lock — the file append must not extend this critical
        section. Called from the liveness tick, throttled to ~1 Hz —
        O(apps) per sample, amortized to noise against the tick's
        existing work."""
        if self._telemetry is None:
            return []
        totals = self._totals_locked()
        primary = 2 if totals[2] > 0 else 0
        now = time.monotonic()
        sample = self._queue_sample_locked(now, totals, primary)
        published: dict[str, float] = {}
        for app_id, d in self._demand.items():
            app = self._apps.get(app_id)
            if app is not None:
                published[app.queue] = (
                    published.get(app.queue, 0.0)
                    + d["workers"] * d["unit"][primary]
                )
        for q, s in sample.items():
            _POOL_QUEUE_USED.set(s["used"], queue=q)
            _POOL_QUEUE_SHARE_CAPACITY.set(s["share_capacity"], queue=q)
            _POOL_QUEUE_DEMAND.set(s["demand"], queue=q)
            _POOL_QUEUE_WAITING.set(s["waiting"], queue=q)
            _POOL_QUEUE_WAIT_AGE.set(s["wait_age_s"], queue=q)
            _POOL_QUEUE_PUBLISHED.set(published.get(q, 0.0), queue=q)
        counters = self.recorder.queue_counters if self.recorder is not None else {}
        self._telemetry.sample(sample, counters=counters)
        return self._telemetry.drain_finalized()

    def _write_series(self, windows: list[dict[str, Any]]) -> None:
        """Append finalized telemetry windows to the cluster-series file
        (one JSONL line per window; histserver/ingest.py sweeps it).
        Runs OUTSIDE the state lock; the tiny ``_series_lock`` only keeps
        concurrent flushers (liveness tick vs stop()) from interleaving
        lines."""
        if not windows or not self._series_file:
            return
        try:
            with self._series_lock:
                with open(self._series_file, "a", encoding="utf-8") as f:  # lint: disable=blocking-under-lock — leaf serializer for the series file; nothing is acquired under it
                    for w in windows:
                        f.write(window_line(self._series_source, w) + "\n")
        except OSError as e:
            obs_logging.warning(
                f"[tony-pool] cluster-series flush failed: {e}")

    def pool_explain(
        self, app_id: str = "", queue: str = "", limit: int = 50,
    ) -> dict[str, Any]:
        """Decision provenance for `tony explain` and the portal.

        - ``app_id``: the app's current scheduling state + its causal chain
          (latest records where it is the subject, funded, or was funded);
        - ``queue``: the queue's snapshot + its recent records + the
          telemetry sample ring (live sparkline source);
        - neither: every queue's sample ring + the newest records.
        """
        with self._lock:
            if self.recorder is None:
                return {"enabled": False}
            out: dict[str, Any] = {
                "enabled": True,
                "scheduler": "indexed" if self._world is not None else "reference",
                "pass_id": self.recorder.pass_id,
            }
            now = time.monotonic()
            if app_id:
                app = self._apps.get(app_id)
                state: dict[str, Any] | None = None
                if app is not None:
                    waiting = self._waiting_sorted_locked(app.queue)
                    position = waiting.index(app) if app in waiting else -1
                    state = {
                        "app_id": app.app_id, "queue": app.queue,
                        "priority": app.priority,
                        "admitted": app.admitted, "preempted": app.preempted,
                        "draining": app_id in self._drains,
                        "drain_mode": (self._drains.get(app_id) or {}).get("mode"),
                        "claim": list(self._claim_locked(app)),
                        "waiting_s": (
                            round(max(now - app.wait_since, 0.0), 3)
                            if not app.admitted else 0.0),
                        "position": position if not app.admitted else -1,
                        "blocked_reason": (
                            self._blocked_reason_locked(app, position)
                            if not app.admitted else None),
                    }
                out["app"] = state
                out["records"] = [
                    r.to_dict() for r in self.recorder.explain(app_id, limit)]
                return out
            if queue:
                totals = self._totals_locked()
                primary = 2 if totals[2] > 0 else 0
                sample = self._queue_sample_locked(now, totals, primary)
                out["queue"] = {
                    "name": queue,
                    "share": self.queues.get(queue),
                    **sample.get(queue, {}),
                    "counters": self.recorder.counters(queue),
                    "waiters": [
                        {"app_id": a.app_id, "position": i,
                         "blocked_reason": self._blocked_reason_locked(a, i)}
                        for i, a in enumerate(self._waiting_sorted_locked(queue))
                    ],
                }
                out["records"] = [
                    r.to_dict() for r in self.recorder.queue_records(queue, limit)]
                out["series"] = (
                    self._telemetry.recent(queue, limit)
                    if self._telemetry is not None else [])
                return out
            out["records"] = [r.to_dict() for r in self.recorder.tail(limit)]
            out["queues"] = {
                q: {
                    "counters": self.recorder.counters(q),
                    "series": (self._telemetry.recent(q, limit)
                               if self._telemetry is not None else []),
                }
                for q in self.queues
            }
            return out

    def cluster_capacity(self) -> dict[str, int]:
        """TOTAL capacity of currently-alive nodes (the admission universe) —
        what the AM's elastic-downsize decision compares gang demand against
        after a node is permanently lost."""
        with self._lock:
            mem, vc, chips = self._totals_locked()
            return {
                "memory_bytes": mem, "vcores": vc, "chips": chips,
                "alive_nodes": sum(1 for n in self._nodes.values() if n.alive),
                "nodes": [
                    {
                        "memory_bytes": n.memory_bytes,
                        "vcores": n.vcores,
                        "chips": len(n.chips),
                    }
                    for n in self._nodes.values()
                    if n.alive
                ],
            }

    # ------------------------------------------------- admission scheduling
    def _totals_locked(self) -> tuple[int, int, int]:
        """(memory, vcores, chips) over alive nodes — the admission universe."""
        alive = [n for n in self._nodes.values() if n.alive]
        return (
            sum(n.memory_bytes for n in alive),
            sum(n.vcores for n in alive),
            sum(len(n.chips) for n in alive),
        )

    def _held_locked(self, app_id: str) -> tuple[int, int, int]:
        h = self._app_held.get(app_id)
        return (h[0], h[1], h[2]) if h else (0, 0, 0)

    def _held_add_locked(self, app_id: str, mem: int, vc: int, chips: int) -> None:
        """Container create/exit/release delta to the app's held totals (the
        incremental twin of scanning every RUNNING container record)."""
        h = self._app_held.setdefault(app_id, [0, 0, 0])
        h[0] += mem
        h[1] += vc
        h[2] += chips
        if not any(h):
            self._app_held.pop(app_id, None)
        app = self._apps.get(app_id)
        if app is not None:
            self._world_upsert_locked(app)

    def _policy_fields_locked(self, app: _App) -> dict[str, Any]:
        """One app's scheduling-relevant state as AppView fields — the ONE
        mapping both scheduler paths consume (the WorldIndex delta feed and
        the reference branch's per-pass view rebuild), so they cannot
        drift."""
        return dict(
            queue=app.queue,
            priority=app.priority,
            seq=app.seq,
            demand=(app.demand_memory, app.demand_vcores, app.demand_chips),
            held=self._held_locked(app.app_id),
            admitted=app.admitted,
            preempted=app.preempted,
            wait_since=app.wait_since,
            admitted_at=app.admitted_at,
            elastic_unit=app.elastic_unit,
            elastic_slack=app.elastic_slack,
            shrink_pending=(
                app.app_id in self._drains
                and self._drains[app.app_id]["mode"] == "shrink"
            ),
        )

    def _world_upsert_locked(self, app: _App) -> None:
        """Reconcile one app's WorldIndex view with its canonical record —
        called from every choke point that mutates scheduling-relevant app
        state (register/admit/evict/shrink/held/drain transitions). A no-op
        when nothing actually changed, so the index's version only moves on
        real deltas."""
        if self._world is None:
            return
        self._world.upsert(app.app_id, **self._policy_fields_locked(app))

    def _rebuild_derived_locked(self) -> None:
        """Recompute held totals and the WorldIndex wholesale — journal
        recovery (and its loud degrade) is the one place the world changes
        by more than a delta."""
        self._app_held = {}
        for rec in self._containers.values():
            if rec["state"] == _RUNNING:
                h = self._app_held.setdefault(rec["app_id"], [0, 0, 0])
                h[0] += rec["memory_bytes"]
                h[1] += rec["vcores"]
                h[2] += len(rec["chips"])
        if self._world is not None:
            self._world = WorldIndex()
            self._sched_seen_version = -1
            for app in self._apps.values():
                self._world_upsert_locked(app)

    def _claim_locked(self, app: _App) -> tuple[int, int, int]:
        held = self._held_locked(app.app_id)
        return (
            max(app.demand_memory, held[0]),
            max(app.demand_vcores, held[1]),
            max(app.demand_chips, held[2]),
        )

    def _schedule_locked(self) -> None:
        """One admission pass: run the pure policy (cluster/policy.py — the
        exact code ``tony sim`` proves invariants over) and apply its
        decision.

        The policy owns the WHOLE decision (claims-based admission, queue
        shares, priority preemption, cross-queue reclaim with shrink-first
        partial reclaim, anti-thrash guards); this method owns only the
        mechanics — journaling, metrics, and initiating drains/kills.

        Indexed path (the default): the pass reads the delta-maintained
        :class:`WorldIndex` — no view rebuilds, no held rescans — and when
        the world hasn't changed since a pass that decided nothing (and no
        grace/min-runtime/budget window consulted by that pass has expired,
        ``last_wake_at``), the tick is skipped outright: an idle pool pays
        microseconds per allocate retry instead of a full pass."""
        if self._world is not None:
            # skip BEFORE the O(alive nodes) totals scan: node-set changes
            # bump the world version (touch()), so the check is complete
            # without recomputing totals — the idle tick really is O(1)
            if (
                self._world.version == self._sched_seen_version
                and self._sched_last_empty
                and (self._sched_wake_at is None
                     or time.monotonic() < self._sched_wake_at)
            ):
                return
            decision = self._policy.schedule_world(self._world, self._totals_locked())
            self._sched_wake_at = self._policy.last_wake_at
        else:
            views = [
                AppView(app_id=a.app_id, **self._policy_fields_locked(a))
                for a in self._apps.values()
            ]
            decision = self._policy.schedule(views, self._totals_locked())
        for sh in decision.shrink:
            self._apply_shrink_locked(sh)
        for ev in decision.evict:
            self._apply_evict_locked(ev)
        for app_id in decision.admit:
            self._apply_admit_locked(app_id)
        if self._world is not None:
            # recorded AFTER applying: the _apply_* choke points sync the
            # canonical records back into the index (authoritative clocks,
            # drain bookkeeping), and only their final version counts as seen
            self._sched_seen_version = self._world.version
            self._sched_last_empty = decision.empty()

    # -------------------------------------------- decision application
    def _apply_admit_locked(self, app_id: str) -> None:
        app = self._apps[app_id]
        app.admitted, app.preempted = True, False
        app.admitted_at = time.monotonic()
        app.admitted_unix = time.time()
        _POOL_ADMISSIONS.inc(queue=app.queue)
        entry = self._drains.get(app_id)
        if entry is not None and entry["mode"] == "drain":
            # a drain victim re-admitted before it yielded (capacity freed
            # elsewhere): the eviction is moot — cancel the drain instead of
            # letting the deadline kill an app that may keep running
            self._drains.pop(app_id, None)
            self._cancelled[app_id] = entry["req_id"]
            self._jlog_locked("drain_done", app_id=app_id)
            obs_logging.info(
                f"[tony-pool] drain of {app_id} cancelled: re-admitted before yielding")
        self._world_upsert_locked(app)
        self._journal_app_locked(app)

    def _apply_evict_locked(self, ev) -> None:
        """Demote an admitted app back to waiting (the policy already chose
        it; claims moved in the same pass) and start the two-phase drain:
        with ``tony.pool.preemption.drain-ms`` > 0 the victim learns it is
        DRAINING through its poll path, urgent-checkpoints, and yields —
        kills fire only at the deadline. drain-ms 0 keeps the classic
        immediate kill path."""
        v = self._apps[ev.app_id]
        v.admitted, v.preempted = False, True
        v.wait_since = time.monotonic()
        v.wait_unix = time.time()
        _POOL_EVICTIONS.inc(queue=v.queue)
        self._world_upsert_locked(v)
        self._journal_app_locked(v)
        running = [
            rec for rec in self._containers.values()
            if rec["app_id"] == v.app_id and rec["state"] == _RUNNING
        ]
        # a new eviction supersedes any stale cancellation from a previous
        # drain episode of this app
        self._cancelled.pop(v.app_id, None)
        if not running:
            return  # nothing to drain or kill (e.g. evicted mid-gang-restart)
        if self.preemption_drain_ms > 0:
            now = time.monotonic()
            entry = {
                "req_id": f"pre-{uuid.uuid4().hex[:8]}",
                "mode": "drain", "workers": 0, "target_primary": 0,
                "deadline": now + self.preemption_drain_ms / 1000,
                "t0": now, "escalated": False,
            }
            self._drains[v.app_id] = entry
            self._jlog_locked(
                "drain", app_id=v.app_id, req_id=entry["req_id"], mode="drain",
                workers=0, target_primary=0,
                deadline_unix=time.time() + self.preemption_drain_ms / 1000,
                t0_unix=time.time(),
            )
            obs_logging.info(
                f"[tony-pool] draining {v.app_id} for {ev.for_app} "
                f"(checkpoint-then-yield, deadline {self.preemption_drain_ms}ms)")
        else:
            for rec in running:
                self._preempt_cids.add(rec["id"])
                self._request_kill_locked(rec)
            _POOL_PREEMPTIONS.inc(mode="kill")

    def _apply_shrink_locked(self, sh, *, origin: str = "sched") -> None:
        """Partial reclaim: reduce the victim's registered demand by the
        shed workers' resources and ask its AM (through the poll path) to
        shrink the elastic jobtype by K. The freed claim funds the head
        admitted in the same pass; escalation whole-gang-evicts at the
        deadline if the AM never sheds.

        ``origin`` tags the episode's provenance: ``"sched"`` (the normal
        scheduling pass) or ``"demand"`` (the capacity market funding
        published demand) — a demand-origin shed that lands cooperatively
        books the workers into the grow-back ledger."""
        v = self._apps[sh.app_id]
        self._cancelled.pop(v.app_id, None)  # superseded by the new episode
        unit = v.elastic_unit
        v.demand_memory = max(v.demand_memory - sh.workers * unit[0], 0)
        v.demand_vcores = max(v.demand_vcores - sh.workers * unit[1], 0)
        v.demand_chips = max(v.demand_chips - sh.workers * unit[2], 0)
        v.elastic_slack = max(v.elastic_slack - sh.workers, 0)
        primary = 2 if self._totals_locked()[2] > 0 else 0
        target = (v.demand_memory, v.demand_vcores, v.demand_chips)[primary]
        now = time.monotonic()
        # shrink always gets a drain window, even with drain-ms 0: the shed
        # itself is a checkpoint-resume rebuild and needs a moment — but the
        # window is bounded, so a non-cooperative AM still escalates
        drain_s = max(self.preemption_drain_ms, 10_000) / 1000
        entry = {
            "req_id": f"pre-{uuid.uuid4().hex[:8]}",
            "mode": "shrink", "workers": sh.workers, "target_primary": target,
            # escalation must UNDO the demand reduction (the shed never
            # landed — a fictional smaller demand could get the victim
            # re-admitted undersized and oversubscribe the claims) — but
            # only while demand still equals what this shrink set: an AM
            # that re-registered since owns its demand
            "undo_demand": [sh.workers * unit[0], sh.workers * unit[1],
                            sh.workers * unit[2]],
            "reduced_demand": [v.demand_memory, v.demand_vcores, v.demand_chips],
            "deadline": now + drain_s, "t0": now, "escalated": False,
            "origin": origin, "for_app": sh.for_app,
        }
        self._drains[v.app_id] = entry
        self._world_upsert_locked(v)
        self._journal_app_locked(v)
        self._jlog_locked(
            "drain", app_id=v.app_id, req_id=entry["req_id"], mode="shrink",
            workers=sh.workers, target_primary=target,
            undo_demand=list(entry["undo_demand"]),
            reduced_demand=list(entry["reduced_demand"]),
            deadline_unix=time.time() + drain_s, t0_unix=time.time(),
            origin=origin, for_app=sh.for_app,
        )
        obs_logging.info(
            f"[tony-pool] asking {v.app_id} to shrink by {sh.workers} elastic "
            f"worker(s) for {sh.for_app} (partial reclaim, deadline {drain_s:.0f}s)")

    # ------------------------------------------------ the capacity market
    def _phys_free_locked(self) -> list[int]:
        """Aggregate physical headroom over alive nodes — the funding pass's
        target: a published deficit is met when this covers it (placement
        granularity is the allocate retry's problem, not the market's)."""
        free = [0, 0, 0]
        for n in self._nodes.values():
            if n.alive:
                free[0] += n.memory_bytes - n.used_memory
                free[1] += n.vcores - n.used_vcores
                free[2] += len(n.free_chips)
        return free

    def _maintain_quiet_clock_locked(self) -> None:
        """The grow-back hysteresis clock: running while NO deficit is
        published, reset by any live demand — spike→ebb→spike cannot thrash
        because grow-back waits a full quiet window each time."""
        if self._demand:
            self._demand_quiet_since = None
        elif self._demand_quiet_since is None:
            self._demand_quiet_since = time.monotonic()

    def _fund_demand_locked(self, app_id: str) -> int:
        """One funding pass for ``app_id``'s published deficit: shed elastic
        workers from over-share borrowers (policy ``fund_demand``, recorder
        rule ``demand-spike``) until physical free capacity covers it.
        Returns workers newly asked to shed; the caller journal-syncs."""
        if (not self.demand_enabled or not self.preemption
                or self._world is None):
            return 0
        d = self._demand.get(app_id)
        app = self._apps.get(app_id)
        if d is None or app is None or not app.admitted:
            return 0
        need = [d["workers"] * u for u in d["unit"]]
        # subtract capacity already being freed by in-flight demand-origin
        # sheds: funding is once per deficit, never once per retry tick —
        # otherwise a 2-worker deficit re-funds every tick of the
        # multi-second drain and strips the borrowers bare
        for entry in self._drains.values():
            if entry.get("origin") == "demand" and not entry["escalated"]:
                pending = entry.get("undo_demand") or (0, 0, 0)
                for i in range(3):
                    need[i] -= int(pending[i])
        need = tuple(max(x, 0) for x in need)
        if not any(need):
            return 0
        decision = self._policy.fund_demand(
            self._world, self._totals_locked(), self._phys_free_locked(),
            app_id=app_id, queue=app.queue, need=need,
            grown_at=self._grown_at,
        )
        funded = 0
        for sh in decision.shrink:
            self._apply_shrink_locked(sh, origin="demand")
            funded += sh.workers
            victim = self._apps.get(sh.app_id)
            _POOL_MARKET_FUNDED.inc(
                sh.workers, queue=victim.queue if victim is not None else "")
        return funded

    def _market_tick_locked(self, now: float) -> None:
        """The liveness tick's market maintenance: TTL-expire stale
        published demand, retry funding for deficits that persist, retract
        unaccepted grow offers, and — once demand has ebbed for the full
        hysteresis window — offer reclaimed capacity back to the oldest
        shrunken borrowers (policy ``plan_growback``, rule ``grow-back``)."""
        if not self.demand_enabled:
            return
        ttl_s = self.demand_ttl_ms / 1000
        for app_id, d in list(self._demand.items()):
            if ttl_s > 0 and now - d["mono"] > ttl_s:
                # publisher went quiet (crashed mid-spike, or ebbed without
                # clearing): stale demand must not keep taxing borrowers
                self._demand.pop(app_id, None)
                self._journal_demand_locked(app_id)
            else:
                self._fund_demand_locked(app_id)
        self._maintain_quiet_clock_locked()
        # retract offers the borrower never accepted (its AM crashed or is
        # mid-rebuild): the debt stays booked, a later pass re-offers
        for app_id, g in list(self._grows.items()):
            if now >= g["deadline"]:
                self._grows.pop(app_id, None)
                self._journal_growback_locked(app_id)
        quiet = self._demand_quiet_since
        if (quiet is None or not self._shrunk or self._world is None
                or now - quiet < self.growback_ebb_ms / 1000):
            return
        free = self._phys_free_locked()
        # offers in flight hold their capacity out of the pool: subtract so
        # two passes can never promise the same free space twice
        for app_id, g in self._grows.items():
            v = self._apps.get(app_id)
            unit = v.elastic_unit if v is not None else (0, 0, 0)
            for i in range(3):
                free[i] -= g["workers"] * unit[i]
        ledger = sorted(
            (
                (app_id, s["workers"], tuple(s["unit"]))
                for app_id, s in self._shrunk.items()
                if app_id not in self._grows
                and app_id not in self._drains
                and app_id not in self._cancelled
            ),
            key=lambda e: self._shrunk[e[0]]["since_unix"],
        )
        if not ledger:
            return
        primary = 2 if self._totals_locked()[2] > 0 else 0
        grants = self._policy.plan_growback(
            self._world, free, ledger, step=self.growback_step)
        for app_id, k in grants:
            app = self._apps.get(app_id)
            if app is None:
                continue
            unit = self._shrunk[app_id]["unit"]
            expected = (app.demand_memory + k * unit[0],
                        app.demand_vcores + k * unit[1],
                        app.demand_chips + k * unit[2])[primary]
            self._grows[app_id] = {
                "req_id": f"grow-{next(self._grow_seq)}-{uuid.uuid4().hex[:6]}",
                "workers": k,
                "expected_primary": expected,
                "deadline": now + max(self.growback_ebb_ms, 30_000) / 1000,
            }
            self._journal_growback_locked(app_id)
            obs_logging.info(
                f"[tony-pool] offering {app_id} {k} worker(s) back "
                "(grow-back: demand ebbed)")

    # ------------------------------------------------ drain lifecycle
    def _preempt_notice_locked(self, app_id: str) -> dict[str, Any] | None:
        """The piggyback ``poll_exited`` carries back to a victim AM: the
        in-flight drain/shrink request, or a cancellation. Both are
        delivered at-least-once (re-sent every poll until superseded or the
        app leaves the pool): a response lost in transit must not leave the
        AM acting on a drain the pool already cancelled — the AM's handler
        is idempotent by req_id either way."""
        entry = self._drains.get(app_id)
        if entry is not None and not entry["escalated"]:
            return {
                "req_id": entry["req_id"],
                "mode": entry["mode"],
                "deadline_ms": max(int((entry["deadline"] - time.monotonic()) * 1000), 0),
                "shrink_workers": entry["workers"],
            }
        req_id = self._cancelled.get(app_id)
        if req_id is not None:
            return {"cancelled": req_id}
        grow = self._grows.get(app_id)
        if grow is not None:
            # grow-back offer (capacity market): demand ebbed, the pool
            # invites this shrunken borrower to resize back up. Accepted by
            # the AM re-registering grown demand; retracted at the deadline.
            return {
                "req_id": grow["req_id"],
                "mode": "grow",
                "deadline_ms": max(int((grow["deadline"] - time.monotonic()) * 1000), 0),
                "grow_workers": grow["workers"],
            }
        return None

    def _resolve_drain_locked(self, app_id: str, *, mode: str) -> None:
        entry = self._drains.pop(app_id, None)
        if entry is None:
            return
        app = self._apps.get(app_id)
        if app is not None:
            self._world_upsert_locked(app)  # shrink_pending cleared
            if mode == "shrink" and entry.get("origin") == "demand":
                # a market-funded shed LANDED: book the debt — these workers
                # come back through the grow-back pass when demand ebbs
                s = self._shrunk.get(app_id)
                if s is None:
                    self._shrunk[app_id] = {
                        "workers": int(entry.get("workers", 0)),
                        "unit": tuple(app.elastic_unit),
                        "queue": app.queue,
                        "since_unix": time.time(),
                    }
                else:
                    s["workers"] += int(entry.get("workers", 0))
                self._journal_growback_locked(app_id)
        self._jlog_locked("drain_done", app_id=app_id)
        _POOL_PREEMPTIONS.inc(mode=mode)
        if mode in ("drain", "shrink"):
            _POOL_DRAIN_SECONDS.observe(time.monotonic() - entry["t0"])
            obs_logging.info(
                f"[tony-pool] {app_id} {'yielded' if mode == 'drain' else 'shed workers'} "
                f"cooperatively after {time.monotonic() - entry['t0']:.1f}s")

    def _check_drains_locked(self) -> None:
        """Resolve drain/shrink episodes whose victims complied: a draining
        app with no RUNNING containers yielded; a shrinking app whose held
        primary capacity dropped to its reduced demand shed. Called from the
        container exit/release paths (the transitions that free capacity)."""
        primary = 2 if self._totals_locked()[2] > 0 else 0
        for app_id, entry in list(self._drains.items()):
            if entry["escalated"]:
                continue
            held = self._held_locked(app_id)
            if entry["mode"] == "drain":
                if not any(
                    rec["app_id"] == app_id and rec["state"] == _RUNNING
                    for rec in self._containers.values()
                ):
                    self._resolve_drain_locked(app_id, mode="drain")
            elif held[primary] <= entry["target_primary"]:
                self._resolve_drain_locked(app_id, mode="shrink")

    def _escalate_drains_locked(self) -> None:
        """Deadline enforcement (liveness loop): a victim that neither
        yielded nor shed by ``tony.pool.preemption.drain-ms`` gets the
        classic kill path — cooperation is an optimization, never a veto."""
        now = time.monotonic()
        for app_id, entry in list(self._drains.items()):
            if self._drains.get(app_id) is not entry:
                # a nested _schedule_locked() from an earlier escalation this
                # tick re-admitted (and cancelled) this victim: killing it
                # off the stale snapshot would defeat the cancellation
                continue
            if entry["escalated"] or now < entry["deadline"]:
                continue
            entry["escalated"] = True
            if self.recorder is not None:
                app = self._apps.get(app_id)
                self.recorder.note(
                    "evict", app_id, app.queue if app else "", "drain-escalated",
                    mode=entry["mode"],
                    overdue_ms=int((now - entry["deadline"]) * 1000))
            if entry["mode"] == "shrink":
                # the partial reclaim failed: fall back to the whole-gang
                # eviction the shrink was trying to avoid — and restore the
                # pre-shrink demand, which never actually shrank
                v = self._apps.get(app_id)
                if v is not None and v.admitted:
                    current = (v.demand_memory, v.demand_vcores, v.demand_chips)
                    if current == tuple(entry.get("reduced_demand") or current):
                        # demand untouched since the shrink was issued: the
                        # reduction is fiction, restore it. An AM that
                        # re-registered meanwhile (its rebuild in flight)
                        # owns its demand — inflating it would be worse.
                        undo = entry.get("undo_demand") or (0, 0, 0)
                        v.demand_memory += int(undo[0])
                        v.demand_vcores += int(undo[1])
                        v.demand_chips += int(undo[2])
                        v.elastic_slack += int(entry.get("workers", 0))
                    v.admitted, v.preempted = False, True
                    v.wait_since = time.monotonic()
                    v.wait_unix = time.time()
                    _POOL_EVICTIONS.inc(queue=v.queue)
                    self._journal_app_locked(v)
                    self._world_upsert_locked(v)
            obs_logging.warning(
                f"[tony-pool] {entry['mode']} of {app_id} escalated to kill "
                f"after {now - entry['t0']:.1f}s (deadline passed)")
            for rec in self._containers.values():
                if rec["app_id"] == app_id and rec["state"] == _RUNNING:
                    self._preempt_cids.add(rec["id"])
                    self._request_kill_locked(rec)
            self._drains.pop(app_id, None)
            app = self._apps.get(app_id)
            if app is not None:
                self._world_upsert_locked(app)  # shrink_pending cleared
            self._jlog_locked("drain_done", app_id=app_id)
            _POOL_PREEMPTIONS.inc(mode="kill")
            self._schedule_locked()

    # -------------------------------------------------------------- internal
    def _request_kill_locked(self, rec: dict[str, Any]) -> None:
        if rec["state"] != _RUNNING:
            return
        node = self._nodes.get(rec["node"])
        if node is not None and node.alive:
            node.pending_kills.append(rec["id"])
        elif not rec.get("kill_requested"):
            # node currently away (pool mid-recovery, agent partitioned):
            # the order must not be silently dropped — with work-preserving
            # re-adoption nothing else would ever kill this container. Mark
            # the record (durably) and deliver at re-registration.
            rec["kill_requested"] = True
            self._jlog_locked("kill_requested", cid=rec["id"])

    def _free_locked(self, rec: dict[str, Any]) -> None:
        node = self._nodes.get(rec["node"])
        if node is not None:
            node.used_memory -= rec["memory_bytes"]
            node.used_vcores -= rec["vcores"]
            node.used_chips.difference_update(tuple(c) for c in rec["chips"])

    def _record_exit_locked(self, cid: str, rc: int) -> None:
        rec = self._containers.get(cid)
        if rec is None or rec["state"] != _RUNNING:
            return
        if cid in self._preempt_cids:
            # we killed it: report the cluster action, not the signal — AMs
            # exclude EXIT_PREEMPTED from restart budgets (YARN PREEMPTED)
            self._preempt_cids.discard(cid)
            rc = constants.EXIT_PREEMPTED
        rec["state"] = _EXITED
        self._free_locked(rec)
        self._held_add_locked(
            rec["app_id"], -rec["memory_bytes"], -rec["vcores"], -len(rec["chips"]))
        self._app_exits.setdefault(rec["app_id"], {})[cid] = rc
        self._jlog_locked("exited", cid=cid, rc=rc)
        self._check_drains_locked()
        self._schedule_locked()

    def _release_locked(self, cid: str) -> None:
        rec = self._containers.pop(cid, None)
        if rec is not None:
            self._jlog_locked("released", cid=cid)
        if rec is not None and rec["state"] == _RUNNING:
            self._free_locked(rec)
            self._held_add_locked(
                rec["app_id"], -rec["memory_bytes"], -rec["vcores"], -len(rec["chips"]))
            # a cooperative victim yields by releasing its containers (the
            # AM's gang restart): resolve the drain the moment it completes
            self._check_drains_locked()

    def _mark_node_lost_locked(self, node: _Node, reason: str) -> None:
        node.alive = False
        if self._world is not None:
            self._world.touch()  # pool totals shrank with the node
        self._jlog_locked(
            "capacity", totals=list(self._totals_locked()), unix=time.time())
        for cid, rec in self._containers.items():
            if rec["node"] == node.name and rec["state"] == _RUNNING:
                self._record_exit_locked(cid, constants.EXIT_NODE_LOST)

    def _liveness_loop(self) -> None:
        timeout_s = self.heartbeat_interval_ms * self.max_missed / 1000
        while not self._stop.wait(self.heartbeat_interval_ms / 1000 / 2):
            if self.chaos is not None and self.chaos.take("pool-crash") is not None:
                # control-plane death fidelity: SIGKILL, no drain, no final
                # journal record beyond what each transition already fsync'd
                os.kill(os.getpid(), signal.SIGKILL)
            now = time.monotonic()
            windows: list[dict[str, Any]] = []
            with self._lock:
                for node in self._nodes.values():
                    if node.alive and now - node.last_heartbeat > timeout_s:
                        self._mark_node_lost_locked(node, reason="missed heartbeats")
                # cooperative-drain deadline enforcement: victims that never
                # yielded/shed get the classic kill path
                self._escalate_drains_locked()
                # the capacity market's maintenance: demand TTL + funding
                # retries + grow-back once demand has ebbed long enough
                self._market_tick_locked(now)
                # per-queue telemetry sample (~1 Hz, whatever the heartbeat
                # cadence): gauges + the cluster_series window ring
                if self._telemetry is not None and now >= self._telemetry_next:
                    self._telemetry_next = now + 1.0
                    windows = self._sample_telemetry_locked()
            # the tick's journal records (node-lost exits, drain kills) and
            # telemetry windows hit the disk with the lock released
            self._journal_sync()
            self._write_series(windows)


class RemoteResourceManager(ResourceManager):
    """AM-side adapter speaking to a PoolService + its agents.

    allocate/release/poll ride the RM; launch/kill go straight to the owning
    node's agent (the NMClient analog). Satisfies the same ``ResourceManager``
    interface the in-process pools do, so the AM, scheduler, and every E2E
    behavior are unchanged.
    """

    def __init__(self, rm_host: str, rm_port: int, secret: str = "", app_id: str = ""):
        self.app_id = app_id or f"app_{uuid.uuid4().hex[:8]}"
        self.rm = RpcClient(rm_host, rm_port, secret=secret)
        self.secret = secret
        self._agents: dict[tuple[str, int], RpcClient] = {}
        self._containers: dict[str, tuple[Container, tuple[str, int], int]] = {}
        self._span: list[int] | None = None
        self._preempt_notice: dict[str, Any] | None = None  # piggybacked on poll_exited
        # pre-drain pool service: rejects the cooperative-preemption kwargs
        # with a TypeError error frame — detected once, then spoken legacy
        self._legacy_pool = False
        # pre-market pool service: no update_demand RPC — detected once,
        # then the demand bridge goes silent (it is advisory by design)
        self._market_unsupported = False
        self._lock = locktrace.make_lock("pool.RemoteResourceManager._lock")

    def _agent(self, addr: tuple[str, int]) -> RpcClient:
        with self._lock:
            cli = self._agents.get(addr)
            if cli is None:
                cli = self._agents[addr] = RpcClient(addr[0], addr[1], secret=self.secret)
            return cli

    @staticmethod
    def _is_unknown_kwarg(e: Exception) -> bool:
        """An old pool's error frame for a parameter it doesn't know."""
        return "TypeError" in str(e) and "unexpected keyword" in str(e)

    def register_app(
        self, queue: str, priority: int, demand: Resources,
        elastic_unit: Resources | None = None, elastic_slack: int = 0,
    ) -> None:
        base = dict(
            app_id=self.app_id,
            queue=queue,
            priority=priority,
            memory_bytes=demand.memory_bytes,
            vcores=demand.vcores,
            chips=demand.chips,
        )
        if not self._legacy_pool:
            try:
                self.rm.call(
                    "register_app", **base,
                    elastic_unit=(
                        [elastic_unit.memory_bytes, elastic_unit.vcores,
                         elastic_unit.chips]
                        if elastic_unit is not None else [0, 0, 0]
                    ),
                    elastic_slack=int(elastic_slack),
                )
                return
            except RpcError as e:
                if not self._is_unknown_kwarg(e):
                    raise
                self._legacy_pool = True  # pre-drain pool: speak legacy from here
        self.rm.call("register_app", **base)

    def total_capacity(self) -> Resources | None:
        try:
            got = self.rm.call("cluster_capacity")
        except (RpcError, OSError):
            return None  # RM unreachable: the AM skips the downsize decision
        return Resources(
            memory_bytes=int(got["memory_bytes"]),
            vcores=int(got["vcores"]),
            chips=int(got["chips"]),
        )

    def node_capacities(self) -> list[Resources] | None:
        try:
            got = self.rm.call("cluster_capacity")
        except (RpcError, OSError):
            return None
        return [
            Resources(
                memory_bytes=int(n["memory_bytes"]),
                vcores=int(n["vcores"]),
                chips=int(n["chips"]),
            )
            for n in got.get("nodes", [])
        ]

    def allocate(self, job_type: str, task_index: int, resources: Resources) -> Container:
        try:
            got = self.rm.call(
                "allocate",
                app_id=self.app_id,
                job_type=job_type,
                task_index=task_index,
                memory_bytes=resources.memory_bytes,
                vcores=resources.vcores,
                chips=resources.chips,
            )
        except RpcError as e:
            if "AllocationError" in str(e):
                raise AllocationError(str(e)) from e
            raise
        if got.get("wait"):
            raise AllocationPending(got.get("reason", "queued"))
        coords = tuple((r, c) for r, c in got["chips"])
        spec = SliceSpec.parse(got["slice_spec"]) if got.get("slice_spec") else None
        container = Container(
            id=got["id"],
            host=got["node"],
            resources=resources,
            chip_coords=coords,
            slice_name=spec.name if spec else "",
            slice_topology=spec.topology if spec else (0, 0),
            job_type=job_type,
            task_index=task_index,
        )
        with self._lock:
            self._containers[container.id] = (
                container,
                (got["agent_host"], got["agent_port"]),
                got["slice_id"],
            )
        return container

    def release(self, container: Container) -> None:
        with self._lock:
            self._containers.pop(container.id, None)
            if not self._containers:
                self._span = None  # gang fully released: next gang re-snapshots
        try:
            self.rm.call("release", app_id=self.app_id, container_id=container.id)
        except (RpcError, OSError):
            pass  # RM unreachable at teardown: release_all in shutdown retries

    def _gang_span(self) -> list[int]:
        """Gang DCN span, append-only across launch waves (same contract as
        MultiSliceResourceManager.gang_slice_span): one wave's tasks all see
        the same span; a later dependency-gated wave appends new slices so
        earlier tasks' TPU_SLICE_ID indices stay valid."""
        with self._lock:
            current = {sid for _, _, sid in self._containers.values() if sid >= 0}
            if self._span is None:
                self._span = sorted(current)
            else:
                self._span.extend(sorted(current - set(self._span)))
            return self._span

    def start_container(
        self, container: Container, command: list[str], env: dict[str, str], log_dir: str
    ) -> None:
        with self._lock:
            entry = self._containers.get(container.id)
        if entry is None:
            raise AllocationError(f"start of unknown container {container.id}")
        _, addr, slice_id = entry
        # ship the job-facing env, not the AM's machine baseline: keys the
        # framework contract owns (TONY_/JAX_/TPU_/... prefixes, same
        # whitelist the docker runtime forwards) plus anything the AM
        # changed relative to its inherited environment. Baseline keys the
        # AM merely inherited (PATH, HOME, ...) come from the REMOTE node's
        # environ, which the agent merges under the shipped delta.
        from tony_tpu.cluster.resources import _DOCKER_ENV_PREFIXES

        delta = {
            k: v
            for k, v in env.items()
            if any(k.startswith(p) for p in _DOCKER_ENV_PREFIXES)
            or os.environ.get(k) != v
        }
        if slice_id >= 0:
            span = self._gang_span()
            delta[constants.ENV_TPU_SLICE_ID] = str(span.index(slice_id))
            delta[constants.ENV_TPU_NUM_SLICES] = str(len(span))
        self._agent(addr).call(
            "launch_container",
            container_id=container.id,
            command=command,
            env=delta,
            log_dir=log_dir,
        )

    def _live_containers(self) -> list[Container]:
        with self._lock:
            return [c for c, _, _ in self._containers.values()]

    def journal_info(self, container: Container) -> dict | None:
        with self._lock:
            entry = self._containers.get(container.id)
        if entry is None:
            return None
        _, (agent_host, agent_port), slice_id = entry
        return {
            **container_to_record(container),
            "agent_host": agent_host, "agent_port": agent_port,
            "slice_id": slice_id,
        }

    def adopt_container(self, record: dict) -> Container | None:
        """Takeover adoption against a remote pool: the POOL survived and
        still holds the allocation under this app id — only this client-side
        tracking (container → owning agent) needs rebuilding."""
        agent_host, agent_port = record.get("agent_host"), record.get("agent_port")
        if not agent_host or not agent_port:
            return None
        c = container_from_record(record)
        with self._lock:
            self._containers[c.id] = (
                c, (str(agent_host), int(agent_port)), int(record.get("slice_id", -1)),
            )
        return c

    def reclaim_orphans(self) -> None:
        """Degraded takeover: release (and kill, via the agents' heartbeat
        kill orders) everything the pool still holds for this app id before
        the fresh gang allocates."""
        try:
            self.rm.call("release_all", app_id=self.app_id)
        except (RpcError, OSError):
            pass  # pool unreachable: allocation conflicts will surface loudly

    def poll_exited(self) -> dict[str, int]:
        try:
            if self._legacy_pool:
                got = self.rm.call("poll_exited", app_id=self.app_id)
            else:
                try:
                    got = self.rm.call(
                        "poll_exited", app_id=self.app_id, with_preempt=True)
                except RpcError as e:
                    # a pre-drain pool rejects the kwarg itself — without
                    # this fallback every poll would error and container
                    # exits would never be delivered for the life of the skew
                    if not self._is_unknown_kwarg(e):
                        raise
                    self._legacy_pool = True
                    got = self.rm.call("poll_exited", app_id=self.app_id)
        except (RpcError, OSError):
            return {}
        if isinstance(got, dict) and "exits" in got:
            # cooperative-preemption piggyback: the pool's drain/shrink
            # notice rides the poll the AM already makes every tick
            with self._lock:
                self._preempt_notice = got.get("preempt") or None
            exits = {cid: int(rc) for cid, rc in (got.get("exits") or {}).items()}
        else:
            # legacy pool: a flat {cid: rc} map and no notices
            exits = {cid: int(rc) for cid, rc in got.items()}
        if self.chaos is not None:
            # chaos node-loss / preempt against a remote pool: the kill rides
            # the real AM→agent path, the exit code is synthesized here (the
            # same seam the in-process RMs use)
            exits = self.chaos.perturb_container_exits(self, exits)
        return exits

    def poll_preemption(self) -> dict[str, Any] | None:
        """The drain/shrink notice (or cancellation) piggybacked on the most
        recent ``poll_exited`` — the AM's monitor loop reads it right after
        handling container exits."""
        with self._lock:
            return self._preempt_notice

    def update_demand(
        self, workers: int, unit: Resources, reason: str = "",
    ) -> bool:
        """Publish this app's unmet replica deficit — ``workers`` each
        needing ``unit`` — to the pool's capacity market (``workers=0``
        clears it). Advisory by design: any failure degrades to silence,
        never to failing the AM; a pool without the RPC is detected once
        and never called again."""
        if self._market_unsupported:
            return False
        try:
            out = self.rm.call(
                "update_demand", app_id=self.app_id, workers=int(workers),
                unit=[unit.memory_bytes, unit.vcores, unit.chips],
                reason=reason,
            )
        except RpcError as e:
            if self._is_unknown_kwarg(e) or "unknown method" in str(e):
                self._market_unsupported = True
            return False
        except OSError:
            return False
        return bool(isinstance(out, dict) and out.get("ack"))

    def kill_container(self, container: Container) -> None:
        with self._lock:
            entry = self._containers.get(container.id)
        if entry is None:
            return
        _, addr, _ = entry
        try:
            self._agent(addr).call("kill_container", container_id=container.id)
        except (RpcError, OSError):
            # agent unreachable (dead node?) — backstop via the RM
            try:
                self.rm.call("request_kill", container_id=container.id)
            except (RpcError, OSError):
                pass

    def shutdown(self) -> None:
        try:
            self.rm.call("release_all", app_id=self.app_id)
        except (RpcError, OSError):
            pass
        with self._lock:
            self._containers.clear()
            agents = list(self._agents.values())
            self._agents.clear()
        for cli in agents:
            cli.close()
        self.rm.close()


def main(argv: list[str] | None = None) -> int:
    from tony_tpu.config import TonyConfig, keys

    p = argparse.ArgumentParser(prog="tony-pool", description="tony-tpu pool service (RM analog)")
    p.add_argument("--bind-host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--secret", default=os.environ.get(constants.ENV_POOL_SECRET, ""))
    p.add_argument("--conf_file", default=None, help="site config supplying tony.node.* liveness keys")
    p.add_argument("--conf", action="append", default=[], help="key=value override (repeatable)")
    p.add_argument("--heartbeat-ms", type=int, default=None,
                   help="overrides tony.node.heartbeat-interval-ms")
    p.add_argument("--max-missed", type=int, default=None,
                   help="overrides tony.node.max-missed-heartbeats")
    p.add_argument("--info-file", default="", help="write host/port JSON here once serving")
    p.add_argument("--journal-file", default=None,
                   help="recovery journal path (overrides tony.pool.journal.file); "
                        "a restarted pool replays it and re-adopts live work")
    args = p.parse_args(argv)
    config = TonyConfig.from_layers(conf_file=args.conf_file, conf_args=args.conf)
    if config.get_bool(keys.DEBUG_LOCKTRACE):
        # before the service constructs its locks — a plain Lock cannot
        # retroactively grow tracing (obs/locktrace.py)
        locktrace.set_enabled(True)
    from tony_tpu.chaos import ChaosContext

    svc = PoolService(
        bind_host=args.bind_host,
        port=args.port,
        secret=args.secret,
        heartbeat_interval_ms=args.heartbeat_ms
        if args.heartbeat_ms is not None
        else config.get_time_ms(keys.NODE_HEARTBEAT_INTERVAL_MS, 1000),
        max_missed_heartbeats=args.max_missed
        if args.max_missed is not None
        else config.get_int(keys.NODE_MAX_MISSED_HEARTBEATS, 10),
        queues=parse_queue_spec(config.get(keys.POOL_QUEUES) or "default=1.0"),
        preemption=config.get_bool(keys.POOL_PREEMPTION_ENABLED),
        preemption_grace_ms=config.get_time_ms(keys.POOL_PREEMPTION_GRACE_MS, 0),
        preemption_drain_ms=config.get_time_ms(keys.POOL_PREEMPTION_DRAIN_MS, 0),
        preemption_min_runtime_ms=config.get_time_ms(keys.POOL_PREEMPTION_MIN_RUNTIME_MS, 0),
        preemption_budget=config.get_int(keys.POOL_PREEMPTION_BUDGET, 0),
        preemption_budget_window_ms=config.get_time_ms(keys.POOL_PREEMPTION_BUDGET_WINDOW_MS, 60_000),
        demand_enabled=config.get_bool(keys.POOL_DEMAND_ENABLED, True),
        demand_ttl_ms=config.get_time_ms(keys.POOL_DEMAND_TTL_MS, 60_000),
        growback_ebb_ms=config.get_time_ms(keys.POOL_DEMAND_GROWBACK_EBB_MS, 30_000),
        growback_step=config.get_int(keys.POOL_DEMAND_GROWBACK_STEP, 0),
        journal_path=args.journal_file
        if args.journal_file is not None
        else (config.get(keys.POOL_JOURNAL_FILE) or None),
        journal_compact_every=config.get_int(keys.POOL_JOURNAL_COMPACT_EVERY, 0),
        scheduler_indexed=config.get_bool(keys.POOL_SCHEDULER_INDEXED, True),
        recorder_enabled=config.get_bool(keys.POOL_RECORDER_ENABLED, True),
        recorder_capacity=config.get_int(keys.POOL_RECORDER_CAPACITY, 2048),
        recorder_window_ms=config.get_time_ms(keys.POOL_RECORDER_WINDOW_MS, 60_000),
        recorder_series_file=config.get(keys.POOL_RECORDER_SERIES_FILE) or None,
        chaos=ChaosContext.from_config(config, identity="pool"),
    )
    svc.start()
    host, port = svc.address
    if args.info_file:
        tmp = args.info_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"host": host, "port": port}, f)
        os.replace(tmp, args.info_file)
    obs_logging.info(f"[tony-pool] serving on {host}:{port}")
    done = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: done.set())
    signal.signal(signal.SIGINT, lambda *_: done.set())
    done.wait()
    svc.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
