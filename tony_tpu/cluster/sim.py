"""Discrete-event scheduler simulator: prove the pool policy, no TPUs needed.

``tony sim`` replays thousands of seeded synthetic job arrivals against the
EXACT :class:`~tony_tpu.cluster.policy.PreemptionPolicy` the live
``PoolService`` runs (cluster/pool.py imports the same class — a parity test
greps for re-divergence), with a virtual clock injected so a 10-hour trace
simulates in milliseconds. The indexed policy (the default) runs over a
persistent :class:`~tony_tpu.cluster.policy.WorldIndex` fed by the event
handlers — the same cross-pass incrementality the live pool uses — and
``tony sim --parity`` replays every mix through BOTH the indexed and the
kept :class:`~tony_tpu.cluster.policy.ReferencePolicy`, diffing decision
traces event-by-event (docs/scheduling.md "Parity mode"). After every event the simulator asserts the
invariants that make the policy's fairness PROVABLE rather than anecdotal
(docs/scheduling.md):

- **no-oversubscription** — admitted demand claims never exceed pool
  capacity, in any dimension, at any instant;
- **no-starvation** — every job eventually completes (the run ends with an
  empty pool; a livelocked policy would leave waiters forever);
- **share-restoration** — an under-share head whose demand fits its own
  guarantee is admitted within ``grace + drain`` of starting to wait (plus
  one decision latency), preemption enabled;
- **eviction-budget** — a queue never causes more evictions/shrinks per
  rolling window than ``tony.pool.preemption.budget`` allows, and no single
  admission evicts more apps than were admitted at decision time;
- **work-conservation** — the pool is never left idle while a waiter's
  demand fits the EMPTY pool (modulo the share gate, which the policy loop
  discharges by construction).

The simulated world mirrors the live pool's semantics: claims move at
eviction time while physical occupancy frees only when the victim actually
dies (drain deadline, or earlier if the victim is cooperative); a
cooperative victim checkpoints at yield time and loses nothing, a
non-cooperative one is killed at the deadline and replays the work since its
last periodic checkpoint (the ``restart_rework`` the goodput ledger meters);
an elastic victim asked to shrink sheds workers after a short rebuild and
keeps running at reduced size.
"""

from __future__ import annotations

import heapq
import json
import random
import zlib
from dataclasses import dataclass, field
from typing import Any

from tony_tpu.cluster.policy import (
    AppView,
    Decision,
    PreemptionPolicy,
    Vec,
    WorldIndex,
    make_policy,
)
from tony_tpu.cluster.recorder import FlightRecorder


@dataclass
class SimJob:
    """One synthetic arrival."""

    app_id: str
    queue: str
    arrival_s: float
    work_s: float                      # productive seconds to complete
    demand: Vec
    priority: int = 0
    cooperative: bool = True           # yields (with a checkpoint) inside the drain
    checkpoint_every_s: float = 60.0   # periodic checkpoint cadence (kill-path rework)
    elastic_unit: Vec = (0, 0, 0)
    elastic_slack: int = 0


@dataclass
class _JobState:
    job: SimJob
    view: AppView
    remaining_s: float
    arrived: bool = False
    started_at: float | None = None    # running since (None → not occupying)
    expected_done_at: float = -1.0     # stale-completion fence across evictions
    checkpointed_s: float = 0.0        # work safely on disk
    wait_started: float | None = None
    #: since when the share-restoration contract has CONTINUOUSLY applied to
    #: this app (queue head, within guarantee, deficit covered by other
    #: queues' over-share borrowing) — the invariant's clock
    restorable_since: float | None = None
    waited_total_s: float = 0.0
    evictions: int = 0
    shrinks: int = 0
    rework_s: float = 0.0
    done_at: float | None = None
    dying_until: float | None = None   # evicted: physical release at this time


@dataclass
class SimReport:
    seed: int
    jobs: int
    completed: int
    violations: list[str] = field(default_factory=list)
    evictions: int = 0
    evictions_cooperative: int = 0
    evictions_killed: int = 0
    shrinks: int = 0
    total_rework_s: float = 0.0
    max_wait_s: float = 0.0
    wall_s: float = 0.0
    utilization: float = 0.0           # busy primary-capacity-seconds / total

    def ok(self) -> bool:
        return not self.violations and self.completed == self.jobs

    def to_dict(self) -> dict[str, Any]:
        return dict(self.__dict__)


class PoolSimulator:
    """Event-driven replay of arrivals/completions/evictions/drains against
    the shared policy. All times are virtual seconds from 0."""

    def __init__(
        self,
        queues: dict[str, float],
        totals: Vec,
        *,
        preemption: bool = True,
        grace_ms: int = 0,
        drain_ms: int = 5_000,
        min_runtime_ms: int = 0,
        eviction_budget: int = 0,
        budget_window_ms: int = 60_000,
        coop_yield_s: float = 1.0,      # a cooperative victim's checkpoint+yield latency
        shrink_rebuild_s: float = 2.0,  # an elastic victim's shed/rebuild latency
        seed: int = 0,
        policy_impl: str = "indexed",   # tony.pool.scheduler.indexed spelling
        record_trace: bool = False,     # collect per-event decision traces (--parity)
        record_decisions: bool = False,  # attach a FlightRecorder (tony sim --explain)
        verify_index: bool = False,     # audit WorldIndex vs brute force per event
    ):
        self.now = 0.0
        self.queues = dict(queues)
        self.totals = totals
        self.drain_s = drain_ms / 1000.0
        self.grace_s = grace_ms / 1000.0
        self.coop_yield_s = coop_yield_s
        self.shrink_rebuild_s = shrink_rebuild_s
        self.eviction_budget = eviction_budget
        self.budget_window_ms = budget_window_ms
        self.policy = make_policy(
            policy_impl,
            queues,
            preemption=preemption,
            grace_ms=grace_ms,
            min_runtime_ms=min_runtime_ms,
            eviction_budget=eviction_budget,
            budget_window_ms=budget_window_ms,
            clock=lambda: self.now,
        )
        self.policy_impl = policy_impl
        # the indexed policy runs over a PERSISTENT world the event handlers
        # feed deltas — the same cross-pass incrementality the live pool
        # uses, exercised here under thousands of seeded arrival/eviction/
        # shed/death transitions (and audited brute-force per event when
        # ``verify_index`` is set)
        self._world: WorldIndex | None = (
            WorldIndex() if policy_impl == "indexed" else None
        )
        # decision provenance (docs/scheduling.md "Explaining decisions"):
        # the SAME FlightRecorder class the live pool attaches, driven on the
        # virtual clock — an offline what-if run and the production pool emit
        # diffable DecisionRecord streams. Indexed only: the reference oracle
        # is deliberately uninstrumented (cluster/policy.py sink contract).
        self.recorder: FlightRecorder | None = None
        if record_decisions and policy_impl == "indexed":
            self.recorder = FlightRecorder(clock=lambda: self.now)
            self.policy.sink = self.recorder
        self.verify_index = verify_index
        self.record_trace = record_trace
        #: (event_no, event kind, event app, virtual now, admits, evicts,
        #: shrinks) per non-empty decision — what ``tony sim --parity`` diffs
        self.trace: list[tuple] = []
        self._event_no = 0
        self.seed = seed
        self._events: list[tuple[float, int, str, str]] = []  # (t, seq, kind, app_id)
        self._seq = 0
        self._jobs: dict[str, _JobState] = {}
        # arrived-and-unfinished jobs: the per-event working set (the policy
        # views and the invariant sweeps must not rescan thousands of done
        # or future jobs on every event)
        self._active: dict[str, _JobState] = {}
        self._tick_pending = False
        self._stagnant_ticks = 0
        self._charge_log: list[tuple[float, str]] = []        # (t, aggressor queue)
        self.report = SimReport(seed=seed, jobs=0, completed=0)
        self._busy_primary_s = 0.0
        self._last_t = 0.0

    # ------------------------------------------------------------- plumbing
    def _push(self, t: float, kind: str, app_id: str) -> None:
        self._seq += 1
        heapq.heappush(self._events, (t, self._seq, kind, app_id))

    @property
    def _primary(self) -> int:
        return 2 if self.totals[2] > 0 else 0

    def _occupancy(self) -> Vec:
        """Physical usage: running jobs plus evicted-but-not-yet-dead ones
        (their containers still hold nodes, exactly like the live pool)."""
        used = [0, 0, 0]
        for st in self._active.values():
            if st.started_at is not None or st.dying_until is not None:
                for i in range(3):
                    used[i] += st.view.held[i]
        return tuple(used)  # type: ignore[return-value]

    def _accrue_busy(self, t: float) -> None:
        self._busy_primary_s += self._occupancy()[self._primary] * (t - self._last_t)
        self._last_t = t

    # ------------------------------------------------------------ lifecycle
    def run(self, jobs: list[SimJob], horizon_s: float = 10_000_000.0) -> SimReport:
        self.report.jobs = len(jobs)
        for j in jobs:
            self._jobs[j.app_id] = _JobState(
                job=j,
                view=AppView(
                    app_id=j.app_id, queue=j.queue, priority=j.priority,
                    demand=j.demand, elastic_unit=j.elastic_unit,
                    elastic_slack=j.elastic_slack,
                ),
                remaining_s=j.work_s,
            )
            self._push(j.arrival_s, "arrive", j.app_id)
        while self._events:
            t, _, kind, app_id = heapq.heappop(self._events)
            if t > horizon_s:
                self.report.violations.append(
                    f"horizon exceeded at {t:.0f}s with {kind}:{app_id} pending")
                break
            self._accrue_busy(t)
            self.now = t
            self._event_no += 1
            self._cur_event = (kind, app_id)
            if kind == "tick":
                self._stagnant_ticks += 1
                if self._stagnant_ticks > 600:
                    # ten virtual minutes of ticks with no other event: the
                    # remaining waiters are starved/livelocked — report it
                    # instead of simulating to the horizon
                    self.report.violations.append(
                        f"livelock: no progress for {self._stagnant_ticks} "
                        f"consecutive ticks at t={self.now:.0f}s")
                    break
            else:
                self._stagnant_ticks = 0
            getattr(self, f"_on_{kind}")(app_id)
            if not self._schedule().empty():
                self._stagnant_ticks = 0  # a tick that admitted IS progress
            self._check_invariants()
            if self._world is not None and self.verify_index:
                errs = self._world.audit(self._policy_views())
                if errs:
                    self.report.violations.append(
                        f"index inconsistency at t={self.now:.1f}s "
                        f"({kind}:{app_id}): " + "; ".join(errs[:5]))
            # the live pool re-runs admission on every AM allocate retry; the
            # sim's analog is a 1 Hz tick while anyone waits, so decisions
            # deferred by grace / minimum-runtime protection / a draining
            # victim are revisited instead of waiting for the next arrival
            if not self._tick_pending and any(
                not st.view.admitted for st in self._active.values()
            ):
                self._tick_pending = True
                self._push(self.now + 1.0, "tick", "")
        self.report.wall_s = self.now
        self.report.completed = sum(
            1 for st in self._jobs.values() if st.done_at is not None)
        if self.report.completed != self.report.jobs:
            stuck = sorted(
                st.view.app_id for st in self._jobs.values() if st.done_at is None)
            self.report.violations.append(
                f"starvation: {len(stuck)} job(s) never completed: {stuck[:5]}...")
        total = self.totals[self._primary] * max(self.now, 1e-9)
        self.report.utilization = round(self._busy_primary_s / total, 4)
        self.report.total_rework_s = round(
            sum(st.rework_s for st in self._jobs.values()), 3)
        self.report.max_wait_s = round(
            max((st.waited_total_s for st in self._jobs.values()), default=0.0), 3)
        return self.report

    # ------------------------------------------------------------ event handlers
    def _on_arrive(self, app_id: str) -> None:
        st = self._jobs[app_id]
        st.arrived = True
        self._active[app_id] = st
        # arrival order IS the FIFO order — and seqs are UNIQUE per app,
        # like the pool's itertools.count (two same-instant arrivals used to
        # share the push counter's value, leaving their relative order to
        # the accident of list position)
        self._seq += 1
        st.view.seq = self._seq
        st.view.wait_since = self.now
        st.wait_started = self.now
        if self._world is not None:
            self._world.adopt(st.view)

    def _on_tick(self, app_id: str) -> None:
        self._tick_pending = False  # the run loop's _schedule does the work

    def _on_complete(self, app_id: str) -> None:
        st = self._jobs[app_id]
        if (
            st.started_at is None
            or st.done_at is not None
            or abs(self.now - st.expected_done_at) > 1e-6
        ):
            return  # stale completion (the job was evicted and resumed since)
        st.remaining_s = 0.0
        st.done_at = self.now
        st.started_at = None
        if self._world is not None:
            self._world.remove(app_id)  # before the flags flip: still admitted
        st.view.admitted = False
        st.view.held = (0, 0, 0)
        self._active.pop(app_id, None)

    def _on_die(self, app_id: str) -> None:
        """An evicted victim's containers actually exit: cooperative yield
        (checkpoint fresh, no rework) or deadline kill (replay since the last
        periodic checkpoint)."""
        st = self._jobs[app_id]
        if st.dying_until is None:
            return  # already dead (or finished) — stale event
        cooperative = st.job.cooperative and self.drain_s >= self.coop_yield_s
        if cooperative:
            self.report.evictions_cooperative += 1
        else:
            self.report.evictions_killed += 1
            done = st.job.work_s - st.remaining_s
            ck = st.job.checkpoint_every_s
            checkpointed = (done // ck) * ck if ck > 0 else 0.0
            lost = done - max(checkpointed, st.checkpointed_s)
            st.remaining_s += lost
            st.rework_s += lost
        st.dying_until = None
        st.view.held = (0, 0, 0)
        if self._world is not None and st.done_at is None:
            if st.view.app_id in self._world.views:
                # evicted and re-admitted in one pass: it never left the
                # world — only its physical holdings just vanished
                self._world.reaccount(st.view)
            else:
                # the victim's containers are gone: it re-enters the
                # policy's world as an ordinary waiter (it left at
                # eviction time)
                self._world.adopt(st.view)

    def _on_shed(self, app_id: str) -> None:
        """An elastic victim finishes its shrink rebuild: physical occupancy
        drops to the reduced demand; the job keeps running (slower —
        remaining work scales with the lost workers)."""
        st = self._jobs[app_id]
        if st.started_at is None or st.done_at is not None:
            return  # was evicted whole (or finished) before the shed landed
        # bank the progress of the current run segment before rescaling
        st.remaining_s = max(st.remaining_s - (self.now - st.started_at), 0.0)
        old = st.view.held
        new = st.view.demand  # reduced by the policy at shrink time
        if old[self._primary] > 0 and new[self._primary] > 0:
            st.remaining_s *= old[self._primary] / new[self._primary]
        st.view.held = new
        st.view.shrink_pending = False
        st.shrinks += 1
        self.report.shrinks += 1
        if self._world is not None:
            self._world.reaccount(st.view)  # held dropped to the shed size
        self._reschedule_completion(st)

    # ------------------------------------------------------------ scheduling
    def _reschedule_completion(self, st: _JobState) -> None:
        st.started_at = self.now
        st.expected_done_at = self.now + st.remaining_s
        self._push(st.expected_done_at, "complete", st.view.app_id)

    def _policy_views(self) -> list[AppView]:
        """The views the policy decides over: everything arrived-and-alive
        except evicted-but-still-dying waiters (their claims moved at
        eviction; their demand re-queues only once the containers die)."""
        return [
            st.view for st in self._active.values()
            if st.view.admitted or st.dying_until is None
        ]

    def _schedule(self) -> Decision:
        if self._world is not None:
            decision = self.policy.schedule_world(self._world, self.totals)
        else:
            decision = self.policy.schedule(self._policy_views(), self.totals)
        if self.record_trace and not decision.empty():
            kind, app_id = self._cur_event
            self.trace.append((
                self._event_no, kind, app_id, round(self.now, 6),
                tuple(decision.admit),
                tuple((e.app_id, e.for_app) for e in decision.evict),
                tuple((s.app_id, s.workers, s.for_app) for s in decision.shrink),
            ))
        for sh in decision.shrink:
            self._charge_log.append((self.now, self._jobs[sh.for_app].view.queue))
            self._push(self.now + self.shrink_rebuild_s, "shed", sh.app_id)
        for ev in decision.evict:
            st = self._jobs[ev.app_id]
            self.report.evictions += 1
            st.evictions += 1
            self._charge_log.append((self.now, self._jobs[ev.for_app].view.queue))
            if st.started_at is not None:
                st.remaining_s = max(st.remaining_s - (self.now - st.started_at), 0.0)
            st.started_at = None
            # cooperative victims yield (checkpoint fresh) well inside the
            # drain; non-cooperative ones occupy nodes until the deadline
            coop = st.job.cooperative and self.drain_s >= self.coop_yield_s
            death = self.now + (min(self.coop_yield_s, self.drain_s) if coop else self.drain_s)
            if coop:
                st.checkpointed_s = st.job.work_s - st.remaining_s
            st.dying_until = death
            st.wait_started = self.now
            if self._world is not None and not st.view.admitted:
                # a dying victim is outside the policy's world until its
                # containers actually exit (_on_die re-adopts it). The guard
                # matters: one decision may evict an app for one head and
                # RE-ADMIT it later in the same pass (an overshooting
                # preemption refits it) — the final state is admitted, and
                # the membership rule (admitted or not-dying) keeps it in
                self._world.remove(ev.app_id)
            self._push(death, "die", ev.app_id)
        for app_id in decision.admit:
            st = self._jobs[app_id]
            if st.wait_started is not None:
                st.waited_total_s += self.now - st.wait_started
                st.wait_started = None
            # physical start: the sim starts work immediately on admission
            # (claims == occupancy for the admittee; a dying victim's nodes
            # overlap transiently, exactly like the live pool's drain)
            st.view.held = st.view.demand
            if self._world is not None:
                self._world.reaccount(st.view)
            self._reschedule_completion(st)
        return decision

    # ------------------------------------------------------------ invariants
    def _check_invariants(self) -> None:
        rep = self.report
        # 1. admitted demand claims never oversubscribe capacity
        admitted_active = [st for st in self._active.values() if st.view.admitted]
        for i in range(3):
            claimed = sum(st.view.demand[i] for st in admitted_active)
            if claimed > self.totals[i]:
                rep.violations.append(
                    f"oversubscription at t={self.now:.1f}s dim {i}: "
                    f"{claimed} > {self.totals[i]}")
        # 2. share-restoration: a waiting QUEUE HEAD within its guarantee,
        # whose deficit is covered by other queues' over-share borrowing,
        # is admitted within grace + drain + min-runtime protection (+ one
        # coop yield and one sim decision tick). The clock runs only while
        # the condition holds CONTINUOUSLY — waiting behind one's own queue,
        # or on queues within their shares, is legitimate queueing, not a
        # broken guarantee.
        bound = (
            self.grace_s + self.drain_s + self.coop_yield_s
            + self.policy.min_runtime_ms / 1000.0 + 2.0
        )
        if self.policy.preemption and self.eviction_budget <= 0:
            p = self._primary
            active = list(self._active.values())
            used_by_q = {q: 0 for q in self.queues}
            for st in active:
                if st.view.admitted:
                    used_by_q[st.view.queue] = (
                        used_by_q.get(st.view.queue, 0) + st.view.claim()[p])
            free_p = self.totals[p] - sum(used_by_q.values())
            excess_elsewhere = {
                q: sum(
                    max(used_by_q.get(qq, 0) - self.queues[qq] * self.totals[p], 0)
                    for qq in self.queues if qq != q
                )
                for q in self.queues
            }
            heads: dict[str, _JobState] = {}
            for st in sorted(active, key=lambda s: s.view.sort_key):
                if not st.view.admitted and st.dying_until is None:
                    heads.setdefault(st.view.queue, st)
            head_set = set(id(h) for h in heads.values())
            for st in active:
                if id(st) not in head_set:
                    st.restorable_since = None
                    continue
                q = st.view.queue
                d = st.view.demand[p]
                restorable = (
                    used_by_q.get(q, 0) + d <= self.queues[q] * self.totals[p] + 1e-9
                    and free_p + excess_elsewhere[q] >= d
                    and free_p < d  # a head that plainly fits is invariant 5's job
                )
                if not restorable:
                    st.restorable_since = None
                elif st.restorable_since is None:
                    st.restorable_since = self.now
                elif self.now - st.restorable_since > bound:
                    rep.violations.append(
                        f"share-restoration: head {st.view.app_id} of {q!r} "
                        f"(under-share, deficit reclaimable) waited "
                        f"{self.now - st.restorable_since:.1f}s > bound {bound:.1f}s")
                    st.restorable_since = None  # report once per episode
        # 3. eviction budget respected per rolling window
        if self.eviction_budget > 0:
            window = self.budget_window_ms / 1000.0
            for q in self.queues:
                recent = [t for t, qq in self._charge_log if qq == q and self.now - t < window]
                if len(recent) > self.eviction_budget:
                    rep.violations.append(
                        f"budget: queue {q!r} caused {len(recent)} disruptions "
                        f"inside {window:.0f}s (budget {self.eviction_budget})")
        # 4. work conservation: never idle while a waiter fits the EMPTY pool
        # and nothing is still draining toward it
        dying = [st for st in self._active.values() if st.dying_until is not None]
        if not admitted_active and not dying:
            for st in self._active.values():
                if st.wait_started is not None and all(
                    d <= t for d, t in zip(st.view.demand, self.totals)
                ):
                    rep.violations.append(
                        f"work-conservation: pool idle at t={self.now:.1f}s while "
                        f"{st.view.app_id} (fits empty pool) waits")
                    break


# ---------------------------------------------------------------------------
# seeded synthetic workload mixes (tony sim --mix ...)
# ---------------------------------------------------------------------------
GB = 1024 ** 3

MIXES = ("batch", "bursty", "elastic", "priority")

#: capacity-market mixes run through :class:`MarketSimulator`, not
#: :class:`PoolSimulator` — deliberately NOT in ``MIXES``: the parity
#: contract (tony sim --parity, tests/test_policy_parity.py) replays MIXES
#: through both policy implementations, and the market passes
#: (fund_demand / plan_growback) are indexed-only by design.
MARKET_MIXES = ("serve-train",)


def generate_jobs(
    mix: str, n: int, queues: dict[str, float], seed: int
) -> list[SimJob]:
    """``n`` seeded arrivals shaped by the named mix. Deterministic per
    (mix, n, queues, seed) ACROSS processes — the whole point is
    reproducible counterexamples, and ``hash()`` is salted per interpreter."""
    rng = random.Random((zlib.crc32(mix.encode()) & 0xFFFF) * 1_000_003 + seed)
    qnames = sorted(queues)
    jobs: list[SimJob] = []
    t = 0.0
    # every mix targets an offered load of ~0.7-0.85 of the default 8 GB
    # pool: a stable system whose queues form and drain — a permanently
    # overloaded pool has unbounded waits by arithmetic, not by policy bug
    for i in range(n):
        if mix == "batch":
            t += rng.expovariate(1 / 10.0)
            work = rng.uniform(10, 50)
            demand = (rng.choice([1, 2, 3]) * GB, rng.choice([1, 2]), 0)
            prio, elastic = 0, False
        elif mix == "bursty":
            # arrival bursts: long quiet stretches then 5-15 jobs at once
            if i % rng.randint(5, 15) == 0:
                t += rng.expovariate(1 / 90.0)
            work = rng.uniform(5, 30)
            demand = (rng.choice([1, 2, 4]) * GB, 1, 0)
            prio, elastic = rng.choice([0, 0, 0, 5]), False
        elif mix == "elastic":
            t += rng.expovariate(1 / 20.0)
            work = rng.uniform(20, 60)
            workers = rng.choice([2, 4])
            demand = (workers * GB, workers, 0)
            prio, elastic = 0, rng.random() < 0.6
        elif mix == "priority":
            t += rng.expovariate(1 / 6.0)
            work = rng.uniform(10, 40)
            demand = (rng.choice([1, 2]) * GB, 1, 0)
            prio, elastic = rng.choice([0, 1, 5, 9]), False
        else:
            raise ValueError(f"unknown mix {mix!r} (choose from {MIXES})")
        queue = rng.choice(qnames)
        unit = (GB, 1, 0) if elastic else (0, 0, 0)
        slack = (demand[0] // GB - 1) if elastic else 0
        jobs.append(SimJob(
            app_id=f"{mix}-{i:05d}",
            queue=queue,
            arrival_s=round(t, 3),
            work_s=round(work, 3),
            demand=demand,
            priority=prio,
            cooperative=rng.random() < 0.8,
            checkpoint_every_s=rng.choice([30.0, 60.0, 120.0]),
            elastic_unit=unit,
            elastic_slack=int(slack),
        ))
    return jobs


def run_mix(
    mix: str,
    n: int = 1000,
    *,
    queues: dict[str, float] | None = None,
    # vcores deliberately ample: queue shares guarantee the PRIMARY dimension
    # (memory here, chips on a TPU pool) — a workload that binds on a
    # secondary dimension is outside the share-restoration contract
    totals: Vec = (8 * GB, 256, 0),
    seed: int = 0,
    preemption: bool = True,
    grace_ms: int = 2_000,
    drain_ms: int = 5_000,
    min_runtime_ms: int = 3_000,
    eviction_budget: int = 0,
    budget_window_ms: int = 60_000,
    policy_impl: str = "indexed",
) -> SimReport:
    """One seeded simulation over ``n`` arrivals of the named mix — the unit
    tier-1 asserts invariants over, and what ``tony sim`` wraps."""
    queues = queues or {"prod": 0.6, "dev": 0.4}
    sim = PoolSimulator(
        queues, totals,
        preemption=preemption, grace_ms=grace_ms, drain_ms=drain_ms,
        min_runtime_ms=min_runtime_ms, eviction_budget=eviction_budget,
        budget_window_ms=budget_window_ms, seed=seed,
        policy_impl=policy_impl,
    )
    return sim.run(generate_jobs(mix, n, queues, seed))


# ---------------------------------------------------------------------------
# the serve/train capacity market (tony sim --mix serve-train)
# ---------------------------------------------------------------------------
@dataclass
class MarketSpike:
    """One serve traffic spike: the autoscaler wants ``replicas`` extra
    replicas from ``start_s`` until ``end_s``."""

    start_s: float
    end_s: float
    replicas: int
    funded_at: float | None = None     # first instant the whole deficit placed


@dataclass
class MarketReport:
    """What a seeded serve-train market run proved (or violated)."""

    seed: int
    spikes: int = 0
    shed_workers: int = 0              # workers shed under rule demand-spike
    growback_workers: int = 0          # workers returned under rule grow-back
    evictions: int = 0                 # whole-gang evictions — MUST stay 0
    max_fund_latency_s: float = 0.0    # slowest spike start → fully placed
    badput_fraction: float = 0.0       # gang seconds lost to shed/grow churn
    restored_all: bool = False         # every gang back at full size by the end
    wall_s: float = 0.0
    violations: list[str] = field(default_factory=list)

    def ok(self) -> bool:
        return not self.violations

    def to_dict(self) -> dict[str, Any]:
        return dict(self.__dict__)


class MarketSimulator:
    """The serve/train capacity market on a virtual clock.

    Co-tenants one serve head (queue ``serve``) with elastic training gangs
    (queue ``train``, borrowing over their share) on a fixed pool, then
    replays a seeded spike schedule through the EXACT market passes the
    live pool runs — :meth:`PreemptionPolicy.fund_demand` when the serve
    deficit is published and :meth:`PreemptionPolicy.plan_growback` once
    demand has ebbed for the hysteresis window — with the same physics the
    event simulator uses: a funded shed frees physical capacity only after
    the drain lands, a grow-back is a gang rebuild, and every disruption is
    metered as badput. The invariants it asserts are the market's contract
    (docs/scheduling.md "Capacity market"):

    - **SLO-capacity** — every spike's deficit is fully placed within
      drain + a few decision ticks, and never clawed back mid-spike;
    - **zero evictions** — the market only ever shrinks; no training gang
      is whole-gang evicted, and none digs below its elastic floor;
    - **bounded badput** — gang seconds lost to shed/grow churn stay under
      a fraction of total gang seconds;
    - **gangs restored** — after the final ebb, every gang is offered its
      shed workers back and returns to full size within the ebb window
      plus one rebuild.
    """

    def __init__(
        self,
        queues: dict[str, float] | None = None,
        totals: Vec = (16 * GB, 256, 0),
        *,
        seed: int = 0,
        drain_s: float = 5.0,           # shed decision → capacity actually free
        rebuild_s: float = 2.0,         # gang restart cost (shed land / grow land)
        coop_yield_s: float = 1.0,      # urgent-checkpoint + yield latency
        ebb_s: float = 20.0,            # grow-back hysteresis (quiet window)
        growback_step: int = 0,         # workers per grow offer (0 = all owed)
        min_runtime_ms: int = 3_000,
        eviction_budget: int = 0,
        budget_window_ms: int = 60_000,
        record_decisions: bool = False,
    ):
        self.now = 0.0
        self.queues = dict(queues or {"serve": 0.7, "train": 0.3})
        self.totals = totals
        self.seed = seed
        self.drain_s = drain_s
        self.rebuild_s = rebuild_s
        self.coop_yield_s = coop_yield_s
        self.ebb_s = ebb_s
        self.growback_step = growback_step
        self.policy = make_policy(
            "indexed", self.queues, preemption=True, grace_ms=0,
            min_runtime_ms=min_runtime_ms, eviction_budget=eviction_budget,
            budget_window_ms=budget_window_ms, clock=lambda: self.now,
        )
        self.world = WorldIndex()
        self.recorder: FlightRecorder | None = None
        if record_decisions:
            self.recorder = FlightRecorder(clock=lambda: self.now)
            self.policy.sink = self.recorder
        self.report = MarketReport(seed=seed)

    # ------------------------------------------------------------- plumbing
    def _phys_free(self) -> list[int]:
        used = [0, 0, 0]
        for v in self.world.views.values():
            for i in range(3):
                used[i] += v.held[i]
        return [t - u for t, u in zip(self.totals, used)]

    # ------------------------------------------------------------ lifecycle
    def run(
        self,
        *,
        gangs: int = 2,
        gang_workers: int = 5,
        gang_floor: int = 2,
        serve_base: int = 2,
        n_spikes: int = 3,
    ) -> MarketReport:
        rng = random.Random(
            (zlib.crc32(b"serve-train") & 0xFFFF) * 1_000_003 + self.seed)
        rep = self.report
        # feasibility: the fixed co-tenancy (gangs at full size + serve base
        # + the largest possible spike funded by every shed) must fit the
        # pool, or the invariants are violated by arithmetic, not by policy
        worst = (
            gangs * gang_floor * GB                      # gangs at their floors
            + (serve_base + 4) * 2 * GB                  # serve at max spike
        )
        if worst > self.totals[0]:
            raise ValueError(
                f"pool too small for the market scenario: needs "
                f">= {worst / GB:.0f} GiB memory, has {self.totals[0] / GB:.0f}")
        # seeded spike schedule: bursts spaced so each has room to fund,
        # ebb, and grow back before the next one tests the thrash guards
        spikes: list[MarketSpike] = []
        t = rng.uniform(20, 40)
        for _ in range(n_spikes):
            dur = rng.uniform(30, 60)
            spikes.append(MarketSpike(
                start_s=round(t, 1), end_s=round(t + dur, 1),
                replicas=rng.randint(2, min(4, gangs * (gang_workers - gang_floor) // 2)),
            ))
            t += dur + self.ebb_s + rng.uniform(40, 70)
        horizon = t + self.ebb_s + 120
        rep.spikes = len(spikes)
        # the co-tenants: elastic train gangs borrowing over their share,
        # and the serve head at its base fleet — all admitted and running
        gang_state: dict[str, dict[str, Any]] = {}
        for g in range(gangs):
            view = AppView(
                app_id=f"train-{g}", queue="train", priority=0, seq=g + 1,
                demand=(gang_workers * GB, gang_workers, 0),
                elastic_unit=(GB, 1, 0),
                elastic_slack=gang_workers - gang_floor,
                admitted=True,
            )
            view.held = view.demand
            self.world.adopt(view)
            gang_state[view.app_id] = {
                "view": view, "workers": gang_workers, "badput_s": 0.0,
                "restored_at": None,
            }
        serve_unit: Vec = (2 * GB, 1, 0)
        serve = AppView(
            app_id="serve-head", queue="serve", priority=5, seq=1000,
            demand=tuple(serve_base * u for u in serve_unit),  # type: ignore[arg-type]
            admitted=True,
        )
        serve.held = serve.demand
        self.world.adopt(serve)
        placed = serve_base

        pending_sheds: list[tuple[float, Any]] = []      # (land_at, Shrink)
        pending_grows: list[tuple[float, str, int]] = []  # (land_at, app, k)
        debt: dict[str, int] = {}                         # grow-back ledger
        debt_since: dict[str, float] = {}
        grown_at: dict[str, float] = {}
        quiet_since: float | None = 0.0
        last_end = spikes[-1].end_s

        step = 0.0
        while step <= horizon:
            self.now = step
            # 1. land sheds whose drains expired: physical capacity frees,
            # the gang rebuilds at the reduced size (badput: yield + rebuild)
            for land_at, sh in [p for p in pending_sheds if p[0] <= step]:
                pending_sheds.remove((land_at, sh))
                st = gang_state[sh.app_id]
                v = st["view"]
                v.held = v.demand            # demand was reduced at decision
                v.shrink_pending = False
                self.world.reaccount(v)
                st["workers"] -= sh.workers
                st["badput_s"] += self.coop_yield_s + self.rebuild_s
                st["restored_at"] = None
                debt[sh.app_id] = debt.get(sh.app_id, 0) + sh.workers
                debt_since.setdefault(sh.app_id, step)
                rep.shed_workers += sh.workers
            # 2. land accepted grow offers: the gang restarts at the grown
            # size (one rebuild of badput) and its debt settles
            for land_at, app_id, k in [p for p in pending_grows if p[0] <= step]:
                pending_grows.remove((land_at, app_id, k))
                st = gang_state[app_id]
                v = st["view"]
                v.demand = tuple(
                    d + k * u for d, u in zip(v.demand, v.elastic_unit))  # type: ignore[assignment]
                v.held = v.demand
                v.elastic_slack += k
                self.world.reaccount(v)
                st["workers"] += k
                st["badput_s"] += self.rebuild_s
                grown_at[app_id] = step
                debt[app_id] -= k
                if debt[app_id] <= 0:
                    debt.pop(app_id)
                    debt_since.pop(app_id, None)
                rep.growback_workers += k
                if st["workers"] == gang_workers:
                    st["restored_at"] = step
            # 3. the serve autoscaler's view: wanted replicas follow the
            # spike schedule; scale-down at spike end is immediate (removing
            # a replica needs no new capacity)
            active = next(
                (s for s in spikes if s.start_s <= step < s.end_s), None)
            wanted = serve_base + (active.replicas if active else 0)
            if placed > wanted:
                placed = wanted
                serve.demand = tuple(placed * u for u in serve_unit)  # type: ignore[assignment]
                serve.held = serve.demand
                self.world.reaccount(serve)
            # place replicas into whatever physically fits (the AM's
            # retrying allocate): this is what consumes funded capacity
            free = self._phys_free()
            while placed < wanted and all(
                u <= f for u, f in zip(serve_unit, free)
            ):
                placed += 1
                serve.demand = tuple(placed * u for u in serve_unit)  # type: ignore[assignment]
                serve.held = serve.demand
                self.world.reaccount(serve)
                free = [f - u for f, u in zip(free, serve_unit)]
            deficit = wanted - placed
            if active and deficit == 0 and active.funded_at is None:
                active.funded_at = step
                rep.max_fund_latency_s = max(
                    rep.max_fund_latency_s, step - active.start_s)
            if active and deficit > 0 and active.funded_at is not None:
                rep.violations.append(
                    f"SLO-capacity: spike at {active.start_s:.0f}s funded at "
                    f"{active.funded_at:.0f}s then clawed back at {step:.0f}s")
                active.funded_at = step  # report once
            # 4. publish + fund: the pool-side demand bridge, minus capacity
            # already in flight from earlier sheds (fund once per deficit,
            # not once per retry tick)
            if deficit > 0:
                quiet_since = None
                need = [deficit * u for u in serve_unit]
                for _, sh in pending_sheds:
                    unit = gang_state[sh.app_id]["view"].elastic_unit
                    for i in range(3):
                        need[i] -= sh.workers * unit[i]
                need = tuple(max(x, 0) for x in need)
                if any(need):
                    decision = self.policy.fund_demand(
                        self.world, self.totals, self._phys_free(),
                        app_id=serve.app_id, queue=serve.queue, need=need,
                        grown_at=grown_at,
                    )
                    rep.evictions += len(decision.evict)
                    if decision.admit or decision.evict:
                        rep.violations.append(
                            f"market pass admitted/evicted at t={step:.0f}s: "
                            f"{decision.admit} {decision.evict}")
                    for sh in decision.shrink:
                        pending_sheds.append((step + self.drain_s, sh))
            elif quiet_since is None:
                quiet_since = step
            # 5. grow back once demand has ebbed for the full window
            if (quiet_since is not None and debt
                    and step - quiet_since >= self.ebb_s):
                in_flight = {a for _, a, _ in pending_grows}
                ledger = sorted(
                    (
                        (a, owed, gang_state[a]["view"].elastic_unit)
                        for a, owed in debt.items() if a not in in_flight
                    ),
                    key=lambda e: debt_since.get(e[0], 0.0),
                )
                free = self._phys_free()
                for _, a, k in pending_grows:   # offers hold their capacity
                    unit = gang_state[a]["view"].elastic_unit
                    for i in range(3):
                        free[i] -= k * unit[i]
                for app_id, k in self.policy.plan_growback(
                    self.world, free, ledger, step=self.growback_step,
                ):
                    pending_grows.append((step + self.rebuild_s, app_id, k))
            # 6. per-tick invariants: claims within capacity, floors held
            for i in range(3):
                claimed = sum(
                    v.claim()[i] for v in self.world.views.values())
                if claimed > self.totals[i]:
                    rep.violations.append(
                        f"oversubscription at t={step:.0f}s dim {i}: "
                        f"{claimed} > {self.totals[i]}")
            for app_id, st in gang_state.items():
                if st["view"].demand[1] < gang_floor:
                    rep.violations.append(
                        f"floor: {app_id} dug to {st['view'].demand[1]} "
                        f"< {gang_floor} at t={step:.0f}s")
            step += 1.0

        # ---------------------------------------------------- final verdict
        rep.wall_s = horizon
        for s in spikes:
            if s.funded_at is None:
                rep.violations.append(
                    f"SLO-capacity: spike at {s.start_s:.0f}s "
                    f"({s.replicas} replicas) never fully placed")
        bound = self.drain_s + 4.0  # drain + a few 1 Hz decision ticks
        if rep.max_fund_latency_s > bound:
            rep.violations.append(
                f"SLO-capacity: slowest funding took "
                f"{rep.max_fund_latency_s:.0f}s > bound {bound:.0f}s")
        if rep.evictions:
            rep.violations.append(
                f"{rep.evictions} whole-gang eviction(s): the market must "
                "only ever shrink")
        restore_bound = last_end + self.ebb_s + self.drain_s + self.rebuild_s + 10
        rep.restored_all = all(
            st["workers"] == gang_workers for st in gang_state.values())
        for app_id, st in gang_state.items():
            if st["workers"] != gang_workers:
                rep.violations.append(
                    f"grow-back: {app_id} ended at {st['workers']}/"
                    f"{gang_workers} workers (debt never repaid)")
            elif st["restored_at"] is not None and st["restored_at"] > restore_bound:
                rep.violations.append(
                    f"grow-back: {app_id} restored at {st['restored_at']:.0f}s "
                    f"> bound {restore_bound:.0f}s after the final ebb")
        gang_seconds = gangs * horizon
        rep.badput_fraction = round(
            sum(st["badput_s"] for st in gang_state.values())
            / max(gang_seconds, 1e-9), 4)
        if rep.badput_fraction > 0.25:
            rep.violations.append(
                f"badput fraction {rep.badput_fraction:.2%} > 25% — the "
                "market is churning gangs faster than they do work")
        return rep


def run_market_mix(
    mix: str = "serve-train",
    *,
    seed: int = 0,
    queues: dict[str, float] | None = None,
    totals: Vec = (16 * GB, 256, 0),
    drain_ms: int = 5_000,
    ebb_ms: int = 20_000,
    growback_step: int = 0,
    min_runtime_ms: int = 3_000,
    record_decisions: bool = False,
) -> tuple[MarketReport, FlightRecorder | None]:
    """One seeded serve-train market run — the unit tier-1 asserts the
    market invariants over, and what ``tony sim --mix serve-train`` wraps.
    Deterministic per (seed, knobs) across processes."""
    if mix not in MARKET_MIXES:
        raise ValueError(f"unknown market mix {mix!r} (choose from {MARKET_MIXES})")
    sim = MarketSimulator(
        queues, totals, seed=seed,
        drain_s=drain_ms / 1000.0, ebb_s=ebb_ms / 1000.0,
        growback_step=growback_step, min_runtime_ms=min_runtime_ms,
        record_decisions=record_decisions,
    )
    return sim.run(), sim.recorder


def render_market_report(report: MarketReport, as_json: bool = False) -> str:
    if as_json:
        return json.dumps(report.to_dict(), indent=1)
    lines = [
        f"market sim seed {report.seed}: {report.spikes} spike(s) over "
        f"{report.wall_s:.0f} virtual seconds",
        f"  workers shed to fund spikes (demand-spike): {report.shed_workers}",
        f"  workers returned after ebb (grow-back): {report.growback_workers}",
        f"  whole-gang evictions: {report.evictions}",
        f"  slowest spike funding: {report.max_fund_latency_s:.1f}s",
        f"  gang badput fraction: {report.badput_fraction:.2%}",
        f"  all gangs restored to full size: {report.restored_all}",
    ]
    if report.violations:
        lines.append(f"  MARKET INVARIANT VIOLATIONS ({len(report.violations)}):")
        lines.extend(f"    - {v}" for v in report.violations[:20])
    else:
        lines.append("  market invariants: OK (SLO-capacity, zero evictions, "
                     "bounded badput, gangs restored)")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# indexed ↔ reference parity (tony sim --parity)
# ---------------------------------------------------------------------------
def diff_traces(indexed: list[tuple], reference: list[tuple]) -> str | None:
    """First divergence between two decision traces, rendered for a human
    (None = byte-identical). Each entry is (event_no, event kind, event app,
    virtual t, admits, evicts, shrinks)."""
    for i, (a, b) in enumerate(zip(indexed, reference)):
        if a != b:
            return (
                f"decision #{i} diverges at event {a[0]} ({a[1]}:{a[2]}, "
                f"t={a[3]}s):\n  indexed:   admits={a[4]} evicts={a[5]} shrinks={a[6]}\n"
                f"  reference: event {b[0]} ({b[1]}:{b[2]}, t={b[3]}s) "
                f"admits={b[4]} evicts={b[5]} shrinks={b[6]}"
            )
    if len(indexed) != len(reference):
        longer, name = (indexed, "indexed") if len(indexed) > len(reference) else (reference, "reference")
        e = longer[min(len(indexed), len(reference))]
        return (
            f"trace lengths differ (indexed={len(indexed)} reference={len(reference)}): "
            f"{name} additionally decided at event {e[0]} ({e[1]}:{e[2]}, t={e[3]}s): "
            f"admits={e[4]} evicts={e[5]} shrinks={e[6]}"
        )
    return None


def run_parity(
    mix: str,
    n: int = 1000,
    *,
    queues: dict[str, float] | None = None,
    totals: Vec = (8 * GB, 256, 0),
    seed: int = 0,
    **knobs,
) -> tuple[SimReport, SimReport, str | None]:
    """Replay one seeded mix through the indexed AND the reference policy,
    diffing decision traces event-by-event — the end-to-end half of the
    parity contract (the per-world property suite is
    tests/test_policy_parity.py). Returns (indexed report, reference
    report, first divergence or None)."""
    queues = queues or {"prod": 0.6, "dev": 0.4}
    defaults = dict(
        preemption=True, grace_ms=2_000, drain_ms=5_000, min_runtime_ms=3_000,
        eviction_budget=0, budget_window_ms=60_000,
    )
    defaults.update(knobs)
    traces: dict[str, list[tuple]] = {}
    reports: dict[str, SimReport] = {}
    for impl in ("indexed", "reference"):
        sim = PoolSimulator(
            queues, totals, seed=seed, policy_impl=impl, record_trace=True,
            **defaults,
        )
        reports[impl] = sim.run(generate_jobs(mix, n, queues, seed))
        traces[impl] = sim.trace
    return (
        reports["indexed"],
        reports["reference"],
        diff_traces(traces["indexed"], traces["reference"]),
    )


def render_report(report: SimReport, as_json: bool = False) -> str:
    if as_json:
        return json.dumps(report.to_dict(), indent=1)
    lines = [
        f"sim seed {report.seed}: {report.completed}/{report.jobs} jobs completed "
        f"over {report.wall_s:.0f} virtual seconds",
        f"  utilization (primary dim): {report.utilization:.1%}",
        f"  evictions: {report.evictions} "
        f"({report.evictions_cooperative} cooperative yield, "
        f"{report.evictions_killed} deadline kill), shrinks: {report.shrinks}",
        f"  rework replayed after kills: {report.total_rework_s:.1f}s",
        f"  max wait: {report.max_wait_s:.1f}s",
    ]
    if report.violations:
        lines.append(f"  INVARIANT VIOLATIONS ({len(report.violations)}):")
        lines.extend(f"    - {v}" for v in report.violations[:20])
    else:
        lines.append("  invariants: OK (no-oversubscription, no-starvation, "
                     "share-restoration, eviction-budget, work-conservation)")
    return "\n".join(lines)
