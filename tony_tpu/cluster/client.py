"""Job submission client.

Analog of the reference's ``TonyClient.java`` (SURVEY.md §2.1, §3.1):
``init`` parses CLI + conf layers and freezes ``tony-final``; ``submit``
prepares the per-app staging dir (the ``.tony/<appId>`` HDFS analog), stages
the src dir, and launches the AM (playing YARN-RM-launches-AM: the AM is a
detached subprocess that outlives the client); ``monitor_application`` polls
the AM for task-state transitions and prints them; AM retry re-launches the
whole gang (``tony.am.retry-count``). ``add_listener`` mirrors the reference's
CallbackHandler hook for app-id/URL notifications.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable

from tony_tpu import constants
from tony_tpu.config import TonyConfig, keys
from tony_tpu.cluster.rpc import RpcClient, RpcError
from tony_tpu.cluster.session import JobStatus
from tony_tpu.obs import logging as obs_logging
from tony_tpu.obs import metrics as obs_metrics
from tony_tpu.obs import trace as obs_trace

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@dataclass
class ApplicationHandle:
    app_id: str
    staging_dir: str
    am_process: subprocess.Popen | None = None
    _rpc: RpcClient | None = field(default=None, repr=False)

    @property
    def am_info_path(self) -> str:
        return os.path.join(self.staging_dir, constants.AM_INFO_FILE)

    @property
    def am_status_path(self) -> str:
        return os.path.join(self.staging_dir, "am_status.json")

    def rpc(self, timeout_s: float = 30.0) -> RpcClient | None:
        """Connect to the AM once it has advertised itself (YARN report analog)."""
        if self._rpc is not None:
            return self._rpc
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if os.path.exists(self.am_info_path):
                with open(self.am_info_path) as f:
                    info = json.load(f)
                self._rpc = RpcClient(info["host"], info["port"], secret=info["secret"])
                return self._rpc
            if self.am_process is not None and self.am_process.poll() is not None:
                return None  # AM died before advertising
            time.sleep(0.1)
        return None

    def final_status(self) -> dict[str, Any] | None:
        if os.path.exists(self.am_status_path):
            with open(self.am_status_path) as f:
                return json.load(f)
        return None


class Client:
    """Submission + monitoring front end (TonyClient analog)."""

    def __init__(self, config: TonyConfig):
        self.config = config
        self.listeners: list[Callable[[str, Any], None]] = []

    def add_listener(self, fn: Callable[[str, Any], None]) -> None:
        """fn(event_name, payload); events: app_id, tensorboard_url, task_transition."""
        self.listeners.append(fn)

    def _notify(self, event: str, payload: Any) -> None:
        for fn in self.listeners:
            fn(event, payload)

    # -- submission --------------------------------------------------------
    def submit(self) -> ApplicationHandle:
        if not self.config.job_types():
            raise ValueError("no job types declared (set tony.<type>.instances > 0)")
        app_id = f"application_{int(time.time())}_{uuid.uuid4().hex[:8]}"
        root = self.config.get(keys.STAGING_ROOT) or constants.default_tony_root()
        staging_dir = os.path.join(root, app_id)
        os.makedirs(staging_dir, exist_ok=True)

        # stage user sources (HDFS upload analog)
        src_dir = self.config.get(keys.SRC_DIR)
        if src_dir:
            if not os.path.isdir(src_dir):
                raise FileNotFoundError(f"--src_dir {src_dir} does not exist")
            shutil.copytree(src_dir, os.path.join(staging_dir, "src"), dirs_exist_ok=True)

        # freeze the whole-job config artifact
        if not self.config.frozen:
            self.config.freeze()
        self.config.write_final(staging_dir)

        obs_metrics.set_enabled(self.config.get_bool(keys.METRICS_ENABLED, True))
        # structured logging (tony.log.*): the submitter's records join the
        # job's <staging>/logs aggregate; console output is unchanged (echo)
        obs_logging.init_from_config(self.config, identity="client", staging_dir=staging_dir)
        # tracing (tony.trace.*): the submit span becomes the whole trace's
        # root — the AM links under it via TONY_TRACE_PARENT, executors under
        # the AM, training children under their executor
        tracer = obs_trace.init_from_config(
            self.config, identity="client", staging_dir=staging_dir, app_id=app_id
        )
        submit_span = submit_token = None
        if tracer is not None:
            submit_span, submit_token = tracer.start_span("client.submit", kind="client")
            submit_span.set(app_id=app_id)
            # later client spans (monitor polls) nest under the submit span
            tracer.root_parent = submit_span.span_id

        # launch the AM as a detached process (process boundary #1)
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
        if submit_span is not None:
            env[constants.ENV_TRACE_PARENT] = submit_span.span_id
        with open(os.path.join(staging_dir, "am.log"), "ab") as am_log:
            proc = subprocess.Popen(
                [
                    sys.executable,
                    "-u",
                    "-m",
                    "tony_tpu.cluster.appmaster",
                    "--app-id",
                    app_id,
                    "--staging-dir",
                    staging_dir,
                ],
                env=env,
                stdout=am_log,
                stderr=subprocess.STDOUT,
                start_new_session=True,
            )
        if tracer is not None:
            tracer.end_span(submit_span, submit_token)
        self._notify("app_id", app_id)
        return ApplicationHandle(app_id, staging_dir, proc)

    # -- monitoring --------------------------------------------------------
    def monitor_application(self, handle: ApplicationHandle, quiet: bool = False) -> JobStatus:
        """Poll task transitions until a final status (reference monitor loop)."""
        last_state: dict[str, str] = {}
        tb_reported = False
        am_attempt_seen = 0
        rpc = handle.rpc()
        while True:
            status = handle.final_status()
            if status is not None:
                final = JobStatus(status["status"])
                if not quiet:
                    _print_final(handle, status)
                return final
            am_dead = handle.am_process is not None and handle.am_process.poll() is not None
            if rpc is None and not am_dead:
                # AM alive but not yet advertised (slow start) — keep waiting
                time.sleep(0.3)
                rpc = handle.rpc(timeout_s=5)
                continue
            if am_dead:
                # AM died without writing a final status → retry or fail
                time.sleep(0.2)  # let a just-written am_status.json land
                status = handle.final_status()
                if status is not None:
                    continue
                retried = self._maybe_retry_am(handle)
                if retried is None:
                    if not quiet:
                        obs_logging.error(f"[tony] AM for {handle.app_id} died without final status → FAILED")
                        _print_am_log_tail(handle)
                    return JobStatus.FAILED
                handle, rpc = retried
                continue
            try:
                infos = rpc.call("get_task_infos")
                app = rpc.call("get_application_status")
            except (RpcError, OSError):
                time.sleep(0.3)
                continue
            am_attempt = int(app.get("am_attempt") or 0)
            if am_attempt != am_attempt_seen:
                # a takeover must be visible to the submitter, not silent
                am_attempt_seen = am_attempt
                outcome = app.get("takeover")
                self._notify("am_attempt", {"am_attempt": am_attempt, "takeover": outcome})
                if not quiet:
                    obs_logging.info(
                        f"[tony] AM attempt {am_attempt} "
                        + ("adopted the running gang (work-preserving takeover)"
                           if outcome == "adopted"
                           else "restarted the gang (takeover degraded)"
                           if outcome == "degraded"
                           else "is serving"))
            for info in infos:
                tid = f"{info['name']}:{info['index']}"
                st = info["status"]
                if last_state.get(tid) != st:
                    last_state[tid] = st
                    self._notify("task_transition", info)
                    if not quiet:
                        loc = f" on {info['host']}:{info['port']}" if info.get("host") else ""
                        obs_logging.info(
                            f"[tony] task {tid} → {st}{loc}"
                            + (f" (logs: {info['log_dir']})"
                               if st in ("FAILED", "LOST") and info.get("log_dir") else ""))
            if app.get("tensorboard_url") and not tb_reported:
                tb_reported = True
                self._notify("tensorboard_url", app["tensorboard_url"])
                if not quiet:
                    obs_logging.info(f"[tony] tensorboard at {app['tensorboard_url']}")
            time.sleep(0.3)

    def _maybe_retry_am(self, handle: ApplicationHandle) -> tuple[ApplicationHandle, RpcClient | None] | None:
        """AM-retry path (SURVEY.md §3.5), now work-preserving: the new
        attempt launches in ``--takeover`` mode, replays ``am_journal.jsonl``
        and ADOPTS the live gang — executors re-resolve the refreshed
        ``am_info`` and resync, the training children never stop. Only a
        missing/corrupt journal degrades (loudly, `AM_TAKEOVER_DEGRADED`) to
        the old whole-gang restart."""
        retries = self.config.get_int(keys.AM_RETRY_COUNT, 0)
        attempt = getattr(handle, "_am_attempt", 0)
        if attempt >= retries:
            return None
        next_attempt = attempt + 1
        for stale in (handle.am_info_path,):
            try:
                os.remove(stale)
            except OSError:
                pass
        obs_logging.warning(
            f"[tony] AM for {handle.app_id} died (attempt {attempt}); "
            f"relaunching attempt {next_attempt} in takeover mode")
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
        with open(os.path.join(handle.staging_dir, f"am_attempt{next_attempt}.log"), "ab") as am_log:
            proc = subprocess.Popen(
                [sys.executable, "-u", "-m", "tony_tpu.cluster.appmaster",
                 "--app-id", handle.app_id, "--staging-dir", handle.staging_dir,
                 "--takeover", "--am-attempt", str(next_attempt)],
                env=env, stdout=am_log, stderr=subprocess.STDOUT, start_new_session=True,
            )
        new_handle = ApplicationHandle(handle.app_id, handle.staging_dir, proc)
        new_handle._am_attempt = next_attempt  # type: ignore[attr-defined]
        return new_handle, new_handle.rpc()

    def run(self, quiet: bool = False) -> int:
        """submit + monitor; exit code = job verdict (reference main flow)."""
        handle = self.submit()
        if not quiet:
            obs_logging.info(f"[tony] submitted {handle.app_id} (staging: {handle.staging_dir})")
        final = self.monitor_application(handle, quiet=quiet)
        return constants.EXIT_SUCCESS if final == JobStatus.SUCCEEDED else constants.EXIT_FAILURE

    @staticmethod
    def kill(handle: ApplicationHandle) -> bool:
        rpc = handle.rpc(timeout_s=5)
        if rpc is None:
            return False
        try:
            rpc.call("finish_application")
            return True
        except (RpcError, OSError):
            return False


def _print_am_log_tail(handle: ApplicationHandle, lines: int = 15) -> None:
    # error level like the "AM died" headline that precedes it, so the whole
    # forensic block lands on one stream (stderr) instead of splitting
    path = os.path.join(handle.staging_dir, "am.log")
    if os.path.exists(path):
        with open(path, errors="replace") as f:
            tail = f.readlines()[-lines:]
        if tail:
            obs_logging.error(f"[tony] last {len(tail)} lines of {path}:")
            for line in tail:
                obs_logging.error(f"[tony-am] {line.rstrip()}")


def _print_final(handle: ApplicationHandle, status: dict[str, Any]) -> None:
    obs_logging.info(f"[tony] application {handle.app_id} finished: {status['status']}")
    if status.get("reason"):
        obs_logging.info(f"[tony]   reason: {status['reason']}")
    # the finalized artifacts' story continues in the history tier — point
    # there instead of leaving the dead AM as the last address
    obs_logging.info(f"[tony]   history: tony history show {handle.app_id}")
    if status.get("am_attempt"):
        obs_logging.info(
            f"[tony]   served by AM attempt {status['am_attempt']}"
            + (f" ({status['takeover']} takeover)" if status.get("takeover") else ""))
    for t in status.get("tasks", []):
        obs_logging.info(
            f"[tony]   {t['name']}:{t['index']} {t['status']}"
            + (f" exit={t['exit_code']}" if t.get("exit_code") is not None else "")
        )


# -- CLI arg surface (reference Commons-CLI options, SURVEY.md §2.1) ---------
def build_config_from_args(argv: list[str]) -> TonyConfig:
    p = argparse.ArgumentParser(prog="tony submit", description="Submit a tony-tpu job")
    p.add_argument("--executes", help="command to run in each task container")
    p.add_argument("--task_params", help="args appended to the --executes command")
    p.add_argument("--conf_file", help="job config file (json/toml/hadoop-xml)")
    p.add_argument("--conf", action="append", default=[], help="key=value override (repeatable)")
    p.add_argument("--src_dir", help="directory staged into every container")
    p.add_argument("--python_venv", help="virtualenv root to activate in containers")
    p.add_argument("--python_binary_path", help="python interpreter for the user process")
    p.add_argument("--shell_env", action="append", default=[], help="extra k=v env (repeatable)")
    p.add_argument("--quiet", action="store_true")
    args = p.parse_args(argv)

    site = os.path.join(os.getcwd(), constants.TONY_SITE_CONF)
    config = TonyConfig.from_layers(
        site_file=site if os.path.exists(site) else None,
        conf_file=args.conf_file,
        conf_args=args.conf,
    )
    if args.executes:
        cmd = args.executes + (f" {args.task_params}" if args.task_params else "")
        config.set(keys.EXECUTES, cmd)
    if args.src_dir:
        config.set(keys.SRC_DIR, args.src_dir)
    if args.python_venv:
        config.set(keys.PYTHON_VENV, args.python_venv)
    if args.python_binary_path:
        config.set(keys.PYTHON_BINARY_PATH, args.python_binary_path)
    if args.shell_env:
        config.set(keys.SHELL_ENV, ",".join(args.shell_env))
    config._quiet = args.quiet  # type: ignore[attr-defined]
    return config


def main(argv: list[str] | None = None) -> int:
    config = build_config_from_args(argv if argv is not None else sys.argv[1:])
    return Client(config).run(quiet=getattr(config, "_quiet", False))


if __name__ == "__main__":
    sys.exit(main())
