"""Control-plane RPC: length-framed JSON over TCP.

Analog of the reference's ``tony-core/.../tony/rpc/`` (``ApplicationRpc`` over
Hadoop protobuf RPC + ``MetricsRpc``; SURVEY.md §2.1, §2.6). The traffic is
low-rate control-plane only — register/heartbeat/spec/result — so a tiny
threaded server with a shared-secret auth token is the idiomatic analog; the
data plane never touches this path (it rides ICI/DCN inside XLA).

Wire format: 4-byte big-endian length, then a UTF-8 JSON object.
Request:  {"method": str, "params": {...}, "auth": str[, "trace": {"t","s"}]}
Response: {"ok": true, "result": ...} | {"ok": false, "error": str}

Observability (docs/observability.md): when tracing is enabled the client
injects its span context as the optional ``trace`` field and the server
parents its handler span on it — causal links cross the RPC boundary in-band.
Old servers ignore the extra field; when tracing is off (the default) the
request is byte-identical to before and no span is allocated. Latency
histograms and retry counters record into the process metrics registry
unconditionally (control-plane rate).
"""

from __future__ import annotations

import json
import random
import socket
import socketserver
import struct
import threading
import time
from typing import TYPE_CHECKING, Any, Callable

from tony_tpu.obs import metrics as _metrics
from tony_tpu.obs import trace as _trace

if TYPE_CHECKING:
    from tony_tpu.chaos import ChaosContext

_LEN = struct.Struct(">I")
MAX_FRAME = 64 * 1024 * 1024

_CLIENT_LATENCY = _metrics.histogram(
    "tony_rpc_client_latency_seconds",
    "RPC client round-trip latency (successful calls)", labelnames=("method",))
_CLIENT_ERRORS = _metrics.counter(
    "tony_rpc_client_errors_total",
    "RPC client calls that raised (connect/transport/remote error)", labelnames=("method",))
_SERVER_LATENCY = _metrics.histogram(
    "tony_rpc_server_latency_seconds",
    "RPC server dispatch latency (auth + handler)", labelnames=("method",))
_SERVER_ERRORS = _metrics.counter(
    "tony_rpc_server_errors_total",
    "RPC dispatches answered with an error frame", labelnames=("method",))
_RETRY_ATTEMPTS = _metrics.counter(
    "tony_rpc_retry_attempts_total",
    "failed attempts inside call_with_retry", labelnames=("method",))
_RETRY_BACKOFF = _metrics.counter(
    "tony_rpc_retry_backoff_seconds_total",
    "total backoff sleep inside call_with_retry", labelnames=("method",))
_RECONNECTS = _metrics.counter(
    "tony_rpc_reconnects_total",
    "client sockets re-established transparently after a broken/stale "
    "persistent connection (each is a fresh TCP handshake the server pays)",
    labelnames=("method",))


class RpcError(RuntimeError):
    """Remote method raised, or protocol violation."""


def _send_frame(sock: socket.socket, obj: Any) -> None:
    payload = json.dumps(obj).encode("utf-8")
    if len(payload) > MAX_FRAME:
        raise RpcError(f"frame too large: {len(payload)}")
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed connection")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> Any:
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if length > MAX_FRAME:
        raise RpcError(f"frame too large: {length}")
    return json.loads(_recv_exact(sock, length))


class RpcServer:
    """Threaded RPC server dispatching to registered methods.

    The AM (ApplicationRpcServer analog) registers its handlers and runs this
    next to its event loop; handlers must be thread-safe (session lock).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0, secret: str = ""):
        self._methods: dict[str, Callable[..., Any]] = {}
        self._secret = secret
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:  # one connection may issue many calls
                sock = self.request
                try:
                    while True:
                        req = _recv_frame(sock)
                        _send_frame(sock, outer._dispatch(req))
                except (ConnectionError, OSError):
                    return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever, name="rpc-server", daemon=True)

    def _dispatch(self, req: Any) -> dict[str, Any]:
        t0 = time.perf_counter()
        name = ""
        try:
            if not isinstance(req, dict):
                raise RpcError("malformed request")
            if self._secret and req.get("auth") != self._secret:
                raise RpcError("authentication failed")
            name = req.get("method", "")
            method = self._methods.get(name)
            if method is None:
                raise RpcError(f"unknown method: {name!r}")
            params = req.get("params") or {}
            tr = _trace.get()
            if tr is None:  # disabled: the incoming trace field (if any) is ignored
                result = method(**params)
            else:
                ctx = req.get("trace") or {}
                with tr.span(f"rpc.server:{name}", kind="server",
                             parent_id=ctx.get("s")):
                    result = method(**params)
            _SERVER_LATENCY.observe(time.perf_counter() - t0, method=name)
            return {"ok": True, "result": result}
        except Exception as e:  # noqa: BLE001 — fault isolation at the RPC boundary
            _SERVER_ERRORS.inc(method=name or "?")
            _SERVER_LATENCY.observe(time.perf_counter() - t0, method=name or "?")
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}

    def register(self, name: str, fn: Callable[..., Any]) -> None:
        self._methods[name] = fn

    def register_object(self, obj: Any, names: list[str]) -> None:
        for n in names:
            self.register(n, getattr(obj, n))

    def start(self) -> None:
        self._thread.start()

    @property
    def address(self) -> tuple[str, int]:
        return self._server.server_address[:2]

    def stop(self) -> None:
        if self._thread.is_alive():
            # shutdown() blocks on the serve_forever loop acknowledging; only
            # safe when that loop is actually running
            self._server.shutdown()
        self._server.server_close()


class RpcClient:
    """Blocking client over ONE persistent connection, with transparent
    broken-pipe reconnect and retry helpers.

    (ApplicationRpcClient analog; executors and the monitoring client use
    it.) The socket opened by the first call is reused for every subsequent
    call — the server's handler loop serves many calls per connection — so
    the per-second heartbeat path costs one TCP handshake per executor
    LIFETIME, not per beat. A call that finds the cached socket dead (AM
    restarted, idle timeout, connection reset) reconnects once and retries
    transparently, counted in ``tony_rpc_reconnects_total``.
    """

    def __init__(
        self,
        host: str,
        port: int,
        secret: str = "",
        timeout_s: float = 10.0,
        chaos: "ChaosContext | None" = None,
    ):
        self.host, self.port, self.secret = host, port, secret
        self.timeout_s = timeout_s
        #: optional fault-injection context (tony.chaos.*); None on the
        #: production path — every injection is guarded on it
        self.chaos = chaos
        self._sock: socket.socket | None = None
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection((self.host, self.port), timeout=self.timeout_s)  # lint: disable=blocking-under-lock — the client lock deliberately serializes the ONE socket (request/response framing); a connect races nothing else
            s.settimeout(self.timeout_s)
            self._sock = s
        return self._sock

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                finally:
                    self._sock = None

    def retarget(self, host: str, port: int, secret: str | None = None) -> None:
        """Re-point this client at a MOVED server (work-preserving AM
        takeover republishes ``am_info`` with a fresh port + secret). The
        stale socket is dropped; the next call reconnects to the new
        address. Thread-safe against in-flight calls (same lock)."""
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                finally:
                    self._sock = None
            self.host, self.port = host, int(port)
            if secret is not None:
                self.secret = secret

    def call(self, method: str, **params: Any) -> Any:
        tr = _trace.get()
        if tr is None:  # disabled fast path: no span objects, no trace field
            return self._observed_call(method, params, None)
        with tr.span(f"rpc.client:{method}", kind="client") as sp:
            return self._observed_call(
                method, params, {"t": sp.trace_id, "s": sp.span_id}
            )

    def _observed_call(
        self, method: str, params: dict[str, Any], trace_ctx: dict[str, str] | None
    ) -> Any:
        t0 = time.perf_counter()
        try:
            with self._lock:
                reconnecting = False
                for attempt in (0, 1):  # one transparent reconnect on a stale socket
                    had_cached = self._sock is not None
                    try:
                        if self.chaos is not None:
                            # may sleep (rpc-delay) or raise (rpc-drop/blackhole)
                            self.chaos.rpc_before_send(method, self.timeout_s)
                        sock = self._connect()
                        req: dict[str, Any] = {"method": method, "params": params, "auth": self.secret}
                        if trace_ctx is not None:
                            req["trace"] = trace_ctx
                        _send_frame(sock, req)
                        if self.chaos is not None and self.chaos.rpc_sever_after_send(method):
                            sock.close()  # response lost mid-call (server may have executed)
                        resp = _recv_frame(sock)
                        if reconnecting:
                            # only now was a broken PERSISTENT connection
                            # actually re-established — initial-connect
                            # failures and failed retries are not handshakes
                            # the server paid
                            _RECONNECTS.inc(method=method)
                        break
                    except (ConnectionError, OSError):
                        self._sock = None
                        if attempt:
                            raise
                        reconnecting = had_cached
                if not resp.get("ok"):
                    raise RpcError(resp.get("error", "unknown remote error"))
                result = resp.get("result")
        except Exception:
            _CLIENT_ERRORS.inc(method=method)
            raise
        _CLIENT_LATENCY.observe(time.perf_counter() - t0, method=method)
        return result

    def call_with_retry(
        self,
        method: str,
        *,
        retries: int = 30,
        delay_s: float = 0.2,
        max_delay_s: float = 2.0,
        deadline_s: float | None = None,
        **params: Any,
    ) -> Any:
        """Retry through AM startup races / transient connect failures.

        Exponential backoff with FULL jitter (sleep ~ U[0, min(max_delay_s,
        delay_s * 2^attempt)]) so a restarted gang's executors don't hammer a
        recovering AM in lockstep, bounded by ``deadline_s`` of overall wall
        time when given — a caller with a contract timeout (registration,
        final-result report) fails crisply instead of retrying past it.
        """
        start = time.monotonic()
        last: Exception | None = None
        for attempt in range(retries):
            try:
                return self.call(method, **params)
            except (ConnectionError, OSError, RpcError) as e:
                last = e
                _RETRY_ATTEMPTS.inc(method=method)
                _trace.add_event("rpc.retry", method=method, attempt=attempt, error=str(e)[:200])
                if attempt + 1 >= retries:
                    break
                cap = min(max_delay_s, delay_s * (2 ** min(attempt, 32)))
                sleep = random.uniform(0, cap)
                if deadline_s is not None:
                    remaining = deadline_s - (time.monotonic() - start)
                    if remaining <= 0:
                        raise RpcError(
                            f"{method} deadline {deadline_s:.1f}s exceeded "
                            f"after {attempt + 1} attempts: {last}"
                        ) from last
                    sleep = min(sleep, remaining)
                _RETRY_BACKOFF.inc(sleep, method=method)
                time.sleep(sleep)
        raise RpcError(f"{method} failed after {retries} retries: {last}")


# Canonical ApplicationRpc method names (reference iface, SURVEY.md §2.1)
APPLICATION_RPC_METHODS = [
    "register_worker_spec",
    "get_cluster_spec",
    "register_execution_result",
    "resync_task",           # post-takeover re-attach (idempotent, epoch-fenced)
    "register_tensorboard_url",
    "register_task_url",
    "task_executor_heartbeat",
    "get_task_infos",
    "get_application_status",
    "finish_application",
    "push_metrics",          # MetricsRpc analog
    "get_metrics",           # process metrics-registry snapshot (obs/metrics.py)
    "push_client_metrics",   # submitter-side registry (fleet router) re-exported by get_metrics
    "resize_jobtype",        # elastic retarget of tony.<type>.instances (autoscaler / tony resize)
    "register_spare",        # hot-spare executor announces itself (tony.elastic.spares)
    "poll_spare_assignment", # parked spare polls for a gang-slot promotion
    "start_profile",         # arm an on-demand profiler capture (tony profile)
    "get_profile_status",    # per-task capture status for the in-flight request
    "report_profile_status", # executors report delivery/capture back to the AM
    "report_drain_saved",    # executors report the child's urgent pre-preemption checkpoint
    "request_task_drain",    # drain ONE task (autoscaler pre-scale-down lever); idempotent poll
    "get_goodput",           # live goodput ledger + straggler skew + active alerts
    "get_slo",               # SLO objectives: budgets, burn rates, exemplars (obs/slo.py)
]
