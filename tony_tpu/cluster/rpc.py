"""Control-plane RPC: length-framed JSON over TCP.

Analog of the reference's ``tony-core/.../tony/rpc/`` (``ApplicationRpc`` over
Hadoop protobuf RPC + ``MetricsRpc``; SURVEY.md §2.1, §2.6). The traffic is
low-rate control-plane only — register/heartbeat/spec/result — so a tiny
threaded server with a shared-secret auth token is the idiomatic analog; the
data plane never touches this path (it rides ICI/DCN inside XLA).

Wire format: 4-byte big-endian length, then a UTF-8 JSON object.
Request:  {"method": str, "params": {...}, "auth": str}
Response: {"ok": true, "result": ...} | {"ok": false, "error": str}
"""

from __future__ import annotations

import json
import random
import socket
import socketserver
import struct
import threading
import time
from typing import TYPE_CHECKING, Any, Callable

if TYPE_CHECKING:
    from tony_tpu.chaos import ChaosContext

_LEN = struct.Struct(">I")
MAX_FRAME = 64 * 1024 * 1024


class RpcError(RuntimeError):
    """Remote method raised, or protocol violation."""


def _send_frame(sock: socket.socket, obj: Any) -> None:
    payload = json.dumps(obj).encode("utf-8")
    if len(payload) > MAX_FRAME:
        raise RpcError(f"frame too large: {len(payload)}")
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed connection")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> Any:
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if length > MAX_FRAME:
        raise RpcError(f"frame too large: {length}")
    return json.loads(_recv_exact(sock, length))


class RpcServer:
    """Threaded RPC server dispatching to registered methods.

    The AM (ApplicationRpcServer analog) registers its handlers and runs this
    next to its event loop; handlers must be thread-safe (session lock).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0, secret: str = ""):
        self._methods: dict[str, Callable[..., Any]] = {}
        self._secret = secret
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:  # one connection may issue many calls
                sock = self.request
                try:
                    while True:
                        req = _recv_frame(sock)
                        _send_frame(sock, outer._dispatch(req))
                except (ConnectionError, OSError):
                    return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever, name="rpc-server", daemon=True)

    def _dispatch(self, req: Any) -> dict[str, Any]:
        try:
            if not isinstance(req, dict):
                raise RpcError("malformed request")
            if self._secret and req.get("auth") != self._secret:
                raise RpcError("authentication failed")
            method = self._methods.get(req.get("method", ""))
            if method is None:
                raise RpcError(f"unknown method: {req.get('method')!r}")
            return {"ok": True, "result": method(**(req.get("params") or {}))}
        except Exception as e:  # noqa: BLE001 — fault isolation at the RPC boundary
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}

    def register(self, name: str, fn: Callable[..., Any]) -> None:
        self._methods[name] = fn

    def register_object(self, obj: Any, names: list[str]) -> None:
        for n in names:
            self.register(n, getattr(obj, n))

    def start(self) -> None:
        self._thread.start()

    @property
    def address(self) -> tuple[str, int]:
        return self._server.server_address[:2]

    def stop(self) -> None:
        if self._thread.is_alive():
            # shutdown() blocks on the serve_forever loop acknowledging; only
            # safe when that loop is actually running
            self._server.shutdown()
        self._server.server_close()


class RpcClient:
    """Blocking client with per-call reconnect-on-failure and retry helpers.

    (ApplicationRpcClient analog; executors and the monitoring client use it.)
    """

    def __init__(
        self,
        host: str,
        port: int,
        secret: str = "",
        timeout_s: float = 10.0,
        chaos: "ChaosContext | None" = None,
    ):
        self.host, self.port, self.secret = host, port, secret
        self.timeout_s = timeout_s
        #: optional fault-injection context (tony.chaos.*); None on the
        #: production path — every injection is guarded on it
        self.chaos = chaos
        self._sock: socket.socket | None = None
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection((self.host, self.port), timeout=self.timeout_s)
            s.settimeout(self.timeout_s)
            self._sock = s
        return self._sock

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                finally:
                    self._sock = None

    def call(self, method: str, **params: Any) -> Any:
        with self._lock:
            for attempt in (0, 1):  # one transparent reconnect on a stale socket
                try:
                    if self.chaos is not None:
                        # may sleep (rpc-delay) or raise (rpc-drop/blackhole)
                        self.chaos.rpc_before_send(method, self.timeout_s)
                    sock = self._connect()
                    _send_frame(sock, {"method": method, "params": params, "auth": self.secret})
                    if self.chaos is not None and self.chaos.rpc_sever_after_send(method):
                        sock.close()  # response lost mid-call (server may have executed)
                    resp = _recv_frame(sock)
                    break
                except (ConnectionError, OSError):
                    self._sock = None
                    if attempt:
                        raise
            if not resp.get("ok"):
                raise RpcError(resp.get("error", "unknown remote error"))
            return resp.get("result")

    def call_with_retry(
        self,
        method: str,
        *,
        retries: int = 30,
        delay_s: float = 0.2,
        max_delay_s: float = 2.0,
        deadline_s: float | None = None,
        **params: Any,
    ) -> Any:
        """Retry through AM startup races / transient connect failures.

        Exponential backoff with FULL jitter (sleep ~ U[0, min(max_delay_s,
        delay_s * 2^attempt)]) so a restarted gang's executors don't hammer a
        recovering AM in lockstep, bounded by ``deadline_s`` of overall wall
        time when given — a caller with a contract timeout (registration,
        final-result report) fails crisply instead of retrying past it.
        """
        start = time.monotonic()
        last: Exception | None = None
        for attempt in range(retries):
            try:
                return self.call(method, **params)
            except (ConnectionError, OSError, RpcError) as e:
                last = e
                if attempt + 1 >= retries:
                    break
                cap = min(max_delay_s, delay_s * (2 ** min(attempt, 32)))
                sleep = random.uniform(0, cap)
                if deadline_s is not None:
                    remaining = deadline_s - (time.monotonic() - start)
                    if remaining <= 0:
                        raise RpcError(
                            f"{method} deadline {deadline_s:.1f}s exceeded "
                            f"after {attempt + 1} attempts: {last}"
                        ) from last
                    sleep = min(sleep, remaining)
                time.sleep(sleep)
        raise RpcError(f"{method} failed after {retries} retries: {last}")


# Canonical ApplicationRpc method names (reference iface, SURVEY.md §2.1)
APPLICATION_RPC_METHODS = [
    "register_worker_spec",
    "get_cluster_spec",
    "register_execution_result",
    "register_tensorboard_url",
    "register_task_url",
    "task_executor_heartbeat",
    "get_task_infos",
    "get_application_status",
    "finish_application",
    "push_metrics",          # MetricsRpc analog
]
