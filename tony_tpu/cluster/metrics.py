"""Per-task resource metrics sampling.

Analog of the reference's GPU/CPU utilization pipeline (SURVEY.md §2.1 "GPU
metrics", §5.5): where the reference forks ``nvidia-smi -q -x`` and JAXB-parses
the XML, the TPU rebuild reads device state through PJRT —
``jax.local_devices()[i].memory_stats()`` — plus ``/proc`` for host CPU/RSS.
Executors push these snapshots over the MetricsRpc analog; the AM attaches the
latest snapshot to each TaskInfo and emits METRICS_SNAPSHOT events.
"""

from __future__ import annotations

import os
import time
from typing import Any

_CLK = os.sysconf("SC_CLK_TCK") if hasattr(os, "sysconf") else 100
_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def sample_host_metrics(pid: int | None = None) -> dict[str, Any]:
    """CPU seconds + RSS for a process tree root, from /proc (no psutil)."""
    pid = pid or os.getpid()
    out: dict[str, Any] = {"timestamp_ms": int(time.time() * 1000), "pid": pid}
    try:
        with open(f"/proc/{pid}/stat") as f:
            fields = f.read().rsplit(")", 1)[1].split()
        # fields are post-comm: [state, ppid, ...]; utime=11, stime=12 (0-based here)
        utime, stime = int(fields[11]), int(fields[12])
        out["cpu_seconds"] = (utime + stime) / _CLK
        out["rss_bytes"] = int(fields[21]) * _PAGE
    except (OSError, IndexError, ValueError):
        pass
    try:
        load1, load5, load15 = os.getloadavg()
        out["host_load1"] = round(load1, 3)
    except OSError:
        pass
    return out


def sample_tpu_metrics() -> dict[str, Any]:
    """HBM usage per local TPU device via PJRT memory stats (nvidia-smi analog).

    Safe to call when jax is absent/unavailable — returns {} rather than
    raising, because metrics must never take down an executor.
    """
    try:
        import jax

        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — metrics are strictly best-effort
        return {}
    per_device = []
    for d in devices:
        entry: dict[str, Any] = {"id": d.id, "kind": getattr(d, "device_kind", "unknown")}
        try:
            stats = d.memory_stats() or {}
            entry["hbm_bytes_in_use"] = stats.get("bytes_in_use", 0)
            entry["hbm_bytes_limit"] = stats.get("bytes_limit", 0)
        except Exception:  # noqa: BLE001
            pass
        per_device.append(entry)
    return {"devices": per_device} if per_device else {}


class MetricsSampler:
    """Combined host+TPU snapshot builder used by the executor push loop.

    Whole-host CPU utilization / memory pressure comes from the native
    sampler (native/tonymon.cc via tony_tpu.data.native.HostMetricsSampler,
    Python /proc fallback inside it); per-process CPU/RSS and per-device HBM
    are sampled here.
    """

    def __init__(self, child_pid: int | None = None, with_tpu: bool = True):
        self.child_pid = child_pid
        self.with_tpu = with_tpu
        try:
            from tony_tpu.data.native import HostMetricsSampler

            self._host = HostMetricsSampler()
        except Exception:  # noqa: BLE001 — metrics are strictly best-effort
            self._host = None

    def sample(self) -> dict[str, Any]:
        m = sample_host_metrics(self.child_pid)
        if self._host is not None:
            try:
                m["host"] = self._host.sample()
            except Exception:  # noqa: BLE001
                pass
        if self.with_tpu:
            tpu = sample_tpu_metrics()
            if tpu:
                m["tpu"] = tpu
        return m
