"""Training: train-step builder, checkpointing, throughput/MFU metrics."""

from tony_tpu.train.trainer import (  # noqa: F401
    OptimizerConfig,
    Throughput,
    TrainState,
    make_train_step,
    sharded_init,
)
