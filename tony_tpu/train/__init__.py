"""Training: train-step builder, checkpointing, throughput/MFU metrics."""

from tony_tpu.train.trainer import (  # noqa: F401
    OptimizerConfig,
    Throughput,
    TrainState,
    make_pp_train_step,
    make_train_step,
    sharded_init,
)
