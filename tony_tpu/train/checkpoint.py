"""Sharded checkpointing + resume (SURVEY.md §5.4 rebuild duty).

The reference never owned checkpoints (user code wrote to HDFS; TonY only
restarted gangs). Here checkpoint/resume is part of the framework because the
AM's gang-restart elasticity (appmaster.py) is only useful if a restarted gang
resumes: Orbax async sharded save (per-host writes, non-blocking train loop) +
latest-step restore with the target sharding applied on load.
"""

from __future__ import annotations

import os
from typing import Any

import jax


class CheckpointManager:
    """Thin wrapper over orbax.checkpoint.CheckpointManager.

    save() is async by default: the train loop keeps stepping while device
    arrays are serialized; wait() (or close()) drains in-flight writes.
    """

    def __init__(
        self,
        directory: str,
        *,
        max_to_keep: int = 3,
        save_interval_steps: int = 0,
        use_async: bool = True,
    ):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps or 1,
            enable_async_checkpointing=use_async,
        )
        self._mgr = ocp.CheckpointManager(self.directory, options=options)

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        return self._mgr.save(step, args=self._ocp.args.StandardSave(state), force=force)

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore(self, state_like: Any, step: int | None = None) -> Any:
        """Restore into the sharding/structure of ``state_like`` (an abstract
        or concrete pytree; concrete shardings are honored on load)."""
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        abstract = jax.tree.map(_abstractify, state_like)
        return self._mgr.restore(step, args=self._ocp.args.StandardRestore(abstract))

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()


def _abstractify(x: Any) -> Any:
    if isinstance(x, jax.Array):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
    return x


def restore_or_init(
    ckpt_dir: str | None,
    init_fn,
    *,
    max_to_keep: int = 3,
    use_async: bool = True,
) -> tuple[Any, "CheckpointManager | None", int]:
    """The gang-restart resume path: (state, manager, start_step).

    With no ckpt_dir configured → (init_fn(), None, 0). With one configured,
    restores the latest checkpoint if present, else initializes fresh.
    """
    if not ckpt_dir:
        return init_fn(), None, 0
    mgr = CheckpointManager(ckpt_dir, max_to_keep=max_to_keep, use_async=use_async)
    state = init_fn()
    step = mgr.latest_step()
    if step is not None:
        state = mgr.restore(state)
        return state, mgr, int(step)
    return state, mgr, 0
