"""Sharded checkpointing + resume (SURVEY.md §5.4 rebuild duty).

The reference never owned checkpoints (user code wrote to HDFS; TonY only
restarted gangs). Here checkpoint/resume is part of the framework because the
AM's gang-restart elasticity (appmaster.py) is only useful if a restarted gang
resumes: Orbax async sharded save (per-host writes, non-blocking train loop) +
latest-step restore with the target sharding applied on load.

Cross-topology restore (the elastic-training contract,
docs/fault-tolerance.md): a checkpoint written on mesh ``{data: N}`` restores
onto ``{data: M}`` for any M — ``restore`` never trusts the sharding recorded
IN the checkpoint, it always imposes the sharding of the caller's
``state_like`` (the state the resized gang just ``sharded_init``-ed on its
OWN mesh), so the arrays land resharded for the new topology in one pass.
Asserted 4-way → 2-way → 1-way in tests/test_elastic.py.
"""

from __future__ import annotations

import os
import time
from typing import Any

import jax

from tony_tpu.obs import logging as obs_logging
from tony_tpu.obs import metrics as obs_metrics
from tony_tpu.obs import trace as obs_trace

_SAVE_SECONDS = obs_metrics.histogram(
    "tony_checkpoint_save_seconds",
    "checkpoint save-dispatch latency (async saves exclude background writes)")
_RESTORE_SECONDS = obs_metrics.histogram(
    "tony_checkpoint_restore_seconds", "checkpoint restore latency")


class CheckpointManager:
    """Thin wrapper over orbax.checkpoint.CheckpointManager.

    save() is async by default: the train loop keeps stepping while device
    arrays are serialized; wait() (or close()) drains in-flight writes.
    """

    def __init__(
        self,
        directory: str,
        *,
        max_to_keep: int = 3,
        save_interval_steps: int = 0,
        use_async: bool = True,
    ):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps or 1,
            enable_async_checkpointing=use_async,
        )
        self._mgr = ocp.CheckpointManager(self.directory, options=options)

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        t0 = time.perf_counter()
        with obs_trace.maybe_span("ckpt.save", step=step):
            saved = self._mgr.save(step, args=self._ocp.args.StandardSave(state), force=force)
        if saved:
            _SAVE_SECONDS.observe(time.perf_counter() - t0)
        return saved

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore(self, state_like: Any, step: int | None = None) -> Any:
        """Restore into the sharding/structure of ``state_like`` (an abstract
        or concrete pytree; concrete shardings are honored on load).

        The TARGET sharding always wins over whatever sharding the
        checkpoint was written under — this is what lets an elastically
        resized gang restore a ``{data: N}`` checkpoint onto its ``{data:
        M}`` mesh directly (re-sharding happens inside the Orbax load, no
        full-size intermediate materialization on any one host)."""
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        t0 = time.perf_counter()
        with obs_trace.maybe_span("ckpt.restore", step=int(step)):
            abstract = jax.tree.map(_abstractify, state_like)
            restored = self._mgr.restore(step, args=self._ocp.args.StandardRestore(abstract))
        _RESTORE_SECONDS.observe(time.perf_counter() - t0)
        return restored

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()


def _abstractify(x: Any) -> Any:
    if isinstance(x, jax.Array):
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
    return x


def restore_or_init(
    ckpt_dir: str | None,
    init_fn,
    *,
    max_to_keep: int = 3,
    use_async: bool = True,
) -> tuple[Any, "CheckpointManager | None", int]:
    """The gang-restart resume path: (state, manager, start_step).

    With no ckpt_dir configured → (init_fn(), None, 0). With one configured,
    restores the newest INTACT checkpoint if present, else initializes fresh.

    Corruption-tolerant: a torn latest checkpoint (the writer crashed
    mid-write, the node died, a chaos ``ckpt-corrupt`` fault fired) must not
    crash the whole restarted gang — a step whose restore fails is
    quarantined (renamed to ``.corrupt-<step>``, invisible to Orbax but kept
    for forensics) and the next-newest step is tried, down to a fresh init.
    """
    if not ckpt_dir:
        return init_fn(), None, 0
    from tony_tpu.chaos import maybe_corrupt_checkpoint

    maybe_corrupt_checkpoint(ckpt_dir)  # no-op unless a chaos fault is armed via env
    state = init_fn()
    while True:
        # a fresh manager per attempt: Orbax caches its step list at init,
        # and a quarantined step must disappear from it before the next try
        mgr = CheckpointManager(ckpt_dir, max_to_keep=max_to_keep, use_async=use_async)
        step = mgr.latest_step()
        if step is None:
            return state, mgr, 0
        try:
            return mgr.restore(state, step=step), mgr, int(step)
        except Exception as e:  # noqa: BLE001 — any torn artifact must fall back, not crash
            obs_logging.warning(
                f"[ckpt] restore of step {step} failed ({type(e).__name__}: {e}); "
                f"quarantining it and falling back to the previous step",
                step=int(step),
            )
            mgr.close()
            _quarantine_step(ckpt_dir, int(step))


class UrgentSaveSignal:
    """Child-side half of the checkpoint-then-yield drain contract
    (docs/scheduling.md): polls ``<TONY_TRAIN_METRICS_FILE>.drain`` (the
    control file the executor's DrainCourier drops when the pool asks this
    job to drain or shrink) at step boundaries, throttled to one monotonic
    compare per step when idle — the same cadence discipline as the
    on-demand profiler's control poll (``tony.profile.poll-interval-ms``).

    The training loop calls :meth:`poll` each step; on a new request it
    force-saves through the existing ``CheckpointManager``, then calls
    :meth:`acknowledge` with the saved step. The loop KEEPS STEPPING after
    acknowledging — yielding is the AM's move (it kills the gang once every
    rank's checkpoint landed), so the few extra steps are exactly the
    bounded rework the goodput ledger meters."""

    def __init__(self) -> None:
        # the shared file contract lives in obs/introspect.py (suffixes +
        # torn-tolerant read + atomic write), same as the profile relay
        from tony_tpu import constants
        from tony_tpu.obs import introspect as _introspect

        self._introspect = _introspect
        self._path = os.environ.get(constants.ENV_TRAIN_METRICS_FILE, "")
        try:
            poll_ms = int(os.environ.get(constants.ENV_PROFILE_POLL_MS, "500") or 500)
        except ValueError:
            poll_ms = 500
        self._interval_s = max(poll_ms, 50) / 1000.0
        self._next_poll = 0.0
        self._handled: set[str] = set()

    def poll(self) -> str | None:
        """The pending request id, at most once per request; None when idle
        (the overwhelmingly common case costs one clock read)."""
        if not self._path:
            return None
        now = time.monotonic()
        if now < self._next_poll:
            return None
        self._next_poll = now + self._interval_s
        ctl = self._introspect.read_json(
            self._path + self._introspect.DRAIN_CONTROL_SUFFIX)
        req_id = str((ctl or {}).get("req_id") or "")
        if not req_id or req_id in self._handled:
            return None
        self._handled.add(req_id)
        return req_id

    def acknowledge(self, req_id: str, step: int) -> None:
        """Atomically publish the done file the courier reports back."""
        if not self._path:
            return
        try:
            self._introspect.write_json_atomic(
                self._path + self._introspect.DRAIN_DONE_SUFFIX,
                {"req_id": req_id, "step": int(step)},
            )
        except OSError:
            pass  # best-effort: the AM's yield deadline covers a lost ack


def _quarantine_step(ckpt_dir: str, step: int) -> None:
    """Move a corrupt step dir out of Orbax's sight (non-numeric name), kept
    on disk for post-mortem. Gang workers share the checkpoint dir and all
    hit the torn step concurrently on a restart — losing the rename race to a
    peer is success, not an error. Raises only when the move persistently
    fails — retrying the same corrupt step forever would be worse."""
    src = os.path.join(ckpt_dir, str(step))
    dst = os.path.join(ckpt_dir, f".corrupt-{step}")
    try:
        os.rename(src, dst)
    except FileNotFoundError:
        return  # a peer gang worker already quarantined this step
    except OSError:
        # leftover quarantine dir from an earlier incident: replace it
        import shutil

        shutil.rmtree(dst, ignore_errors=True)
        try:
            os.rename(src, dst)
        except FileNotFoundError:
            return
