"""Train-step builder: the compute loop the reference left to user frameworks.

Functional and jit-first: one ``TrainState`` pytree, one compiled
``train_step`` (value_and_grad → optax update), gradient accumulation as a
``lax.scan`` over microbatches (stays on-device, no host sync), donation of
the input state so params/optimizer memory is reused in place.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax

from tony_tpu.parallel.sharding import ShardingRules


@jax.tree_util.register_dataclass
@dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jax.Array

    @classmethod
    def create(cls, params: Any, optimizer: optax.GradientTransformation) -> "TrainState":
        return cls(params=params, opt_state=optimizer.init(params), step=jnp.zeros((), jnp.int32))


@dataclass(frozen=True)
class OptimizerConfig:
    learning_rate: float = 3e-4
    warmup_steps: int = 100
    total_steps: int = 10_000
    weight_decay: float = 0.1
    grad_clip: float = 1.0
    b1: float = 0.9
    b2: float = 0.95
    # dtype of Adam's first moment; "" keeps optax's default (the PARAM
    # dtype — so bf16-param models already hold bf16 moments). Set
    # "bfloat16" to halve mu's HBM when params are f32, or "float32" to
    # upcast it for extra stability on bf16-param models.
    mu_dtype: str = ""

    def build(self) -> optax.GradientTransformation:
        schedule = optax.warmup_cosine_decay_schedule(
            0.0, self.learning_rate, self.warmup_steps, max(self.total_steps, self.warmup_steps + 1)
        )
        return optax.chain(
            optax.clip_by_global_norm(self.grad_clip),
            optax.adamw(
                schedule, b1=self.b1, b2=self.b2, weight_decay=self.weight_decay,
                mu_dtype=jnp.dtype(self.mu_dtype) if self.mu_dtype else None,
            ),
        )


def make_train_step(
    loss_fn: Callable[[Any, Any], tuple[jax.Array, dict]],
    optimizer: optax.GradientTransformation,
    accum_steps: int = 1,
) -> Callable[[TrainState, Any], tuple[TrainState, dict]]:
    """loss_fn(params, batch) -> (loss, aux). Returns a jitted step with the
    state donated (in-place param/optimizer update on device).

    With accum_steps > 1, the batch's leading dim must be
    ``accum_steps * microbatch`` and gradients average over a lax.scan.
    """

    def compute_grads(params, batch):
        if accum_steps == 1:
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params, batch)
            return loss, aux, grads

        def micro(carry, mb):
            loss_acc, grads_acc = carry
            (loss, _aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params, mb)
            return (loss_acc + loss, jax.tree.map(jnp.add, grads_acc, grads)), None

        microbatches = jax.tree.map(
            lambda x: x.reshape(accum_steps, x.shape[0] // accum_steps, *x.shape[1:]), batch
        )
        zeros = jax.tree.map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)
        (loss_sum, grads_sum), _ = jax.lax.scan(micro, (jnp.zeros((), jnp.float32), zeros), microbatches)
        inv = 1.0 / accum_steps
        return loss_sum * inv, {}, jax.tree.map(lambda g: g * inv, grads_sum)

    def train_step(state: TrainState, batch: Any) -> tuple[TrainState, dict]:
        loss, aux, grads = compute_grads(state.params, batch)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        metrics = {
            "loss": loss.astype(jnp.float32),
            "grad_norm": optax.global_norm(grads).astype(jnp.float32),
            "step": state.step + 1,
            **{k: v for k, v in aux.items() if k != "loss"},
        }
        return TrainState(params, opt_state, state.step + 1), metrics

    return jax.jit(train_step, donate_argnums=0)


def make_pp_train_step(
    value_and_grad_fn: Callable[[Any, Any], tuple[jax.Array, dict, Any]],
    optimizer: optax.GradientTransformation,
) -> Callable[[TrainState, Any], tuple[TrainState, dict]]:
    """Train step from a function that produces gradients itself —
    ``value_and_grad_fn(params, batch) -> (loss, aux, grads)``. The 1F1B
    pipeline schedule (llama.pp_value_and_grad) hand-runs its backward
    inside the pipeline loop, so it cannot go through jax.value_and_grad;
    everything after gradients (optimizer, metrics, donation) is identical
    to make_train_step."""

    def train_step(state: TrainState, batch: Any) -> tuple[TrainState, dict]:
        loss, aux, grads = value_and_grad_fn(state.params, batch)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        metrics = {
            "loss": loss.astype(jnp.float32),
            "grad_norm": optax.global_norm(grads).astype(jnp.float32),
            "step": state.step + 1,
            **{k: v for k, v in aux.items() if k != "loss"},
        }
        return TrainState(params, opt_state, state.step + 1), metrics

    return jax.jit(train_step, donate_argnums=0)


def sharded_init(
    init_fn: Callable[[], Any],
    rules: ShardingRules,
    mesh,
    optimizer: optax.GradientTransformation,
) -> TrainState:
    """Initialize params directly onto the mesh (jit with out_shardings so
    large models never materialize unsharded on one device), then build the
    optimizer state under the same sharding."""
    from jax.sharding import NamedSharding, PartitionSpec

    abstract = jax.eval_shape(init_fn)
    out_sharding = rules.sharding_tree(abstract, mesh)
    params = jax.jit(init_fn, out_shardings=out_sharding)()
    # zeros_like under optax.init inherits each param's sharding, so the
    # optimizer state (the FSDP memory win) lands sharded too.
    opt_state = optimizer.init(params)
    # scalar leaves (optax step counts, TrainState.step) get a DEFAULT
    # single-device placement — harmless uncommitted at init, but a restored
    # checkpoint COMMITS every leaf to its recorded sharding, and a scalar
    # pinned to device 0 next to mesh-sharded params is an incompatible-
    # devices error in the first jitted step after resume. Replicate them
    # over the mesh so the whole TrainState (and any checkpoint of it)
    # lives on the mesh — which also makes checkpoints restore cleanly onto
    # a DIFFERENT mesh shape (elastic re-pack).
    repl = NamedSharding(mesh, PartitionSpec())

    def _on_mesh(x):
        if isinstance(x, jax.Array) and not isinstance(x.sharding, NamedSharding):
            return jax.device_put(x, repl)
        return x

    opt_state = jax.tree.map(_on_mesh, opt_state)
    return TrainState(
        params=params, opt_state=opt_state,
        step=jax.device_put(jnp.zeros((), jnp.int32), repl),
    )


class Throughput:
    """Wall-clock tokens/s + MFU meter around the jitted step (host side)."""

    def __init__(self, tokens_per_step: int, flops_per_token: int, n_chips: int, peak_flops: float):
        self.tokens_per_step = tokens_per_step
        self.flops_per_token = flops_per_token
        self.n_chips = max(n_chips, 1)
        self.peak_flops = peak_flops
        self._t0: float | None = None
        self.steps = 0

    def start(self) -> None:
        self._t0 = time.perf_counter()
        self.steps = 0

    def step(self) -> None:
        self.steps += 1

    def report(self) -> dict:
        dt = time.perf_counter() - (self._t0 or time.perf_counter())
        if dt <= 0 or self.steps == 0:
            return {"tokens_per_sec": 0.0, "mfu": 0.0, "step_time_ms": 0.0}
        tps = self.tokens_per_step * self.steps / dt
        flops = tps * self.flops_per_token
        return {
            "tokens_per_sec": tps,
            "tokens_per_sec_per_chip": tps / self.n_chips,
            "step_time_ms": 1000 * dt / self.steps,
            "mfu": flops / (self.peak_flops * self.n_chips),
        }
