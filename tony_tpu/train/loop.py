"""Reusable training loop: what a user program run by `tony submit` calls.

The analog of the reference's example training scripts' shared structure
(tony-examples, SURVEY.md §2.3) promoted into the framework: join the gang
(init_distributed), build the mesh from the env/args, shard-init the model,
step with throughput metrics, checkpoint on an interval, resume after a gang
restart.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time
from dataclasses import dataclass

import jax

from tony_tpu import constants
from tony_tpu.obs import logging as obs_logging
from tony_tpu.obs import metrics as obs_metrics
from tony_tpu.obs import trace as obs_trace
from tony_tpu.parallel import MeshSpec
from tony_tpu.runtime import init_distributed
from tony_tpu.train.checkpoint import UrgentSaveSignal, restore_or_init
from tony_tpu.train.input_pipeline import InputPipeline
from tony_tpu.train.metrics import detect_peak_flops, flops_per_token_for_batch
from tony_tpu.train.profiling import StepProfiler
from tony_tpu.train.trainer import (
    OptimizerConfig,
    Throughput,
    make_pp_train_step,
    make_train_step,
    sharded_init,
)

_FIRST_STEP_SECONDS = obs_metrics.gauge(
    "tony_train_first_step_seconds",
    "wall time of the first executed step (XLA compile + first run)")
_STEP_SECONDS = obs_metrics.histogram(
    "tony_train_step_seconds",
    "mean per-step wall time, sampled once per logging window")


@dataclass(frozen=True)
class LoopConfig:
    steps: int = 100
    #: LR-schedule horizon; 0 → ``steps``. Set it when a run will be
    #: extended (or was cut short) so warmup/decay stay anchored to the
    #: FULL plan — otherwise a 4-step run resumed to 8 decays twice as fast
    #: over its first half as the uninterrupted 8-step run did
    schedule_steps: int = 0
    #: GLOBAL batch rows per step — constant across gang sizes. Each of the
    #: K gang processes contributes ``batch_size // K`` rows, so an elastic
    #: restart onto a smaller gang keeps the optimization trajectory AND
    #: the data-replay contract (global-order draw) intact.
    batch_size: int = 8
    seq_len: int = 512
    log_every: int = 10
    checkpoint_dir: str = ""
    checkpoint_every: int = 0
    learning_rate: float = 3e-4
    warmup_steps: int = 100
    model_axis: int = 1
    context_axis: int = 1
    expert_axis: int = 1
    stage_axis: int = 1        # >1: pipeline parallelism (1F1B schedule)
    pp_microbatches: int = 4   # microbatches per 1F1B step (batch must divide)
    pp_chunks: int = 1         # >1: interleaved virtual stages per device
    data_dir: str = ""  # dir of *.tonytok shards; empty → synthetic batches
    data_seed: int = 0  # window-draw seed; FIXED across restarts (replay)
    #: input-pipeline lookahead: batch N+1 is assembled (loader read /
    #: synthetic draw + device transfer) on a background thread while the
    #: device runs step N (train/input_pipeline.py). -1 → the executor's
    #: tony.train.prefetch-depth (TONY_PREFETCH_DEPTH env; 2 outside a
    #: container); 0 → synchronous per-step assembly (the legacy path).
    prefetch_depth: int = -1


def _drop_train_metrics(line: dict) -> None:
    """Atomically publish the latest step report to the path the executor
    advertised (ENV_TRAIN_METRICS_FILE) — the metrics push loop attaches
    it to this task's heartbeat metrics so the AM/portal see training
    progress (loss/tokens_per_sec/mfu), not just host counters. No-op
    outside a tony container; never raises."""
    path = os.environ.get(constants.ENV_TRAIN_METRICS_FILE)
    if not path:
        return
    try:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(line, f)
        os.replace(tmp, path)
    except OSError:
        pass


def _drop_obs_metrics() -> None:
    """Atomically publish this child's non-empty metrics-registry snapshot
    next to the step report (<train-metrics-file>.obs): the executor merges
    it into its push_metrics piggyback so checkpoint/step-time instruments
    reach the AM's get_metrics and the portal's /metrics. No-op outside a
    tony container; never raises."""
    path = os.environ.get(constants.ENV_TRAIN_METRICS_FILE)
    if not path:
        return
    snap = [m for m in obs_metrics.REGISTRY.snapshot() if m["samples"]]
    if not snap:
        return
    try:
        tmp = path + ".obs.tmp"
        with open(tmp, "w") as f:
            json.dump(snap, f)
        os.replace(tmp, path + ".obs")
    except OSError:
        pass


def run_lm_training(model_module, model_cfg, loop: LoopConfig) -> dict:
    """Generic decoder-LM pretraining loop (llama/mixtral modules).

    model_module must expose init/loss_fn/sharding_rules/synthetic_batch and
    the config flops_per_token(). Returns the final metrics dict.

    Under a traced tony job (TONY_TRACE_* env from the executor) the whole
    run is one span with first-step (compile) and checkpoint child spans;
    outside a container the tracer is None and nothing is recorded.
    """
    if os.environ.get(constants.ENV_METRICS_ENABLED) == "0":
        obs_metrics.set_enabled(False)  # the job opted out (tony.metrics.enabled)
    # structured logging (tony.log.*): this child's records join the job-wide
    # <staging>/logs aggregate; outside a container the helpers echo only
    obs_logging.init_from_env()
    tracer = obs_trace.init_from_env()
    if tracer is None:
        return _run_lm_training(model_module, model_cfg, loop, None)
    root, token = tracer.start_span("train.run")
    root.set(steps=loop.steps, batch_size=loop.batch_size)
    tracer.root_parent = root.span_id
    try:
        result = _run_lm_training(model_module, model_cfg, loop, tracer)
    except BaseException:
        tracer.end_span(root, token, status="error")
        obs_trace.shutdown()
        raise
    tracer.end_span(root, token)
    obs_trace.shutdown()
    return result


def _run_lm_training(model_module, model_cfg, loop: LoopConfig, tracer) -> dict:
    if loop.stage_axis > 1 and not hasattr(model_module, "pp_value_and_grad"):
        # fail in milliseconds, not after a multi-GB sharded init/restore
        raise ValueError(
            f"{model_module.__name__} has no pp_value_and_grad — "
            "pipeline parallelism (stage_axis > 1) needs a model with a "
            "1F1B train-step core (llama and mixtral families have one)"
        )
    if loop.stage_axis > 1 and loop.pp_chunks > 1:
        import inspect

        sig = inspect.signature(model_module.pp_value_and_grad)
        if "num_chunks" not in sig.parameters:
            raise ValueError(
                f"{model_module.__name__}.pp_value_and_grad has no interleaved "
                "schedule (num_chunks) — --pp_chunks > 1 is llama-family only"
            )
    init_distributed()  # no-op off-gang; joins jax.distributed under tony
    spec = MeshSpec.auto(
        model=loop.model_axis, context=loop.context_axis, expert=loop.expert_axis,
        stage=loop.stage_axis,
    )
    # multi-slice pools (MultiSliceResourceManager) announce the DCN layout;
    # build() then restricts DCN crossings to data/pipeline axes
    num_slices = int(os.environ.get(constants.ENV_TPU_NUM_SLICES, "1") or "1")
    mesh = spec.build(num_slices=num_slices)
    n_chips = len(jax.devices())

    opt_cfg = OptimizerConfig(
        learning_rate=loop.learning_rate, warmup_steps=loop.warmup_steps,
        total_steps=loop.schedule_steps or loop.steps,
    )
    opt = opt_cfg.build()
    rules = model_module.sharding_rules(model_cfg)

    def init_state():
        return sharded_init(
            lambda: model_module.init(jax.random.PRNGKey(0), model_cfg), rules, mesh, opt
        )

    state, ckpt_mgr, start_step = restore_or_init(loop.checkpoint_dir or None, init_state)
    if start_step:
        obs_logging.info(f"[train] resumed from checkpoint step {start_step}", step=start_step)

    if loop.stage_axis > 1:
        # pipeline parallelism: the 1F1B schedule produces its own gradients
        # (hand-scheduled interleaved backward; see parallel/pipeline.py)
        step_fn = make_pp_train_step(
            functools.partial(
                model_module.pp_value_and_grad, cfg=model_cfg, mesh=mesh,
                num_microbatches=loop.pp_microbatches,
                **({"num_chunks": loop.pp_chunks} if loop.pp_chunks > 1 else {}),
            ),
            opt,
        )
    else:
        step_fn = make_train_step(
            functools.partial(model_module.loss_fn, cfg=model_cfg, mesh=mesh), opt
        )
    # gathered-MLM batches (BERT) project only the masked positions through
    # the vocab head — derive the flops basis from an actual batch so the
    # reported MFU matches the work done (shared helper with bench.py)
    probe = model_module.synthetic_batch(
        jax.random.PRNGKey(0), 1, loop.seq_len, model_cfg
    )
    meter = Throughput(
        tokens_per_step=loop.batch_size * loop.seq_len,
        flops_per_token=flops_per_token_for_batch(model_cfg, probe, loop.seq_len),
        n_chips=n_chips,
        peak_flops=detect_peak_flops(),
    )

    key = jax.random.PRNGKey(start_step + 1)
    procs = jax.process_count()
    if loop.batch_size % procs:
        raise ValueError(
            f"global batch_size {loop.batch_size} must divide by the gang's "
            f"{procs} processes (elastic restarts re-split the SAME global "
            "batch across the new gang)"
        )
    local_rows = loop.batch_size // procs
    loader = None
    if loop.data_dir:
        # Real data: the native prefetching loader, data-parallel split by
        # process (the TF_CONFIG-analog contract: each gang member owns a
        # contiguous row-slice of every GLOBAL batch).
        from pathlib import Path

        from tony_tpu.data import TokenLoader
        from tony_tpu.data.dataset import ConsumptionCursor

        paths = sorted(Path(loop.data_dir).glob("*.tonytok"))
        # exact replay on resume: the draw is a pure function of
        # (data_seed, GLOBAL slot), so keeping the seed and global batch
        # FIXED and starting the loader at the resumed step replays the
        # uninterrupted stream — no sample repeated or skipped — even when
        # the gang restarted at a DIFFERENT size (global-order contract,
        # data/native.py). The consumption cursor persisted next to each
        # checkpoint proves the resumed stream IS the checkpointed one: a
        # silently changed global batch or seed fails here instead of
        # double-consuming or dropping samples across the resize.
        if start_step and loop.checkpoint_dir:
            cursor = ConsumptionCursor.load(loop.checkpoint_dir, start_step)
            if cursor is not None:
                cursor.validate_resume(loop.batch_size, loop.data_seed, start_step)
                obs_logging.info(
                    f"[train] data cursor validated: resuming the global "
                    f"stream at batch {start_step} "
                    f"(written at world size {cursor.world_size}, now {procs})",
                    step=start_step,
                )
        loader = TokenLoader(
            paths, local_rows, loop.seq_len,
            shard_id=jax.process_index(), num_shards=procs,
            seed=loop.data_seed, start_index=start_step,
        )
        obs_logging.info(f"[train] data: {len(paths)} shards, {loader.total_tokens} tokens, "
                         f"native={loader.is_native}")

        def drop_cursor(next_batch: int) -> None:
            # rank 0 persists the consumption position with every checkpoint
            if jax.process_index() == 0:
                ConsumptionCursor(
                    global_batch_index=next_batch,
                    global_batch_size=loop.batch_size,
                    seed=loop.data_seed,
                    world_size=procs,
                ).save(loop.checkpoint_dir)
    else:
        def drop_cursor(next_batch: int) -> None:
            pass

    assemble = None
    if procs > 1:
        # each process contributes its contiguous row-slice; the global
        # batch array is sharded over the data-parallel mesh axes (the
        # spmd_train E2E pattern promoted into the loop)
        from jax.sharding import NamedSharding, PartitionSpec

        batch_sharding = NamedSharding(mesh, PartitionSpec(("data", "fsdp")))

        def assemble(local):
            import numpy as np

            return jax.make_array_from_process_local_data(
                batch_sharding, np.asarray(local)
            )

    def make_batch(step: int):
        """Pure-enough batch assembly for one step — the single definition
        both the synchronous and the overlapped pipeline paths run, so the
        fed batch sequence is bit-identical either way (the loader is only
        ever called from one thread, in step order)."""
        if loader is not None:
            local = loader.next()
            return {
                "tokens": assemble(local) if assemble else jax.numpy.asarray(local)
            }
        if assemble is not None:
            local = model_module.synthetic_batch(
                jax.random.fold_in(jax.random.fold_in(key, step), jax.process_index()),
                local_rows, loop.seq_len, model_cfg,
            )
            return {k: assemble(v) for k, v in local.items()}
        return model_module.synthetic_batch(
            jax.random.fold_in(key, step), loop.batch_size, loop.seq_len, model_cfg
        )

    metrics: dict = {}
    profiler = StepProfiler()  # no-op unless the executor exported TONY_PROFILE_DIR
    urgent = UrgentSaveSignal()  # cooperative-preemption checkpoint trigger
    pipeline = InputPipeline(
        make_batch, start_step, loop.steps,
        depth=None if loop.prefetch_depth < 0 else loop.prefetch_depth,
        tracer=tracer,
    )
    if pipeline.overlapped:
        obs_logging.info(
            f"[train] input pipeline: overlapped, depth {pipeline.depth}"
        )
    meter.start()
    # sampled step timing: one histogram observation (mean step wall time)
    # per logging window — the hot loop itself pays two int compares
    window_t0 = time.perf_counter()
    window_step0 = start_step
    try:
        for step in range(start_step, loop.steps):
            profiler.step(step)
            batch = pipeline.next(step)
            first = step == start_step
            if first:
                t_first = time.perf_counter()
            state, metrics = step_fn(state, batch)
            if first:
                # the first executed step is dominated by XLA compilation —
                # the critical-path item `tony trace` reports per worker
                jax.block_until_ready(metrics["loss"])
                first_s = time.perf_counter() - t_first
                _FIRST_STEP_SECONDS.set(first_s)
                if tracer is not None:
                    with tracer.span("train.first_step", step=step) as sp:
                        sp.start_ms -= first_s * 1000.0
                window_t0, window_step0 = time.perf_counter(), step + 1
            meter.step()
            if (step + 1) % loop.log_every == 0 or step + 1 == loop.steps:
                jax.block_until_ready(metrics["loss"])
                report = meter.report()
                line = {
                    "step": int(metrics["step"]),
                    "loss": round(float(metrics["loss"]), 4),
                    "grad_norm": round(float(metrics["grad_norm"]), 4),
                    "tokens_per_sec": round(report["tokens_per_sec"], 1),
                    "mfu": round(report["mfu"], 4),
                    "time": time.strftime("%H:%M:%S"),
                }
                obs_logging.info(json.dumps(line), **line)
                _drop_train_metrics(line)
                n_window = step + 1 - window_step0
                if n_window > 0:
                    _STEP_SECONDS.observe((time.perf_counter() - window_t0) / n_window)
                window_t0, window_step0 = time.perf_counter(), step + 1
                _drop_obs_metrics()  # after observe: the window's sample ships with it
                meter.start()
            saved_this_step = False
            if (
                ckpt_mgr is not None
                and loop.checkpoint_every
                and (step + 1) % loop.checkpoint_every == 0
            ):
                ckpt_mgr.save(step + 1, state)
                drop_cursor(step + 1)
                saved_this_step = True
            if ckpt_mgr is not None and (drain_req := urgent.poll()) is not None:
                # the pool is preempting this job (checkpoint-then-yield):
                # force-save NOW — synchronously, the gang dies the moment
                # every rank acknowledges — so the resumed gang loses only
                # the steps between this one and the kill. A periodic save
                # of this very step is not rewritten, just drained.
                obs_logging.warning(
                    f"[train] urgent pre-preemption checkpoint at step {step + 1}",
                    step=step + 1,
                )
                if not saved_this_step:
                    ckpt_mgr.save(step + 1, state, force=True)
                    drop_cursor(step + 1)
                ckpt_mgr.wait()
                urgent.acknowledge(drain_req, step + 1)
    finally:
        # a failed step/save must not leak the input-pipeline thread, the
        # loader's native prefetch threads + mmapped shards (gang restarts
        # re-enter this function in the same process) nor a dangling
        # profiler capture; pipeline first — its producer calls the loader
        producer_dead = pipeline.close()
        if loader is not None:
            if producer_dead:
                loader.close()
            else:
                # the producer is still inside a stalled loader read:
                # unmapping the shards under it would segfault — leak the
                # loader (daemon thread dies with the process) and say so
                obs_logging.warning(
                    "[train] input-pipeline producer did not exit within the "
                    "close deadline; leaving the data loader open"
                )
        profiler.stop()  # flush if the run ended inside the capture window
    if ckpt_mgr is not None:
        # skip if this step is already on disk (resume that ran no new steps)
        if ckpt_mgr.latest_step() != loop.steps:
            ckpt_mgr.save(loop.steps, state, force=True)
            drop_cursor(loop.steps)
        ckpt_mgr.wait()
        ckpt_mgr.close()
    _drop_obs_metrics()  # final flush: last window + final checkpoint sample
    return {k: float(v) for k, v in metrics.items() if hasattr(v, "item") or isinstance(v, (int, float))}


def parse_loop_args(argv: list[str] | None = None) -> tuple[LoopConfig, dict]:
    """Shared CLI for example scripts; returns (LoopConfig, extra model args)."""
    import argparse

    import os

    from tony_tpu import constants

    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--schedule_steps", type=int, default=0,
                   help="LR-schedule horizon (0 = --steps); set when extending runs")
    p.add_argument("--batch_size", type=int, default=8)
    p.add_argument("--seq_len", type=int, default=512)
    p.add_argument("--log_every", type=int, default=10)
    # checkpoint settings default from the executor-injected env (the
    # tony.checkpoint.* keys of the frozen job conf); CLI flags override
    p.add_argument(
        "--checkpoint_dir", default=os.environ.get(constants.ENV_CHECKPOINT_DIR, "")
    )
    try:
        env_interval = int(os.environ.get(constants.ENV_CHECKPOINT_INTERVAL, "0") or 0)
    except ValueError:
        # a malformed tony.checkpoint.interval-steps must not crash every
        # worker at argparse-construction time; fall back to "final only"
        obs_logging.warning(
            f"[train] ignoring non-integer {constants.ENV_CHECKPOINT_INTERVAL}="
            f"{os.environ[constants.ENV_CHECKPOINT_INTERVAL]!r}"
        )
        env_interval = 0
    p.add_argument("--checkpoint_every", type=int, default=env_interval)
    p.add_argument("--learning_rate", type=float, default=3e-4)
    p.add_argument("--warmup_steps", type=int, default=100)
    p.add_argument("--model_axis", type=int, default=1)
    p.add_argument("--context_axis", type=int, default=1)
    p.add_argument("--expert_axis", type=int, default=1)
    p.add_argument("--stage_axis", type=int, default=1,
                   help="pipeline stages (1F1B schedule when > 1)")
    p.add_argument("--pp_microbatches", type=int, default=4)
    p.add_argument("--pp_chunks", type=int, default=1,
                   help=">1: interleaved 1F1B (virtual stage chunks per device; "
                        "llama family)")
    p.add_argument("--data_dir", default="")
    p.add_argument("--data_seed", type=int, default=0)
    p.add_argument("--prefetch_depth", type=int, default=-1,
                   help="input-pipeline lookahead; -1 = tony.train.prefetch-"
                        "depth via env (default 2), 0 = synchronous assembly")
    p.add_argument("--preset", default="tiny")
    args = p.parse_args(argv if argv is not None else sys.argv[1:])
    d = vars(args)
    preset = d.pop("preset")
    return LoopConfig(**d), {"preset": preset}
