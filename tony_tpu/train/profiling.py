"""Per-worker profiler capture: first-class what the reference delegated.

The reference's only profiling story is scheduling a ``tensorboard`` task and
registering its URL (SURVEY.md §5.1); trace capture itself lived inside the
user's TF. Here the framework owns it, two ways:

- **Submit-time window** (``tony.task.profile=true``): each executor exports
  ``TONY_PROFILE_DIR`` and the training loop captures a ``jax.profiler``
  trace for a fixed step window into that directory.
- **On-demand** (``tony profile <app_id>``, docs/observability.md): a RUNNING
  job is asked to capture with no resubmit. The executor relays the request
  by writing a control file next to ``<train-metrics-file>`` (the established
  piggyback contract; obs/introspect.py); :class:`StepProfiler` polls for it
  at step boundaries — a time-throttled ``stat``, nothing allocated while
  unarmed — arms at the next boundary, captures N steps (plus an optional
  device memory profile), records per-step wall times, and drops a done file
  the executor reports back through the AM.

Artifacts are TensorBoard-profile-plugin viewable either way (including via
the ``tensorboard`` sidecar task type, whose URL the AM registers).
"""

from __future__ import annotations

import json
import os
import time

from tony_tpu import constants
from tony_tpu.obs import introspect as _introspect
from tony_tpu.obs import trace as obs_trace

#: the env names are defined in constants so the executor supervisor can
#: export them without importing this package (and with it jax)
ENV_PROFILE_DIR = constants.ENV_PROFILE_DIR
ENV_PROFILE_START_STEP = constants.ENV_PROFILE_START_STEP
ENV_PROFILE_NUM_STEPS = constants.ENV_PROFILE_NUM_STEPS
ENV_PROFILE_POLL_MS = constants.ENV_PROFILE_POLL_MS


class StepProfiler:
    """Captures ``jax.profiler`` traces over windows of training steps.

    Driven from env (the executor↔user-process contract) so any training
    program run under tony profiles without code changes beyond calling
    ``step()`` once per iteration — the framework's own loop does.

    Static window semantics: trace starts when ``step()`` is called with
    ``step == start_step`` and stops ``num_steps`` steps later (default:
    start at 3 — past compile — for 5 steps).

    On-demand semantics: when a control file appears next to the
    train-metrics drop, the capture arms at the next step boundary, runs for
    the requested number of steps (wall-timing each), then finalizes into the
    requested artifact directory and writes the done record. ``stop()`` —
    called from the train-loop ``finally`` — finalizes a capture the run
    ended inside of, so the trace file is never left unterminated and the
    done record always lands (marked ``truncated``).
    """

    def __init__(self, env: dict[str, str] | None = None):
        env = dict(os.environ if env is None else env)
        self.trace_dir = env.get(ENV_PROFILE_DIR) or ""
        self.start_step = int(env.get(ENV_PROFILE_START_STEP, "3"))
        self.num_steps = int(env.get(ENV_PROFILE_NUM_STEPS, "5"))
        self.active = False
        self.done = False
        # on-demand plane: armed only inside a tony container (the executor
        # exported the train-metrics path the control file sits next to)
        metrics_path = env.get(constants.ENV_TRAIN_METRICS_FILE) or ""
        self.control_path = metrics_path + _introspect.CONTROL_SUFFIX if metrics_path else ""
        self.done_path = metrics_path + _introspect.DONE_SUFFIX if metrics_path else ""
        try:
            poll_ms = float(env.get(ENV_PROFILE_POLL_MS, "500") or "500")
        except ValueError:
            poll_ms = 500.0
        self._poll_s = max(poll_ms, 1.0) / 1000.0
        self._next_poll = 0.0
        self._request: dict | None = None   # the armed on-demand capture
        self._handled: set[str] = set()     # req_ids already acted on
        self._step_times_ms: list[float] = []
        self._last_step_t = 0.0
        self._span = None                   # (Span, token) while capturing

    @property
    def enabled(self) -> bool:
        return bool(self.trace_dir)

    def step(self, step: int) -> None:
        """Call once per training step (before or after the step body)."""
        if self._request is not None:
            self._on_demand_step(step)
        elif self.control_path:
            now = time.monotonic()
            if now >= self._next_poll:
                self._next_poll = now + self._poll_s
                self._maybe_arm(step)
        if not self.enabled or self.done:
            return
        if not self.active and self._request is None and step >= self.start_step:
            self._start()
        elif self.active and step >= self.start_step + self.num_steps:
            self.stop()

    # -- static window -----------------------------------------------------
    def _start(self) -> None:
        import jax

        os.makedirs(self.trace_dir, exist_ok=True)
        jax.profiler.start_trace(self.trace_dir)
        self.active = True

    def stop(self) -> None:
        """Idempotent; also the end-of-training flush for short runs — and
        for an on-demand capture the run ended inside of (the train-loop
        ``finally`` calls this, so neither window leaks an open trace)."""
        if self._request is not None:
            self._finalize_on_demand(truncated=True)
        if not self.active:
            return
        import jax

        jax.profiler.stop_trace()
        self.active = False
        self.done = True

    # -- on-demand capture -------------------------------------------------
    def _maybe_arm(self, step: int) -> None:
        req = _introspect.read_json(self.control_path)
        if req is None:
            return
        req_id = str(req.get("req_id") or "")
        if not req_id or req_id in self._handled:
            return
        if self.active:
            return  # a static window is live; retry once it closes
        self._handled.add(req_id)
        num_steps = max(int(req.get("num_steps", 5) or 5), 1)
        out_dir = req.get("dir") or os.path.join(
            os.path.dirname(self.control_path), "profile", req_id
        )
        try:
            import jax

            os.makedirs(out_dir, exist_ok=True)
            jax.profiler.start_trace(out_dir)
        except Exception as e:  # noqa: BLE001 — capture failure must not kill training
            self._write_done(req_id, out_dir, ok=False,
                             error=f"{type(e).__name__}: {e}")
            return
        self._request = {
            "req_id": req_id,
            "dir": out_dir,
            "num_steps": num_steps,
            "memory": bool(req.get("memory")),
            "start_step": step,
        }
        self._step_times_ms = []
        self._last_step_t = time.perf_counter()
        tracer = obs_trace.get()
        if tracer is not None:
            span, token = tracer.start_span("profile.capture")
            span.set(req_id=req_id, num_steps=num_steps)
            self._span = (span, token)

    def _on_demand_step(self, step: int) -> None:
        now = time.perf_counter()
        self._step_times_ms.append((now - self._last_step_t) * 1000.0)
        self._last_step_t = now
        req = self._request
        assert req is not None
        if step >= req["start_step"] + req["num_steps"]:
            self._finalize_on_demand(truncated=False)

    def _finalize_on_demand(self, truncated: bool) -> None:
        req = self._request
        if req is None:
            return
        self._request = None
        error = ""
        try:
            import jax

            jax.profiler.stop_trace()
            if req["memory"]:
                jax.profiler.save_device_memory_profile(
                    os.path.join(req["dir"], "memory.prof")
                )
        except Exception as e:  # noqa: BLE001 — capture failure must not kill training
            error = f"{type(e).__name__}: {e}"
        self._write_done(
            req["req_id"], req["dir"],
            ok=not error,
            error=error,
            steps_captured=len(self._step_times_ms),
            step_times_ms=[round(t, 3) for t in self._step_times_ms],
            truncated=truncated,
        )
        if self._span is not None:
            span, token = self._span
            self._span = None
            span.set(truncated=truncated)
            tracer = obs_trace.get()
            if tracer is not None:
                tracer.end_span(span, token, status="error" if error else "ok")

    def _write_done(self, req_id: str, out_dir: str, ok: bool, error: str = "",
                    **extra) -> None:
        artifacts = []
        for root, _, files in os.walk(out_dir):
            for fn in files:
                artifacts.append(
                    os.path.relpath(os.path.join(root, fn), out_dir)
                )
        payload = {
            "req_id": req_id, "ok": ok, "dir": out_dir,
            "artifacts": sorted(artifacts), "error": error, **extra,
        }
        try:
            _introspect.write_json_atomic(self.done_path, payload)
        except OSError:
            pass  # reporting is best-effort; the artifacts are on disk
