"""Per-worker profiler capture: first-class what the reference delegated.

The reference's only profiling story is scheduling a ``tensorboard`` task and
registering its URL (SURVEY.md §5.1); trace capture itself lived inside the
user's TF. Here the framework owns it: when a job is submitted with
``tony.task.profile=true``, each executor exports ``TONY_PROFILE_DIR`` and the
training loop captures a ``jax.profiler`` trace for a step window into that
directory — viewable with TensorBoard's profile plugin (including via the
``tensorboard`` sidecar task type, whose URL the AM registers).
"""

from __future__ import annotations

import os

ENV_PROFILE_DIR = "TONY_PROFILE_DIR"
ENV_PROFILE_START_STEP = "TONY_PROFILE_START_STEP"
ENV_PROFILE_NUM_STEPS = "TONY_PROFILE_NUM_STEPS"


class StepProfiler:
    """Captures a ``jax.profiler`` trace over a window of training steps.

    Driven from env (the executor↔user-process contract) so any training
    program run under tony profiles without code changes beyond calling
    ``step()`` once per iteration — the framework's own loop does.

    Window semantics: trace starts when ``step() `` is called with
    ``step == start_step`` and stops ``num_steps`` steps later (default:
    start at 3 — past compile — for 5 steps).
    """

    def __init__(self, env: dict[str, str] | None = None):
        env = dict(os.environ if env is None else env)
        self.trace_dir = env.get(ENV_PROFILE_DIR) or ""
        self.start_step = int(env.get(ENV_PROFILE_START_STEP, "3"))
        self.num_steps = int(env.get(ENV_PROFILE_NUM_STEPS, "5"))
        self.active = False
        self.done = False

    @property
    def enabled(self) -> bool:
        return bool(self.trace_dir)

    def step(self, step: int) -> None:
        """Call once per training step (before or after the step body)."""
        if not self.enabled or self.done:
            return
        if not self.active and step >= self.start_step:
            self._start()
        elif self.active and step >= self.start_step + self.num_steps:
            self.stop()

    def _start(self) -> None:
        import jax

        os.makedirs(self.trace_dir, exist_ok=True)
        jax.profiler.start_trace(self.trace_dir)
        self.active = True

    def stop(self) -> None:
        """Idempotent; also the end-of-training flush for short runs."""
        if not self.active:
            return
        import jax

        jax.profiler.stop_trace()
        self.active = False
        self.done = True
