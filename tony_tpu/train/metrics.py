"""Throughput/MFU accounting (SURVEY.md §5.5 rebuild duty).

Peak-FLOPs table for MFU is per-chip bf16 dense compute; MFU =
model_flops_per_token * tokens_per_sec / (peak * chips). The reference
published no throughput numbers (BASELINE.md) — these are the numbers this
framework measures about itself.
"""

from __future__ import annotations

import jax

# bf16 dense peak FLOPs per chip
PEAK_FLOPS = {
    "v5e": 197e12,
    "v5 lite": 197e12,   # PJRT device_kind spelling on v5e
    "v6e": 918e12,
    "v5p": 459e12,
    "v4": 275e12,
    "cpu": 1e12,         # nominal; keeps MFU finite in CPU test runs
}


def detect_peak_flops(device=None) -> float:
    d = device or jax.devices()[0]
    kind = getattr(d, "device_kind", "cpu").lower()
    for name, peak in PEAK_FLOPS.items():
        if name in kind:
            return peak
    return PEAK_FLOPS["cpu"]


def flops_per_token_for_batch(model_cfg, batch: dict, seq_len: int) -> int:
    """The model's flops/token ON THIS BATCH LAYOUT — the one place that
    knows gathered-MLM batches (``masked_pos``) only project the masked
    fraction through the vocab head. Both bench.py and the training loop
    derive their MFU basis here so they cannot drift."""
    if "masked_pos" in batch:
        return model_cfg.flops_per_token(batch["masked_pos"].shape[1] / seq_len)
    return model_cfg.flops_per_token()


def transformer_flops_per_token(
    n_params: int, n_layers: int, d_model: int, seq_len: int, *, training: bool = True
) -> int:
    """6N (fwd+bwd) + causal-attention term 12·L·D·T (PaLM appendix formula)."""
    mult = 6 if training else 2
    attn = (12 if training else 4) * n_layers * d_model * seq_len // 2  # causal halves it
    return mult * n_params + attn
