"""Overlapped input pipeline: assemble batch N+1 while the device runs step N.

The training loop's per-step input work — TokenLoader read, synthetic
generation, and the host-to-device transfer with the target batch sharding
(``jax.make_array_from_process_local_data`` / ``jnp.asarray``) — used to run
synchronously on the step path: the device sat idle while the host built the
next batch, and the host sat idle while the device computed. This module
double-buffers the two: a background thread assembles batches ahead (bounded
by ``depth``, default 2) and the step loop's :meth:`next` is a queue pop that
only blocks when input assembly is genuinely slower than compute.

Contracts the train loop relies on:

- **Batch-sequence parity**: ``make_batch(step)`` is invoked for exactly
  ``start_step, start_step+1, …`` in order, once each, on one thread —
  identical to the synchronous path, so a seeded run feeds bit-identical
  batches either way (asserted in tests/test_input_pipeline.py). With
  ``depth <= 0`` the pipeline IS the synchronous path: ``next`` calls
  ``make_batch`` inline, no thread exists.
- **Exception propagation**: a producer failure is re-raised from ``next``
  on the step loop's thread (with the original traceback as ``__cause__``),
  never swallowed — the loop's existing ``finally`` teardown runs.
- **Clean shutdown**: ``close`` is idempotent, unblocks a producer parked on
  a full queue, and joins the thread — safe to call from the ``finally``
  block mid-run (step failure, urgent-save drain) or after exhaustion.
- **Attributable waits**: every blocking ``next`` feeds the
  ``tony_train_input_wait_seconds`` histogram, and waits at or above
  ``span_min_ms`` emit a backdated ``train.input_wait`` span so the goodput
  ledger (obs/goodput.py) can charge the stall to the ``input_wait`` phase
  instead of diluting ``productive``.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Any, Callable

from tony_tpu import constants
from tony_tpu.obs import metrics as obs_metrics

_INPUT_WAIT_SECONDS = obs_metrics.histogram(
    "tony_train_input_wait_seconds",
    "time the step loop blocked waiting on the input pipeline, per step")

#: queue entries: ("batch", step, value) | ("error", step, exc) | ("end",)
_BATCH, _ERROR, _END = "batch", "error", "end"


def depth_from_env(env: dict[str, str] | None = None) -> int:
    """The executor-exported prefetch depth (``tony.train.prefetch-depth``
    → ``TONY_PREFETCH_DEPTH``); 2 outside a tony container. 0 disables the
    overlap (synchronous assembly, the pre-pipeline behavior)."""
    env = os.environ if env is None else env
    try:
        return int(env.get(constants.ENV_PREFETCH_DEPTH, "2") or "2")
    except ValueError:
        return 2


def span_min_ms_from_env(env: dict[str, str] | None = None) -> float:
    env = os.environ if env is None else env
    try:
        return float(env.get(constants.ENV_INPUT_WAIT_SPAN_MS, "25") or "25")
    except ValueError:
        return 25.0


class InputPipelineError(RuntimeError):
    """A batch producer failure, re-raised on the step loop's thread."""


class InputPipeline:
    """Bounded-lookahead batch prefetcher over a ``make_batch(step)`` callable.

    ``make_batch`` must be a pure-enough function of ``step`` (stateful
    sources like TokenLoader are fine — they are only ever called from the
    single producer thread, in step order). The producer runs ``depth``
    batches ahead at most; device-transfer work inside ``make_batch``
    (``jnp.asarray`` / ``make_array_from_process_local_data``) is safe on
    the background thread — JAX transfers are thread-safe and enqueue
    without blocking device compute.
    """

    def __init__(
        self,
        make_batch: Callable[[int], Any],
        start_step: int,
        end_step: int,
        depth: int | None = None,
        tracer=None,
        span_min_ms: float | None = None,
    ):
        self.make_batch = make_batch
        self.start_step = start_step
        self.end_step = end_step
        self.depth = depth_from_env() if depth is None else depth
        self.tracer = tracer
        self.span_min_ms = span_min_ms_from_env() if span_min_ms is None else span_min_ms
        self.wait_s_total = 0.0
        self._next_step = start_step          # sync path / parity bookkeeping
        self._closed = False
        self._thread: threading.Thread | None = None
        if self.depth > 0 and end_step > start_step:
            self._queue: queue.Queue = queue.Queue(maxsize=self.depth)
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._produce, name="tony-input-pipeline", daemon=True
            )
            self._thread.start()

    @property
    def overlapped(self) -> bool:
        return self._thread is not None

    # -- producer ------------------------------------------------------------
    def _produce(self) -> None:
        step = self.start_step
        try:
            while step < self.end_step and not self._stop.is_set():
                item = (_BATCH, step, self.make_batch(step))
                step += 1
                while not self._stop.is_set():
                    try:
                        self._queue.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue  # consumer is busy computing; re-check stop
            if not self._stop.is_set():
                self._queue.put((_END,))
        except BaseException as e:  # noqa: BLE001 — ship it to the consumer
            # same stop-rechecking retry as the batch path: with the queue
            # full of ready batches and a slow device step, a bounded put
            # would drop the error and leave next() parked forever once the
            # buffered batches drain — the error must outlive the backlog
            item = (_ERROR, step, e)
            while not self._stop.is_set():
                try:
                    self._queue.put(item, timeout=0.1)
                    break
                except queue.Full:
                    continue

    # -- consumer ------------------------------------------------------------
    def next(self, step: int) -> Any:
        """The batch for ``step``; called with consecutive steps starting at
        ``start_step``. Blocks only while the producer is behind; re-raises
        a producer failure; raises StopIteration past ``end_step``."""
        if self._closed:
            raise RuntimeError("InputPipeline.next() after close()")
        if step != self._next_step:
            raise ValueError(
                f"out-of-order batch request: step {step}, expected {self._next_step}"
            )
        if step >= self.end_step:
            raise StopIteration(step)
        self._next_step = step + 1
        if self._thread is None:
            return self.make_batch(step)
        t0 = time.perf_counter()
        item = self._queue.get()
        wait = time.perf_counter() - t0
        self.wait_s_total += wait
        _INPUT_WAIT_SECONDS.observe(wait)
        if self.tracer is not None and wait * 1000.0 >= self.span_min_ms:
            # backdated like train.first_step: the span covers the stall
            with self.tracer.span("train.input_wait", step=step) as sp:
                sp.start_ms -= wait * 1000.0
        if item[0] == _ERROR:
            raise InputPipelineError(
                f"input pipeline failed assembling batch {item[1]}"
            ) from item[2]
        if item[0] == _END:
            raise StopIteration(step)
        return item[2]

    # -- teardown ------------------------------------------------------------
    def close(self) -> bool:
        """Idempotent; stops the producer, drains the queue so a producer
        parked on ``put`` wakes, and joins the thread. Returns True when the
        producer is known dead (or never existed) — False means it is still
        inside ``make_batch`` (a stalled loader read) and the caller must
        NOT tear down resources the producer may be touching."""
        if self._closed:
            return self._thread is None or not self._thread.is_alive()
        self._closed = True
        if self._thread is None:
            return True
        self._stop.set()
        while True:  # drain: the producer's put(timeout) re-checks _stop
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=5.0)
        return not self._thread.is_alive()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
