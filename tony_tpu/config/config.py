"""Layered job configuration with freeze-to-artifact semantics.

Analog of the reference's layered Hadoop ``Configuration``
(SURVEY.md §5.6): ``tony-default.xml`` ← ``tony-site.xml`` ← ``--conf_file`` ←
``--conf k=v``, frozen to a single ``tony-final.xml`` artifact shipped to the
AM and every executor so one config artifact is the whole-job truth.

Here the carrier is a flat ``str -> str`` mapping (like Hadoop Configuration)
with typed accessors, and the frozen artifact is ``tony-final.json``.
Conf files may be JSON (flat or nested), TOML, or Hadoop-style XML
(``<configuration><property><name>..</name><value>..</value>``) for parity
with reference job files like tony-examples/mnist-tensorflow/tony.xml.
"""

from __future__ import annotations

import json
import os
import re
import xml.etree.ElementTree as ET
from typing import Any, Iterator, Mapping

from tony_tpu import constants
from tony_tpu.config import keys

_TIME_RE = re.compile(r"^(\d+)(ms|s|m|h|d)?$")
_MEM_RE = re.compile(r"^(\d+)([kmgt]?)b?$", re.IGNORECASE)

_TIME_MULT = {"ms": 1, "s": 1000, "m": 60_000, "h": 3_600_000, "d": 86_400_000, None: 1}
_MEM_MULT = {"": 1, "k": 1024, "m": 1024**2, "g": 1024**3, "t": 1024**4}


def _flatten(obj: Any, prefix: str = "") -> Iterator[tuple[str, str]]:
    """Flatten nested dicts to dotted keys; scalars become strings."""
    if isinstance(obj, Mapping):
        for k, v in obj.items():
            key = f"{prefix}.{k}" if prefix else str(k)
            yield from _flatten(v, key)
    elif isinstance(obj, bool):
        yield prefix, "true" if obj else "false"
    elif isinstance(obj, (list, tuple)):
        yield prefix, ",".join(str(x) for x in obj)
    elif obj is None:
        yield prefix, ""
    else:
        yield prefix, str(obj)


def parse_memory_string(mem: str) -> int:
    """'2g' → bytes. Analog of Utils.parseMemoryString (reference Utils.java)."""
    m = _MEM_RE.match(str(mem).strip())
    if not m:
        raise ValueError(f"unparseable memory string: {mem!r}")
    return int(m.group(1)) * _MEM_MULT[m.group(2).lower()]


def parse_time_ms(val: str) -> int:
    """'500', '500ms', '5s', '2m' → milliseconds."""
    m = _TIME_RE.match(str(val).strip())
    if not m:
        raise ValueError(f"unparseable time string: {val!r}")
    return int(m.group(1)) * _TIME_MULT[m.group(2)]


class TonyConfig:
    """Flat, layered, string-valued configuration.

    Layering is applied by construction order: later ``set``/``update_from``
    calls win. ``freeze()`` produces the immutable whole-job artifact.
    """

    def __init__(self, data: Mapping[str, str] | None = None, *, with_defaults: bool = True):
        self._data: dict[str, str] = dict(keys.DEFAULTS) if with_defaults else {}
        self._frozen = False
        if data:
            self.update_from(data)

    # -- mutation ----------------------------------------------------------
    def set(self, key: str, value: Any) -> "TonyConfig":
        if self._frozen:
            raise RuntimeError("config is frozen (tony-final artifact is immutable)")
        for k, v in _flatten(value, key):
            self._data[k] = v
        return self

    def update_from(self, mapping: Mapping[str, Any]) -> "TonyConfig":
        for k, v in mapping.items():
            self.set(k, v)
        return self

    def load_file(self, path: str | os.PathLike) -> "TonyConfig":
        """Layer a conf file on top: .json, .toml, or Hadoop-style .xml."""
        path = os.fspath(path)
        if path.endswith(".xml"):
            self.update_from(_parse_hadoop_xml(path))
        elif path.endswith(".toml"):
            try:
                import tomllib
            except ImportError:  # py<3.11: the backport package, same API
                import tomli as tomllib

            with open(path, "rb") as f:
                self.update_from(dict(_flatten(tomllib.load(f))))
        else:
            with open(path) as f:
                self.update_from(dict(_flatten(json.load(f))))
        return self

    def set_kv_args(self, conf_args: list[str]) -> "TonyConfig":
        """Apply ``--conf key=value`` CLI overrides (highest layer)."""
        for arg in conf_args:
            if "=" not in arg:
                raise ValueError(f"--conf expects key=value, got {arg!r}")
            k, _, v = arg.partition("=")
            self.set(k.strip(), v.strip())
        return self

    # -- typed accessors ---------------------------------------------------
    def get(self, key: str, default: str | None = None) -> str | None:
        return self._data.get(key, default)

    def __getitem__(self, key: str) -> str:
        return self._data[key]

    def __contains__(self, key: str) -> bool:
        return key in self._data

    def get_int(self, key: str, default: int = 0) -> int:
        v = self._data.get(key)
        return int(v) if v not in (None, "") else default

    def get_float(self, key: str, default: float = 0.0) -> float:
        v = self._data.get(key)
        return float(v) if v not in (None, "") else default

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self._data.get(key)
        if v in (None, ""):
            return default
        return str(v).strip().lower() in ("true", "1", "yes", "on")

    def get_time_ms(self, key: str, default: int = 0) -> int:
        v = self._data.get(key)
        return parse_time_ms(v) if v not in (None, "") else default

    def get_memory_bytes(self, key: str, default: int = 0) -> int:
        v = self._data.get(key)
        return parse_memory_string(v) if v not in (None, "") else default

    def get_list(self, key: str, default: tuple[str, ...] = ()) -> tuple[str, ...]:
        v = self._data.get(key)
        if v in (None, ""):
            return tuple(default)
        return tuple(s.strip() for s in v.split(",") if s.strip())

    # -- per-jobtype parameterized access (tony.<type>.*) ------------------
    def job_types(self) -> tuple[str, ...]:
        """All job types with a declared instance count, stable order.

        Mirrors how the reference discovers the gang from
        ``tony.<jobtype>.instances`` keys (TonyConfigurationKeys / Utils).
        """
        found = []
        for k in self._data:
            m = re.match(r"^tony\.([A-Za-z0-9_\-]+)\.instances$", k)
            if m and m.group(1) not in ("task", "am", "application"):
                if self.get_int(k, 0) > 0:
                    found.append(m.group(1))
        return tuple(sorted(found))

    def instances(self, jobtype: str) -> int:
        return self.get_int(keys.jobtype_key(jobtype, keys.INSTANCES_SUFFIX), 0)

    def untracked_types(self) -> frozenset[str]:
        return frozenset(self.get_list(keys.APPLICATION_UNTRACKED_TYPES))

    def tracked_types(self) -> tuple[str, ...]:
        untracked = self.untracked_types()
        return tuple(t for t in self.job_types() if t not in untracked)

    def dependencies(self) -> dict[str, dict[str, int]]:
        """{depender: {dependee: timeout_ms}} from dependency.* keys."""
        out: dict[str, dict[str, int]] = {}
        pat = re.compile(
            re.escape(keys.DEPENDENCY_PREFIX) + r"([A-Za-z0-9_\-]+)\.timeout\.after\.([A-Za-z0-9_\-]+)$"
        )
        for k, v in self._data.items():
            m = pat.match(k)
            if m:
                out.setdefault(m.group(1), {})[m.group(2)] = parse_time_ms(v)
        return out

    # -- freeze / artifact I/O --------------------------------------------
    def freeze(self) -> "TonyConfig":
        self._frozen = True
        return self

    @property
    def frozen(self) -> bool:
        return self._frozen

    def to_dict(self) -> dict[str, str]:
        return dict(self._data)

    def write_final(self, directory: str | os.PathLike) -> str:
        """Write the frozen whole-job artifact (tony-final.xml analog)."""
        path = os.path.join(os.fspath(directory), constants.TONY_FINAL_CONF)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._data, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return path

    @classmethod
    def load_final(cls, path: str | os.PathLike) -> "TonyConfig":
        """Load a frozen artifact verbatim (no re-layering of defaults)."""
        with open(path) as f:
            cfg = cls(json.load(f), with_defaults=False)
        cfg.freeze()
        return cfg

    @classmethod
    def from_layers(
        cls,
        site_file: str | None = None,
        conf_file: str | None = None,
        conf_args: list[str] | None = None,
    ) -> "TonyConfig":
        """defaults ← site ← conf_file ← --conf k=v (reference layer order)."""
        cfg = cls()
        if site_file and os.path.exists(site_file):
            cfg.load_file(site_file)
        if conf_file:
            cfg.load_file(conf_file)
        if conf_args:
            cfg.set_kv_args(conf_args)
        return cfg

    def __len__(self) -> int:
        return len(self._data)

    def __repr__(self) -> str:
        return f"TonyConfig({len(self._data)} keys, frozen={self._frozen})"


def _parse_hadoop_xml(path: str) -> dict[str, str]:
    """Parse ``<configuration><property><name/><value/></property>...`` files."""
    root = ET.parse(path).getroot()
    out: dict[str, str] = {}
    for prop in root.iter("property"):
        name = prop.findtext("name")
        if name is None:
            raise ValueError(f"{path}: <property> missing <name>")
        out[name.strip()] = (prop.findtext("value") or "").strip()
    return out
