"""The ``tony.*`` configuration-key namespace, with defaults.

Analog of the reference's ``TonyConfigurationKeys.java`` plus
``tony-core/src/main/resources/tony-default.xml`` (SURVEY.md §2.1, §5.6):
every knob the framework reads is declared here, with its default, so the
config-completeness unit test (mirroring TestTonyConfigurationFields) can
assert the registry and the defaults artifact never drift apart.

Naming keeps the reference's dotted namespace (``tony.application.*``,
``tony.am.*``, ``tony.task.*``, per-job-type ``tony.<jobtype>.*``) so configs
look familiar; TPU-specific keys replace GPU/YARN ones (``tony.<type>.gpus`` →
``tony.<type>.chips`` / ``tony.<type>.slice``).
"""

from __future__ import annotations

# ---------------------------------------------------------------------------
# tony.application.* — job-level
# ---------------------------------------------------------------------------
APPLICATION_NAME = "tony.application.name"
APPLICATION_QUEUE = "tony.application.queue"
APPLICATION_PRIORITY = "tony.application.priority"  # int; higher runs first within a queue
# Elastic-downsize hysteresis: the pool's capacity must stay short for this
# long (continuously) before the AM applies a min-instances shrink — a node
# heartbeat blip coinciding with an unrelated restart must not permanently
# halve the gang. While waiting, the gang queues at full size and retries.
APPLICATION_DOWNSIZE_GRACE_MS = "tony.application.downsize-grace-ms"
APPLICATION_FRAMEWORK = "tony.application.framework"      # jax|tensorflow|pytorch|horovod|mxnet|generic
APPLICATION_UNTRACKED_TYPES = "tony.application.untracked.jobtypes"  # csv; don't gate job verdict
APPLICATION_NODE_LABEL = "tony.application.node-label"
APPLICATION_SECURITY_ENABLED = "tony.application.security.enabled"
APPLICATION_PREPARE_STAGE_TIMEOUT_MS = "tony.application.prepare-timeout-ms"
# dependency ordering: tony.application.dependency.<A>.timeout.after.<B> = ms
DEPENDENCY_PREFIX = "tony.application.dependency."
APPLICATION_TAGS = "tony.application.tags"

# ---------------------------------------------------------------------------
# tony.am.* — application master
# ---------------------------------------------------------------------------
AM_RETRY_COUNT = "tony.am.retry-count"
# Work-preserving AM restart (docs/fault-tolerance.md "Control-plane
# failures"): the AM journals its recoverable state (gang epoch, per-task
# registrations, container map, pending resizes, chaos progress) to
# <staging>/am_journal.jsonl, and a retried AM attempt replays it to ADOPT
# the live gang — executors ride out the outage on their missed-heartbeat
# budget and re-sync, the training children never stop. false restores the
# pre-takeover behavior: every AM retry is a full gang restart.
AM_TAKEOVER_ENABLED = "tony.am.takeover.enabled"
# Takeover-journal compaction, same contract as tony.pool.journal.compact-every:
# after this many appends the monitor loop folds the recoverable state into a
# snapshot record and rotates am_journal.jsonl. 0 (default) never compacts.
AM_JOURNAL_COMPACT_EVERY = "tony.am.journal.compact-every"
AM_RPC_PORT = "tony.am.rpc.port"                  # 0 = ephemeral
AM_GANG_TIMEOUT_MS = "tony.am.gang-timeout-ms"    # max wait for full gang registration
AM_MONITOR_INTERVAL_MS = "tony.am.monitor-interval-ms"
AM_MEMORY = "tony.am.memory"
AM_VCORES = "tony.am.vcores"

# ---------------------------------------------------------------------------
# tony.task.* — executor / liveness contract
# ---------------------------------------------------------------------------
TASK_HEARTBEAT_INTERVAL_MS = "tony.task.heartbeat-interval-ms"
TASK_MAX_MISSED_HEARTBEATS = "tony.task.max-missed-heartbeats"
TASK_METRICS_INTERVAL_MS = "tony.task.metrics-interval-ms"
TASK_EXECUTOR_REGISTRATION_TIMEOUT_MS = "tony.task.registration-timeout-ms"
TASK_EXECUTOR_EXECUTION_TIMEOUT_MS = "tony.task.execution-timeout-ms"  # 0 = unlimited
TASK_KILL_GRACE_MS = "tony.task.kill-grace-ms"     # SIGTERM→SIGKILL window (serve tasks drain here)
TASK_RESTART_ON_FAILURE = "tony.task.restart-on-failure"  # gang-restart-from-checkpoint
TASK_MAX_TOTAL_INSTANCE_FAILURES = "tony.task.max-total-instance-failures"
TASK_PROFILE = "tony.task.profile"                 # capture jax.profiler traces per worker
TASK_PROFILE_START_STEP = "tony.task.profile.start-step"
TASK_PROFILE_NUM_STEPS = "tony.task.profile.num-steps"

# ---------------------------------------------------------------------------
# Per-job-type parameterized keys: tony.<jobtype>.<suffix>
# (analog: tony.<jobtype>.{instances,memory,vcores,gpus}; gpus→chips/slice)
# ---------------------------------------------------------------------------
INSTANCES_SUFFIX = "instances"
MEMORY_SUFFIX = "memory"
VCORES_SUFFIX = "vcores"
CHIPS_SUFFIX = "chips"          # TPU chips per task (reference: gpus)
SLICE_SUFFIX = "slice"          # TPU slice spec per task gang, e.g. "v5e-8" or "2x4"
COMMAND_SUFFIX = "command"      # per-type command override (reference: tony.<type>.command)
# Elastic floor: on gang restart, if the pool's ALIVE capacity can no longer
# fit the configured gang (node permanently lost), the AM may re-plan this
# type down to min-instances and the workers restore the checkpoint onto the
# smaller mesh (data/fsdp-axis jobs — the global-order data replay keeps the
# sample stream exact). Absent/0 → the type never shrinks (default).
MIN_INSTANCES_SUFFIX = "min-instances"


def jobtype_key(jobtype: str, suffix: str) -> str:
    """`tony.<jobtype>.<suffix>` — per-type parameterized key."""
    return f"tony.{jobtype}.{suffix}"


def dependency_key(depender: str, dependee: str) -> str:
    """`tony.application.dependency.<A>.timeout.after.<B>` — A starts after B."""
    return f"{DEPENDENCY_PREFIX}{depender}.timeout.after.{dependee}"


# ---------------------------------------------------------------------------
# tony.docker.* — container image passthrough (reference parity)
# ---------------------------------------------------------------------------
DOCKER_ENABLED = "tony.docker.enabled"
DOCKER_IMAGE = "tony.docker.containers.image"
DOCKER_BINARY = "tony.docker.binary"  # docker CLI (tests substitute a fake)

# ---------------------------------------------------------------------------
# tony.keytab.* — security analog (no Kerberos here; shared-secret auth)
# ---------------------------------------------------------------------------
KEYTAB_USER = "tony.keytab.user"
KEYTAB_LOCATION = "tony.keytab.location"

# ---------------------------------------------------------------------------
# tony.tpu.* — TPU-native resource model (replaces GPU-on-YARN)
# ---------------------------------------------------------------------------
TPU_POOL_SPEC = "tony.tpu.pool"                 # RM inventory, e.g. "v5e-64" or "host:v5e,8x8"
TPU_POOL_SECRET = "tony.tpu.pool.secret"        # shared secret for a remote (rm:) pool service
TPU_ACCELERATOR_TYPE = "tony.tpu.accelerator-type"  # v5e | v5p | v4 | cpu
TPU_ICI_STRICT = "tony.tpu.ici-strict"          # never split a slice across DCN
TPU_CHIPS_PER_HOST = "tony.tpu.chips-per-host"

# ---------------------------------------------------------------------------
# tony.heartbeat.* — executor → AM heartbeat shaping (docs/performance.md
# "Control-plane scalability"): a thousand-executor gang whose supervisors
# all beat on the same whole-second boundary knocks the AM in lockstep;
# per-beat jitter spreads the fan-in. A stretched gap can span up to
# (1 + pct) of the AM's missed-heartbeat intervals, so the false-positive
# margin shrinks by up to pct intervals — keep pct well under
# tony.task.max-missed-heartbeats (trivial at the defaults: 0.25 vs 25).
# ---------------------------------------------------------------------------
HEARTBEAT_BACKOFF_ENABLED = "tony.heartbeat.backoff-enabled"
# Each beat waits interval * (1 + U[0, pct]) from a per-task seeded RNG —
# deterministic per identity, decorrelated across the gang.
HEARTBEAT_BACKOFF_JITTER_PCT = "tony.heartbeat.backoff-jitter-pct"

# ---------------------------------------------------------------------------
# tony.node.* — host-agent liveness (pool-service ↔ NodeAgent contract)
# ---------------------------------------------------------------------------
NODE_HEARTBEAT_INTERVAL_MS = "tony.node.heartbeat-interval-ms"
NODE_MAX_MISSED_HEARTBEATS = "tony.node.max-missed-heartbeats"

# ---------------------------------------------------------------------------
# tony.pool.* — pool-service multi-tenancy (capacity-queue analog, SURVEY §3.1)
# ---------------------------------------------------------------------------
POOL_QUEUES = "tony.pool.queues"                # "name=share,..." e.g. "prod=0.7,dev=0.3"
POOL_PREEMPTION_ENABLED = "tony.pool.preemption.enabled"
# Cross-queue reclaim grace: a waiting under-share head must wait this long
# before the scheduler evicts over-share borrowers from OTHER queues
# (same-queue priority preemption has no grace — it is an explicit ranking).
POOL_PREEMPTION_GRACE_MS = "tony.pool.preemption.grace-ms"
# Cooperative drain window (docs/scheduling.md): eviction becomes two-phase —
# the victim AM learns it is DRAINING through its poll path, triggers an
# urgent checkpoint, and yields; the pool escalates to the kill path only at
# this deadline. 0 (the default) keeps the classic immediate kill.
POOL_PREEMPTION_DRAIN_MS = "tony.pool.preemption.drain-ms"
# Anti-thrash guard: a just-admitted app is not evictable (or shrinkable)
# until it has run this long — evict→admit→evict ping-pong is structurally
# impossible. 0 disables the protection.
POOL_PREEMPTION_MIN_RUNTIME_MS = "tony.pool.preemption.min-runtime-ms"
# Anti-thrash guard: a queue may CAUSE at most this many evictions/shrinks
# per budget window; an exhausted aggressor's heads wait for free capacity
# like anyone else. 0 = unlimited.
POOL_PREEMPTION_BUDGET = "tony.pool.preemption.budget"
POOL_PREEMPTION_BUDGET_WINDOW_MS = "tony.pool.preemption.budget-window-ms"
# Pool-service recovery journal (docs/fault-tolerance.md "Control-plane
# failures"): app registrations/admissions/allocations are journaled here so
# a restarted pool rebuilds its queue state (admitted apps stay admitted,
# waiting apps keep their place) and re-adopts live containers from agent
# re-registration instead of forgetting every admitted app. Empty (the
# default) disables journaling — a restarted pool starts empty and agents
# kill the orphaned containers, the pre-journal behavior.
POOL_JOURNAL_FILE = "tony.pool.journal.file"
# Incremental journal compaction (docs/performance.md "Control-plane
# scalability"): after this many appended records the pool folds its live
# state into one durable snapshot record and rotates the file, so restart
# replay is O(live apps + containers), not O(everything that ever happened).
# 0 (the default) never compacts — the pre-compaction behavior exactly.
POOL_JOURNAL_COMPACT_EVERY = "tony.pool.journal.compact-every"
# Indexed scheduler pass (docs/performance.md "Scheduler pass"): the pool
# evaluates admission/preemption over an incrementally-maintained WorldIndex
# (heap heads, O(1) waiting counters, delta-fed claim aggregates) instead of
# rebuilding every view each pass — ~100x faster at 10k queued apps, with
# decision-trace equality to the reference pass property-tested and
# replayable via `tony sim --parity`. false restores the reference
# (full-rescan) implementation verbatim — the kill switch, not a semantic
# choice: both produce byte-identical decisions.
POOL_SCHEDULER_INDEXED = "tony.pool.scheduler.indexed"
# Scheduler flight recorder (docs/scheduling.md "Explaining decisions"): the
# pool keeps a bounded in-memory ring of DecisionRecords — every committed
# admit/evict/shrink plus each blocked queue head's binding rule — served by
# the `pool_explain` RPC and rendered by `tony explain <app_id|--queue Q>`.
# Per-queue telemetry (used/share/demand/wait-age/disruption counters) is
# sampled on the liveness tick into `tony_pool_queue_*` gauges and
# fixed-width windows. Provenance needs the indexed scheduler pass (the
# default); under the reference kill switch only pool-side records appear.
POOL_RECORDER_ENABLED = "tony.pool.recorder.enabled"
POOL_RECORDER_CAPACITY = "tony.pool.recorder.capacity"      # ring size, records
# telemetry aggregation window; each finalized window is one cluster_series row
POOL_RECORDER_WINDOW_MS = "tony.pool.recorder.window-ms"
# finalized windows append here as JSONL; the history server sweeps this file
# into the store's cluster_series table (empty disables the flush — the
# in-memory ring and gauges still work)
POOL_RECORDER_SERIES_FILE = "tony.pool.recorder.series-file"
# The capacity market (docs/scheduling.md "Capacity market"): admitted apps
# may publish unmet demand via the update_demand RPC; with preemption on,
# the pool funds it by shrinking over-share elastic borrowers (recorder
# rule demand-spike) and grows them back once demand ebbs (rule grow-back).
POOL_DEMAND_ENABLED = "tony.pool.demand.enabled"
# A published deficit whose publisher goes quiet expires after this long —
# a crashed spike must not keep taxing borrowers. 0 = never expire.
POOL_DEMAND_TTL_MS = "tony.pool.demand.ttl-ms"
# Grow-back hysteresis: ALL published demand must have been clear for this
# long before shed workers are offered back (spike→ebb→spike cannot thrash).
POOL_DEMAND_GROWBACK_EBB_MS = "tony.pool.demand.growback-ebb-ms"
# Max workers offered back per borrower per grow-back pass; 0 = all owed.
POOL_DEMAND_GROWBACK_STEP = "tony.pool.demand.growback-step"

# ---------------------------------------------------------------------------
# tony.history.* / tony.portal.* — events, history, portal, history server
# ---------------------------------------------------------------------------
HISTORY_LOCATION = "tony.history.location"
HISTORY_MOVE_INTERVAL_MS = "tony.history.move-interval-ms"
# Persistent history tier (docs/history.md): the `tony history-server`
# daemon ingests finalized jobs' artifacts into a SQLite store and serves a
# query API; `tony history ingest` is the inline one-shot path.
HISTORY_STORE = "tony.history.store"                # sqlite path; empty → <history>/history.sqlite
HISTORY_SERVER_PORT = "tony.history.server.port"    # daemon HTTP port (0 = ephemeral)
HISTORY_SCAN_INTERVAL_MS = "tony.history.scan-interval-ms"  # ingestion sweep cadence
# Retention window, days: store rows past it are purged each sweep, and
# `tony history gc` (or the daemon with gc enabled) removes ingested jobs'
# raw staging dirs past it. 0 (the default) keeps everything forever.
HISTORY_RETENTION_DAYS = "tony.history.retention-days"
# Series compaction: at most this many evenly-strided points are stored per
# (job, metric) series — bounds the store however long a job ran.
HISTORY_MAX_SERIES_POINTS = "tony.history.max-series-points"
# Let the DAEMON's sweep also GC raw staging dirs past retention (the CLI
# `tony history gc` works regardless). Never touches live/un-ingested jobs.
HISTORY_GC_ENABLED = "tony.history.gc.enabled"
# Cluster-series sources: comma-separated JSONL paths the sweep ingests into
# the store's cluster_series table (each line = one finalized per-queue
# telemetry window the pool wrote via tony.pool.recorder.series-file). The
# portal's /history capacity dashboards chart these across runs.
HISTORY_CLUSTER_SERIES = "tony.history.cluster-series"
PORTAL_PORT = "tony.portal.port"
# O(changed) portal scrape (docs/performance.md "Control-plane scalability"):
# a running AM's get_metrics result is cached and re-served for up to this
# long, re-scraped early only when the AM's am_info.json moved (takeover).
# Stale entries are exported with a `tony_portal_scrape_age_seconds` label so
# dashboards can see they are cached. 0 (the default) scrapes every AM on
# every exposition — the pre-cache behavior exactly.
PORTAL_SCRAPE_TTL_MS = "tony.portal.scrape-ttl-ms"

# ---------------------------------------------------------------------------
# tony.elastic.* — elastic training (docs/fault-tolerance.md)
# ---------------------------------------------------------------------------
# Which jobtype is the data-parallel axis the AM may resize live (shrink on
# preemption/capacity loss, grow/shrink on resize_jobtype). The workers of
# this type restore the checkpoint onto the resized mesh and the loader's
# global-order draw keeps the sample stream exact (keep the GLOBAL batch
# constant across sizes).
ELASTIC_JOBTYPE = "tony.elastic.jobtype"
# Shrink floor for the elastic jobtype; 0 (the default) disables elastic
# shrinking entirely (equivalent to leaving tony.<type>.min-instances unset).
ELASTIC_MIN_WORKERS = "tony.elastic.min-workers"
# Grow ceiling for resize_jobtype on the elastic jobtype; 0 = no ceiling
# beyond what the pool can place.
ELASTIC_MAX_WORKERS = "tony.elastic.max-workers"
# Preemption response: instead of re-queuing the FULL gang and waiting for
# the pool to give the capacity back, shrink the elastic jobtype to the
# largest divisor count the surviving workers can form (>= min-workers) and
# resume from the latest checkpoint immediately.
ELASTIC_SHRINK_ON_PREEMPT = "tony.elastic.shrink-on-preempt"
# Hot spares: keep this many pre-registered spare executors of the elastic
# jobtype parked next to the gang. A grow or preemption-replacement promotes
# a spare — skipping container allocation and executor startup — cutting the
# restart epoch from a full relaunch to a spec re-fence.
ELASTIC_SPARES = "tony.elastic.spares"

# ---------------------------------------------------------------------------
# tony.serve.* — replicated serving control plane (docs/serving.md)
# ---------------------------------------------------------------------------
# Replica autoscaling bounds for the ``serve`` jobtype. max-replicas > 0
# enables the autoscaler (runs next to the fleet router in the submitting
# `tony serve` process); min-replicas is its floor. Scaling drives the AM's
# elastic-resize path (``resize_jobtype`` RPC → session/scheduler rebuild),
# never a re-submission.
SERVE_MIN_REPLICAS = "tony.serve.min-replicas"
SERVE_MAX_REPLICAS = "tony.serve.max-replicas"
SERVE_AUTOSCALE_INTERVAL_MS = "tony.serve.autoscale-interval-ms"
# Scale-up triggers: mean engine admission-queue depth per healthy replica,
# or fleet slot utilization above the high watermark (whichever fires first,
# sustained for the up-hysteresis ticks).
SERVE_SCALE_UP_QUEUE_DEPTH = "tony.serve.scale-up-queue-depth"
SERVE_SCALE_UP_UTILIZATION = "tony.serve.scale-up-utilization"
# Scale-down trigger: empty queues AND fleet slot utilization below the low
# watermark, sustained for the down-hysteresis ticks (longer than up: adding
# capacity is cheap, a restart to remove it is not).
SERVE_SCALE_DOWN_UTILIZATION = "tony.serve.scale-down-utilization"
SERVE_SCALE_UP_TICKS = "tony.serve.scale-up-ticks"
SERVE_SCALE_DOWN_TICKS = "tony.serve.scale-down-ticks"
# Fleet router (the HTTP front door the submitter runs).
SERVE_ROUTER_PORT = "tony.serve.router.port"          # 0 = ephemeral
SERVE_ROUTER_RETRIES = "tony.serve.router.retries"    # failover attempts before waiting
# How long the router keeps retrying/waiting for a healthy replica before a
# request is answered 503 — sized to cover a whole-gang restart (replica
# relaunch + engine compile), so a replica crash is not client-visible.
SERVE_FAILOVER_DEADLINE_MS = "tony.serve.failover-deadline-ms"
# Hedging (non-streaming requests only): p>0 duplicates a request to a second
# replica once it outlives the p-th percentile of recent router latencies
# (floored at hedge-min-ms); first response wins. 0 disables.
SERVE_HEDGE_PERCENTILE = "tony.serve.hedge-percentile"
SERVE_HEDGE_MIN_MS = "tony.serve.hedge-min-ms"
# Active health checks against each replica's /stats endpoint.
SERVE_HEALTH_INTERVAL_MS = "tony.serve.health-interval-ms"
SERVE_HEALTH_FAIL_THRESHOLD = "tony.serve.health-fail-threshold"
# Session affinity (X-Tony-Session → replica pins, serve/sessions.py):
# idle pins expire after ttl-ms; the table is LRU-capped at max-sessions;
# prefix-span is how many leading prompt tokens the cross-session prefix
# hint fingerprints (match the engine's page_len so a hint implies at least
# one warm cache page; 0 disables hints).
SERVE_SESSION_TTL_MS = "tony.serve.session.ttl-ms"
SERVE_SESSION_MAX_SESSIONS = "tony.serve.session.max-sessions"
SERVE_SESSION_PREFIX_SPAN = "tony.serve.session.prefix-span"
# Drain-aware scale-down: before resize_jobtype removes the victim replica,
# the autoscaler asks it to drain (request_task_drain → DrainCourier) and
# waits up to this long for the ack before shrinking anyway.
SERVE_SCALE_DOWN_DRAIN_MS = "tony.serve.scale-down-drain-ms"
# ``tony loadtest`` defaults (serve/loadgen.py): open-loop session arrival
# rate (sessions/s), session count, turns per session, prompt-length mix
# ("len:weight,len:weight"), and generated tokens per turn.
SERVE_LOADTEST_RATE = "tony.serve.loadtest.rate"
SERVE_LOADTEST_SESSIONS = "tony.serve.loadtest.sessions"
SERVE_LOADTEST_TURNS = "tony.serve.loadtest.turns"
SERVE_LOADTEST_PROMPT_MIX = "tony.serve.loadtest.prompt-mix"
SERVE_LOADTEST_MAX_TOKENS = "tony.serve.loadtest.max-tokens"
SERVE_LOADTEST_STREAM = "tony.serve.loadtest.stream"
# Capacity market (serve side): when enabled, a serve AM whose allocation
# request sits pending (the autoscaler asked for replicas the pool cannot
# place) publishes the deficit to the pool via ``update_demand``; the pool's
# preemption policy may fund it by partially shrinking elastic training
# borrowers (see ``tony.pool.demand.*``). slo-ttft-ms is the serve-side p99
# time-to-first-token objective the live market e2e/loadtest verdict checks.
SERVE_MARKET_ENABLED = "tony.serve.market.enabled"
SERVE_MARKET_SLO_TTFT_MS = "tony.serve.market.slo-ttft-ms"
# Router tier sharding (serve/disagg.py RouterShardFront): N FleetRouter
# workers, each owning a consistent-hash shard of the session-pin space,
# behind one front (``tony serve --routers N``); prefix hints replicate
# between shards every gossip tick.
SERVE_ROUTERS = "tony.serve.routers"
SERVE_ROUTER_GOSSIP_INTERVAL_MS = "tony.serve.router.gossip-interval-ms"
# Disaggregated prefill/decode serving (serve/disagg.py): a second jobtype
# (``prefill``) runs the prompt phase and ships finished KV pages to the
# decode tier over the paged-KV handoff contract. prefill-replicas sizes the
# tier at submit; prefill-min/max-replicas bound its own autoscaler (max 0 =
# no autoscaling); handoff-timeout-ms bounds one prefill leg end-to-end.
SERVE_DISAGG_ENABLED = "tony.serve.disagg.enabled"
SERVE_DISAGG_PREFILL_REPLICAS = "tony.serve.disagg.prefill-replicas"
SERVE_DISAGG_PREFILL_MIN_REPLICAS = "tony.serve.disagg.prefill-min-replicas"
SERVE_DISAGG_PREFILL_MAX_REPLICAS = "tony.serve.disagg.prefill-max-replicas"
SERVE_DISAGG_HANDOFF_TIMEOUT_MS = "tony.serve.disagg.handoff-timeout-ms"
# Decode-tier memory-bound scaling: paged-KV occupancy (live/total pages)
# above which the autoscaler counts up-pressure even with idle slots. 0
# disables (dense fleets report occupancy 0).
SERVE_SCALE_UP_KV_OCCUPANCY = "tony.serve.scale-up-kv-occupancy"

# ---------------------------------------------------------------------------
# tony.cbench.* — control-plane benchmark sizes (`tony cbench`,
# docs/performance.md "Control-plane scalability"). These parameterize the
# five seeded in-process microbenchmarks; the checked-in CBENCH_r<N>.json
# rounds are produced at the full-scale defaults, tier-1 runs scaled down.
# ---------------------------------------------------------------------------
CBENCH_APPS = "tony.cbench.apps"                    # queued apps in the scheduler bench
CBENCH_QUEUES = "tony.cbench.queues"                # queues the apps spread over
CBENCH_EXECUTORS = "tony.cbench.executors"          # simulated executors in the heartbeat fan-in
CBENCH_HEARTBEAT_SECONDS = "tony.cbench.heartbeat-seconds"  # sustained-knock window per phase
CBENCH_JOURNAL_RECORDS = "tony.cbench.journal-records"      # pool-journal history length
CBENCH_JOURNAL_LIVE_APPS = "tony.cbench.journal-live-apps"  # live apps the replay must rebuild
CBENCH_HISTORY_JOBS = "tony.cbench.history-jobs"    # finalized fixture jobs the sweep ingests
CBENCH_PORTAL_AMS = "tony.cbench.portal-ams"        # registered AMs the portal scrapes
CBENCH_SEED = "tony.cbench.seed"                    # every benchmark draw is seeded from this

# ---- tony sim --from-history (cluster/replay.py, docs/scheduling.md
# "What-if capacity planning"): trace-driven replay of recorded history
SIM_REPLAY_DEFAULT_WORK_S = "tony.sim.replay.default-work-s"    # work for apps recorded waiting-only
SIM_REPLAY_HORIZON_S = "tony.sim.replay.horizon-s"              # virtual-seconds cap per replay
SIM_REPLAY_COOP_YIELD_S = "tony.sim.replay.coop-yield-s"        # cooperative victim yield latency
SIM_REPLAY_SHRINK_REBUILD_S = "tony.sim.replay.shrink-rebuild-s"  # elastic shed/rebuild latency

# ---------------------------------------------------------------------------
# tony.profile.* — ON-DEMAND profiler capture (docs/observability.md)
# ---------------------------------------------------------------------------
# `tony profile <app_id>` asks a RUNNING job's workers to capture a
# jax.profiler trace at the next step boundary — no resubmit, unlike the
# submit-time `tony.task.profile` window. These keys set the defaults the
# AM applies when the CLI omits the flags, and the contract knobs.
PROFILE_STEPS = "tony.profile.steps"            # default capture window (steps)
PROFILE_MEMORY = "tony.profile.memory"          # also save a device memory profile
# How often (at most) the training child stats the control file for a new
# capture request — the only recurring cost of the on-demand plane when idle.
PROFILE_POLL_INTERVAL_MS = "tony.profile.poll-interval-ms"

# ---------------------------------------------------------------------------
# tony.log.* — aggregated structured logging (docs/observability.md)
# ---------------------------------------------------------------------------
# Every job process (client, AM, executors, training children) appends JSONL
# records to <staging>/logs/<identity>.log.jsonl; `tony logs <app_id>` merges
# and tails them in timestamp order. Records below the level are never built.
LOG_LEVEL = "tony.log.level"                    # debug|info|warning|error|off
LOG_DIR = "tony.log.dir"                        # sink override; empty → <staging>/logs

# ---------------------------------------------------------------------------
# tony.chaos.* — deterministic fault injection (docs/fault-tolerance.md)
# ---------------------------------------------------------------------------
# Fault schedule, e.g. "rpc-drop:p=0.05;exec-crash:worker:1@gang_complete";
# empty (the default) disables every injection point. Grammar in
# tony_tpu/chaos/schedule.py.
CHAOS_SPEC = "tony.chaos.spec"
# Seed for the injection PRNGs: the same (spec, seed) pair reproduces the
# same injected-fault sequence exactly.
CHAOS_SEED = "tony.chaos.seed"

# ---------------------------------------------------------------------------
# tony.trace.* / tony.metrics.* — observability (docs/observability.md)
# ---------------------------------------------------------------------------
# Distributed tracing: one trace per job (trace_id = app_id), spans appended
# to <staging>/trace/<identity>.spans.jsonl per process, context propagated
# in-band through RPC frames and via TONY_TRACE_PARENT across process spawns.
# Disabled (the default) costs one None check per hook and allocates nothing.
TRACE_ENABLED = "tony.trace.enabled"
# Span sink directory override; empty → <staging>/trace
TRACE_DIR = "tony.trace.dir"
# Process-wide metrics registry (RPC latency histograms, retry/backoff
# counters, heartbeat RTT, queue wait, checkpoint durations, sampled train
# step time) — exposed at the portal's /metrics (Prometheus text) and the
# AM's get_metrics RPC. false turns every recording call into a no-op.
METRICS_ENABLED = "tony.metrics.enabled"
# Traced control-plane locks (obs/locktrace.py): record real acquisition
# order, hold times (tony_lock_hold_seconds), and contention for every lock
# the static lock-order graph models. Debug/test-only — false (the default)
# hands out plain threading locks, zero overhead and byte-identical
# behavior. Also settable via TONY_LOCKTRACE=1 before process start.
DEBUG_LOCKTRACE = "tony.debug.locktrace"

# ---------------------------------------------------------------------------
# tony.goodput.* — goodput accounting + straggler detection (docs/observability.md)
# ---------------------------------------------------------------------------
# The AM's goodput tick: classifies wall-time into phases (obs/goodput.py),
# feeds the straggler detector from the piggybacked per-task step-time
# histograms, and evaluates the tony.alerts.* rules. false turns the whole
# plane off (no tick, no events, no gauges).
GOODPUT_ENABLED = "tony.goodput.enabled"
GOODPUT_INTERVAL_MS = "tony.goodput.interval-ms"      # tick cadence
# Trailing window the LIVE goodput value (alert input, tony top header) is
# computed over — cumulative goodput can never recover from one early stall;
# a windowed value resolves once the job is productive again.
GOODPUT_WINDOW_MS = "tony.goodput.window-ms"
# A rank is a straggler when its step time stays >= factor × the gang median
# for `checks` consecutive goodput ticks (needs >= 3 reporting ranks).
GOODPUT_STRAGGLER_FACTOR = "tony.goodput.straggler-factor"
GOODPUT_STRAGGLER_CHECKS = "tony.goodput.straggler-checks"

# ---------------------------------------------------------------------------
# tony.alerts.* — declarative alert rules (obs/alerts.py; empty = disabled)
# ---------------------------------------------------------------------------
ALERTS_GOODPUT_FLOOR = "tony.alerts.goodput-floor"        # fires while windowed goodput < this
ALERTS_STEP_TIME_P99_MS = "tony.alerts.step-time-p99-ms"  # fires while step-time p99 > this
ALERTS_HEARTBEAT_AGE_MS = "tony.alerts.heartbeat-age-ms"  # fires while any task heartbeat older
ALERTS_QUEUE_DEPTH = "tony.alerts.queue-depth"            # fires while any serve queue deeper
ALERTS_SINK = "tony.alerts.sink"        # transition JSONL; empty → <staging>/alerts.jsonl
ALERTS_WEBHOOK = "tony.alerts.webhook"  # optional URL POSTed each transition

# ---------------------------------------------------------------------------
# tony.slo.* — declarative SLO objectives + error budgets (obs/slo.py,
# docs/observability.md "SLOs & error budgets"). An objective is active when
# its target is non-empty (mirrors tony.alerts.*); the AM's goodput tick
# feeds the budget ledgers and compiles the burn-rate rules into the alert
# engine (SLO_BURN_ALERT/SLO_BURN_RESOLVED events, tony_slo_* gauges).
# ---------------------------------------------------------------------------
SLO_WINDOW_MS = "tony.slo.window-ms"    # compliance window the budget spans
SLO_BUCKET_MS = "tony.slo.bucket-ms"    # ledger bucket width (accounting grain)
# serve-ttft: fraction of requests whose TTFT lands under threshold-ms.
# Empty threshold inherits tony.serve.market.slo-ttft-ms so the market's
# defended number and the measured objective can't drift apart.
SLO_SERVE_TTFT_TARGET = "tony.slo.serve-ttft-target"
SLO_SERVE_TTFT_THRESHOLD_MS = "tony.slo.serve-ttft-threshold-ms"
# serve-availability: fraction of requests answered without server error.
SLO_SERVE_AVAILABILITY_TARGET = "tony.slo.serve-availability-target"
# train-goodput: windowed goodput fraction floor (per queue, from the ledger).
SLO_TRAIN_GOODPUT_TARGET = "tony.slo.train-goodput-target"
# Multi-window multi-burn-rate alerting (SRE workbook shape): the fast rule
# pages when the short-window burn rate exceeds fast-burn (budget gone in
# hours), the slow rule warns on sustained slow leaks.
SLO_FAST_BURN = "tony.slo.fast-burn"
SLO_FAST_WINDOW_MS = "tony.slo.fast-window-ms"
SLO_SLOW_BURN = "tony.slo.slow-burn"
SLO_SLOW_WINDOW_MS = "tony.slo.slow-window-ms"
SLO_SINK = "tony.slo.sink"  # budget-window JSONL; empty → <staging>/<app>/slo.jsonl

# ---------------------------------------------------------------------------
# tony.train.* — step-path knobs of the framework train loop (docs/performance.md)
# ---------------------------------------------------------------------------
# Input-pipeline lookahead: batch N+1 is assembled (loader read / synthetic
# draw + host-to-device transfer) on a background thread while the device
# runs step N (train/input_pipeline.py). 0 restores synchronous per-step
# assembly; >2 rarely helps (the queue only hides assembly jitter).
TRAIN_PREFETCH_DEPTH = "tony.train.prefetch-depth"
# A step-loop stall on the input pipeline at or above this emits a
# train.input_wait span, so the goodput ledger's input_wait phase charges it
# precisely; sub-floor waits stay inside productive (they are noise).
TRAIN_INPUT_WAIT_SPAN_MS = "tony.train.input-wait-span-ms"

# ---------------------------------------------------------------------------
# tony.tune.* — Pallas kernel autotuner (ops/tune.py, docs/performance.md)
# ---------------------------------------------------------------------------
# Cache of measured block-size winners keyed by (op, device kind, shape,
# dtype); `tony tune` writes it, every kernel entry point consults it at
# trace time. Empty → $TONY_TUNE_CACHE or ~/.cache/tony-tpu/tune.json.
TUNE_CACHE_FILE = "tony.tune.cache-file"
# false → kernels ignore the cache (module-constant defaults only); the
# per-job kill switch when a tuning looks implicated in a regression.
TUNE_ENABLED = "tony.tune.enabled"

# ---------------------------------------------------------------------------
# tony.checkpoint.* — gang-restart-from-checkpoint (rebuild-only; SURVEY §5.3/5.4)
# ---------------------------------------------------------------------------
CHECKPOINT_DIR = "tony.checkpoint.dir"
CHECKPOINT_INTERVAL_STEPS = "tony.checkpoint.interval-steps"
CHECKPOINT_MAX_TO_KEEP = "tony.checkpoint.max-to-keep"
CHECKPOINT_ASYNC = "tony.checkpoint.async"

# ---------------------------------------------------------------------------
# Submission-time keys filled by client (paths, venv, shell env)
# ---------------------------------------------------------------------------
EXECUTES = "tony.submit.executes"               # user training command
SRC_DIR = "tony.submit.src-dir"
PYTHON_BINARY_PATH = "tony.submit.python-binary-path"
PYTHON_VENV = "tony.submit.python-venv"
SHELL_ENV = "tony.submit.shell-env"             # csv k=v extra env
STAGING_ROOT = "tony.submit.staging-root"

# ---------------------------------------------------------------------------
# Defaults — the tony-default.xml analog. Single source of truth.
# ---------------------------------------------------------------------------
DEFAULTS: dict[str, str] = {
    APPLICATION_NAME: "tony-tpu-app",
    APPLICATION_QUEUE: "default",
    APPLICATION_PRIORITY: "0",
    APPLICATION_DOWNSIZE_GRACE_MS: "10s",
    APPLICATION_FRAMEWORK: "jax",
    APPLICATION_UNTRACKED_TYPES: "ps,tensorboard,notebook",
    APPLICATION_NODE_LABEL: "",
    APPLICATION_SECURITY_ENABLED: "true",
    APPLICATION_PREPARE_STAGE_TIMEOUT_MS: "60000",
    APPLICATION_TAGS: "",

    AM_RETRY_COUNT: "0",
    AM_TAKEOVER_ENABLED: "true",
    AM_JOURNAL_COMPACT_EVERY: "0",
    AM_RPC_PORT: "0",
    AM_GANG_TIMEOUT_MS: "300000",
    AM_MONITOR_INTERVAL_MS: "200",
    AM_MEMORY: "2g",
    AM_VCORES: "1",

    TASK_HEARTBEAT_INTERVAL_MS: "1000",
    TASK_MAX_MISSED_HEARTBEATS: "25",
    TASK_METRICS_INTERVAL_MS: "5000",
    TASK_EXECUTOR_REGISTRATION_TIMEOUT_MS: "60000",
    TASK_EXECUTOR_EXECUTION_TIMEOUT_MS: "0",
    TASK_KILL_GRACE_MS: "3000",
    TASK_RESTART_ON_FAILURE: "false",
    TASK_MAX_TOTAL_INSTANCE_FAILURES: "3",  # only consulted when restart-on-failure
    TASK_PROFILE: "false",
    TASK_PROFILE_START_STEP: "3",
    TASK_PROFILE_NUM_STEPS: "5",

    DOCKER_ENABLED: "false",
    DOCKER_IMAGE: "",
    DOCKER_BINARY: "docker",

    KEYTAB_USER: "",
    KEYTAB_LOCATION: "",

    TPU_POOL_SPEC: "local:cpu,1x1",
    TPU_POOL_SECRET: "",
    TPU_ACCELERATOR_TYPE: "cpu",
    TPU_ICI_STRICT: "true",
    TPU_CHIPS_PER_HOST: "4",

    HEARTBEAT_BACKOFF_ENABLED: "false",
    HEARTBEAT_BACKOFF_JITTER_PCT: "0.25",

    NODE_HEARTBEAT_INTERVAL_MS: "1000",
    NODE_MAX_MISSED_HEARTBEATS: "10",

    POOL_QUEUES: "default=1.0",
    POOL_PREEMPTION_ENABLED: "false",
    POOL_PREEMPTION_GRACE_MS: "0",
    POOL_PREEMPTION_DRAIN_MS: "0",
    POOL_PREEMPTION_MIN_RUNTIME_MS: "0",
    POOL_PREEMPTION_BUDGET: "0",
    POOL_PREEMPTION_BUDGET_WINDOW_MS: "60s",
    POOL_JOURNAL_FILE: "",
    POOL_JOURNAL_COMPACT_EVERY: "0",
    POOL_SCHEDULER_INDEXED: "true",
    POOL_RECORDER_ENABLED: "true",
    POOL_RECORDER_CAPACITY: "2048",
    POOL_RECORDER_WINDOW_MS: "60s",
    POOL_RECORDER_SERIES_FILE: "",
    POOL_DEMAND_ENABLED: "true",
    POOL_DEMAND_TTL_MS: "60s",
    POOL_DEMAND_GROWBACK_EBB_MS: "30s",
    POOL_DEMAND_GROWBACK_STEP: "0",

    HISTORY_LOCATION: "",            # empty → <staging-root>/history
    HISTORY_MOVE_INTERVAL_MS: "1000",
    HISTORY_STORE: "",               # empty → <history>/history.sqlite
    HISTORY_SERVER_PORT: "28081",
    HISTORY_SCAN_INTERVAL_MS: "2000",
    HISTORY_RETENTION_DAYS: "0",
    HISTORY_MAX_SERIES_POINTS: "512",
    HISTORY_GC_ENABLED: "false",
    HISTORY_CLUSTER_SERIES: "",
    PORTAL_PORT: "28080",
    PORTAL_SCRAPE_TTL_MS: "0",

    ELASTIC_JOBTYPE: "worker",
    ELASTIC_MIN_WORKERS: "0",
    ELASTIC_MAX_WORKERS: "0",
    ELASTIC_SHRINK_ON_PREEMPT: "false",
    ELASTIC_SPARES: "0",

    SERVE_MIN_REPLICAS: "0",
    SERVE_MAX_REPLICAS: "0",
    SERVE_AUTOSCALE_INTERVAL_MS: "5000",
    SERVE_SCALE_UP_QUEUE_DEPTH: "4",
    SERVE_SCALE_UP_UTILIZATION: "0.85",
    SERVE_SCALE_DOWN_UTILIZATION: "0.25",
    SERVE_SCALE_UP_TICKS: "2",
    SERVE_SCALE_DOWN_TICKS: "6",
    SERVE_ROUTER_PORT: "0",
    SERVE_ROUTER_RETRIES: "3",
    SERVE_FAILOVER_DEADLINE_MS: "120000",
    SERVE_HEDGE_PERCENTILE: "0",
    SERVE_HEDGE_MIN_MS: "50",
    SERVE_HEALTH_INTERVAL_MS: "1000",
    SERVE_HEALTH_FAIL_THRESHOLD: "3",
    SERVE_SESSION_TTL_MS: "600000",
    SERVE_SESSION_MAX_SESSIONS: "10000",
    SERVE_SESSION_PREFIX_SPAN: "256",
    SERVE_SCALE_DOWN_DRAIN_MS: "10000",
    SERVE_LOADTEST_RATE: "4",
    SERVE_LOADTEST_SESSIONS: "16",
    SERVE_LOADTEST_TURNS: "3",
    SERVE_LOADTEST_PROMPT_MIX: "16:0.5,64:0.3,256:0.2",
    SERVE_LOADTEST_MAX_TOKENS: "16",
    SERVE_LOADTEST_STREAM: "true",
    SERVE_MARKET_ENABLED: "false",
    SERVE_MARKET_SLO_TTFT_MS: "2000",
    SERVE_ROUTERS: "1",
    SERVE_ROUTER_GOSSIP_INTERVAL_MS: "2000",
    SERVE_DISAGG_ENABLED: "false",
    SERVE_DISAGG_PREFILL_REPLICAS: "1",
    SERVE_DISAGG_PREFILL_MIN_REPLICAS: "0",
    SERVE_DISAGG_PREFILL_MAX_REPLICAS: "0",
    SERVE_DISAGG_HANDOFF_TIMEOUT_MS: "30000",
    SERVE_SCALE_UP_KV_OCCUPANCY: "0",

    CBENCH_APPS: "10000",
    CBENCH_QUEUES: "8",
    CBENCH_EXECUTORS: "1000",
    CBENCH_HEARTBEAT_SECONDS: "5",
    CBENCH_JOURNAL_RECORDS: "100000",
    CBENCH_JOURNAL_LIVE_APPS: "200",
    CBENCH_HISTORY_JOBS: "10000",
    CBENCH_PORTAL_AMS: "500",
    CBENCH_SEED: "0",
    SIM_REPLAY_DEFAULT_WORK_S: "30",
    SIM_REPLAY_HORIZON_S: "10000000",
    SIM_REPLAY_COOP_YIELD_S: "1.0",
    SIM_REPLAY_SHRINK_REBUILD_S: "2.0",

    PROFILE_STEPS: "5",
    PROFILE_MEMORY: "false",
    PROFILE_POLL_INTERVAL_MS: "500",

    LOG_LEVEL: "info",
    LOG_DIR: "",                     # empty → <staging>/logs

    CHAOS_SPEC: "",
    CHAOS_SEED: "0",

    TRACE_ENABLED: "false",
    TRACE_DIR: "",                   # empty → <staging>/trace
    METRICS_ENABLED: "true",
    DEBUG_LOCKTRACE: "false",

    GOODPUT_ENABLED: "true",
    GOODPUT_INTERVAL_MS: "5000",
    GOODPUT_WINDOW_MS: "60000",
    GOODPUT_STRAGGLER_FACTOR: "1.5",
    GOODPUT_STRAGGLER_CHECKS: "3",

    ALERTS_GOODPUT_FLOOR: "",
    ALERTS_STEP_TIME_P99_MS: "",
    ALERTS_HEARTBEAT_AGE_MS: "",
    ALERTS_QUEUE_DEPTH: "",
    ALERTS_SINK: "",
    ALERTS_WEBHOOK: "",

    SLO_WINDOW_MS: "3600000",
    SLO_BUCKET_MS: "5000",
    SLO_SERVE_TTFT_TARGET: "",
    SLO_SERVE_TTFT_THRESHOLD_MS: "",  # empty → tony.serve.market.slo-ttft-ms
    SLO_SERVE_AVAILABILITY_TARGET: "",
    SLO_TRAIN_GOODPUT_TARGET: "",
    SLO_FAST_BURN: "14.4",
    SLO_FAST_WINDOW_MS: "300000",
    SLO_SLOW_BURN: "6.0",
    SLO_SLOW_WINDOW_MS: "1800000",
    SLO_SINK: "",

    TRAIN_PREFETCH_DEPTH: "2",
    TRAIN_INPUT_WAIT_SPAN_MS: "25",

    TUNE_CACHE_FILE: "",
    TUNE_ENABLED: "true",

    CHECKPOINT_DIR: "",
    CHECKPOINT_INTERVAL_STEPS: "0",
    CHECKPOINT_MAX_TO_KEEP: "3",
    CHECKPOINT_ASYNC: "true",

    EXECUTES: "",
    SRC_DIR: "",
    PYTHON_BINARY_PATH: "",
    PYTHON_VENV: "",
    SHELL_ENV: "",
    STAGING_ROOT: "",                # empty → constants.default_tony_root()
}

# Known per-jobtype suffixes, for validation + docs.
JOBTYPE_SUFFIXES = (
    INSTANCES_SUFFIX,
    MEMORY_SUFFIX,
    VCORES_SUFFIX,
    CHIPS_SUFFIX,
    SLICE_SUFFIX,
    COMMAND_SUFFIX,
    MIN_INSTANCES_SUFFIX,
)


def all_known_keys() -> frozenset[str]:
    """Every fixed (non-parameterized) key declared in this module."""
    return frozenset(
        v
        for k, v in globals().items()
        if isinstance(v, str)
        and k.isupper()
        and v.startswith("tony.")
        and not k.endswith("_PREFIX")  # key-family prefixes are parameterized, not fixed keys
    )
