"""Config system: the tony.* key registry and the layered, freezable config.

Analog of TonyConfigurationKeys.java + Hadoop Configuration layering +
tony-default.xml / tony-final.xml (SURVEY.md §2.1, §5.6).
"""

from tony_tpu.config import keys  # noqa: F401
from tony_tpu.config.config import (  # noqa: F401
    TonyConfig,
    parse_memory_string,
    parse_time_ms,
)
