"""events-discipline: the `.jhist` event vocabulary is documented.

Every member of an ``EventType`` enum (cluster/events.py — the types the
``EventHandler`` writes into the job history stream and every consumer —
portal, ``tony history``/``goodput``/``trace``, the ingest distiller —
switches on) must appear in docs/observability.md's event table. Same
ratchet as ``metrics-discipline``, and the drift it catches is just as
real: four generations of observability (PRs 9–14) added preemption /
straggler / alert / takeover events faster than the docs followed, so the
one table operators grep to interpret a ``.jhist`` stream went stale.

Declaration-site check on purpose: consumers can only emit declared
members (``EventType.X`` on an undeclared ``X`` is an ``AttributeError``),
so documenting the declaration covers every emission. Exempt by path:
tests, fixtures, examples, docs. A deliberately undocumented member (e.g.
an experiment behind a flag) carries an inline
``# lint: disable=events-discipline — <why>``.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable

from tony_tpu.analysis.analyzer import Checker, Finding, Module, dotted_name

EXEMPT_PARTS = frozenset({"tests", "fixtures", "examples", "docs"})

_DOC_RELPATH = os.path.join("docs", "observability.md")
#: backticked ALL_CAPS tokens — the event table's name cells
_NAME_RE = re.compile(r"`([A-Z][A-Z0-9_]{2,})`")

#: enum base spellings under which EventType classes are declared
_ENUM_BASES = frozenset({"enum.Enum", "Enum", "enum.StrEnum", "StrEnum"})


def _documented_names(start: str) -> "set[str] | None":
    """All backticked ALL-CAPS names in docs/observability.md, found by
    walking up from ``start``; None when the doc is missing (a vendored
    checkout without docs — nothing to ratchet against)."""
    d = os.path.dirname(os.path.abspath(start))
    for _ in range(12):
        doc = os.path.join(d, _DOC_RELPATH)
        if os.path.exists(doc):
            try:
                with open(doc, encoding="utf-8") as f:
                    return set(_NAME_RE.findall(f.read()))
            except OSError:
                return None
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    return None


class EventsDisciplineChecker(Checker):
    name = "events-discipline"
    description = (
        "every EventType member (the .jhist event vocabulary) has a row in "
        "docs/observability.md's event table"
    )

    def __init__(self) -> None:
        self._doc_names: "set[str] | None" = None
        self._doc_loaded = False

    @staticmethod
    def _is_event_enum(node: ast.ClassDef) -> bool:
        if node.name != "EventType":
            return False
        return any(
            (dotted_name(b) or "") in _ENUM_BASES for b in node.bases
        )

    def check(self, module: Module) -> Iterable[Finding]:
        parts = set(os.path.normpath(module.path).split(os.sep))
        if parts & EXEMPT_PARTS:
            return
        if not self._doc_loaded:
            self._doc_loaded = True
            self._doc_names = _documented_names(module.abspath)
        if self._doc_names is None:
            return  # no docs tree in scope: nothing to ratchet against
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ClassDef) or not self._is_event_enum(node):
                continue
            for stmt in node.body:
                if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
                    continue
                target = stmt.targets[0]
                if not isinstance(target, ast.Name):
                    continue
                value = stmt.value
                if not (isinstance(value, ast.Constant) and isinstance(value.value, str)):
                    continue
                if value.value not in self._doc_names and target.id not in self._doc_names:
                    yield self.finding(
                        module, stmt,
                        f"event type {value.value!r} is not in "
                        "docs/observability.md's event table — an "
                        "undocumented event is a .jhist record operators "
                        "cannot interpret; add a row (name in backticks)",
                    )
