"""config-keys: every ``tony.*`` string literal must be a declared key.

The runtime resolves unknown keys to their default silently
(``TonyConfig.get`` → DEFAULTS → ""), so a typo'd key is a latent
misconfiguration, not an error. The reference guards this with
TestTonyConfigurationFields (SURVEY.md §2.1); this checker closes the same
gap at lint time: any string literal shaped like a config key
(``tony.<segment>.<segment>...``) appearing outside the declaration module
must be declared in ``tony_tpu/config/keys.py`` — either as a fixed key, or
covered by a declared ``*_PREFIX`` key family.

Declaration sites are modules named ``keys`` (phase 1 collects every
module-level ``UPPER_NAME = "tony...."`` assignment; names ending in
``_PREFIX`` declare parameterized families matched by prefix). Dynamically
built keys (``keys.jobtype_key(...)``, f-strings) never form a full-match
literal and are out of scope by construction.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable

from tony_tpu.analysis.analyzer import Checker, Finding, Module

#: a whole literal that looks like a config key: dotted, lowercase segments
_KEY_SHAPED = re.compile(r"^tony\.[a-z0-9][a-z0-9_-]*(\.[a-z0-9][a-z0-9_.-]*)+$")


class ConfigKeyChecker(Checker):
    name = "config-keys"
    description = (
        'every "tony.*" key literal is declared in config/keys.py '
        "(catches typos the runtime silently defaults)"
    )

    def __init__(self) -> None:
        self.declared: set[str] = set()
        self.prefixes: set[str] = set()
        self._declaration_modules: set[str] = set()

    # ------------------------------------------------------------- phase 1
    def collect(self, module: Module) -> None:
        if module.name != "keys":
            return
        self._declaration_modules.add(module.abspath)
        for node in module.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            if not (
                isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
                and node.value.value.startswith("tony.")
            ):
                continue
            for target in node.targets:
                if isinstance(target, ast.Name) and target.id.isupper():
                    if target.id.endswith("_PREFIX"):
                        self.prefixes.add(node.value.value)
                    else:
                        self.declared.add(node.value.value)

    # ------------------------------------------------------------- phase 2
    def check(self, module: Module) -> Iterable[Finding]:
        if module.abspath in self._declaration_modules:
            return
        if not self.declared and not self.prefixes:
            return  # no registry in scope: nothing to validate against
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Constant) and isinstance(node.value, str)):
                continue
            value = node.value
            if not _KEY_SHAPED.match(value):
                continue
            if value in self.declared:
                continue
            if any(value.startswith(p) for p in self.prefixes):
                continue
            hint = _closest(value, self.declared)
            yield self.finding(
                module, node,
                f"undeclared config key {value!r}"
                + (f" (did you mean {hint!r}?)" if hint else "")
                + " — declare it in tony_tpu/config/keys.py",
            )


def _closest(value: str, declared: set[str]) -> str | None:
    """Typo hint: the most similar declared key at difflib ratio >= 0.85.
    Runs once per undeclared-key finding, against short dotted keys, so
    SequenceMatcher's cost is irrelevant here."""
    import difflib

    matches = difflib.get_close_matches(value, sorted(declared), n=1, cutoff=0.85)
    return matches[0] if matches else None
