"""donation-safety: a buffer passed at a ``donate_argnums`` position must
not be used again.

Donation lets XLA alias the argument's memory for an output — after the
call, the donor may hold garbage (on TPU the runtime *sometimes* errors,
sometimes silently reuses). The safe idiom rebinds the donor from the
call's result in the same statement::

    toks, seq, self.cache = decode_steps(params, self.cache, ...)   # ok
    new = decode_steps(params, self.cache, ...)
    log(self.cache.lengths)                                         # FLAGGED

Phase 1 builds a registry of donating callables from every module:
``@functools.partial(jax.jit, donate_argnums=...)`` decorators, plus the
application forms ``f = jax.jit(g, donate_argnums=...)`` and
``f = functools.partial(jax.jit, donate_argnums=...)(g)``. Phase 2 walks
each function scope linearly: a donated argument that is *read* after the
donating call — before being rebound — is flagged. The scan is lexical
(source order within the scope, nested defs skipped), so loop-carried reuse
is out of scope; tests pin the supported shapes.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Iterable

from tony_tpu.analysis.analyzer import (
    JIT_NAMES as _JIT_NAMES,
    PARTIAL_NAMES as _PARTIAL_NAMES,
    Checker,
    Finding,
    Module,
    dotted_name,
)


@dataclass(frozen=True)
class _Donor:
    positions: tuple[int, ...]
    params: tuple[str, ...]  # positional param names of the wrapped fn ("" unknown)


def _donate_positions(call: ast.Call) -> tuple[int, ...]:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                out = []
                for el in v.elts:
                    if isinstance(el, ast.Constant) and isinstance(el.value, int):
                        out.append(el.value)
                return tuple(out)
    return ()


def _jit_call_donations(node: ast.AST) -> tuple[int, ...]:
    """donate_argnums carried by a jit expression (``jax.jit(...)`` call or
    ``functools.partial(jax.jit, ...)``), else ()."""
    if not isinstance(node, ast.Call):
        return ()
    fname = dotted_name(node.func)
    if fname in _JIT_NAMES:
        return _donate_positions(node)
    if fname in _PARTIAL_NAMES and node.args and dotted_name(node.args[0]) in _JIT_NAMES:
        return _donate_positions(node)
    return ()


def _positional_params(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> tuple[str, ...]:
    a = fn.args
    return tuple(arg.arg for arg in [*a.posonlyargs, *a.args])


class DonationChecker(Checker):
    name = "donation-safety"
    description = (
        "no reuse of a buffer after it was passed at a donate_argnums "
        "position (XLA may alias its memory for an output)"
    )

    def __init__(self) -> None:
        self.registry: dict[str, _Donor] = {}
        # module → names it defines as plain (non-donating) callables: a
        # local `def update(...)` shadows a same-named donor registered by
        # another module, so its call sites must not be flagged
        self._local_plain: dict[str, set[str]] = {}

    # ------------------------------------------------------------- phase 1
    def collect(self, module: Module) -> None:
        defs = {
            n.name: n
            for n in ast.walk(module.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        local_donors: set[str] = set()
        # decorated definitions
        for fn in defs.values():
            for dec in fn.decorator_list:
                pos = _jit_call_donations(dec)
                if pos:
                    self.registry[fn.name] = _Donor(pos, _positional_params(fn))
                    local_donors.add(fn.name)
        # application forms bound to a name
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
                continue
            call = node.value
            inner: str | None = None
            pos: tuple[int, ...] = ()
            fname = dotted_name(call.func)
            if fname in _JIT_NAMES and call.args and isinstance(call.args[0], ast.Name):
                # f = jax.jit(g, donate_argnums=...)
                inner, pos = call.args[0].id, _donate_positions(call)
            elif (
                isinstance(call.func, ast.Call)
                and _jit_call_donations(call.func)
                and call.args
                and isinstance(call.args[0], ast.Name)
            ):
                # f = functools.partial(jax.jit, donate_argnums=...)(g)
                inner, pos = call.args[0].id, _jit_call_donations(call.func)
            if not inner or not pos:
                continue
            wrapped = defs.get(inner)
            params = _positional_params(wrapped) if wrapped else ()
            for target in node.targets:
                if isinstance(target, ast.Name):
                    self.registry[target.id] = _Donor(pos, params)
                    local_donors.add(target.id)
        self._local_plain[module.abspath] = set(defs) - local_donors

    # ------------------------------------------------------------- phase 2
    def check(self, module: Module) -> Iterable[Finding]:
        if not self.registry:
            return
        for scope in ast.walk(module.tree):
            if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_scope(module, scope)

    def _check_scope(self, module: Module, scope: ast.AST) -> Iterable[Finding]:
        shadowed = self._local_plain.get(module.abspath, set())
        own = list(_scope_nodes(scope))
        calls = [
            n for n in own
            if isinstance(n, ast.Call)
            and isinstance(n.func, ast.Name)
            and n.func.id in self.registry
            and n.func.id not in shadowed  # local plain def wins over a
                                           # same-named donor elsewhere
        ]
        if not calls:
            return
        stmts = [n for n in own if isinstance(n, ast.stmt)]
        for call in calls:
            donor = self.registry[call.func.id]
            stmt = _enclosing_stmt(stmts, call)
            for pos in donor.positions:
                arg = _argument_at(call, pos, donor.params)
                key = dotted_name(arg) if arg is not None else None
                if key is None:
                    continue
                if stmt is not None and _stmt_rebinds(stmt, key):
                    continue  # canonical idiom: result rebinds the donor
                use = _first_use_after(own, stmts, call, key)
                if use is not None:
                    # no line numbers in the message: fingerprints must stay
                    # stable when unrelated edits shift the file (baseline)
                    yield self.finding(
                        module, use,
                        f"{key!r} was donated (donate_argnums={pos}) to "
                        f"{call.func.id!r} and is read here — a donated "
                        f"buffer may hold garbage; rebind it from the "
                        f"call's result",
                    )

    # no collect-time findings: the registry is global, so a clean module
    # can still teach the checker about donors other modules call


def _scope_nodes(scope: ast.AST) -> Iterable[ast.AST]:
    """All nodes of a function scope, excluding nested function/class
    bodies (closure use is not lexically ordered)."""
    def visit(node: ast.AST, top: bool) -> Iterable[ast.AST]:
        if not top and isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)
        ):
            return
        yield node
        for child in ast.iter_child_nodes(node):
            yield from visit(child, False)

    yield from visit(scope, True)


def _enclosing_stmt(stmts: list[ast.stmt], call: ast.Call) -> ast.stmt | None:
    """Smallest statement whose span contains the call."""
    best: ast.stmt | None = None
    for s in stmts:
        if s.lineno <= call.lineno and (s.end_lineno or s.lineno) >= (call.end_lineno or call.lineno):
            if best is None or (s.lineno, -(s.end_lineno or 0)) >= (best.lineno, -(best.end_lineno or 0)):
                best = s
    return best


def _rebinds_key(node: ast.AST, key: str) -> bool:
    """A store to ``key`` itself or to a prefix of it (rebinding
    ``self.cache`` invalidates the stale ``self.cache.lengths`` chain)."""
    d = dotted_name(node)
    return d is not None and (d == key or key.startswith(d + "."))


def _stmt_rebinds(stmt: ast.stmt, key: str) -> bool:
    targets: list[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    for t in targets:
        for el in ast.walk(t):
            if isinstance(el, (ast.Name, ast.Attribute)) and _rebinds_key(el, key):
                return True
    return False


def _first_use_after(
    own: Iterable[ast.AST], stmts: list[ast.stmt], call: ast.Call, key: str
) -> ast.AST | None:
    """First reference to ``key`` lexically after the call: a Load before
    any (exact or prefix) Store means the donated buffer is reused. Within
    one statement RHS loads execute before the target store, so loads sort
    first there regardless of column."""
    call_end = (call.end_lineno or call.lineno, call.end_col_offset or 0)

    def stmt_order(node: ast.AST) -> tuple[int, int]:
        # innermost containing statement = the latest-starting one
        containing = [
            s for s in stmts
            if (s.lineno, s.col_offset) <= (node.lineno, node.col_offset)
            and ((s.end_lineno or s.lineno), (s.end_col_offset or 10**9))
            >= (node.lineno, node.col_offset)
        ]
        if containing:
            s = max(containing, key=lambda s: (s.lineno, s.col_offset))
            return (s.lineno, s.col_offset)
        return (node.lineno, node.col_offset)

    refs: list[tuple[tuple, ast.AST, bool]] = []
    for node in own:
        if not isinstance(node, (ast.Name, ast.Attribute)):
            continue
        is_store = isinstance(node.ctx, (ast.Store, ast.Del))
        matches = _rebinds_key(node, key) if is_store else dotted_name(node) == key
        if not matches:
            continue
        at = (node.lineno, node.col_offset)
        if at <= call_end:
            continue
        refs.append(((stmt_order(node), is_store, at), node, is_store))
    refs.sort(key=lambda r: r[0])
    for _, node, is_store in refs:
        return None if is_store else node
    return None


def _argument_at(
    call: ast.Call, pos: int, params: tuple[str, ...]
) -> ast.AST | None:
    if pos < len(call.args):
        return call.args[pos]
    if pos < len(params):
        want = params[pos]
        for kw in call.keywords:
            if kw.arg == want:
                return kw.value
    return None
