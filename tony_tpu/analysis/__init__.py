"""AST-based static-analysis suite (``tony lint``).

The reference guards its config surface with a drift test
(TestTonyConfigurationFields, SURVEY.md §2.1); this package generalizes that
idea into checkers for the hazard classes the TPU-native rebuild actually
added: config-key discipline, traced-code purity, donated-buffer reuse,
cross-thread lock discipline, and mesh-axis naming. See
docs/static-analysis.md for the checker catalogue and suppression syntax.
"""

from tony_tpu.analysis.analyzer import (
    Analyzer,
    Checker,
    Finding,
    Module,
    Severity,
    all_checkers,
)

__all__ = [
    "Analyzer",
    "Checker",
    "Finding",
    "Module",
    "Severity",
    "all_checkers",
]
