"""blocking-under-lock: no RPC, socket/HTTP I/O, subprocess, sleep,
fsync/file write, or SQLite statement while a lock is held.

A blocking call under a control-plane lock is a latency cliff: every
thread that needs the lock — RPC handlers, the liveness loop, telemetry —
stalls behind one fsync or socket round-trip. The checker combines

- *direct ops*: a vocabulary of blocking calls (``time.sleep``,
  ``os.fsync``, ``subprocess.run``, ``socket.create_connection``, ``open``,
  ``os.replace``…) plus receiver-typed methods on attributes whose
  constructor was collected (``self._db = sqlite3.connect(...)`` makes
  ``self._db.execute(...)`` a SQLite op; ``RpcClient`` attrs make
  ``.call(...)`` an RPC op), and
- *effect summaries*: every function summarizes to a set of
  ``(kind, locks-held-at-the-op)`` pairs, accumulated transitively through
  resolved calls. A call site holding lock L is a finding iff L is NOT
  already in the op's held set — so the journal's fsync under the journal
  lock is one (suppressed, deliberate) finding inside the journal, while
  the pool calling ``journal.append`` under the POOL lock is a separate,
  real finding at the pool's call site: a new lock held across the same
  blocking op.

Deliberately-synchronous sites (the journal's fsync under the journal
lock, the RPC client's socket under its serializer lock) carry inline
``# lint: disable=blocking-under-lock`` suppressions with justifications.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tony_tpu.analysis.analyzer import Checker, Finding, Module, dotted_name
from tony_tpu.analysis.callgraph import (
    CallGraph,
    FunctionInfo,
    build_callgraph,
)

#: dotted call name -> effect kind
BLOCKING_CALLS: dict[str, str] = {
    "time.sleep": "time.sleep",
    "os.fsync": "fsync",
    "subprocess.run": "subprocess",
    "subprocess.Popen": "subprocess",
    "subprocess.call": "subprocess",
    "subprocess.check_call": "subprocess",
    "subprocess.check_output": "subprocess",
    "socket.create_connection": "network I/O",
    "urllib.request.urlopen": "network I/O",
    "open": "file I/O",
    "io.open": "file I/O",
    "os.replace": "file I/O",
    "os.rename": "file I/O",
}

#: receiver type tag -> method names -> effect kind
_TYPED_METHODS: dict[str, tuple[frozenset[str], str]] = {
    "sqlite": (frozenset({"execute", "executemany", "executescript",
                          "commit"}), "sqlite"),
    "file": (frozenset({"write", "flush"}), "file I/O"),
    "rpc": (frozenset({"call", "call_with_retry"}), "rpc"),
}


def _classify(call: ast.Call, fn: FunctionInfo) -> str | None:
    """Effect kind of a direct blocking op, else None."""
    fname = dotted_name(call.func)
    if fname in BLOCKING_CALLS:
        return BLOCKING_CALLS[fname]
    func = call.func
    if (isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Attribute)
            and isinstance(func.value.value, ast.Name)
            and func.value.value.id == "self"
            and fn.cls is not None):
        tag = fn.cls.attr_types.get(func.value.attr)
        if tag in _TYPED_METHODS:
            methods, kind = _TYPED_METHODS[tag]
            if func.attr in methods:
                return kind
    return None


class BlockingUnderLockChecker(Checker):
    name = "blocking-under-lock"
    description = (
        "no RPC / socket / subprocess / sleep / fsync / file-write / "
        "SQLite work while holding a lock (latency cliff for every "
        "thread behind it)"
    )

    def __init__(self) -> None:
        self._modules: list[Module] = []
        self._findings: dict[str, list[Finding]] | None = None
        self._graph: CallGraph | None = None
        self._effects_memo: dict[str, frozenset[tuple[str, frozenset[str]]]] = {}
        self._effects_stack: set[str] = set()

    def collect(self, module: Module) -> None:
        self._modules.append(module)

    # --------------------------------------------------------- summaries
    def _effects(self, qualname: str) -> frozenset[tuple[str, frozenset[str]]]:
        """``(kind, locks held at the op)`` for every blocking op a call to
        ``qualname`` may transitively perform. The held set is what the
        op's own call chain accounts for; a caller holding anything beyond
        it stretches a NEW lock across the blocking work."""
        memo = self._effects_memo.get(qualname)
        if memo is not None:
            return memo
        if qualname in self._effects_stack:
            return frozenset()
        graph = self._graph
        assert graph is not None
        fn = graph.functions.get(qualname)
        if fn is None:
            return frozenset()
        self._effects_stack.add(qualname)
        try:
            out: set[tuple[str, frozenset[str]]] = set()
            for node, held in graph.iter_held(fn):
                if not isinstance(node, ast.Call):
                    continue
                kind = _classify(node, fn)
                if kind is not None:
                    out.add((kind, held))
                    continue
                callee = graph.resolve_call(node, fn)
                if callee is not None:
                    for k, oheld in self._effects(callee.qualname):
                        out.add((k, oheld | held))
        finally:
            self._effects_stack.discard(qualname)
        result = frozenset(out)
        self._effects_memo[qualname] = result
        return result

    # ---------------------------------------------------------- findings
    def _finalize(self) -> dict[str, list[Finding]]:
        graph = self._graph = build_callgraph(self._modules)
        by_path: dict[str, list[Finding]] = {}
        for fn in graph.functions.values():
            reported: set[str] = set()   # effect kinds already flagged here
            for node, held in graph.iter_held(fn):
                if not held or not isinstance(node, ast.Call):
                    continue
                locks = ", ".join(sorted(held))
                kind = _classify(node, fn)
                if kind is not None:
                    if kind in reported:
                        continue
                    reported.add(kind)
                    msg = (f"{kind} in {fn.qualname!r} while holding "
                           f"{locks} — move it outside the critical section")
                    by_path.setdefault(fn.module.path, []).append(Finding(
                        checker=self.name, path=fn.module.path,
                        line=node.lineno, col=node.col_offset, message=msg,
                    ))
                    continue
                callee = graph.resolve_call(node, fn)
                if callee is None:
                    continue
                kinds = {
                    k for (k, oheld) in self._effects(callee.qualname)
                    if held - oheld
                } - reported
                if not kinds:
                    continue
                reported |= kinds
                msg = (f"call to {callee.qualname!r} performs "
                       f"{', '.join(sorted(kinds))} while "
                       f"{fn.qualname!r} holds {locks} — move the call "
                       f"outside the critical section")
                by_path.setdefault(fn.module.path, []).append(Finding(
                    checker=self.name, path=fn.module.path,
                    line=node.lineno, col=node.col_offset, message=msg,
                ))
        return by_path

    def check(self, module: Module) -> Iterable[Finding]:
        if self._findings is None:
            self._findings = self._finalize()
        return self._findings.get(module.path, [])
