"""print-discipline: library code routes output through the structured logger.

With the aggregated-logging plane in place (obs/logging.py, ``tony logs``),
a bare ``print()`` in library code is a record that never reaches the
job-wide ``<staging>/logs`` aggregate — invisible to ``tony logs``, missing
the identity/epoch/span correlation, and un-filterable by level. The
``tony_tpu.obs.logging`` helpers echo to the console exactly like the print
they replace, so there is no console-UX reason to keep the bare call.

Exempt by path: ``cli/`` (interactive front ends where stdout IS the
product), tests and fixtures, ``examples/``, and docs. Deliberate stdout
contracts in library code (e.g. a command whose output is machine-parsed
JSON) carry an inline ``# lint: disable=print-discipline — <why>``.
"""

from __future__ import annotations

import ast
import os
from typing import Iterable

from tony_tpu.analysis.analyzer import Checker, Finding, Module

#: any path segment here exempts the whole file
EXEMPT_PARTS = frozenset({"cli", "tests", "fixtures", "examples", "docs"})


class PrintDisciplineChecker(Checker):
    name = "print-discipline"
    description = (
        "library code emits output via tony_tpu.obs.logging (aggregated, "
        "correlated, leveled), not bare print()"
    )

    def check(self, module: Module) -> Iterable[Finding]:
        parts = set(os.path.normpath(module.path).split(os.sep))
        if parts & EXEMPT_PARTS:
            return
        for node in ast.walk(module.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"
            ):
                yield self.finding(
                    module, node,
                    "bare print() in library code — use tony_tpu.obs.logging "
                    "(info/warning/error echo to the console AND land in the "
                    "<staging>/logs aggregate `tony logs` merges); a "
                    "deliberate stdout contract takes an inline "
                    "`# lint: disable=print-discipline — <why>`",
                )
