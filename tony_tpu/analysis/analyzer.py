"""Core of the ``tony lint`` framework: findings, suppressions, the checker
base class, the two-phase driver, and the text/JSON reporters.

Checkers are pure AST walkers — linted code is never imported, so a broken
(or side-effectful) module can be analyzed safely. The driver runs two
phases over every module: ``collect`` builds cross-module registries
(declared config keys, donating jit wrappers, mesh axes), then ``check``
emits findings. Suppression comments:

    x = do_thing()  # lint: disable=jit-purity        (this line, one checker)
    y = other()     # lint: disable=all               (this line, all checkers)
    # lint: disable-file=lock-discipline              (whole file, anywhere)

Every suppression should carry a justification in the same comment; the
baseline file (``.lint-baseline.json``) exists only for grandfathered
findings that cannot carry an inline comment (generated code, vendored
files) — see docs/static-analysis.md.
"""

from __future__ import annotations

import ast
import hashlib
import io
import json
import os
import re
import time
import tokenize
from dataclasses import dataclass, field
from enum import Enum
from typing import Iterable, Iterator


class Severity(Enum):
    WARNING = "warning"
    ERROR = "error"


# Shared vocabularies — single definitions so checkers cannot drift.
#: spellings under which jax's tracing compiler is imported/applied
JIT_NAMES = frozenset({
    "jax.jit", "jit", "pjit", "jax.pjit", "pjit.pjit",
    "jax.experimental.pjit.pjit",
})
#: spellings of functools.partial (used to curry jit with options)
PARTIAL_NAMES = frozenset({"functools.partial", "partial"})
#: container methods that mutate their receiver in place
MUTATOR_METHODS = frozenset({
    "append", "extend", "insert", "remove", "pop", "clear", "update",
    "setdefault", "popitem", "add", "discard", "sort", "reverse",
})


@dataclass(frozen=True)
class Finding:
    """One diagnostic: where, which checker, what."""

    checker: str
    path: str          # repo-relative (or as-given) path for display
    line: int          # 1-based
    col: int           # 0-based, matching ast
    message: str
    severity: Severity = Severity.ERROR

    def fingerprint(self) -> str:
        """Line-insensitive identity for baselining: a finding keeps its
        fingerprint when unrelated edits shift it up or down the file."""
        raw = f"{self.path}::{self.checker}::{self.message}"
        return hashlib.sha1(raw.encode("utf-8")).hexdigest()[:16]

    def to_dict(self) -> dict:
        return {
            "checker": self.checker,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "severity": self.severity.value,
            "fingerprint": self.fingerprint(),
        }


_SUPPRESS_RE = re.compile(r"#\s*lint:\s*(disable|disable-file)\s*=\s*([\w,\- ]+)")


@dataclass
class Module:
    """One parsed source file plus its suppression table."""

    path: str                 # display path (repo-relative when possible)
    abspath: str
    source: str
    tree: ast.Module
    line_suppressions: dict[int, set[str]] = field(default_factory=dict)
    file_suppressions: set[str] = field(default_factory=set)

    @property
    def name(self) -> str:
        """Module stem, e.g. ``keys`` for ``tony_tpu/config/keys.py``."""
        return os.path.splitext(os.path.basename(self.path))[0]

    def suppressed(self, checker: str, line: int) -> bool:
        if self.file_suppressions & {checker, "all"}:
            return True
        on_line = self.line_suppressions.get(line, set())
        return bool(on_line & {checker, "all"})


def _parse_suppressions(source: str) -> tuple[dict[int, set[str]], set[str]]:
    per_line: dict[int, set[str]] = {}
    per_file: set[str] = set()
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            names = {n.strip() for n in m.group(2).split(",") if n.strip()}
            if m.group(1) == "disable-file":
                per_file |= names
            else:
                per_line.setdefault(tok.start[0], set()).update(names)
    except tokenize.TokenError:
        pass  # the ast parse will surface the real syntax problem
    return per_line, per_file


def load_module(abspath: str, display_path: str | None = None) -> Module:
    with tokenize.open(abspath) as f:  # honors PEP 263 coding cookies
        source = f.read()
    tree = ast.parse(source, filename=abspath)
    per_line, per_file = _parse_suppressions(source)
    return Module(
        path=display_path or abspath,
        abspath=abspath,
        source=source,
        tree=tree,
        line_suppressions=per_line,
        file_suppressions=per_file,
    )


class Checker:
    """Base class: subclass, set ``name``/``description``, implement
    ``check``; override ``collect`` to build cross-module state first."""

    name = "base"
    description = ""

    def collect(self, module: Module) -> None:  # phase 1, every module
        pass

    def check(self, module: Module) -> Iterable[Finding]:  # phase 2
        raise NotImplementedError

    # -- shared helpers -----------------------------------------------------
    def finding(
        self, module: Module, node: ast.AST, message: str,
        severity: Severity = Severity.ERROR,
    ) -> Finding:
        return Finding(
            checker=self.name,
            path=module.path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            message=message,
            severity=severity,
        )


def dotted_name(node: ast.AST) -> str | None:
    """``jax.lax.psum`` for an Attribute/Name chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def iter_functions(tree: ast.AST) -> Iterator[ast.FunctionDef | ast.AsyncFunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def discover(paths: Iterable[str]) -> list[str]:
    """Expand files/directories into a sorted list of .py file paths."""
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs if not d.startswith(".") and d != "__pycache__"
                )
                for f in sorted(files):
                    if f.endswith(".py"):
                        out.append(os.path.join(root, f))
        elif p.endswith(".py"):
            out.append(p)
        else:
            raise FileNotFoundError(f"not a python file or directory: {p}")
    return out


class Analyzer:
    """Two-phase driver: collect registries over every module, then check."""

    def __init__(self, checkers: list[Checker], root: str | None = None):
        self.checkers = checkers
        self.root = root or os.getcwd()
        #: per-checker wall-clock seconds (collect + check) from the last
        #: :meth:`run` — ``tony lint --format json`` reports these, and the
        #: CLI warns (non-fatally) when one exceeds its budget
        self.timings: dict[str, float] = {}

    def _display(self, abspath: str) -> str:
        try:
            rel = os.path.relpath(abspath, self.root)
        except ValueError:  # different drive (windows)
            return abspath
        return abspath if rel.startswith("..") else rel

    def run(
        self, paths: Iterable[str], check_paths: Iterable[str] | None = None,
    ) -> list[Finding]:
        """Collect over every module under ``paths``, then check. With
        ``check_paths`` (the ``--changed`` incremental mode) findings are
        only emitted for those files, but collection still covers the full
        path set — cross-module registries (declared config keys, the call
        graph, RPC method lists) must see the whole tree or the filtered
        check would be unsound, not just incomplete."""
        modules: list[Module] = []
        findings: list[Finding] = []
        for abspath in discover(paths):
            display = self._display(os.path.abspath(abspath))
            try:
                modules.append(load_module(os.path.abspath(abspath), display))
            except SyntaxError as e:
                findings.append(Finding(
                    checker="parse", path=display,
                    line=e.lineno or 1, col=(e.offset or 1) - 1,
                    message=f"syntax error: {e.msg}",
                ))
            except (UnicodeDecodeError, ValueError) as e:
                # undecodable bytes / NUL: a per-file finding, never a
                # whole-run abort — the other files' findings must survive
                findings.append(Finding(
                    checker="parse", path=display, line=1, col=0,
                    message=f"unreadable source: {e}",
                ))
        if check_paths is None:
            to_check = modules
        else:
            wanted = {os.path.abspath(p) for p in check_paths}
            to_check = [m for m in modules if m.abspath in wanted]
        self.timings = {}
        for checker in self.checkers:
            t0 = time.perf_counter()
            for mod in modules:
                checker.collect(mod)
            self.timings[checker.name] = time.perf_counter() - t0
        for checker in self.checkers:
            t0 = time.perf_counter()
            for mod in to_check:
                for f in checker.check(mod):
                    if not mod.suppressed(checker.name, f.line):
                        findings.append(f)
            self.timings[checker.name] += time.perf_counter() - t0
        # dedup: a node can be reached through two walks (e.g. a jitted
        # function nested inside another jitted function)
        findings = list(dict.fromkeys(findings))
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.checker))
        return findings


# --------------------------------------------------------------- baseline
def load_baseline(path: str) -> set[str]:
    """Fingerprints of grandfathered findings (empty set if no file)."""
    if not os.path.exists(path):
        return set()
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return {e["fingerprint"] for e in data.get("findings", [])}

def write_baseline(path: str, findings: list[Finding]) -> None:
    data = {
        "comment": "grandfathered `tony lint` findings; prefer inline "
                   "`# lint: disable=<checker>` with a justification",
        "findings": [
            {
                "fingerprint": f.fingerprint(),
                "checker": f.checker,
                "path": f.path,
                "message": f.message,
            }
            for f in findings
        ],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=False)
        f.write("\n")

def apply_baseline(
    findings: list[Finding], baseline: set[str]
) -> tuple[list[Finding], int]:
    """(new findings, grandfathered count)."""
    fresh = [f for f in findings if f.fingerprint() not in baseline]
    return fresh, len(findings) - len(fresh)


# --------------------------------------------------------------- reporters
def render_text(findings: list[Finding], grandfathered: int = 0) -> str:
    lines = [
        f"{f.path}:{f.line}:{f.col + 1}: [{f.checker}] {f.message}"
        for f in findings
    ]
    summary = f"{len(findings)} finding{'s' if len(findings) != 1 else ''}"
    if grandfathered:
        summary += f" ({grandfathered} grandfathered by baseline)"
    lines.append(summary)
    return "\n".join(lines)

def render_json(
    findings: list[Finding], grandfathered: int = 0,
    timings: dict[str, float] | None = None, budget_s: float = 0.0,
) -> str:
    doc: dict = {
        "findings": [f.to_dict() for f in findings],
        "summary": {
            "total": len(findings),
            "grandfathered": grandfathered,
            "by_checker": _counts(findings),
        },
    }
    if timings is not None:
        doc["timings"] = {
            "per_checker_s": {n: round(t, 4) for n, t in sorted(timings.items())},
            "budget_s": budget_s,
            "over_budget": sorted(
                n for n, t in timings.items() if budget_s > 0 and t > budget_s),
        }
    return json.dumps(doc, indent=1)

def _counts(findings: list[Finding]) -> dict[str, int]:
    out: dict[str, int] = {}
    for f in findings:
        out[f.checker] = out.get(f.checker, 0) + 1
    return dict(sorted(out.items()))


def all_checkers() -> list[Checker]:
    """One fresh instance of every built-in checker (registries are
    per-run state, so instances must not be shared between runs)."""
    from tony_tpu.analysis.blocking import BlockingUnderLockChecker
    from tony_tpu.analysis.config_keys import ConfigKeyChecker
    from tony_tpu.analysis.donation import DonationChecker
    from tony_tpu.analysis.events_discipline import EventsDisciplineChecker
    from tony_tpu.analysis.guarded_fields import GuardedFieldsChecker
    from tony_tpu.analysis.host_sync import HostSyncChecker
    from tony_tpu.analysis.jit_purity import JitPurityChecker
    from tony_tpu.analysis.lock_order import LockOrderingChecker
    from tony_tpu.analysis.locks import LockDisciplineChecker
    from tony_tpu.analysis.mesh_axes import MeshAxisChecker
    from tony_tpu.analysis.metrics_discipline import MetricsDisciplineChecker
    from tony_tpu.analysis.print_discipline import PrintDisciplineChecker

    return [
        ConfigKeyChecker(),
        JitPurityChecker(),
        DonationChecker(),
        LockDisciplineChecker(),
        LockOrderingChecker(),
        BlockingUnderLockChecker(),
        GuardedFieldsChecker(),
        MeshAxisChecker(),
        PrintDisciplineChecker(),
        MetricsDisciplineChecker(),
        EventsDisciplineChecker(),
        HostSyncChecker(),
    ]
