"""Interprocedural call-graph and lock-identity substrate for the
concurrency checkers (lock-ordering, blocking-under-lock, guarded-fields).

Pure AST, like every other checker: linted code is never imported. The
graph resolves three call shapes — ``self._method(...)`` (same class),
``self._attr.method(...)`` when ``self._attr`` was assigned a constructor
call of a collected class (``self._journal = Journal(p)``) or carries a
class annotation, and bare/alias module-function calls — which is exactly
enough for lock effects to propagate through the repo's ``_locked`` helper
convention and through owned collaborators like the journal.

Lock identity is a string id stable across modules:

    ``<module-stem>.<Class>.<attr>``   instance locks (``pool.PoolService._lock``)
    ``<module-stem>.<name>``           module-level locks (``native._build_lock``)

These are the SAME strings callers pass to :func:`tony_tpu.obs.locktrace.
make_lock`, so the statically-derived order graph and the runtime witness
graph compare directly. When a lock is created via ``make_lock("...")`` the
explicit name wins over the derived id.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterator

from tony_tpu.analysis.analyzer import Module, dotted_name

#: spellings that construct a plain mutex
LOCK_FACTORIES = frozenset({"threading.Lock", "Lock"})
#: spellings that construct a reentrant mutex
RLOCK_FACTORIES = frozenset({"threading.RLock", "RLock"})
#: spellings that construct a condition variable
CONDITION_FACTORIES = frozenset({"threading.Condition", "Condition"})
#: spellings of the traced-lock factory (obs/locktrace.py)
MAKE_LOCK_FACTORIES = frozenset({
    "locktrace.make_lock", "obs_locktrace.make_lock", "make_lock",
})
#: class names treated as framed-RPC clients (receiver-typed blocking calls)
RPC_CLIENT_CLASSES = frozenset({"RpcClient"})


def lock_kind_of_call(call: ast.Call) -> str | None:
    """'lock' | 'rlock' | 'condition' for a lock-constructing call."""
    fname = dotted_name(call.func)
    if fname in LOCK_FACTORIES:
        return "lock"
    if fname in RLOCK_FACTORIES:
        return "rlock"
    if fname in CONDITION_FACTORIES:
        return "condition"
    if fname in MAKE_LOCK_FACTORIES:
        for kw in call.keywords:
            if kw.arg == "reentrant" and isinstance(kw.value, ast.Constant):
                if kw.value.value:
                    return "rlock"
        return "lock"
    return None


def _make_lock_name(call: ast.Call) -> str | None:
    """The explicit name argument of a ``make_lock("...")`` call, if any."""
    if dotted_name(call.func) not in MAKE_LOCK_FACTORIES:
        return None
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


@dataclass
class ClassInfo:
    stem: str                     # module file stem
    name: str                     # bare class name
    node: ast.ClassDef
    module: Module
    #: lock attr -> 'lock' | 'rlock' | 'condition'
    locks: dict[str, str] = field(default_factory=dict)
    #: explicit make_lock("...") name per lock attr (wins over derived id)
    lock_names: dict[str, str] = field(default_factory=dict)
    #: condition attr -> owning lock attr (threading.Condition(self._lock))
    cond_owner: dict[str, str] = field(default_factory=dict)
    #: self attr -> constructor tag: 'sqlite' | 'file' | 'rpc' | <ClassName>
    attr_types: dict[str, str] = field(default_factory=dict)
    methods: dict[str, ast.FunctionDef | ast.AsyncFunctionDef] = field(
        default_factory=dict)

    def lock_id(self, attr: str) -> str:
        explicit = self.lock_names.get(attr)
        return explicit or f"{self.stem}.{self.name}.{attr}"

    @property
    def primary_lock(self) -> str | None:
        """The lock a ``*_locked`` method of this class is trusted to hold:
        the attr named ``_lock`` when declared, else the single declared
        non-condition lock, else unknown."""
        plain = [a for a, k in self.locks.items() if k != "condition"]
        if "_lock" in plain:
            return "_lock"
        if len(plain) == 1:
            return plain[0]
        return None


@dataclass
class FunctionInfo:
    qualname: str                 # '<stem>.<Class>.<method>' or '<stem>.<fn>'
    module: Module
    cls: ClassInfo | None
    node: ast.FunctionDef | ast.AsyncFunctionDef


_SQLITE_CTORS = frozenset({"sqlite3.connect"})
_FILE_CTORS = frozenset({"open", "io.open", "tokenize.open"})
_THREAD_NAMES = frozenset({"threading.Thread", "Thread"})


class CallGraph:
    """Cross-module registries plus lazy lock-effect summaries."""

    def __init__(self) -> None:
        #: bare class name -> ClassInfo, or None when two modules collide
        self.classes: dict[str, ClassInfo | None] = {}
        self.functions: dict[str, FunctionInfo] = {}
        #: stem -> module-level lock name -> kind
        self.module_locks: dict[str, dict[str, str]] = {}
        #: stem -> module-level lock name -> explicit make_lock name
        self.module_lock_names: dict[str, dict[str, str]] = {}
        #: stem -> import alias -> imported module stem
        self.aliases: dict[str, dict[str, str]] = {}
        #: lock id -> kind (filled as ids are minted)
        self.lock_kinds: dict[str, str] = {}
        self._closure_memo: dict[str, frozenset[str]] = {}
        self._on_stack: set[str] = set()
        #: qualname -> locks held on entry; None until the fixpoint ran
        self._entry: dict[str, frozenset[str]] | None = None
        #: qualnames referenced as bare attributes (callbacks, Thread
        #: targets) — their call sites are invisible, so no inference
        self._escaped: set[str] = set()
        #: module-level NAME = ["str", ...] constants (RPC method lists),
        #: cross-module like LockDisciplineChecker's registry
        self.string_lists: dict[str, list[str]] = {}
        self._contexts_memo: dict[tuple[str, str], dict[str, frozenset[str]]] = {}

    # ------------------------------------------------------------ building
    def add_module(self, module: Module) -> None:
        stem = module.name
        self.aliases.setdefault(stem, {})
        self.module_locks.setdefault(stem, {})
        self.module_lock_names.setdefault(stem, {})
        for node in module.tree.body:
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                self._collect_import(stem, node)
            elif (isinstance(node, ast.Assign)
                    and isinstance(node.value, (ast.List, ast.Tuple))):
                values = [
                    el.value for el in node.value.elts
                    if isinstance(el, ast.Constant) and isinstance(el.value, str)
                ]
                if values and len(values) == len(node.value.elts):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.string_lists[t.id] = values
            elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                kind = lock_kind_of_call(node.value)
                if kind:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.module_locks[stem][t.id] = kind
                            explicit = _make_lock_name(node.value)
                            if explicit:
                                self.module_lock_names[stem][t.id] = explicit
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{stem}.{node.name}"
                self.functions[qn] = FunctionInfo(qn, module, None, node)
            elif isinstance(node, ast.ClassDef):
                self._collect_class(stem, module, node)

    def _collect_import(self, stem: str, node: ast.Import | ast.ImportFrom) -> None:
        table = self.aliases[stem]
        if isinstance(node, ast.Import):
            for alias in node.names:
                leaf = alias.name.split(".")[-1]
                table[alias.asname or alias.name.split(".")[0]] = leaf
        else:
            for alias in node.names:
                # `from tony_tpu.cluster import journal [as j]` — module
                # imports and class imports both land here; class names are
                # resolved through self.classes instead, so a wrong module
                # mapping for them is simply never consulted.
                table[alias.asname or alias.name] = alias.name

    def _collect_class(self, stem: str, module: Module, node: ast.ClassDef) -> None:
        ci = ClassInfo(stem=stem, name=node.name, node=node, module=module)
        for n in node.body:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ci.methods[n.name] = n
        # __init__ parameter annotations: `def __init__(self, journal: Journal)`
        ann: dict[str, str] = {}
        init = ci.methods.get("__init__")
        if init is not None:
            for a in list(init.args.args) + list(init.args.kwonlyargs):
                if a.annotation is not None:
                    t = dotted_name(a.annotation)
                    if t:
                        ann[a.arg] = t.split(".")[-1]
        for n in ast.walk(node):
            if not isinstance(n, ast.Assign):
                continue
            targets = [
                t for t in n.targets
                if isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name) and t.value.id == "self"
            ]
            if not targets:
                continue
            if isinstance(n.value, ast.Call):
                kind = lock_kind_of_call(n.value)
                if kind:
                    for t in targets:
                        ci.locks[t.attr] = kind
                        explicit = _make_lock_name(n.value)
                        if explicit:
                            ci.lock_names[t.attr] = explicit
                        if kind == "condition" and n.value.args:
                            owner = n.value.args[0]
                            if (isinstance(owner, ast.Attribute)
                                    and isinstance(owner.value, ast.Name)
                                    and owner.value.id == "self"):
                                ci.cond_owner[t.attr] = owner.attr
                    continue
                fname = dotted_name(n.value.func)
                tag = None
                if fname in _SQLITE_CTORS:
                    tag = "sqlite"
                elif fname in _FILE_CTORS:
                    tag = "file"
                elif fname and fname.split(".")[-1] in RPC_CLIENT_CLASSES:
                    tag = "rpc"
                elif fname and fname.split(".")[-1][:1].isupper():
                    tag = fname.split(".")[-1]   # candidate class constructor
                if tag:
                    for t in targets:
                        ci.attr_types.setdefault(t.attr, tag)
            elif isinstance(n.value, ast.Name) and n.value.id in ann:
                for t in targets:
                    ci.attr_types.setdefault(t.attr, ann[n.value.id])
        if node.name in self.classes and self.classes[node.name] is not ci:
            self.classes[node.name] = None   # ambiguous across modules
        else:
            self.classes[node.name] = ci
        for mname, fn in ci.methods.items():
            qn = f"{stem}.{node.name}.{mname}"
            self.functions[qn] = FunctionInfo(qn, module, ci, fn)
        for attr in ci.locks:
            self.lock_kinds[ci.lock_id(attr)] = ci.locks[attr]

    def finalize(self) -> None:
        for stem, table in self.module_locks.items():
            for name, kind in table.items():
                lid = self.module_lock_names.get(stem, {}).get(name) \
                    or f"{stem}.{name}"
                self.lock_kinds[lid] = kind

    # ----------------------------------------------------------- resolution
    def class_of(self, name: str) -> ClassInfo | None:
        """ClassInfo for a bare class name, None if unknown or ambiguous."""
        return self.classes.get(name)

    def with_item_locks(self, expr: ast.AST, fn: FunctionInfo) -> list[str]:
        """Lock ids acquired by one ``with`` item's context expression.
        A condition owning a lock acquires the owner's id (that is the
        mutex wait/notify contend on)."""
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name) and expr.value.id == "self"
                and fn.cls is not None):
            attr = expr.attr
            kind = fn.cls.locks.get(attr)
            if kind is None:
                return []
            if kind == "condition":
                owner = fn.cls.cond_owner.get(attr)
                if owner and owner in fn.cls.locks:
                    return [fn.cls.lock_id(owner)]
            return [fn.cls.lock_id(attr)]
        if isinstance(expr, ast.Name):
            stem = fn.module.name
            if expr.id in self.module_locks.get(stem, {}):
                return [self.module_lock_names.get(stem, {}).get(expr.id)
                        or f"{stem}.{expr.id}"]
        if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
            stem = fn.module.name
            target = self.aliases.get(stem, {}).get(expr.value.id)
            if target and expr.attr in self.module_locks.get(target, {}):
                return [self.module_lock_names.get(target, {}).get(expr.attr)
                        or f"{target}.{expr.attr}"]
        return []

    def _declared_entry(self, fn: FunctionInfo) -> frozenset[str]:
        """The ``_locked`` naming contract: trusted to hold the class's
        primary lock on entry."""
        if fn.cls is not None and fn.node.name.endswith("_locked"):
            primary = fn.cls.primary_lock
            if primary:
                return frozenset({fn.cls.lock_id(primary)})
        return frozenset()

    def entry_holds(self, fn: FunctionInfo) -> frozenset[str]:
        """Locks a function holds on entry: the ``_locked`` naming contract
        plus inference — a private function whose every resolved call site
        holds lock L effectively runs under L (``_perform_takeover`` calling
        ``_adopt_state`` inside ``with self._epoch_lock`` covers the callee's
        writes). Inference is skipped for functions whose name escapes as a
        bare attribute (callbacks, ``Thread(target=...)``): those have
        invisible call sites."""
        if self._entry is None:
            self._compute_entry_holds()
        assert self._entry is not None
        return self._entry.get(fn.qualname, frozenset())

    def _compute_entry_holds(self) -> None:
        # bare `self.m` / `mod.f` references that are not the func of a
        # call: their targets can run with any lockset
        for fn in self.functions.values():
            call_funcs = {
                id(n.func) for n in ast.walk(fn.node) if isinstance(n, ast.Call)
            }
            for n in ast.walk(fn.node):
                if (isinstance(n, ast.Attribute) and id(n) not in call_funcs
                        and isinstance(n.value, ast.Name)
                        and n.value.id == "self" and fn.cls is not None
                        and n.attr in fn.cls.methods):
                    self._escaped.add(f"{fn.cls.stem}.{fn.cls.name}.{n.attr}")
        entry = {qn: self._declared_entry(f) for qn, f in self.functions.items()}
        # monotone fixpoint: call-site held sets only grow as caller entry
        # sets grow, so the per-callee intersections only grow
        while True:
            changed = False
            site_holds: dict[str, list[frozenset[str]]] = {}
            for fn in self.functions.values():
                for node, held in self._iter_held(fn, entry[fn.qualname]):
                    if not isinstance(node, ast.Call):
                        continue
                    callee = self.resolve_call(node, fn)
                    if callee is None:
                        continue
                    leaf = callee.qualname.rsplit(".", 1)[-1]
                    if (not leaf.startswith("_") or leaf.startswith("__")
                            or callee.qualname in self._escaped):
                        continue
                    site_holds.setdefault(callee.qualname, []).append(held)
            for qn, holds in site_holds.items():
                inferred = frozenset.intersection(*holds)
                new = entry[qn] | inferred
                if new != entry[qn]:
                    entry[qn] = new
                    changed = True
            if not changed:
                break
        self._entry = entry

    def resolve_call(self, call: ast.Call, fn: FunctionInfo) -> FunctionInfo | None:
        func = call.func
        if isinstance(func, ast.Attribute):
            base = func.value
            if isinstance(base, ast.Name):
                if base.id == "self" and fn.cls is not None:
                    if func.attr in fn.cls.methods:
                        return self.functions.get(
                            f"{fn.cls.stem}.{fn.cls.name}.{func.attr}")
                    return None
                # alias.func_name — imported analyzed module
                target = self.aliases.get(fn.module.name, {}).get(base.id)
                if target:
                    return self.functions.get(f"{target}.{func.attr}")
                # ClassName.method staticmethod-style
                ci = self.class_of(base.id)
                if ci and func.attr in ci.methods:
                    return self.functions.get(f"{ci.stem}.{ci.name}.{func.attr}")
                return None
            # self.<attr>.<method> through a typed collaborator
            if (isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "self" and fn.cls is not None):
                tag = fn.cls.attr_types.get(base.attr)
                if tag and tag not in ("sqlite", "file", "rpc"):
                    ci = self.class_of(tag)
                    if ci and func.attr in ci.methods:
                        return self.functions.get(
                            f"{ci.stem}.{ci.name}.{func.attr}")
            return None
        if isinstance(func, ast.Name):
            got = self.functions.get(f"{fn.module.name}.{func.id}")
            if got is not None:
                return got
            ci = self.class_of(func.id)
            if ci is not None:
                return self.functions.get(f"{ci.stem}.{ci.name}.__init__")
        return None

    # --------------------------------------------------------- held walking
    def iter_held(self, fn: FunctionInfo) -> Iterator[tuple[ast.AST, frozenset[str]]]:
        """Pre-order (node, held-lock-ids) over a function body. ``with``
        bodies extend the held set; nested function/lambda bodies are
        skipped (they execute later, on an unknown thread and lockset)."""
        return self._iter_held(fn, self.entry_holds(fn))

    def _iter_held(
        self, fn: FunctionInfo, entry: frozenset[str]
    ) -> Iterator[tuple[ast.AST, frozenset[str]]]:
        def visit(node: ast.AST, held: frozenset[str]):
            yield node, held
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                return
            if isinstance(node, ast.With):
                inner = held
                for item in node.items:
                    yield from visit(item.context_expr, inner)
                    inner = inner | frozenset(self.with_item_locks(
                        item.context_expr, fn))
                for stmt in node.body:
                    yield from visit(stmt, inner)
                return
            for child in ast.iter_child_nodes(node):
                yield from visit(child, held)

        for stmt in fn.node.body:
            yield from visit(stmt, entry)

    def class_contexts(self, ci: ClassInfo) -> dict[str, frozenset[str]]:
        """Concurrency context(s) each method of ``ci`` runs in: the thread
        roots (``threading.Thread(target=self.m)``) and the shared RPC
        handler pool (``rpc.register_object``) it is reachable from through
        self-calls, or ``{"main"}`` for caller-thread-only methods — the
        same model LockDisciplineChecker uses to decide what is shared."""
        key = (ci.stem, ci.name)
        memo = self._contexts_memo.get(key)
        if memo is not None:
            return memo
        roots: dict[str, set[str]] = {}
        for node in ast.walk(ci.node):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func)
            if fname in _THREAD_NAMES:
                for kw in node.keywords:
                    if kw.arg != "target":
                        continue
                    tgt = kw.value
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"
                            and tgt.attr in ci.methods):
                        roots.setdefault(f"thread:{tgt.attr}", set()).add(tgt.attr)
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "register_object"
                    and len(node.args) >= 2):
                names: list[str] = []
                second = node.args[1]
                if isinstance(second, ast.Name):
                    names = self.string_lists.get(second.id, [])
                elif isinstance(second, (ast.List, ast.Tuple)):
                    names = [
                        el.value for el in second.elts
                        if isinstance(el, ast.Constant)
                        and isinstance(el.value, str)
                    ]
                handlers = {n for n in names if n in ci.methods}
                if handlers:
                    roots.setdefault("rpc", set()).update(handlers)
        closures: dict[str, set[str]] = {}
        for label, seeds in roots.items():
            out = set(seeds)
            frontier = list(seeds)
            while frontier:
                m = ci.methods.get(frontier.pop())
                if m is None:
                    continue
                for node in ast.walk(m):
                    if (isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Attribute)
                            and isinstance(node.func.value, ast.Name)
                            and node.func.value.id == "self"
                            and node.func.attr in ci.methods
                            and node.func.attr not in out):
                        out.add(node.func.attr)
                        frontier.append(node.func.attr)
            closures[label] = out
        result: dict[str, frozenset[str]] = {}
        for mname in ci.methods:
            got = {label for label, cl in closures.items() if mname in cl}
            result[mname] = frozenset(got or {"main"})
        self._contexts_memo[key] = result
        return result

    def direct_calls(self, fn: FunctionInfo) -> Iterator[tuple[ast.Call, FunctionInfo, frozenset[str]]]:
        """(call node, resolved callee, held ids) for resolvable calls."""
        for node, held in self.iter_held(fn):
            if isinstance(node, ast.Call):
                callee = self.resolve_call(node, fn)
                if callee is not None:
                    yield node, callee, held

    def acquire_closure(self, qualname: str) -> frozenset[str]:
        """Every lock id a call to ``qualname`` may acquire, transitively,
        beyond what it is trusted to hold on entry."""
        memo = self._closure_memo.get(qualname)
        if memo is not None:
            return memo
        if qualname in self._on_stack:
            return frozenset()        # break recursion; caller memoizes
        fn = self.functions.get(qualname)
        if fn is None:
            return frozenset()
        self._on_stack.add(qualname)
        try:
            out: set[str] = set()
            entry = self.entry_holds(fn)
            for node, held in self.iter_held(fn):
                if isinstance(node, ast.With):
                    inner = held
                    for item in node.items:
                        for lid in self.with_item_locks(item.context_expr, fn):
                            if lid not in inner and lid not in entry:
                                out.add(lid)
                            inner = inner | {lid}
                elif isinstance(node, ast.Call):
                    callee = self.resolve_call(node, fn)
                    if callee is not None:
                        out |= self.acquire_closure(callee.qualname) - entry
        finally:
            self._on_stack.discard(qualname)
        result = frozenset(out)
        self._closure_memo[qualname] = result
        return result


def build_callgraph(modules: list[Module]) -> CallGraph:
    graph = CallGraph()
    for m in modules:
        graph.add_module(m)
    graph.finalize()
    return graph
