"""mesh-axes: collective axis names must be declared mesh axes.

``jax.lax.psum(x, "contxt")`` fails only at trace time inside the target
mesh context — on a v5e-64 run, after minutes of setup. The canonical axis
names live in ``tony_tpu/parallel/mesh.py`` (``AXIS_* = "..."``); phase 1
collects every such declaration (any module declaring ``AXIS_*`` string
constants is a declaration site, so fixtures can carry their own). Checked:

- the axis argument (keyword ``axis_name`` or the collective's positional
  slot) of ``jax.lax.psum/pmean/pmax/pmin/all_gather/ppermute/all_to_all/
  psum_scatter/axis_index/axis_size``;
- an ``axis_name=`` keyword on ANY call (wrappers like ``ring_attention``
  thread it straight into collectives);
- a string default on a function parameter named ``axis_name``.

String literals (or tuples of them) must be declared axes; names threaded
in as variables/parameters are trusted — that is the approved way to
parameterize an axis.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tony_tpu.analysis.analyzer import Checker, Finding, Module, dotted_name

# collective → positional slot of its axis-name argument
_COLLECTIVES = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "all_gather": 1,
    "ppermute": 1, "all_to_all": 1, "psum_scatter": 1,
    "axis_index": 0, "axis_size": 0,
}


class MeshAxisChecker(Checker):
    name = "mesh-axes"
    description = (
        "axis names passed to collectives are declared mesh axes "
        "(parallel/mesh.py) or threaded parameters"
    )

    def __init__(self) -> None:
        self.declared: set[str] = set()

    # ------------------------------------------------------------- phase 1
    def collect(self, module: Module) -> None:
        for node in module.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            if not (
                isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
            ):
                continue
            for target in node.targets:
                if isinstance(target, ast.Name) and target.id.startswith("AXIS_"):
                    self.declared.add(node.value.value)

    # ------------------------------------------------------------- phase 2
    def check(self, module: Module) -> Iterable[Finding]:
        if not self.declared:
            return  # no axis registry in scope
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                yield from self._check_call(module, node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_defaults(module, node)

    def _check_call(self, module: Module, call: ast.Call) -> Iterable[Finding]:
        fname = dotted_name(call.func) or ""
        parts = fname.rsplit(".", 1)
        is_lax_collective = (
            len(parts) == 2
            and parts[1] in _COLLECTIVES
            and parts[0] in ("lax", "jax.lax")
        )
        axis_arg: ast.AST | None = None
        for kw in call.keywords:
            if kw.arg == "axis_name":
                axis_arg = kw.value
        if axis_arg is None and is_lax_collective:
            slot = _COLLECTIVES[parts[1]]
            if len(call.args) > slot:
                axis_arg = call.args[slot]
        if axis_arg is None:
            return
        if not is_lax_collective and not any(
            kw.arg == "axis_name" for kw in call.keywords
        ):
            return
        yield from self._validate(module, axis_arg, context=fname or "call")

    def _check_defaults(self, module: Module, fn) -> Iterable[Finding]:
        a = fn.args
        pos = [*a.posonlyargs, *a.args]
        defaults = a.defaults
        for arg, default in zip(pos[len(pos) - len(defaults):], defaults):
            if arg.arg == "axis_name":
                yield from self._validate(
                    module, default, context=f"default of {fn.name}()"
                )
        for arg, default in zip(a.kwonlyargs, a.kw_defaults):
            if default is not None and arg.arg == "axis_name":
                yield from self._validate(
                    module, default, context=f"default of {fn.name}()"
                )

    def _validate(
        self, module: Module, node: ast.AST, context: str
    ) -> Iterable[Finding]:
        literals: list[ast.Constant] = []
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            literals = [node]
        elif isinstance(node, (ast.Tuple, ast.List)):
            literals = [
                el for el in node.elts
                if isinstance(el, ast.Constant) and isinstance(el.value, str)
            ]
        for lit in literals:
            if lit.value not in self.declared:
                yield self.finding(
                    module, lit,
                    f"axis name {lit.value!r} ({context}) is not a declared "
                    f"mesh axis — declared: {', '.join(sorted(self.declared))}",
                )
