"""metrics-discipline: instruments are namespaced AND documented.

Every metric registered through the obs registry (``obs_metrics.counter`` /
``gauge`` / ``histogram``, or the module-level helpers imported from
``tony_tpu.obs.metrics``) must

1. carry the ``tony_`` prefix — the exposition merges many processes' groups
   under one scrape; an unprefixed name collides with whatever else the
   operator's Prometheus ingests, and
2. appear in docs/observability.md's instrument table — the drift this
   catches is real: the `tony trace` critical-path summary went stale for
   two PRs because new episode instruments/spans landed without the docs
   (and the summary they anchor) following.

Exempt by path: tests, fixtures, examples, docs. A deliberate off-registry
name carries an inline ``# lint: disable=metrics-discipline — <why>``.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable

from tony_tpu.analysis.analyzer import Checker, Finding, Module, dotted_name

EXEMPT_PARTS = frozenset({"tests", "fixtures", "examples", "docs"})

#: registry factory method names (obs/metrics.py module helpers and
#: MetricsRegistry methods share them)
_FACTORIES = frozenset({"counter", "gauge", "histogram"})

_DOC_RELPATH = os.path.join("docs", "observability.md")
_NAME_RE = re.compile(r"`(tony_[a-z0-9_]+)`")


def _documented_names(start: str) -> "set[str] | None":
    """All backticked ``tony_*`` instrument names in docs/observability.md,
    found by walking up from ``start``; None when the doc is missing (a
    vendored checkout without docs — the prefix rule still applies)."""
    d = os.path.dirname(os.path.abspath(start))
    for _ in range(12):
        doc = os.path.join(d, _DOC_RELPATH)
        if os.path.exists(doc):
            try:
                with open(doc, encoding="utf-8") as f:
                    return set(_NAME_RE.findall(f.read()))
            except OSError:
                return None
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    return None


class MetricsDisciplineChecker(Checker):
    name = "metrics-discipline"
    description = (
        "registered instruments use the tony_ prefix and appear in "
        "docs/observability.md's instrument table"
    )

    def __init__(self) -> None:
        self._doc_names: "set[str] | None" = None
        self._doc_loaded = False

    def _registration_name(self, node: ast.Call) -> str | None:
        """The literal instrument name of a registry factory call, or None
        (not a registration / dynamic name)."""
        func = node.func
        called = None
        if isinstance(func, ast.Attribute) and func.attr in _FACTORIES:
            recv = dotted_name(func.value)
            # obs_metrics.counter(...), metrics.gauge(...), REGISTRY.histogram(...)
            if recv and (recv.split(".")[-1].lower().endswith("metrics")
                         or recv == "REGISTRY" or recv.endswith(".REGISTRY")):
                called = func.attr
        elif isinstance(func, ast.Name) and func.id in _FACTORIES:
            called = func.id  # from tony_tpu.obs.metrics import counter
        if called is None or not node.args:
            return None
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            return first.value
        return None

    def check(self, module: Module) -> Iterable[Finding]:
        parts = set(os.path.normpath(module.path).split(os.sep))
        if parts & EXEMPT_PARTS:
            return
        if module.abspath.replace(os.sep, "/").endswith("tony_tpu/obs/metrics.py"):
            return  # the registry itself (generic helpers, no instruments)
        if not self._doc_loaded:
            self._doc_loaded = True
            self._doc_names = _documented_names(module.abspath)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = self._registration_name(node)
            if name is None:
                continue
            if not name.startswith("tony_"):
                yield self.finding(
                    module, node,
                    f"instrument {name!r} lacks the tony_ prefix — the "
                    "merged exposition shares a namespace with everything "
                    "else the operator's Prometheus scrapes",
                )
            elif self._doc_names is not None and name not in self._doc_names:
                yield self.finding(
                    module, node,
                    f"instrument {name!r} is not in docs/observability.md's "
                    "instrument table — undocumented metrics are how the "
                    "trace summary went stale; add a row (name in backticks)",
                )
