"""lock-discipline: cross-thread writes to ``self._*`` must hold the lock.

Heuristic lockset pass for the control-plane daemons (appmaster, executor,
pool, agent): their background loops run as ``threading.Thread`` targets,
and their RPC handlers run on the RPC server's handler threads — both race
the object's main-loop methods. Per class:

- *declared locks*: attributes assigned ``threading.Lock()``/``RLock()``;
- *entry methods*: ``threading.Thread(target=self.m)`` targets plus methods
  registered via ``rpc.register_object(self, METHOD_LIST)`` (the list is
  resolved from module-level string-list constants, cross-module), expanded
  transitively through ``self.m()`` calls;
- *writes*: assignments (attribute, subscript, augmented) and bare mutating
  method statements on ``self._x``.

An attribute written both from an entry method and from any other method
(or from two distinct entry methods — two racing threads) is shared state:
every write to it must be lexically inside ``with self.<lock>:`` for a
declared lock. Methods whose name ends in ``_locked`` are trusted to be
called with the lock held (the repo's naming contract) — their writes count
as locked.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tony_tpu.analysis.analyzer import (
    MUTATOR_METHODS as _MUTATORS,
    Checker,
    Finding,
    Module,
    dotted_name,
)

_LOCK_FACTORIES = {
    "threading.Lock", "threading.RLock", "Lock", "RLock",
    # traced named locks (obs/locktrace) — same discipline as plain locks
    "locktrace.make_lock", "obs_locktrace.make_lock", "make_lock",
}
_COND_FACTORIES = {"threading.Condition", "Condition"}
_THREAD_NAMES = {"threading.Thread", "Thread"}
#: Condition methods that REQUIRE the owning lock held (RuntimeError at
#: runtime otherwise — but only on the path that actually races)
_COND_METHODS = {"wait", "wait_for", "notify", "notify_all"}


class LockDisciplineChecker(Checker):
    name = "lock-discipline"
    description = (
        "self._* state shared between a thread/RPC entry point and other "
        "methods is only written under a declared lock"
    )

    def __init__(self) -> None:
        # module-level NAME = ["str", ...] constants, cross-module (RPC
        # method lists like APPLICATION_RPC_METHODS live in another file
        # than the class that registers them)
        self.string_lists: dict[str, list[str]] = {}

    # ------------------------------------------------------------- phase 1
    def collect(self, module: Module) -> None:
        for node in module.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            if not isinstance(node.value, (ast.List, ast.Tuple)):
                continue
            values = [
                el.value
                for el in node.value.elts
                if isinstance(el, ast.Constant) and isinstance(el.value, str)
            ]
            if len(values) != len(node.value.elts) or not values:
                continue
            for target in node.targets:
                if isinstance(target, ast.Name):
                    self.string_lists[target.id] = values

    # ------------------------------------------------------------- phase 2
    def check(self, module: Module) -> Iterable[Finding]:
        for node in module.tree.body:
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(module, node)

    def _check_class(self, module: Module, cls: ast.ClassDef) -> Iterable[Finding]:
        methods = {
            n.name: n
            for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        locks, conds, cond_owner = self._declared_locks(cls)
        # a Condition IS a lock (it wraps one): ``with self._cv:`` protects
        # writes exactly like ``with self._lock:``
        locks = locks | set(conds)
        yield from self._check_conditions(module, methods, conds, cond_owner)
        roots = self._entry_roots(cls, methods)
        if not roots:
            return  # no concurrency inside this class
        closures = {
            label: self._transitive(seeds, methods)
            for label, seeds in roots.items()
        }
        entries = set().union(*closures.values())

        def contexts(method: str) -> set[str]:
            """Concurrency contexts a method runs in: the thread roots it is
            reachable from, or the caller's ("main") context otherwise."""
            got = {label for label, cl in closures.items() if method in cl}
            return got or {"main"}

        # attr → [(method, node, locked)]
        writes: dict[str, list[tuple[str, ast.AST, bool]]] = {}
        for name, fn in methods.items():
            if name == "__init__" or name.startswith("__"):
                continue
            trusted = name.endswith("_locked")
            for attr, node, locked in self._writes(fn, locks):
                writes.setdefault(attr, []).append((name, node, locked or trusted))

        for attr, sites in sorted(writes.items()):
            # shared = written from two distinct concurrency contexts (two
            # different threads). Methods reachable from one thread root
            # only — however many of them — are that single thread's state.
            seen: set[str] = set()
            for m, _, _ in sites:
                seen |= contexts(m)
            if len(seen) < 2:
                continue
            for method, node, locked in sites:
                if locked:
                    continue
                hint = (
                    f"hold one of: {', '.join(sorted('self.' + lk for lk in locks))}"
                    if locks
                    else f"declare a threading.Lock on {cls.name} and hold it"
                )
                yield self.finding(
                    module, node,
                    f"self.{attr} is written in {method!r} without a lock, "
                    f"but is also written from "
                    f"{'thread/RPC entry ' if method not in entries else ''}"
                    f"{self._other_writers(method, sites)} — {hint}",
                )

    @staticmethod
    def _other_writers(method: str, sites: list[tuple[str, ast.AST, bool]]) -> str:
        others = sorted({m for m, _, _ in sites if m != method})
        return ", ".join(repr(m) for m in others) or "another thread"

    # ----------------------------------------------------------- conditions
    def _check_conditions(
        self,
        module: Module,
        methods: dict,
        conds: set[str],
        cond_owner: dict[str, str | None],
    ) -> Iterable[Finding]:
        """``self._cv.wait()/notify()`` must run with the condition's lock
        held — lexically inside ``with self._cv:`` (or ``with self._lock:``
        for ``Condition(self._lock)``). At runtime the miss raises only on
        the interleaving that actually races; statically it is always
        wrong."""
        if not conds:
            return
        for name, fn in methods.items():
            if name.startswith("__"):
                continue
            if name.endswith("_locked"):
                continue  # caller-holds-the-lock contract covers the cv too
            for cv, call, held in self._cond_calls(fn, conds):
                owner = cond_owner.get(cv)
                if cv in held or (owner is not None and owner in held):
                    continue
                need = f"self.{cv}" + (f" (or self.{owner})" if owner else "")
                yield self.finding(
                    module, call,
                    f"self.{cv}.{call.func.attr}() in {name!r} without "
                    f"holding {need} — Condition wait/notify requires the "
                    f"owning lock (runtime RuntimeError, but only on the "
                    f"interleaving that races)",
                )

    @staticmethod
    def _cond_calls(
        fn: ast.AST, conds: set[str]
    ) -> Iterable[tuple[str, ast.Call, set[str]]]:
        """(cv_attr, call, self-attrs lexically held) for every
        wait/notify-family call on a declared Condition."""

        def visit(node: ast.AST, held: set[str]) -> Iterable[tuple[str, ast.Call, set[str]]]:
            if isinstance(node, ast.With):
                inner = set(held)
                for item in node.items:
                    d = dotted_name(item.context_expr)
                    if d and d.startswith("self."):
                        inner.add(d[len("self."):])
                for child in ast.iter_child_nodes(node):
                    yield from visit(child, inner)
                return
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _COND_METHODS
                and isinstance(node.func.value, ast.Attribute)
                and isinstance(node.func.value.value, ast.Name)
                and node.func.value.value.id == "self"
                and node.func.value.attr in conds
            ):
                yield node.func.value.attr, node, held
            for child in ast.iter_child_nodes(node):
                yield from visit(child, held)

        yield from visit(fn, set())

    # ------------------------------------------------------------ gathering
    def _declared_locks(
        self, cls: ast.ClassDef
    ) -> tuple[set[str], set[str], dict[str, str | None]]:
        """(plain locks, conditions, condition -> wrapped lock attr)."""
        locks: set[str] = set()
        conds: set[str] = set()
        cond_owner: dict[str, str | None] = {}
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign):
                continue
            if not isinstance(node.value, ast.Call):
                continue
            fname = dotted_name(node.value.func)
            if fname in _LOCK_FACTORIES:
                dest = locks
            elif fname in _COND_FACTORIES:
                dest = conds
            else:
                continue
            for t in node.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    dest.add(t.attr)
                    if dest is conds:
                        owner = None
                        if node.value.args:
                            d = dotted_name(node.value.args[0])
                            if d and d.startswith("self."):
                                owner = d[len("self."):]
                        cond_owner[t.attr] = owner
        return locks, conds, cond_owner

    def _entry_roots(self, cls: ast.ClassDef, methods: dict) -> dict[str, set[str]]:
        """Concurrency roots: each ``threading.Thread`` target is its own
        thread; all RPC-registered handlers share the server's handler-
        thread pool (one root)."""
        roots: dict[str, set[str]] = {}
        for node in ast.walk(cls):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func)
            if fname in _THREAD_NAMES:
                for kw in node.keywords:
                    if kw.arg != "target":
                        continue
                    tgt = kw.value
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                        and tgt.attr in methods
                    ):
                        roots.setdefault(f"thread:{tgt.attr}", set()).add(tgt.attr)
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "register_object"
                and len(node.args) >= 2
            ):
                names: list[str] = []
                second = node.args[1]
                if isinstance(second, ast.Name):
                    names = self.string_lists.get(second.id, [])
                elif isinstance(second, (ast.List, ast.Tuple)):
                    names = [
                        el.value
                        for el in second.elts
                        if isinstance(el, ast.Constant) and isinstance(el.value, str)
                    ]
                handlers = {n for n in names if n in methods}
                if handlers:
                    roots.setdefault("rpc", set()).update(handlers)
        return roots

    @staticmethod
    def _transitive(entries: set[str], methods: dict) -> set[str]:
        """Grow the entry set through self-method calls: a helper invoked
        from a thread entry runs on that thread."""
        out = set(entries)
        frontier = list(entries)
        while frontier:
            fn = methods.get(frontier.pop())
            if fn is None:
                continue
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"
                    and node.func.attr in methods
                    and node.func.attr not in out
                ):
                    out.add(node.func.attr)
                    frontier.append(node.func.attr)
        return out

    def _writes(
        self, fn: ast.AST, locks: set[str]
    ) -> Iterable[tuple[str, ast.AST, bool]]:
        """(attr, node, lexically_locked) for every write to self._* in fn."""

        def self_underscore_attr(node: ast.AST) -> str | None:
            """'x' for an access chain rooted at ``self._x``."""
            while isinstance(node, (ast.Attribute, ast.Subscript)):
                if (
                    isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                ):
                    attr = node.attr
                    return attr if attr.startswith("_") and attr not in locks else None
                node = node.value
            return None

        def visit(node: ast.AST, locked: bool) -> Iterable[tuple[str, ast.AST, bool]]:
            if isinstance(node, ast.With):
                holds = locked or any(
                    dotted_name(item.context_expr) in {f"self.{lk}" for lk in locks}
                    for item in node.items
                )
                for child in ast.iter_child_nodes(node):
                    yield from visit(child, holds)
                return
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign) else [node.target]
                )
                for t in targets:
                    els = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
                    for el in els:
                        attr = self_underscore_attr(el)
                        if attr is not None and not isinstance(el, ast.Name):
                            yield attr, el, locked
            elif (
                isinstance(node, ast.Expr)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Attribute)
                and node.value.func.attr in _MUTATORS
            ):
                attr = self_underscore_attr(node.value.func.value)
                if attr is not None:
                    yield attr, node, locked
            for child in ast.iter_child_nodes(node):
                yield from visit(child, locked)

        yield from visit(fn, False)
