"""lock-ordering: build the cross-module lock-acquisition order graph and
report every cycle as a potential deadlock, with both acquisition paths.

An edge ``A -> B`` means some execution acquires lock ``B`` while already
holding ``A`` — either lexically (``with self._a: ... with self._b:``) or
through a resolved call whose transitive acquire-closure contains ``B``
(:meth:`CallGraph.acquire_closure`, which propagates through the
``_locked`` helper convention and owned collaborators like the journal).
Two threads taking a cycle's edges in opposite order can deadlock; a
re-acquisition of a non-reentrant ``threading.Lock`` (directly or through
a call) deadlocks a single thread and is reported as a self-cycle.

The same graph backs ``tony lint --lock-graph`` and the locktrace
witness-embedding test (:func:`build_lock_graph`).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterable

from tony_tpu.analysis.analyzer import Checker, Finding, Module, load_module
from tony_tpu.analysis.callgraph import CallGraph, FunctionInfo, build_callgraph


@dataclass(frozen=True)
class Witness:
    """Where an order edge was observed: which function, which line."""

    qualname: str
    path: str
    line: int

    def describe(self) -> str:
        return f"in {self.qualname!r} ({self.path}:{self.line})"


@dataclass
class LockGraph:
    """The acquisition-order digraph over lock ids, plus its defects."""

    nodes: set[str] = field(default_factory=set)
    #: (held, acquired) -> first witness
    edges: dict[tuple[str, str], Witness] = field(default_factory=dict)
    #: cycles as edge lists, deterministic order
    cycles: list[list[tuple[str, str]]] = field(default_factory=list)

    def has_path(self, a: str, b: str) -> bool:
        """True when the graph orders ``a`` before ``b`` (edge or path)."""
        if a == b:
            return True
        frontier, seen = [a], {a}
        succ: dict[str, list[str]] = {}
        for (x, y) in self.edges:
            succ.setdefault(x, []).append(y)
        while frontier:
            n = frontier.pop()
            for m in succ.get(n, ()):
                if m == b:
                    return True
                if m not in seen:
                    seen.add(m)
                    frontier.append(m)
        return False

    def render(self) -> str:
        lines = [f"lock-order graph: {len(self.nodes)} locks, "
                 f"{len(self.edges)} edges, {len(self.cycles)} cycles"]
        for (a, b) in sorted(self.edges):
            w = self.edges[(a, b)]
            lines.append(f"  {a} -> {b}   [{w.describe()}]")
        for cyc in self.cycles:
            chain = " -> ".join([cyc[0][0]] + [e[1] for e in cyc])
            lines.append(f"  CYCLE: {chain}")
        return "\n".join(lines)


def _collect_edges(graph: CallGraph) -> dict[tuple[str, str], Witness]:
    edges: dict[tuple[str, str], Witness] = {}

    def add(a: str, b: str, fn: FunctionInfo, line: int) -> None:
        key = (a, b)
        if key not in edges:
            edges[key] = Witness(fn.qualname, fn.module.path, line)

    for fn in graph.functions.values():
        for node, held in graph.iter_held(fn):
            if isinstance(node, ast.With):
                inner = held
                for item in node.items:
                    for lid in graph.with_item_locks(item.context_expr, fn):
                        if lid in inner:
                            if graph.lock_kinds.get(lid) == "lock":
                                add(lid, lid, fn, item.context_expr.lineno)
                        else:
                            for h in inner:
                                add(h, lid, fn, item.context_expr.lineno)
                        inner = inner | {lid}
            elif isinstance(node, ast.Call) and held:
                callee = graph.resolve_call(node, fn)
                if callee is None:
                    continue
                closure = graph.acquire_closure(callee.qualname)
                for b in closure:
                    if b in held:
                        if graph.lock_kinds.get(b) == "lock":
                            add(b, b, fn, node.lineno)
                        continue
                    for h in held:
                        add(h, b, fn, node.lineno)
    return edges


def _find_cycles(edges: dict[tuple[str, str], Witness]) -> list[list[tuple[str, str]]]:
    """Each strongly connected component with a cycle, reduced to one
    concrete cycle (edge list), deterministically ordered."""
    succ: dict[str, list[str]] = {}
    nodes: set[str] = set()
    for (a, b) in edges:
        nodes |= {a, b}
        succ.setdefault(a, []).append(b)
    for outs in succ.values():
        outs.sort()

    # Tarjan SCC, iterative.
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on: set[str] = set()
    stack: list[str] = []
    sccs: list[list[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        work = [(v, iter(succ.get(v, ())))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(succ.get(w, ()))))
                    advanced = True
                    break
                if w in on:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                sccs.append(comp)

    for v in sorted(nodes):
        if v not in index:
            strongconnect(v)

    cycles: list[list[tuple[str, str]]] = []
    # every self-loop is its own single-thread deadlock, reported even when
    # its node also sits inside a larger SCC — one must not mask the other
    for (a, b) in sorted(edges):
        if a == b:
            cycles.append([(a, b)])
    for comp in sccs:
        comp_set = set(comp)
        if len(comp) == 1:
            continue  # self-loops already reported above
        # one concrete multi-lock cycle inside the SCC: DFS from its
        # smallest node, ignoring self-edges
        start = min(comp)
        path: list[tuple[str, str]] = []
        seen: set[str] = set()

        def dfs(v: str) -> bool:
            for w in succ.get(v, ()):
                if w == v or w not in comp_set:
                    continue
                if w == start:
                    path.append((v, w))
                    return True
                if w in seen:
                    continue
                seen.add(w)
                path.append((v, w))
                if dfs(w):
                    return True
                path.pop()
            return False

        seen.add(start)
        if dfs(start):
            cycles.append(list(path))
    cycles.sort(key=lambda c: (c[0][0], c[0][1], len(c)))
    return cycles


def lock_graph_of(graph: CallGraph) -> LockGraph:
    edges = _collect_edges(graph)
    nodes = set(graph.lock_kinds)
    for (a, b) in edges:
        nodes |= {a, b}
    return LockGraph(nodes=nodes, edges=edges, cycles=_find_cycles(edges))


def build_lock_graph(paths: Iterable[str]) -> LockGraph:
    """Load .py files/dirs and return their lock-order graph — the entry
    point for ``tony lint --lock-graph`` and the locktrace witness test."""
    from tony_tpu.analysis.analyzer import discover
    import os

    modules: list[Module] = []
    for abspath in discover(paths):
        try:
            modules.append(load_module(os.path.abspath(abspath), abspath))
        except (SyntaxError, UnicodeDecodeError, ValueError):
            continue
    return lock_graph_of(build_callgraph(modules))


class LockOrderingChecker(Checker):
    name = "lock-ordering"
    description = (
        "the cross-module lock-acquisition order graph is cycle-free "
        "(a cycle is a potential deadlock; a re-acquired non-reentrant "
        "lock is a single-thread deadlock)"
    )

    def __init__(self) -> None:
        self._modules: list[Module] = []
        self._findings: dict[str, list[Finding]] | None = None

    def collect(self, module: Module) -> None:
        self._modules.append(module)

    def _finalize(self) -> dict[str, list[Finding]]:
        graph = build_callgraph(self._modules)
        lg = lock_graph_of(graph)
        by_path: dict[str, list[Finding]] = {}
        for cyc in lg.cycles:
            first = lg.edges[cyc[0]]
            if len(cyc) == 1 and cyc[0][0] == cyc[0][1]:
                lid = cyc[0][0]
                msg = (
                    f"non-reentrant lock {lid} is re-acquired while already "
                    f"held {first.describe()} — a single-thread deadlock; "
                    f"use threading.RLock or restructure the call"
                )
            else:
                chain = " -> ".join([cyc[0][0]] + [e[1] for e in cyc])
                paths = "; ".join(
                    f"{a} -> {b} acquired {lg.edges[(a, b)].describe()}"
                    for (a, b) in cyc
                )
                msg = (
                    f"potential deadlock: lock acquisition cycle {chain}; "
                    f"{paths} — threads taking these edges in opposite "
                    f"order can deadlock"
                )
            f = Finding(
                checker=self.name, path=first.path,
                line=first.line, col=0, message=msg,
            )
            by_path.setdefault(first.path, []).append(f)
        return by_path

    def check(self, module: Module) -> Iterable[Finding]:
        if self._findings is None:
            self._findings = self._finalize()
        return self._findings.get(module.path, [])
