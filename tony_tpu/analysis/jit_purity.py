"""jit-purity: Python side effects inside traced (jitted) functions.

``jax.jit`` runs the Python body ONCE per compile cache entry; side effects
fire at trace time only and silently stop happening on cached calls — the
classic "my print/append/time.time() works the first step and never again"
bug class. Flagged inside any jit/pjit-compiled function:

- ``print(...)``
- stdlib ``time.*`` / ``random.*`` calls (``jax.random`` is fine — its root
  is ``jax``)
- ``global`` / ``nonlocal`` declarations
- assignments to ``self.*`` (or any closed-over object's attributes/items)
- bare mutating-method statements (``.append/.update/...``) on closed-over
  state — calls whose *result is used* are not flagged, so API methods that
  merely share a name (``optimizer.update(...)`` in an assignment) pass

Jitted functions are recognized by decorator (``@jax.jit``,
``@functools.partial(jax.jit, ...)``) and by application
(``f = jax.jit(g, ...)``, ``f = functools.partial(jax.jit, ...)(g)``,
``return jax.jit(g)``) anywhere in the module.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tony_tpu.analysis.analyzer import (
    JIT_NAMES as _JIT_NAMES,
    MUTATOR_METHODS as _MUTATORS,
    PARTIAL_NAMES as _PARTIAL_NAMES,
    Checker,
    Finding,
    Module,
    dotted_name,
)

_IMPURE_ROOTS = {"time", "random"}


def _is_jit_expr(node: ast.AST) -> bool:
    """``jax.jit`` or ``functools.partial(jax.jit, ...)``."""
    if dotted_name(node) in _JIT_NAMES:
        return True
    return (
        isinstance(node, ast.Call)
        and dotted_name(node.func) in _PARTIAL_NAMES
        and bool(node.args)
        and dotted_name(node.args[0]) in _JIT_NAMES
    )


def _jit_applied_to(node: ast.AST) -> str | None:
    """Name of the function a jit application wraps, for forms like
    ``jax.jit(f, ...)`` and ``functools.partial(jax.jit, ...)(f)``."""
    if not isinstance(node, ast.Call):
        return None
    if _is_jit_expr(node.func) and node.args and isinstance(node.args[0], ast.Name):
        # partial(jax.jit, ...)(f) — func is itself the jit expr;
        # jax.jit(f, ...) — func is the jax.jit name
        if dotted_name(node.func) in _JIT_NAMES or (
            isinstance(node.func, ast.Call)
        ):
            return node.args[0].id
    return None


def _bound_names(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> set[str]:
    """Every name bound within ``fn`` (params, assignments, loop/with/except
    targets, comprehensions, nested defs) — anything NOT in here that gets
    mutated is closed-over state."""
    bound: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            a = node.args
            for arg in [*a.posonlyargs, *a.args, *a.kwonlyargs]:
                bound.add(arg.arg)
            if a.vararg:
                bound.add(a.vararg.arg)
            if a.kwarg:
                bound.add(a.kwarg.arg)
            if not isinstance(node, ast.Lambda):
                bound.add(node.name)
        elif isinstance(node, ast.ClassDef):
            bound.add(node.name)
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            bound.add(node.id)
    return bound


def _root_name(node: ast.AST) -> str | None:
    """Leftmost Name of an attribute/subscript chain."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


class JitPurityChecker(Checker):
    name = "jit-purity"
    description = (
        "no Python side effects (print/time/random/global/self or "
        "closed-over mutation) inside jit-compiled functions"
    )

    def _jitted_functions(self, module: Module) -> list[ast.FunctionDef | ast.AsyncFunctionDef]:
        jit_applied: set[str] = set()
        for node in ast.walk(module.tree):
            target = _jit_applied_to(node)
            if target:
                jit_applied.add(target)
        out = []
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name in jit_applied or any(
                _is_jit_expr(dec) for dec in node.decorator_list
            ):
                out.append(node)
        return out

    def check(self, module: Module) -> Iterable[Finding]:
        for fn in self._jitted_functions(module):
            bound = _bound_names(fn)
            yield from self._visit(module, fn, fn, bound, nested_params=set())

    def _visit(
        self, module, fn, node, bound, nested_params: set[str]
    ) -> Iterable[Finding]:
        """Recursive walk tracking names bound as params of *nested* defs:
        a nested helper's own ``self`` (e.g. a trace-time utility class's
        ``__init__``) is that object's state, not the jitted caller's."""
        if node is not fn and isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            a = node.args
            params = {arg.arg for arg in [*a.posonlyargs, *a.args, *a.kwonlyargs]}
            if a.vararg:
                params.add(a.vararg.arg)
            if a.kwarg:
                params.add(a.kwarg.arg)
            nested_params = nested_params | params
        yield from self._check_node(module, fn, node, bound, nested_params)
        for child in ast.iter_child_nodes(node):
            yield from self._visit(module, fn, child, bound, nested_params)

    def _check_node(self, module, fn, node, bound, nested_params) -> Iterable[Finding]:
        where = f"jitted function {fn.name!r}"
        if isinstance(node, (ast.Global, ast.Nonlocal)):
            kw = "global" if isinstance(node, ast.Global) else "nonlocal"
            yield self.finding(
                module, node,
                f"{kw} declaration inside {where}: writes happen at trace "
                f"time only, not per call",
            )
            return
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name == "print":
                yield self.finding(
                    module, node,
                    f"print() inside {where} fires at trace time only "
                    f"(use jax.debug.print for per-call output)",
                )
            elif name and name.split(".", 1)[0] in _IMPURE_ROOTS and "." in name:
                yield self.finding(
                    module, node,
                    f"{name}() inside {where} is evaluated once at trace "
                    f"time and baked into the compiled program",
                )
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                for el in ast.walk(t):
                    if not isinstance(el, (ast.Attribute, ast.Subscript)):
                        continue
                    if not isinstance(el.ctx, ast.Store):
                        continue
                    root = _root_name(el)
                    if root == "self" and "self" not in nested_params:
                        yield self.finding(
                            module, el,
                            f"assignment to self.* inside {where}: object "
                            f"state mutates at trace time only — return the "
                            f"new value instead",
                        )
                    elif root is not None and root not in bound:
                        yield self.finding(
                            module, el,
                            f"mutation of closed-over {root!r} inside "
                            f"{where}: happens at trace time only — thread "
                            f"it through the function's inputs/outputs",
                        )
            return
        # bare mutating-method statement on closed-over state; calls whose
        # result is consumed (assignments, args) are not mutation idioms
        if (
            isinstance(node, ast.Expr)
            and isinstance(node.value, ast.Call)
            and isinstance(node.value.func, ast.Attribute)
            and node.value.func.attr in _MUTATORS
        ):
            root = _root_name(node.value.func.value)
            is_self = root == "self" and "self" not in nested_params
            if is_self or (root is not None and root not in bound):
                owner = "self" if is_self else f"closed-over {root!r}"
                yield self.finding(
                    module, node,
                    f".{node.value.func.attr}() on {owner} inside {where}: "
                    f"container mutates at trace time only",
                )
