"""host-sync: no unconditional device sync inside a step loop.

A training/measurement step loop keeps the device busy only while the host
stays ahead of it: JAX dispatch is asynchronous, so the device pipelines
step N+1's launch behind step N's compute — until the host touches a device
value. ``float(loss)``, ``.item()``, ``.tolist()``, ``jax.device_get`` and
``jax.block_until_ready`` all block the host until the device drains, and
doing that EVERY step serializes dispatch against compute (on a tunneled
backend each one also pays a host⇄device round trip). The repo's own hot
loops lost measurable MFU to exactly this (bench.py's per-step
``float(metrics["loss"])``; see docs/performance.md).

The discipline this checker enforces: syncs inside a step loop must be
**throttled** — nested under an ``if`` (a logging window like
``(step + 1) % log_every == 0``, a first-step branch, an error path) — or
moved off the loop entirely (sync once after the loop; fetch step N−1's
value while step N computes). An *unconditional* sync-forcing call in the
loop body is flagged.

Step loops are recognized syntactically: a ``for`` loop whose target binds a
name containing ``step``, or whose iterable's source mentions ``step``
(``range(start_step, loop.steps)``, ``range(steps)``, ...). Other loops are
out of scope — a data-prep loop over files may convert floats freely.

Deliberate per-step syncs (e.g. a lockstep-handshake test fixture) carry an
inline ``# lint: disable=host-sync — <why>``.
"""

from __future__ import annotations

import ast
from typing import Iterable

from tony_tpu.analysis.analyzer import Checker, Finding, Module, dotted_name

#: bare-name calls that force a transfer when handed a device value
_SYNC_NAME_CALLS = frozenset({"float", "int", "bool"})
#: attribute/method tails that force a sync on jax arrays
_SYNC_METHODS = frozenset({"item", "tolist", "block_until_ready"})
#: fully-dotted calls that force a sync / host materialization
_SYNC_DOTTED = frozenset({
    "jax.block_until_ready", "jax.device_get",
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "jnp.asarray", "jax.numpy.asarray",
})


def _is_step_loop(node: ast.For, source: str) -> bool:
    """A loop driving training/measurement steps, by naming convention."""
    for el in ast.walk(node.target):
        if isinstance(el, ast.Name) and "step" in el.id.lower():
            return True
    try:
        it = ast.get_source_segment(source, node.iter) or ""
    except Exception:  # noqa: BLE001 — source slicing is best-effort
        it = ""
    return "step" in it.lower()


def _sync_call_reason(node: ast.Call) -> str | None:
    """Why this call forces a host⇄device sync, or None."""
    func = node.func
    if isinstance(func, ast.Name) and func.id in _SYNC_NAME_CALLS:
        # float(0.5) / int("3") literals can't hold device values
        if node.args and not isinstance(node.args[0], ast.Constant):
            return f"{func.id}() materializes its argument on the host"
        return None
    name = dotted_name(func)
    if name in _SYNC_DOTTED:
        return f"{name}() forces a device transfer"
    if isinstance(func, ast.Attribute) and func.attr in _SYNC_METHODS:
        return f".{func.attr}() blocks until the device catches up"
    return None


class HostSyncChecker(Checker):
    name = "host-sync"
    description = (
        "no unconditional host⇄device sync (float/.item/device_get/"
        "block_until_ready) inside a step loop — throttle it behind a "
        "window `if` or move it off the step path"
    )

    def check(self, module: Module) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.For) and _is_step_loop(node, module.source):
                yield from self._check_loop(module, node)

    def _check_loop(self, module: Module, loop: ast.For) -> Iterable[Finding]:
        """Walk the loop body, skipping anything conditional (If/Try/While
        branches run a data-dependent subset of iterations — that IS the
        throttling idiom) and nested defs/loops (nested step loops are
        visited by the outer walk on their own)."""
        stack = list(loop.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.If, ast.While)):
                # the BODY is conditional (that is the throttling idiom),
                # but the TEST expression evaluates every iteration — a
                # sync hiding in `if float(loss) > 8.0:` is still per-step
                stack.append(node.test)
                continue
            if isinstance(node, ast.For):
                stack.append(node.iter)  # evaluated once per outer iteration
                continue
            if isinstance(node, (ast.Try, ast.FunctionDef,
                                 ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(node, ast.Call):
                reason = _sync_call_reason(node)
                if reason is not None:
                    yield self.finding(
                        module, node,
                        f"unconditional device sync in a step loop: {reason} "
                        f"every iteration, serializing host dispatch against "
                        f"device compute — throttle it behind a logging-"
                        f"window `if`, or sync once after the loop; a "
                        f"deliberate per-step sync takes an inline "
                        f"`# lint: disable=host-sync — <why>`",
                    )
                    # fall through: a flagged call's ARGUMENTS are still
                    # walked — float(jax.device_get(x)) is two syncs, and
                    # fixing only the outer one must not re-lint clean
            stack.extend(ast.iter_child_nodes(node))
