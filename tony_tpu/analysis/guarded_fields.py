"""guarded-fields: infer GuardedBy and flag lock-free accesses.

A ``self._x`` written under the same class lock at two or more distinct
sites has an inferred guard; any other read or write of it that does not
hold that lock is a candidate data race. ``__init__`` and other dunders
are construction-time (single-threaded) and never count; ``*_locked``
methods are trusted to run under the class's primary lock (the repo's
naming contract), so their accesses are guarded.

The two-site threshold keeps set-once configuration attributes (written
in ``__init__``, read everywhere) out of scope — those are immutable
after construction and safely read bare.
"""

from __future__ import annotations

import ast
from collections import Counter
from typing import Iterable

from tony_tpu.analysis.analyzer import (
    MUTATOR_METHODS as _MUTATORS,
    Checker,
    Finding,
    Module,
)
from tony_tpu.analysis.callgraph import build_callgraph


class GuardedFieldsChecker(Checker):
    name = "guarded-fields"
    description = (
        "self._* fields written under a lock in >=2 sites (inferred "
        "GuardedBy) are never read or written lock-free elsewhere"
    )

    def __init__(self) -> None:
        self._modules: list[Module] = []
        self._findings: dict[str, list[Finding]] | None = None

    def collect(self, module: Module) -> None:
        self._modules.append(module)

    def _finalize(self) -> dict[str, list[Finding]]:
        graph = build_callgraph(self._modules)
        by_path: dict[str, list[Finding]] = {}
        classes = [ci for ci in graph.classes.values() if ci is not None]
        for ci in classes:
            lock_ids = {ci.lock_id(a) for a, k in ci.locks.items()
                        if k != "condition"}
            if not lock_ids:
                continue
            # attr -> [(method, node, held, is_write)]
            sites: dict[str, list[tuple[str, ast.AST, frozenset[str], bool]]] = {}
            for mname, mnode in ci.methods.items():
                if mname.startswith("__"):
                    continue   # construction / dunder protocol: one thread
                fn = graph.functions.get(f"{ci.stem}.{ci.name}.{mname}")
                if fn is None:
                    continue
                claimed: set[int] = set()   # write-root Attribute node ids

                def root_attr(node: ast.AST) -> ast.Attribute | None:
                    """The ``self._x`` attribute at the base of an access
                    chain (``self._x[k].y`` -> the ``self._x`` node)."""
                    while isinstance(node, (ast.Attribute, ast.Subscript)):
                        if (isinstance(node, ast.Attribute)
                                and isinstance(node.value, ast.Name)
                                and node.value.id == "self"):
                            a = node.attr
                            if (a.startswith("_") and a not in ci.locks):
                                return node
                            return None
                        node = node.value
                    return None

                held_of: dict[int, frozenset[str]] = {}
                order: list[tuple[ast.AST, frozenset[str]]] = []
                for node, held in graph.iter_held(fn):
                    held_of[id(node)] = held
                    order.append((node, held))
                # pass 1: writes (assignment chain roots, mutator calls)
                for node, held in order:
                    if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                        targets = (node.targets if isinstance(node, ast.Assign)
                                   else [node.target])
                        for t in targets:
                            els = (t.elts if isinstance(t, (ast.Tuple, ast.List))
                                   else [t])
                            for el in els:
                                root = root_attr(el)
                                if root is not None:
                                    claimed.add(id(root))
                                    sites.setdefault(root.attr, []).append(
                                        (mname, root, held, True))
                    elif (isinstance(node, ast.Call)
                          and isinstance(node.func, ast.Attribute)
                          and node.func.attr in _MUTATORS):
                        root = root_attr(node.func.value)
                        if root is not None:
                            claimed.add(id(root))
                            sites.setdefault(root.attr, []).append(
                                (mname, root, held_of.get(id(root), held), True))
                # pass 2: bare reads (any remaining self._x load)
                for node, held in order:
                    if (isinstance(node, ast.Attribute)
                            and isinstance(node.ctx, ast.Load)
                            and isinstance(node.value, ast.Name)
                            and node.value.id == "self"
                            and node.attr.startswith("_")
                            and node.attr not in ci.locks
                            and id(node) not in claimed):
                        sites.setdefault(node.attr, []).append(
                            (mname, node, held, False))
            contexts = graph.class_contexts(ci)
            for attr, accesses in sorted(sites.items()):
                locked_writes = [
                    (m, n, h) for (m, n, h, w) in accesses if w and h & lock_ids
                ]
                if len(locked_writes) < 2:
                    continue
                # the lock only mediates this field if its writers span two
                # concurrency contexts; a single-writer-thread field whose
                # locked writes are incidental (the lock was held for other
                # state) is the documented snapshot-read pattern, not a guard
                writer_contexts: set[str] = set()
                for (m, _, _, w) in accesses:
                    if w:
                        writer_contexts |= contexts.get(m, frozenset({"main"}))
                if len(writer_contexts) < 2:
                    continue
                guard = Counter(
                    lid for (_, _, h) in locked_writes for lid in h & lock_ids
                ).most_common(1)[0][0]
                guarded_writes = [x for x in locked_writes if guard in x[2]]
                if len(guarded_writes) < 2:
                    continue
                for (m, n, h, w) in accesses:
                    if guard in h:
                        continue
                    verb = "written" if w else "read"
                    msg = (
                        f"self.{attr} is guarded by {guard} "
                        f"({len(guarded_writes)} writes hold it) but is "
                        f"{verb} in {m!r} without the lock — hold "
                        f"{guard} or document why the access is safe"
                    )
                    by_path.setdefault(ci.module.path, []).append(Finding(
                        checker=self.name, path=ci.module.path,
                        line=getattr(n, "lineno", ci.node.lineno),
                        col=getattr(n, "col_offset", 0), message=msg,
                    ))
        return by_path

    def check(self, module: Module) -> Iterable[Finding]:
        if self._findings is None:
            self._findings = self._finalize()
        return self._findings.get(module.path, [])
