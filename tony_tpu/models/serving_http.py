"""HTTP front end for the continuous-batching engine: the ``serve`` jobtype.

The reference runs training jobs and interactive notebooks under the AM
(SURVEY.md §3.4: the notebook jobtype registers its URL so the submitter can
proxy it); serving is new TPU-era capability built the same way — a
long-running, AM-supervised task that:

- boots a ``ContinuousBatcher`` (models/serving.py) over a model preset,
  HF checkpoint, or random-init weights (bench/test mode), optionally int8;
- serves a streaming completions API (stdlib ThreadingHTTPServer — one
  user-facing control path, no framework dependency):
    POST /v1/completions   {"prompt_tokens": [...], "max_tokens": N,
                            "stream": true|false, "temperature": ..,
                            "top_k": ..}  → JSON or SSE token stream
    GET  /healthz           liveness
    GET  /stats             engine counters (slots, queue depth, tok/s)
- when launched inside a tony container (TONY_AM_* env present), registers
  its URL over the AM RPC (``register_task_url`` — the §3.4 path) and drops
  engine throughput into ENV_TRAIN_METRICS_FILE so the executor's existing
  metrics loop feeds the portal;
- drains on SIGTERM: stops admitting, finishes the in-flight decode chunk,
  answers in-flight streams, exits 0;
- drains on a **cooperative-preemption notice** the same way: a watcher
  thread polls ``<TONY_TRAIN_METRICS_FILE>.drain`` — the control file the
  executor's DrainCourier drops when the pool asks this gang to drain —
  exactly like the training loop's UrgentSaveSignal. On a notice the server
  flips ``draining`` (the fleet HealthMonitor sheds it from routing and the
  SessionTable re-pins its sessions), finishes in-flight streams, publishes
  ``.drain.done`` (the courier reports ``report_drain_saved``), and exits
  clean inside the pool's deadline — serving survives preemption as
  gracefully as training does.

Threading model: HTTP handler threads only ever touch thread-safe queues;
ONE engine thread owns the batcher (submit → step → drain_stream), so the
engine itself needs no locks — the same host/device split the engine's
docstring promises stays intact.
"""

from __future__ import annotations

import argparse
import json
import os
import queue
import signal
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

import jax

from tony_tpu import constants
from tony_tpu.models.llama import PRESETS, init
from tony_tpu.models.serving import ContinuousBatcher
from tony_tpu.obs import logging as obs_logging
from tony_tpu.obs import metrics as obs_metrics
from tony_tpu.obs import trace as obs_trace

# Serving instruments (obs registry, satellite of the training child's:
# snapshots drop at <train-metrics-file>.obs and ride the executor's
# push_metrics piggyback to the AM's get_metrics → the portal's /metrics).
_QUEUE_DEPTH = obs_metrics.gauge(
    "tony_serve_queue_depth",
    "engine admission + staging queue depth (requests waiting for a slot)")
_TTFT = obs_metrics.histogram(
    "tony_serve_ttft_seconds",
    "time from request submission to its first generated-token fanout")
_TOKEN_LATENCY = obs_metrics.histogram(
    "tony_serve_token_latency_seconds",
    "per-token decode latency (chunk interval / tokens in the chunk)")
_DELIVERED = obs_metrics.counter(
    "tony_serve_tokens_delivered_total", "tokens actually written to client sockets")
_REQUESTS_DONE = obs_metrics.counter(
    "tony_serve_requests_total", "finished engine requests by outcome",
    labelnames=("outcome",))
_PREFIX_HITS = obs_metrics.counter(
    "tony_serve_prefix_hit_tokens_total",
    "prompt tokens whose prefill was skipped via paged prefix-cache hits")
_KV_HANDOFF = obs_metrics.counter(
    "tony_serve_kv_handoff_total",
    "KV pages moved through the disaggregated prefill→decode handoff "
    "(exported by the prefill tier / adopted into the decode tier's pool)",
    labelnames=("side",))
_HANDOFF_LATENCY = obs_metrics.histogram(
    "tony_serve_kv_handoff_seconds",
    "disaggregated handoff wall time on the prefill replica: prompt done → "
    "pages exported, shipped, and acked by the decode replica")


class RequestStream:
    """The per-request event channel ``submit()`` returns. Quacks like the
    plain Queue it used to be (``get`` the events), plus ``cancel()`` —
    the client-disconnect/deadline path: the engine thread picks the flag
    up within one decode chunk and frees the slot/pages."""

    __slots__ = ("q", "cancelled", "submitted_s", "last_fanout_s",
                 "request_id", "span", "stage", "defer_finish")

    def __init__(self, maxsize: int = 0, request_id: str = ""):
        self.q: queue.Queue = queue.Queue(maxsize)
        self.cancelled = threading.Event()
        # instrument timestamps (engine-thread only): TTFT measures from
        # SUBMISSION, so admission-queue wait is included — the number a
        # client actually experiences
        self.submitted_s = time.time()
        self.last_fanout_s = 0.0
        #: router-propagated id (X-Tony-Request-Id) — exemplar + span key
        self.request_id = request_id
        #: disagg handoff: True → on "done" the engine opens a serve.handoff
        #: stage instead of closing the span; the /v1/prefill handler owns
        #: finish_trace after the pages ship (safe: the engine thread never
        #: touches the stream again after its terminal event)
        self.defer_finish = False
        # per-request span chain (queue → prefill → decode) under one
        # serve.request umbrella; both stay None with tracing disabled, so
        # every hot-path hook below is a single attribute check
        self.span = None
        self.stage = None

    def get(self, timeout: float | None = None):
        return self.q.get(timeout=timeout)

    def put(self, item) -> None:
        self.q.put(item)

    def cancel(self) -> None:
        self.cancelled.set()

    # ------------------------------------------------------ request spans
    def open_trace(self) -> None:
        """Start the serve.request umbrella + its queue stage (no-op — and
        allocation-free — when tracing is disabled)."""
        self.span = obs_trace.start_manual("serve.request", rid=self.request_id)
        if self.span is not None:
            self.stage = obs_trace.start_manual(
                "serve.queue", parent_id=self.span.span_id)

    def begin_stage(self, name: str, **attrs: Any) -> None:
        """End the current stage span and open the next one in the chain."""
        if self.span is not None:
            obs_trace.end_manual(self.stage)
            self.stage = obs_trace.start_manual(
                name, parent_id=self.span.span_id, **attrs)

    def finish_trace(self, status: str = "ok") -> None:
        if self.span is not None:
            obs_trace.end_manual(self.stage, status)
            obs_trace.end_manual(self.span, status)
            self.span = self.stage = None


class EngineServer:
    """Thread-safe facade over one ContinuousBatcher.

    HTTP threads call ``submit()`` (enqueue + wait on a per-request stream);
    the engine thread drains the inbox, steps the batcher, fans tokens out,
    and processes cancellations/deadlines between chunks. ``stop()``
    initiates the drain.

    Load shedding: the admission inbox is BOUNDED (``max_queue``) — when
    it is full, submit() refuses with an "overloaded" error the HTTP layer
    maps to 429, so overload surfaces as fast rejection, not unbounded
    latency. Per-stream queues are bounded too: a consumer that stops
    draining (dead-slow SSE client) trips the bound and is cancelled like
    a disconnect instead of growing host memory without limit."""

    STREAM_QUEUE_CHUNKS = 1024  # per-request event bound (chunks, not tokens)

    def __init__(self, engine: ContinuousBatcher, on_fatal=None,
                 max_queue: int = 256, request_timeout_s: float = 0.0,
                 role: str = "serve"):
        self.engine = engine
        #: tier this replica serves in ("serve" = decode-capable default,
        #: "prefill" = disagg prompt tier) — advisory: /stats carries it so
        #: the per-tier health monitors and the docs' tier diagram line up
        self.role = role
        self._inbox: "queue.Queue[tuple]" = queue.Queue(maxsize=max_queue)
        #: engine-thread control channel (disagg KV export/adopt): closures
        #: that must run where the allocator + cache live. Drained at the
        #: top of every loop iteration, answered (ok, value) on a per-op box.
        self._control: "queue.Queue[tuple]" = queue.Queue()
        self._streams: dict[int, RequestStream] = {}
        self._deadlines: dict[int, float] = {}
        self.request_timeout_s = request_timeout_s
        self._draining = threading.Event()
        self._stopped = threading.Event()
        # serializes the draining-check+enqueue in submit() against the
        # loop's final refuse-sweep: without it a request slipping between
        # the sweep and _stopped would sit in an inbox nobody reads
        self._admit_lock = threading.Lock()
        self._thread = threading.Thread(target=self._loop, name="engine", daemon=True)
        self.error: BaseException | None = None  # fatal engine failure, if any
        self._on_fatal = on_fatal
        # engine counters (read by /stats without locking: ints are atomic)
        self.started_s = time.time()
        self.tokens_out = 0         # GENERATED by the engine (fanout time)
        self.tokens_delivered = 0   # actually written to a client socket
        self.requests_done = 0
        self.requests_cancelled = 0
        self._prefix_hits_exported = 0  # engine-thread watermark → registry delta
        # disagg handoff accounting (engine-thread only: export/adopt both
        # run as control ops, so plain ints need no lock)
        self.kv_handoff_exported = 0    # pages shipped toward decode replicas
        self.kv_handoff_adopted = 0     # pages adopted into this pool
        # delivered is the ONE counter with multiple writers (every HTTP
        # handler thread); unsynchronized += would lose updates
        self._delivered_lock = threading.Lock()

    def add_delivered(self, n: int) -> None:
        with self._delivered_lock:
            self.tokens_delivered += n
        _DELIVERED.inc(n)

    def run_on_engine(self, fn, timeout_s: float = 30.0):
        """Run ``fn()`` ON the engine thread (between decode chunks) and
        return its result. The disagg KV export/adopt path: the page
        allocator and the cache arrays have exactly one owner, and a handler
        thread mutating them mid-step would race the loop's functional
        cache updates. Raises what ``fn`` raised; TimeoutError when the
        engine never picked the op up (draining / wedged)."""
        box: "queue.Queue[tuple]" = queue.Queue(1)
        self._control.put((fn, box))
        try:
            ok, val = box.get(timeout=timeout_s)
        except queue.Empty:
            raise TimeoutError("engine did not service the control op "
                               f"within {timeout_s:.0f}s") from None
        if not ok:
            raise val
        return val

    def _drain_control(self) -> None:
        """Service queued control ops (engine thread only). A failing op
        answers its caller and never takes the loop down — export/adopt
        problems are per-request errors, not engine fatals."""
        while True:
            try:
                fn, box = self._control.get_nowait()
            except queue.Empty:
                return
            try:
                box.put((True, fn()))
            except Exception as e:  # noqa: BLE001 — answered to the caller
                box.put((False, e))

    def start(self) -> "EngineServer":
        self._thread.start()
        return self

    def submit(
        self, prompt_tokens: list[int], max_tokens: int,
        sampling: dict | None = None, timeout_s: float | None = None,
        request_id: str = "",
    ) -> RequestStream:
        """Enqueue a request; returns the stream its events arrive on:
        ("tokens", [..]) zero or more times, then ("done", all_tokens) —
        or ("error", message). ``sampling``: per-request temperature /
        top_k / top_p overrides. ``timeout_s`` overrides the server's
        default per-request deadline (0/None → no deadline).
        ``request_id``: router-propagated id for spans/exemplars."""
        out = RequestStream(self.STREAM_QUEUE_CHUNKS, request_id=request_id)
        # span chain opens BEFORE the inbox put: once the engine thread can
        # see the stream, only it touches the spans
        out.open_trace()
        with self._admit_lock:
            if self._draining.is_set() or self.error is not None:
                out.put(("error", "server is draining" if self.error is None
                         else f"engine failed: {self.error}"))
                out.finish_trace("error")
                return out
            timeout = timeout_s if timeout_s is not None else self.request_timeout_s
            # the deadline clock starts at SUBMISSION, so time spent queued
            # in the admission inbox counts — exactly the overload case a
            # deadline exists for
            deadline_abs = time.time() + timeout if timeout and timeout > 0 else 0.0
            try:
                self._inbox.put_nowait((prompt_tokens, max_tokens, sampling or {},
                                        deadline_abs, out))
            except queue.Full:
                out.put(("error", "overloaded: admission queue full"))
                out.finish_trace("error")
        return out

    def _queue_depth(self) -> int:
        """Requests waiting for a slot: engine pending + staged prefills +
        the admission inbox. THE definition of queue depth — /stats (what
        the fleet health monitor and autoscaler consume) and the
        tony_serve_queue_depth gauge must never diverge."""
        eng = self.engine
        return len(eng.pending) + len(eng._staged) + self._inbox.qsize()

    def stats(self) -> dict[str, Any]:
        eng = self.engine
        up = max(time.time() - self.started_s, 1e-9)
        return {
            "slots_total": eng.S,
            "slots_active": len(eng.running),
            "queue_depth": self._queue_depth(),
            "requests_done": self.requests_done,
            "requests_cancelled": self.requests_cancelled,
            "tokens_out": self.tokens_out,
            "tokens_delivered": self.tokens_delivered,
            "tokens_per_s": round(self.tokens_out / up, 2),
            "uptime_s": round(up, 1),
            "draining": self._draining.is_set(),
            "healthy": self.error is None,
            "role": self.role,
            **(
                {
                    "pages_live": eng.allocator.live_pages(),
                    "pages_total": eng.num_pages - 1,
                    "prefix_hit_tokens": eng.prefix_hit_tokens,
                    "kv_handoff_exported": self.kv_handoff_exported,
                    "kv_handoff_adopted": self.kv_handoff_adopted,
                }
                if getattr(eng, "kv", "dense") == "paged"
                else {}
            ),
        }

    def stop(self, timeout_s: float = 10.0) -> bool:
        """Drain: no new admissions; in-flight requests finish. Returns True
        if the drain completed inside ``timeout_s`` (False → truncated)."""
        self._draining.set()
        return self._stopped.wait(timeout_s)

    def _loop(self) -> None:
        try:
            self._loop_inner()
        except BaseException as e:  # noqa: BLE001 — a dead silent engine thread
            # is the worst failure mode: every in-flight stream would block
            # forever while /healthz keeps answering ok. Record, error out
            # every stream, and tell the process (the AM supervises restarts).
            import traceback

            self.error = e
            traceback.print_exc()
            if self._streams:
                _REQUESTS_DONE.inc(len(self._streams), outcome="error")
            for out in self._streams.values():
                self._finish_stream(out, ("error", f"engine failed: {e}"))
                out.finish_trace("error")
            self._streams.clear()
            if self._on_fatal is not None:
                self._on_fatal()
        finally:
            # refuse anything still queued (or enqueued mid-teardown)
            with self._admit_lock:
                self._draining.set()
                while True:
                    try:
                        self._inbox.get_nowait()[-1].put(("error", "server is draining"))
                    except queue.Empty:
                        break
                while True:  # control ops must not leave their caller hanging
                    try:
                        _, box = self._control.get_nowait()
                        box.put((False, RuntimeError("engine stopped")))
                    except queue.Empty:
                        break
                self._stopped.set()

    @staticmethod
    def _finish_stream(stream: RequestStream, event: tuple) -> None:
        """Deliver a TERMINAL event without ever blocking the engine thread:
        if the stream's bounded queue is full (slow consumer), evict one
        buffered chunk to make room — the handler always sees an end-of-
        stream event instead of blocking forever on a silently-dead queue."""
        try:
            stream.q.put_nowait(event)
        except queue.Full:
            try:
                stream.q.get_nowait()
            except queue.Empty:
                pass
            try:
                stream.q.put_nowait(event)
            except queue.Full:
                pass  # racing consumer refilled it: it is draining, fine

    def _sweep_cancellations(self) -> None:
        """Between chunks: propagate client cancellations (disconnect, slow
        consumer) and expired deadlines into the engine — the slot/pages
        free at the next retirement flush, within one decode chunk."""
        eng = self.engine
        now = time.time()
        for rid, stream in list(self._streams.items()):
            expired = (
                rid in self._deadlines and now > self._deadlines[rid]
            )
            if stream.cancelled.is_set() or expired:
                eng.cancel(rid)
                # ALWAYS terminate the stream (the handler may still be
                # attached — slow-consumer cancels have a live socket)
                self._finish_stream(
                    stream,
                    ("error", "deadline exceeded" if expired
                     else "cancelled: consumer stopped draining"),
                )
                self.requests_cancelled += 1
                _REQUESTS_DONE.inc(outcome="cancelled")
                stream.finish_trace("error")
                del self._streams[rid]
                self._deadlines.pop(rid, None)

    def _loop_inner(self) -> None:
        eng = self.engine
        carry = None  # item pulled by the idle wait — admitted FIRST (FIFO)
        while True:
            while True:
                if carry is not None:
                    prompt, max_tokens, sampling, deadline, out = carry
                    carry = None
                else:
                    try:
                        prompt, max_tokens, sampling, deadline, out = (
                            self._inbox.get_nowait()
                        )
                    except queue.Empty:
                        break
                if out.cancelled.is_set():
                    out.finish_trace("error")
                    continue  # client gone before the engine ever saw it
                if deadline and time.time() > deadline:
                    out.put(("error", "deadline exceeded"))
                    self.requests_cancelled += 1
                    _REQUESTS_DONE.inc(outcome="cancelled")
                    out.finish_trace("error")
                    continue  # expired while queued in the inbox
                try:
                    rid = eng.submit(prompt, max_tokens, **sampling)
                except (ValueError, TypeError) as e:
                    out.put(("error", str(e)))
                    out.finish_trace("error")
                    continue
                self._streams[rid] = out
                out.begin_stage("serve.prefill")
                if deadline:
                    self._deadlines[rid] = deadline
            self._sweep_cancellations()
            self._drain_control()
            _QUEUE_DEPTH.set(self._queue_depth())
            had_work = eng.step()
            # export the engine's prefix-reuse win as a REAL instrument, not
            # a /stats-payload-only field: the loadtest harness and the
            # portal read the registry, and "reuse happened" must be
            # observable wherever tony_serve_* metrics flow
            hits = getattr(eng, "prefix_hit_tokens", 0)
            if hits > self._prefix_hits_exported:
                _PREFIX_HITS.inc(hits - self._prefix_hits_exported)
                self._prefix_hits_exported = hits
            now_s = time.time()
            for rid, (toks, done) in eng.drain_stream().items():
                out = self._streams.get(rid)
                final = eng.done.pop(rid, None) if done else None
                if out is None:
                    continue
                if toks:
                    if out.last_fanout_s:
                        _TOKEN_LATENCY.observe((now_s - out.last_fanout_s) / len(toks))
                    else:
                        ttft = now_s - out.submitted_s
                        # worst-offender exemplars: id-carrying requests link
                        # a burning TTFT SLO straight to their trace
                        _TTFT.observe(ttft, exemplar=out.request_id or None)
                        out.begin_stage("serve.decode", ttft_s=round(ttft, 6))
                    out.last_fanout_s = now_s
                self.tokens_out += len(toks)
                if done:
                    self.requests_done += 1
                    _REQUESTS_DONE.inc(outcome="done")
                    # terminal event via the non-blocking evict-then-put: a
                    # full queue (consumer stalled since the last chunk) must
                    # not block the ONE engine thread on out.put()
                    self._finish_stream(
                        out, ("done", final if final is not None else toks)
                    )
                    if out.defer_finish:
                        # disagg: the span stays open through the KV handoff;
                        # the /v1/prefill handler closes it after the ship
                        out.begin_stage("serve.handoff")
                    else:
                        out.finish_trace("ok")
                    del self._streams[rid]
                    self._deadlines.pop(rid, None)
                else:
                    try:
                        out.q.put_nowait(("tokens", toks))
                    except queue.Full:
                        # dead-slow consumer: cap host memory by treating it
                        # as a disconnect (picked up by the next sweep)
                        out.cancel()
            if not had_work:
                if self._draining.is_set():
                    return
                # idle: block until the next request (or drain) arrives; the
                # pulled item is carried to the admission pass directly —
                # re-queuing it would reorder it behind later arrivals
                try:
                    carry = self._inbox.get(timeout=0.2)
                except queue.Empty:
                    pass


def _json_body(handler: BaseHTTPRequestHandler) -> dict:
    n = int(handler.headers.get("Content-Length") or 0)
    return json.loads(handler.rfile.read(n) or b"{}")


class _Handler(BaseHTTPRequestHandler):
    server_ref: EngineServer = None  # set by serve()
    tokenizer = None

    def log_message(self, *a) -> None:  # quiet
        pass

    def _reply(self, code: int, obj: Any) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802
        if self.path == "/healthz":
            err = self.server_ref.error
            if err is None:
                self._reply(200, {"ok": True})
            else:
                self._reply(503, {"ok": False, "error": str(err)})
        elif self.path == "/stats":
            self._reply(200, self.server_ref.stats())
        else:
            self._reply(404, {"error": "not found"})

    def do_POST(self) -> None:  # noqa: N802
        if self.path == "/v1/prefill":
            self._handle_prefill()
            return
        if self.path == "/v1/kv/adopt":
            self._handle_adopt()
            return
        if self.path != "/v1/completions":
            self._reply(404, {"error": "not found"})
            return
        try:
            req = _json_body(self)
            if not isinstance(req, dict):
                raise ValueError("request body must be a JSON object")
            prompt = req.get("prompt_tokens")
            if prompt is None and "prompt" in req:
                if self.tokenizer is None:
                    raise ValueError("text prompts need --tokenizer; send prompt_tokens")
                prompt = self.tokenizer.encode(req["prompt"])
            if not prompt:
                raise ValueError("empty prompt")
            max_tokens = int(req.get("max_tokens", 16))
            stream = bool(req.get("stream", False))
            sampling = {
                k: (float(req[k]) if k != "top_k" else int(req[k]))
                for k in ("temperature", "top_k", "top_p")
                if req.get(k) is not None
            }
            timeout_s = (
                float(req["timeout_s"]) if req.get("timeout_s") is not None else None
            )
            if timeout_s is not None and timeout_s <= 0:
                raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
            prompt = [int(t) for t in prompt]
        except (TypeError, ValueError, json.JSONDecodeError) as e:
            self._reply(400, {"error": str(e)})
            return
        request_id = (self.headers.get("X-Tony-Request-Id") or "").strip()
        out = self.server_ref.submit(prompt, max_tokens, sampling,
                                     timeout_s=timeout_s, request_id=request_id)
        if stream:
            self._stream_response(out)
        else:
            self._block_response(out)

    def _handle_prefill(self) -> None:
        """Disagg prefill leg (serve/disagg.py contract): run the prompt
        through this engine for exactly ONE generated token (the prefill +
        first sample), export the finished full-prompt KV pages, POST them
        to the assigned decode replica's ``/v1/kv/adopt``, and reply with
        the first token + handoff accounting. The handoff is best-effort
        past the first token: a failed ship degrades to a decode-side
        recompute, never to a client-visible error."""
        from tony_tpu.serve import disagg

        srv = self.server_ref
        try:
            req = _json_body(self)
            if not isinstance(req, dict):
                raise ValueError("request body must be a JSON object")
            prompt = [int(t) for t in (req.get("prompt_tokens") or [])]
            if not prompt:
                raise ValueError("empty prompt")
            decode_url = str(req.get("decode_url") or "").rstrip("/")
            sampling = {
                k: (float(req[k]) if k != "top_k" else int(req[k]))
                for k in ("temperature", "top_k", "top_p")
                if req.get(k) is not None
            }
        except (TypeError, ValueError, json.JSONDecodeError) as e:
            self._reply(400, {"error": str(e)})
            return
        if getattr(srv.engine, "kv", "dense") != "paged":
            self._reply(409, {"error": "kv handoff needs a paged engine "
                                       "(--kv paged)"})
            return
        request_id = (self.headers.get("X-Tony-Request-Id") or "").strip()
        t0 = time.perf_counter()
        out = srv.submit(prompt, 1, sampling, request_id=request_id)
        out.defer_finish = True
        while True:
            kind, payload = out.get()
            if kind in ("done", "error"):
                break
        if kind == "error":
            self._error_reply(payload)
            return
        first = list(payload)
        shipped = have = pages = 0
        ship_error = ""
        try:
            exported = srv.run_on_engine(
                lambda: disagg.export_prefix_pages(srv, prompt))
            if exported is not None:
                pages = len(exported["keys"])
                if decode_url:
                    shipped, have = disagg.ship_pages(
                        decode_url, exported,
                        timeout_s=float(req.get("timeout_s") or 30.0))
        except Exception as e:  # noqa: BLE001 — degrade to decode recompute
            ship_error = str(e)[:200]
        took = time.perf_counter() - t0
        _HANDOFF_LATENCY.observe(took, exemplar=request_id or None)
        out.finish_trace("ok" if not ship_error else "error")
        resp = {
            "first_token": first[-1] if first else None,
            "pages": pages,
            "adopted": shipped,
            "already_resident": have,
            "handoff_ms": round(took * 1000, 3),
        }
        if ship_error:
            resp["ship_error"] = ship_error
        self._reply(200, resp)

    def _handle_adopt(self) -> None:
        """Adopt shipped KV pages into this replica's paged pool (the decode
        half of the handoff): alloc → scatter → register → park in the reuse
        pool, where the next matching prompt's prefix match picks them up
        instead of recomputing the prefill."""
        from tony_tpu.serve import disagg

        srv = self.server_ref
        if getattr(srv.engine, "kv", "dense") != "paged":
            self._reply(409, {"error": "kv adopt needs a paged engine"})
            return
        try:
            payload = _json_body(self)
            if not isinstance(payload, dict):
                raise ValueError("adopt body must be a JSON object")
            adopted, have = srv.run_on_engine(
                lambda: disagg.adopt_pages(srv, payload))
        except (TypeError, ValueError, KeyError, json.JSONDecodeError) as e:
            self._reply(400, {"error": str(e)})
            return
        except (TimeoutError, RuntimeError) as e:
            self._reply(503, {"error": str(e)})
            return
        self._reply(200, {"adopted": adopted, "already_resident": have})

    def _error_reply(self, payload: str) -> None:
        if "overloaded" in payload:
            # fast rejection, not unbounded latency: tell the client when
            # to come back instead of letting it camp on the socket
            body = json.dumps({"error": payload}).encode()
            self.send_response(429)
            self.send_header("Content-Type", "application/json")
            self.send_header("Retry-After", "1")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if "deadline" in payload:
            self._reply(504, {"error": payload})
            return
        self._reply(503 if "draining" in payload else 400, {"error": payload})

    def _block_response(self, out) -> None:
        toks: list[int] = []
        while True:
            kind, payload = out.get()
            if kind == "error":
                self._error_reply(payload)
                return
            if kind == "tokens":
                toks.extend(payload)
            else:  # done → payload is the authoritative full list
                self._reply(200, {"tokens": list(payload), "finished": True})
                self.server_ref.add_delivered(len(payload))
                return

    def _stream_response(self, out) -> None:
        """SSE: one ``data: {"tokens": [...]}`` event per decode chunk, then
        ``data: {"finished": true, ...}``. A write failure (client went
        away) CANCELS the engine request — the slot frees within one decode
        chunk instead of decoding to max_tokens for nobody."""
        first_kind, first_payload = out.get()
        if first_kind == "error":
            self._error_reply(first_payload)
            return
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.end_headers()

        def emit(obj: Any) -> None:
            self.wfile.write(b"data: " + json.dumps(obj).encode() + b"\n\n")
            self.wfile.flush()

        delivered = 0
        kind, payload = first_kind, first_payload
        try:
            while True:
                if kind == "tokens":
                    emit({"tokens": payload})
                    delivered += len(payload)
                    self.server_ref.add_delivered(len(payload))
                elif kind == "done":
                    emit({"finished": True, "tokens": list(payload)})
                    # chunks already emitted; only the remainder is new bytes
                    self.server_ref.add_delivered(max(len(payload) - delivered, 0))
                    return
                else:
                    emit({"error": payload})
                    return
                kind, payload = out.get()
        except OSError:
            out.cancel()  # dropped client: free the slot mid-decode


def _register_with_am(url: str) -> None:
    """Inside a tony container, publish the endpoint through the AM
    (SURVEY.md §3.4 register_task_url path). No-op standalone."""
    host = os.environ.get(constants.ENV_AM_HOST)
    if not host:
        return
    from tony_tpu.cluster.rpc import RpcClient, RpcError

    try:
        cli = RpcClient(
            host,
            int(os.environ[constants.ENV_AM_PORT]),
            secret=os.environ.get(constants.ENV_AM_SECRET, ""),
        )
        cli.call(
            "register_task_url",
            job_name=os.environ.get(constants.ENV_JOB_NAME, "serve"),
            index=int(os.environ.get(constants.ENV_TASK_INDEX, "0")),
            url=url,
            attempt=int(os.environ.get("TONY_RESTART_ATTEMPT", "0")),
        )
        cli.close()
    except (RpcError, OSError, ValueError):
        pass  # AM unreachable: serving still works, just unadvertised


def _metrics_pump(srv: EngineServer, stop: threading.Event, interval_s: float = 2.0) -> None:
    """Drop engine stats into ENV_TRAIN_METRICS_FILE (atomic rename) — the
    executor's metrics loop ships them to the AM, so the portal charts
    serving throughput with the machinery training already uses. The obs
    metrics-registry snapshot (queue-depth gauge, TTFT / per-token-latency
    histograms, delivered-tokens counter) drops next to it at
    ``<train-metrics-file>.obs`` — the same contract as the training child's
    loop.py — so serving instruments reach the executor's push_metrics
    piggyback and the portal's /metrics."""
    path = os.environ.get(constants.ENV_TRAIN_METRICS_FILE)
    if not path:
        return
    step = 0
    last_tokens = 0
    last_t = time.time()
    while not stop.wait(interval_s):
        step += 1
        now, toks = time.time(), srv.tokens_out
        rate = (toks - last_tokens) / max(now - last_t, 1e-9)
        last_tokens, last_t = toks, now
        st = srv.stats()
        line = {
            "step": step,
            "tokens_per_s": round(rate, 2),
            "slots_active": st["slots_active"],
            "queue_depth": st["queue_depth"],
            "requests_done": st["requests_done"],
        }
        try:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(line, f)
            os.replace(tmp, path)
        except OSError:
            pass
        snap = [m for m in obs_metrics.REGISTRY.snapshot() if m["samples"]]
        if snap:
            try:
                tmp = path + ".obs.tmp"
                with open(tmp, "w") as f:
                    json.dump(snap, f)
                os.replace(tmp, path + ".obs")
            except OSError:
                pass


def _drain_watch(srv: EngineServer, stop: threading.Event,
                 budget_s: float = 10.0) -> None:
    """Replica half of the cooperative-preemption drain contract
    (docs/scheduling.md): poll ``<TONY_TRAIN_METRICS_FILE>.drain`` — the
    control file the executor's DrainCourier drops when the AM's heartbeat
    fan-out reaches this task — at the same cadence UrgentSaveSignal uses.

    On a notice: stop admitting (``/stats`` flips ``draining`` so the fleet
    HealthMonitor sheds this replica and the SessionTable re-pins its
    sessions), finish in-flight streams (``EngineServer.stop``), then ack
    via :func:`_ack_drain` so the courier reports ``report_drain_saved``
    and the AM can yield without burning its margin. Like the training
    loop after UrgentSaveSignal, the process then PARKS — yielding is the
    AM's move; its SIGTERM finds an already-drained server and the exit is
    immediate and clean, well inside the deadline."""
    from tony_tpu.obs import introspect

    path = os.environ.get(constants.ENV_TRAIN_METRICS_FILE)
    if not path:
        return
    try:
        poll_ms = int(os.environ.get(constants.ENV_PROFILE_POLL_MS, "500") or 500)
    except ValueError:
        poll_ms = 500
    interval_s = max(poll_ms, 50) / 1000.0
    acked: set[str] = set()
    while not stop.wait(interval_s):
        ctl = introspect.read_json(path + introspect.DRAIN_CONTROL_SUFFIX)
        req_id = str((ctl or {}).get("req_id") or "")
        if not req_id or req_id in acked:
            continue
        if not acked:
            obs_logging.warning(
                f"[tony-serve] drain notice {req_id} (cooperative preemption) "
                "— refusing new admissions, finishing in-flight streams")
            if not srv.stop(timeout_s=budget_s):
                obs_logging.warning(
                    f"[tony-serve] drain {req_id} timed out with "
                    f"{len(srv._streams)} request(s) in flight — truncating")
        # later requests against an already-drained server (a gang-wide
        # preemption following a scale-down drain) ack instantly — stop()
        # is idempotent and the AM must not burn its margin waiting
        _ack_drain(req_id, step=srv.requests_done)
        acked.add(req_id)
        obs_logging.info(
            f"[tony-serve] drain {req_id} acknowledged "
            f"({srv.requests_done} request(s) completed) — parked, "
            "awaiting the AM's yield")


def _ack_drain(req_id: str, step: int) -> None:
    """Publish the drain done-file (atomic) the courier reports back. For a
    serving replica the 'saved step' is the completed-request count — there
    is no checkpoint to land, the state that matters (in-flight streams) is
    already drained by the time this is called."""
    from tony_tpu.obs import introspect

    path = os.environ.get(constants.ENV_TRAIN_METRICS_FILE)
    if not path:
        return
    try:
        introspect.write_json_atomic(
            path + introspect.DRAIN_DONE_SUFFIX,
            {"req_id": req_id, "step": int(step)})
    except OSError:
        pass  # best-effort: the AM's yield margin covers a lost ack


def _resolve_kv(args) -> str:
    """Resolve ``--kv`` when unset. Defaults to paged (shared-prefix wins,
    3x slot capacity at equal HBM, decode at parity — BASELINE.md r5) but
    only where paged can actually run, which the CLI cannot see and this
    process can: dense under TP (per-device page indirection), on CPU
    backends without Pallas interpret mode (the paged kernel has no XLA
    fallback), and when --max_len doesn't fit the page geometry (dense
    accepts any multiple of 128; a defaulted paged would turn that into a
    startup error). An EXPLICIT --kv paged keeps the hard errors."""
    if args.kv is not None:
        return args.kv
    if getattr(args, "tp", 1) > 1:
        return "dense"
    backend = jax.default_backend()
    if backend == "cpu" and os.environ.get("TONY_PALLAS_INTERPRET", "") == "1":
        pass  # interpret harness runs the Pallas paged kernel fine
    elif backend not in ("tpu", "axon"):
        return "dense"  # gpu/rocm/cpu: the paged decode kernel is TPU-only
    if args.page_len <= 0 or args.max_len % args.page_len:
        obs_logging.warning(
            f"[tony-serve] kv defaulting to dense: max_len {args.max_len} "
            f"is not a positive multiple of page_len {args.page_len} "
            f"(pass --kv paged --page_len <divisor> for paged)")
        return "dense"
    return "paged"


def build_engine(args) -> ContinuousBatcher:
    args.kv = _resolve_kv(args)
    cfg = PRESETS[args.preset]
    if args.hf:
        from tony_tpu.models.convert import from_hf

        params, cfg = from_hf(args.hf)
    else:
        params = init(jax.random.PRNGKey(args.seed), cfg)
    if args.int8:
        from tony_tpu.ops.quant import quantize_tree

        params, _, _ = quantize_tree(params)
    mesh = None
    if getattr(args, "tp", 1) > 1:
        from tony_tpu.parallel import MeshSpec

        # model-axis TP decode over the FIRST tp visible devices: the host
        # may expose more chips than the mesh uses (MeshSpec.build requires
        # an exact count, so hand it the slice explicitly)
        if len(jax.devices()) < args.tp:
            raise ValueError(
                f"--tp {args.tp} needs {args.tp} devices but only "
                f"{len(jax.devices())} are visible"
            )
        mesh = MeshSpec(model=args.tp).build(devices=jax.devices()[:args.tp])
    return ContinuousBatcher(
        params, cfg,
        num_slots=args.slots, max_len=args.max_len, eos_id=args.eos_id,
        temperature=args.temperature, top_k=args.top_k,
        decode_chunk=args.decode_chunk, attn=args.attn,
        prefill_chunk=args.prefill_chunk,
        kv=args.kv, page_len=args.page_len,
        num_pages=args.num_pages if args.num_pages > 0 else None,
        mesh=mesh,
    )


def main(argv: list[str] | None = None) -> int:
    # under a tony container the executor exports the structured-logging
    # contract; outside it the helpers echo to the console only
    obs_logging.init_from_env(role="serve")
    p = argparse.ArgumentParser(
        prog="tony-serve", description="continuous-batching HTTP inference server"
    )
    p.add_argument("--preset", default="tiny", choices=sorted(PRESETS),
                   help="model preset (random init unless --hf)")
    p.add_argument("--hf", default="", help="HuggingFace checkpoint dir to load")
    p.add_argument("--tokenizer", default="", help="tokenizer dir for text prompts")
    p.add_argument("--int8", action="store_true", help="int8 weight-only quantization")
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--max-len", type=int, default=512)
    p.add_argument("--decode-chunk", type=int, default=8)
    p.add_argument("--prefill-chunk", type=int, default=0)
    p.add_argument("--attn", default="auto", choices=["auto", "ragged", "bucketed"])
    p.add_argument("--kv", default=None, choices=["dense", "paged"],
                   help="paged: block-paged KV pool + shared-prefix reuse. "
                        "Default: paged where it can run (TPU, tp=1, "
                        "page-aligned max_len), else dense — see _resolve_kv")
    p.add_argument("--page-len", type=int, default=256)
    p.add_argument("--num-pages", type=int, default=0,
                   help="page pool size (0 = dense-equivalent: slots x max_len)")
    p.add_argument("--tp", type=int, default=1,
                   help="model-axis tensor parallelism for the decode step "
                        "(shards projections + KV heads over the mesh; dense kv only)")
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--top-k", type=int, default=0)
    p.add_argument("--eos-id", type=int, default=-1)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--host", default="",
                   help="bind AND advertise this host; default: bind all "
                        "interfaces, advertise the container's reachable "
                        "address (loopback deployments stay on loopback)")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--url-file", default="", help="write the bound URL here once serving")
    p.add_argument("--admission-queue", type=int, default=256,
                   help="bounded admission inbox; a full inbox returns 429")
    p.add_argument("--request-timeout-s", type=float, default=0.0,
                   help="default per-request deadline (0 = none); requests "
                        "may override via the timeout_s body field")
    p.add_argument("--role", default="serve", choices=["serve", "prefill"],
                   help="disagg tier this replica serves in: 'prefill' "
                        "replicas take /v1/prefill legs and ship KV pages; "
                        "'serve' replicas decode (and adopt shipped pages). "
                        "Both answer the full API — the role is advisory "
                        "(stats/logs), routing is the router's job")
    p.add_argument("--slo-ttft-ms", type=float,
                   default=float(os.environ.get(constants.ENV_SLO_TTFT_MS, "0") or 0),
                   help="align a TTFT histogram bucket edge to this SLO "
                        "threshold (exact good/bad counts; default from "
                        "TONY_SLO_TTFT_MS, 0 = off)")
    args = p.parse_args(argv)

    if os.environ.get(constants.ENV_METRICS_ENABLED) == "0":
        obs_metrics.set_enabled(False)  # job opted out (tony.metrics.enabled)
    if args.slo_ttft_ms > 0:
        _TTFT.ensure_bucket(args.slo_ttft_ms / 1000.0)
    # per-request span chain sink (no-op unless the executor exported the
    # tracing contract — the training child's init_from_env, reused)
    obs_trace.init_from_env()
    done = threading.Event()
    srv = EngineServer(
        build_engine(args), on_fatal=done.set,
        max_queue=args.admission_queue, request_timeout_s=args.request_timeout_s,
        role=args.role,
    ).start()
    tokenizer = None
    if args.tokenizer:
        from transformers import AutoTokenizer

        tokenizer = AutoTokenizer.from_pretrained(args.tokenizer)
    handler = type("Handler", (_Handler,), {"server_ref": srv, "tokenizer": tokenizer})
    if args.host:
        bind_host, adv_host = args.host, args.host
    else:
        # same reachability rule as the executor's URL registration: a
        # remote pool needs a routable address, a loopback deployment must
        # NOT advertise a hostname other containers can't resolve
        from tony_tpu.cluster.executor import _own_host

        bind_host = "0.0.0.0"
        adv_host = _own_host(os.environ.get(constants.ENV_AM_HOST, "127.0.0.1"))
    httpd = ThreadingHTTPServer((bind_host, args.port), handler)
    url = f"http://{adv_host}:{httpd.server_address[1]}"
    if args.url_file:
        tmp = args.url_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(url)
        os.replace(tmp, args.url_file)
    _register_with_am(url)
    stop_metrics = threading.Event()
    threading.Thread(
        target=_metrics_pump, args=(srv, stop_metrics), daemon=True
    ).start()

    def _drain(*_):
        done.set()

    signal.signal(signal.SIGTERM, _drain)
    signal.signal(signal.SIGINT, _drain)
    # drain budget for SIGTERM and preemption notices alike: the container's
    # SIGTERM→SIGKILL window (tony.task.kill-grace-ms) minus teardown margin
    grace_ms = float(os.environ.get(constants.ENV_KILL_GRACE_MS, "0") or 0)
    budget_s = max(grace_ms / 1000 - 1.0, 2.0) if grace_ms else 10.0
    # cooperative-preemption watcher: DrainCourier notice → drain, ack, park
    stop_drain_watch = threading.Event()
    threading.Thread(
        target=_drain_watch, args=(srv, stop_drain_watch, budget_s), daemon=True
    ).start()
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    obs_logging.info(f"[tony-serve] {url} role={args.role} preset={args.preset} "
                     f"slots={args.slots} max_len={args.max_len}")
    # poll rather than block forever: a process-directed SIGTERM may be
    # delivered to a busy worker thread, in which case CPython only runs the
    # Python-level handler once the MAIN thread executes bytecode again — a
    # main thread parked in an untimed Event.wait() never does, and the
    # signal (and the whole drain) would be swallowed. Waking twice a second
    # bounds drain-start latency without relying on who the kernel picked.
    while not done.wait(0.5):
        pass
    if srv.error is not None:
        obs_logging.error(f"[tony-serve] engine failed: {srv.error}")
        httpd.shutdown()
        return 1
    # graceful drain: refuse new work, finish in-flight, then exit 0.
    obs_logging.info(f"[tony-serve] draining (budget {budget_s:.0f}s)")
    if not srv.stop(timeout_s=budget_s):
        obs_logging.warning(f"[tony-serve] drain timed out with {len(srv._streams)} "
                            f"request(s) in flight — truncating")
    stop_drain_watch.set()
    stop_metrics.set()
    httpd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
