"""HTTP front end for the continuous-batching engine: the ``serve`` jobtype.

The reference runs training jobs and interactive notebooks under the AM
(SURVEY.md §3.4: the notebook jobtype registers its URL so the submitter can
proxy it); serving is new TPU-era capability built the same way — a
long-running, AM-supervised task that:

- boots a ``ContinuousBatcher`` (models/serving.py) over a model preset,
  HF checkpoint, or random-init weights (bench/test mode), optionally int8;
- serves a streaming completions API (stdlib ThreadingHTTPServer — one
  user-facing control path, no framework dependency):
    POST /v1/completions   {"prompt_tokens": [...], "max_tokens": N,
                            "stream": true|false, "temperature": ..,
                            "top_k": ..}  → JSON or SSE token stream
    GET  /healthz           liveness
    GET  /stats             engine counters (slots, queue depth, tok/s)
- when launched inside a tony container (TONY_AM_* env present), registers
  its URL over the AM RPC (``register_task_url`` — the §3.4 path) and drops
  engine throughput into ENV_TRAIN_METRICS_FILE so the executor's existing
  metrics loop feeds the portal;
- drains on SIGTERM: stops admitting, finishes the in-flight decode chunk,
  answers in-flight streams, exits 0.

Threading model: HTTP handler threads only ever touch thread-safe queues;
ONE engine thread owns the batcher (submit → step → drain_stream), so the
engine itself needs no locks — the same host/device split the engine's
docstring promises stays intact.
"""

from __future__ import annotations

import argparse
import json
import os
import queue
import signal
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

import jax

from tony_tpu import constants
from tony_tpu.models.llama import PRESETS, init
from tony_tpu.models.serving import ContinuousBatcher


class EngineServer:
    """Thread-safe facade over one ContinuousBatcher.

    HTTP threads call ``submit()`` (enqueue + wait on a per-request queue);
    the engine thread drains the inbox, steps the batcher, and fans tokens
    out. ``stop()`` initiates the drain."""

    def __init__(self, engine: ContinuousBatcher, on_fatal=None):
        self.engine = engine
        self._inbox: "queue.Queue[tuple[list[int], int, queue.Queue]]" = queue.Queue()
        self._streams: dict[int, queue.Queue] = {}
        self._draining = threading.Event()
        self._stopped = threading.Event()
        # serializes the draining-check+enqueue in submit() against the
        # loop's final refuse-sweep: without it a request slipping between
        # the sweep and _stopped would sit in an inbox nobody reads
        self._admit_lock = threading.Lock()
        self._thread = threading.Thread(target=self._loop, name="engine", daemon=True)
        self.error: BaseException | None = None  # fatal engine failure, if any
        self._on_fatal = on_fatal
        # engine counters (read by /stats without locking: ints are atomic)
        self.started_s = time.time()
        self.tokens_out = 0
        self.requests_done = 0

    def start(self) -> "EngineServer":
        self._thread.start()
        return self

    def submit(
        self, prompt_tokens: list[int], max_tokens: int,
        sampling: dict | None = None,
    ) -> queue.Queue:
        """Enqueue a request; returns the queue its events arrive on:
        ("tokens", [..]) zero or more times, then ("done", all_tokens) —
        or ("error", message). ``sampling``: per-request temperature /
        top_k / top_p overrides."""
        out: queue.Queue = queue.Queue()
        with self._admit_lock:
            if self._draining.is_set() or self.error is not None:
                out.put(("error", "server is draining" if self.error is None
                         else f"engine failed: {self.error}"))
                return out
            self._inbox.put((prompt_tokens, max_tokens, sampling or {}, out))
        return out

    def stats(self) -> dict[str, Any]:
        eng = self.engine
        up = max(time.time() - self.started_s, 1e-9)
        return {
            "slots_total": eng.S,
            "slots_active": len(eng.running),
            "queue_depth": len(eng.pending) + len(eng._staged) + self._inbox.qsize(),
            "requests_done": self.requests_done,
            "tokens_out": self.tokens_out,
            "tokens_per_s": round(self.tokens_out / up, 2),
            "uptime_s": round(up, 1),
            "draining": self._draining.is_set(),
            "healthy": self.error is None,
            **(
                {
                    "pages_live": eng.allocator.live_pages(),
                    "pages_total": eng.num_pages - 1,
                    "prefix_hit_tokens": eng.prefix_hit_tokens,
                }
                if getattr(eng, "kv", "dense") == "paged"
                else {}
            ),
        }

    def stop(self, timeout_s: float = 10.0) -> bool:
        """Drain: no new admissions; in-flight requests finish. Returns True
        if the drain completed inside ``timeout_s`` (False → truncated)."""
        self._draining.set()
        return self._stopped.wait(timeout_s)

    def _loop(self) -> None:
        try:
            self._loop_inner()
        except BaseException as e:  # noqa: BLE001 — a dead silent engine thread
            # is the worst failure mode: every in-flight stream would block
            # forever while /healthz keeps answering ok. Record, error out
            # every stream, and tell the process (the AM supervises restarts).
            import traceback

            self.error = e
            traceback.print_exc()
            for out in self._streams.values():
                out.put(("error", f"engine failed: {e}"))
            self._streams.clear()
            if self._on_fatal is not None:
                self._on_fatal()
        finally:
            # refuse anything still queued (or enqueued mid-teardown)
            with self._admit_lock:
                self._draining.set()
                while True:
                    try:
                        self._inbox.get_nowait()[-1].put(("error", "server is draining"))
                    except queue.Empty:
                        break
                self._stopped.set()

    def _loop_inner(self) -> None:
        eng = self.engine
        carry = None  # item pulled by the idle wait — admitted FIRST (FIFO)
        while True:
            while True:
                if carry is not None:
                    prompt, max_tokens, sampling, out = carry
                    carry = None
                else:
                    try:
                        prompt, max_tokens, sampling, out = self._inbox.get_nowait()
                    except queue.Empty:
                        break
                try:
                    rid = eng.submit(prompt, max_tokens, **sampling)
                except (ValueError, TypeError) as e:
                    out.put(("error", str(e)))
                    continue
                self._streams[rid] = out
            had_work = eng.step()
            for rid, (toks, done) in eng.drain_stream().items():
                out = self._streams.get(rid)
                final = eng.done.pop(rid, None) if done else None
                if out is None:
                    continue
                self.tokens_out += len(toks)
                if done:
                    self.requests_done += 1
                    out.put(("done", final if final is not None else toks))
                    del self._streams[rid]
                else:
                    out.put(("tokens", toks))
            if not had_work:
                if self._draining.is_set():
                    return
                # idle: block until the next request (or drain) arrives; the
                # pulled item is carried to the admission pass directly —
                # re-queuing it would reorder it behind later arrivals
                try:
                    carry = self._inbox.get(timeout=0.2)
                except queue.Empty:
                    pass


def _json_body(handler: BaseHTTPRequestHandler) -> dict:
    n = int(handler.headers.get("Content-Length") or 0)
    return json.loads(handler.rfile.read(n) or b"{}")


class _Handler(BaseHTTPRequestHandler):
    server_ref: EngineServer = None  # set by serve()
    tokenizer = None

    def log_message(self, *a) -> None:  # quiet
        pass

    def _reply(self, code: int, obj: Any) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802
        if self.path == "/healthz":
            err = self.server_ref.error
            if err is None:
                self._reply(200, {"ok": True})
            else:
                self._reply(503, {"ok": False, "error": str(err)})
        elif self.path == "/stats":
            self._reply(200, self.server_ref.stats())
        else:
            self._reply(404, {"error": "not found"})

    def do_POST(self) -> None:  # noqa: N802
        if self.path != "/v1/completions":
            self._reply(404, {"error": "not found"})
            return
        try:
            req = _json_body(self)
            if not isinstance(req, dict):
                raise ValueError("request body must be a JSON object")
            prompt = req.get("prompt_tokens")
            if prompt is None and "prompt" in req:
                if self.tokenizer is None:
                    raise ValueError("text prompts need --tokenizer; send prompt_tokens")
                prompt = self.tokenizer.encode(req["prompt"])
            if not prompt:
                raise ValueError("empty prompt")
            max_tokens = int(req.get("max_tokens", 16))
            stream = bool(req.get("stream", False))
            sampling = {
                k: (float(req[k]) if k != "top_k" else int(req[k]))
                for k in ("temperature", "top_k", "top_p")
                if req.get(k) is not None
            }
            prompt = [int(t) for t in prompt]
        except (TypeError, ValueError, json.JSONDecodeError) as e:
            self._reply(400, {"error": str(e)})
            return
        out = self.server_ref.submit(prompt, max_tokens, sampling)
        if stream:
            self._stream_response(out)
        else:
            self._block_response(out)

    def _block_response(self, out: "queue.Queue") -> None:
        toks: list[int] = []
        while True:
            kind, payload = out.get()
            if kind == "error":
                self._reply(503 if "draining" in payload else 400, {"error": payload})
                return
            if kind == "tokens":
                toks.extend(payload)
            else:  # done → payload is the authoritative full list
                self._reply(200, {"tokens": list(payload), "finished": True})
                return

    def _stream_response(self, out: "queue.Queue") -> None:
        """SSE: one ``data: {"tokens": [...]}`` event per decode chunk, then
        ``data: {"finished": true, ...}``."""
        first_kind, first_payload = out.get()
        if first_kind == "error":
            self._reply(503 if "draining" in first_payload else 400, {"error": first_payload})
            return
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.end_headers()

        def emit(obj: Any) -> None:
            self.wfile.write(b"data: " + json.dumps(obj).encode() + b"\n\n")
            self.wfile.flush()

        kind, payload = first_kind, first_payload
        while True:
            if kind == "tokens":
                emit({"tokens": payload})
            elif kind == "done":
                emit({"finished": True, "tokens": list(payload)})
                return
            else:
                emit({"error": payload})
                return
            kind, payload = out.get()


def _register_with_am(url: str) -> None:
    """Inside a tony container, publish the endpoint through the AM
    (SURVEY.md §3.4 register_task_url path). No-op standalone."""
    host = os.environ.get(constants.ENV_AM_HOST)
    if not host:
        return
    from tony_tpu.cluster.rpc import RpcClient, RpcError

    try:
        cli = RpcClient(
            host,
            int(os.environ[constants.ENV_AM_PORT]),
            secret=os.environ.get(constants.ENV_AM_SECRET, ""),
        )
        cli.call(
            "register_task_url",
            job_name=os.environ.get(constants.ENV_JOB_NAME, "serve"),
            index=int(os.environ.get(constants.ENV_TASK_INDEX, "0")),
            url=url,
            attempt=int(os.environ.get("TONY_RESTART_ATTEMPT", "0")),
        )
        cli.close()
    except (RpcError, OSError, ValueError):
        pass  # AM unreachable: serving still works, just unadvertised


def _metrics_pump(srv: EngineServer, stop: threading.Event, interval_s: float = 2.0) -> None:
    """Drop engine stats into ENV_TRAIN_METRICS_FILE (atomic rename) — the
    executor's metrics loop ships them to the AM, so the portal charts
    serving throughput with the machinery training already uses."""
    path = os.environ.get(constants.ENV_TRAIN_METRICS_FILE)
    if not path:
        return
    step = 0
    last_tokens = 0
    last_t = time.time()
    while not stop.wait(interval_s):
        step += 1
        now, toks = time.time(), srv.tokens_out
        rate = (toks - last_tokens) / max(now - last_t, 1e-9)
        last_tokens, last_t = toks, now
        st = srv.stats()
        line = {
            "step": step,
            "tokens_per_s": round(rate, 2),
            "slots_active": st["slots_active"],
            "queue_depth": st["queue_depth"],
            "requests_done": st["requests_done"],
        }
        try:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(line, f)
            os.replace(tmp, path)
        except OSError:
            pass


def build_engine(args) -> ContinuousBatcher:
    cfg = PRESETS[args.preset]
    if args.hf:
        from tony_tpu.models.convert import from_hf

        params, cfg = from_hf(args.hf)
    else:
        params = init(jax.random.PRNGKey(args.seed), cfg)
    if args.int8:
        from tony_tpu.ops.quant import quantize_tree

        params, _, _ = quantize_tree(params)
    mesh = None
    if getattr(args, "tp", 1) > 1:
        from tony_tpu.parallel import MeshSpec

        # model-axis TP decode over the FIRST tp visible devices: the host
        # may expose more chips than the mesh uses (MeshSpec.build requires
        # an exact count, so hand it the slice explicitly)
        if len(jax.devices()) < args.tp:
            raise ValueError(
                f"--tp {args.tp} needs {args.tp} devices but only "
                f"{len(jax.devices())} are visible"
            )
        mesh = MeshSpec(model=args.tp).build(devices=jax.devices()[:args.tp])
    return ContinuousBatcher(
        params, cfg,
        num_slots=args.slots, max_len=args.max_len, eos_id=args.eos_id,
        temperature=args.temperature, top_k=args.top_k,
        decode_chunk=args.decode_chunk, attn=args.attn,
        prefill_chunk=args.prefill_chunk,
        kv=args.kv, page_len=args.page_len,
        num_pages=args.num_pages if args.num_pages > 0 else None,
        mesh=mesh,
    )


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="tony-serve", description="continuous-batching HTTP inference server"
    )
    p.add_argument("--preset", default="tiny", choices=sorted(PRESETS),
                   help="model preset (random init unless --hf)")
    p.add_argument("--hf", default="", help="HuggingFace checkpoint dir to load")
    p.add_argument("--tokenizer", default="", help="tokenizer dir for text prompts")
    p.add_argument("--int8", action="store_true", help="int8 weight-only quantization")
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--max-len", type=int, default=512)
    p.add_argument("--decode-chunk", type=int, default=8)
    p.add_argument("--prefill-chunk", type=int, default=0)
    p.add_argument("--attn", default="auto", choices=["auto", "ragged", "bucketed"])
    p.add_argument("--kv", default="dense", choices=["dense", "paged"],
                   help="paged: block-paged KV pool + shared-prefix reuse")
    p.add_argument("--page-len", type=int, default=256)
    p.add_argument("--num-pages", type=int, default=0,
                   help="page pool size (0 = dense-equivalent: slots x max_len)")
    p.add_argument("--tp", type=int, default=1,
                   help="model-axis tensor parallelism for the decode step "
                        "(shards projections + KV heads over the mesh; dense kv only)")
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--top-k", type=int, default=0)
    p.add_argument("--eos-id", type=int, default=-1)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--host", default="",
                   help="bind AND advertise this host; default: bind all "
                        "interfaces, advertise the container's reachable "
                        "address (loopback deployments stay on loopback)")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--url-file", default="", help="write the bound URL here once serving")
    args = p.parse_args(argv)

    done = threading.Event()
    srv = EngineServer(build_engine(args), on_fatal=done.set).start()
    tokenizer = None
    if args.tokenizer:
        from transformers import AutoTokenizer

        tokenizer = AutoTokenizer.from_pretrained(args.tokenizer)
    handler = type("Handler", (_Handler,), {"server_ref": srv, "tokenizer": tokenizer})
    if args.host:
        bind_host, adv_host = args.host, args.host
    else:
        # same reachability rule as the executor's URL registration: a
        # remote pool needs a routable address, a loopback deployment must
        # NOT advertise a hostname other containers can't resolve
        from tony_tpu.cluster.executor import _own_host

        bind_host = "0.0.0.0"
        adv_host = _own_host(os.environ.get(constants.ENV_AM_HOST, "127.0.0.1"))
    httpd = ThreadingHTTPServer((bind_host, args.port), handler)
    url = f"http://{adv_host}:{httpd.server_address[1]}"
    if args.url_file:
        tmp = args.url_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(url)
        os.replace(tmp, args.url_file)
    _register_with_am(url)
    stop_metrics = threading.Event()
    threading.Thread(
        target=_metrics_pump, args=(srv, stop_metrics), daemon=True
    ).start()

    def _drain(*_):
        done.set()

    signal.signal(signal.SIGTERM, _drain)
    signal.signal(signal.SIGINT, _drain)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    print(f"[tony-serve] {url} preset={args.preset} slots={args.slots} "
          f"max_len={args.max_len}", flush=True)
    done.wait()
    if srv.error is not None:
        print(f"[tony-serve] engine failed: {srv.error}", file=sys.stderr, flush=True)
        httpd.shutdown()
        return 1
    # graceful drain: refuse new work, finish in-flight, then exit 0. The
    # budget is the container's SIGTERM→SIGKILL window
    # (tony.task.kill-grace-ms) minus a margin for teardown itself.
    grace_ms = float(os.environ.get(constants.ENV_KILL_GRACE_MS, "0") or 0)
    budget_s = max(grace_ms / 1000 - 1.0, 2.0) if grace_ms else 10.0
    print(f"[tony-serve] draining (budget {budget_s:.0f}s)", flush=True)
    if not srv.stop(timeout_s=budget_s):
        print(f"[tony-serve] drain timed out with {len(srv._streams)} "
              f"request(s) in flight — truncating", file=sys.stderr, flush=True)
    stop_metrics.set()
    httpd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
