"""Model families (BASELINE.json configs #1-#5), pure-functional JAX."""
