"""Llama-family decoder (the flagship model; BASELINE.json config #4).

Pure-functional JAX: params are a plain pytree with **stacked layers**
(leading dim L on every block param) so the forward pass is a single
``lax.scan`` — one compiled block regardless of depth — and pipeline
parallelism can split the same stacked dim over the ``stage`` axis.

Parallelism (SURVEY.md §2.5 rebuild plan):
- FSDP: weights sharded over ``fsdp`` (all-gather on use via XLA propagation)
- TP: Megatron-style — qkv/gate/up column-parallel over ``model``, wo/down
  row-parallel; vocab-parallel embedding + lm head
- CP: sequence dim over ``context`` with ring attention (parallel/context.py)
- bf16 params/activations, f32 norm+softmax accumulation, optional remat
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tony_tpu.compat import shard_map
from tony_tpu.ops import attention as attn_ops
from tony_tpu.ops import layers as L
from tony_tpu.parallel.context import ring_attention
from tony_tpu.parallel.sharding import ShardingRules, constrain

BATCH_AXES = ("data", "fsdp")


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128_256
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14_336
    max_seq: int = 8192
    rope_theta: float = 500_000.0
    norm_eps: float = 1e-5
    dtype: str = "bfloat16"
    remat: bool = True
    remat_policy: str = "full"  # full | dots (save matmul outputs, recompute the rest)
    attn_impl: str = "auto"   # auto | flash | reference
    cp_impl: str = "xla"      # context parallel: xla (ppermute ring) | pallas (remote-DMA ring) | ulysses (all-to-all)
    ce_chunk: int = 512       # fused lm-head+CE chunk length; 0 = materialize logits
    sliding_window: int = 0   # >0: Mistral/Mixtral-style sliding-window attention
    rope_scaling: tuple = ()  # () | ("linear", f) | ("llama3", f, lo, hi, orig) — see ops/layers.rope_frequencies

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)

    def num_params(self) -> int:
        D, F, V, Dh = self.d_model, self.d_ff, self.vocab_size, self.head_dim
        per_layer = (
            D * self.n_heads * Dh            # wq
            + 2 * D * self.n_kv_heads * Dh   # wk, wv
            + self.n_heads * Dh * D          # wo
            + 3 * D * F                      # gate, up, down
            + 2 * D                          # norms
        )
        return V * D + self.n_layers * per_layer + D + D * V

    def flops_per_token(self) -> int:
        """Training FLOPs/token — the one shared formula (train/metrics.py):
        6N + causal-attention term 12·L·D·T/2."""
        from tony_tpu.train.metrics import transformer_flops_per_token

        return transformer_flops_per_token(
            self.num_params(), self.n_layers, self.d_model, self.max_seq, training=True
        )


# -- presets (BASELINE.json configs) ----------------------------------------
LLAMA3_8B = LlamaConfig()
LLAMA_1B = LlamaConfig(
    vocab_size=32_000, d_model=2048, n_layers=16, n_heads=16, n_kv_heads=8,
    d_ff=5504, max_seq=2048,
)
LLAMA_TINY = LlamaConfig(
    vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_seq=128, remat=False, attn_impl="reference",
)

PRESETS = {"llama3-8b": LLAMA3_8B, "llama-1b": LLAMA_1B, "tiny": LLAMA_TINY}


def init(key: jax.Array, cfg: LlamaConfig) -> dict:
    """Initialize the parameter pytree (truncated-normal fan-in scaling)."""
    D, F, V = cfg.d_model, cfg.d_ff, cfg.vocab_size
    Dh, H, Hkv, Lyr = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads, cfg.n_layers
    dt = cfg.jdtype
    ks = jax.random.split(key, 9)

    def norm_init(*shape):
        return jnp.ones(shape, dt)

    def dense(k, *shape, fan_in):
        return (jax.random.truncated_normal(k, -2, 2, shape, jnp.float32) * fan_in**-0.5).astype(dt)

    return {
        "embed": dense(ks[0], V, D, fan_in=1.0),
        "layers": {
            "attn_norm": norm_init(Lyr, D),
            "wq": dense(ks[1], Lyr, D, H * Dh, fan_in=D),
            "wk": dense(ks[2], Lyr, D, Hkv * Dh, fan_in=D),
            "wv": dense(ks[3], Lyr, D, Hkv * Dh, fan_in=D),
            "wo": dense(ks[4], Lyr, H * Dh, D, fan_in=H * Dh),
            "mlp_norm": norm_init(Lyr, D),
            "w_gate": dense(ks[5], Lyr, D, F, fan_in=D),
            "w_up": dense(ks[6], Lyr, D, F, fan_in=D),
            "w_down": dense(ks[7], Lyr, F, D, fan_in=F),
        },
        "final_norm": norm_init(D),
        "lm_head": dense(ks[8], D, V, fan_in=D),  # independent of embed (not tied)
    }


def sharding_rules(cfg: LlamaConfig) -> ShardingRules:
    """FSDP × TP rules (stacked leading layer dim never sharded here; the
    pipeline module re-shards it over 'stage')."""
    return ShardingRules([
        (r"embed", P("model", "fsdp")),                  # vocab-parallel
        (r"layers/(wq|wk|wv|w_gate|w_up)", P(None, "fsdp", "model")),
        (r"layers/(wo|w_down)", P(None, "model", "fsdp")),
        (r"layers/.*norm", P(None, None)),
        (r"final_norm", P(None)),
        (r"lm_head", P("fsdp", "model")),
    ])


def _attention(q, k, v, cfg: LlamaConfig, mesh, segment_ids=None) -> jax.Array:
    """Dispatch: context-parallel attention (cfg.cp_impl: XLA ring,
    Pallas remote-DMA ring, or Ulysses all-to-all) when the context axis is
    real, else fused single-device MHA.

    q: [B, H, T, Dh]; k/v: [B, Hkv, T, Dh]; segment_ids [B, T] (packing).
    """
    if cfg.cp_impl not in ("xla", "pallas", "ulysses"):
        raise ValueError(
            f"cp_impl must be 'xla', 'pallas', or 'ulysses', got {cfg.cp_impl!r}"
        )
    if mesh is not None and mesh.shape.get("context", 1) > 1:
        if cfg.cp_impl != "pallas":
            if segment_ids is not None:
                raise ValueError(
                    "sequence packing (segment_ids) composes with a context "
                    "axis only via cp_impl='pallas' (the ring kernel carries "
                    "the global segment table); xla/ulysses do not"
                )
            if cfg.sliding_window > 0:
                raise ValueError(
                    "sliding_window composes with a context axis only via "
                    "cp_impl='pallas' (in-kernel band skipping)"
                )
        if cfg.cp_impl == "pallas":
            # remote-DMA ring kernel: GQA-native (KV stays at Hkv width on
            # the wire); fully-manual shard_map because the kernel manages
            # its own collectives (and interpret-mode emulation requires it)
            from tony_tpu.ops.ring import (
                ring_attention_pallas,
                ring_attention_pallas_seg,
            )

            model_deg = mesh.shape.get("model", 1)
            batch_deg = mesh.shape.get("data", 1) * mesh.shape.get("fsdp", 1)
            if cfg.n_kv_heads % model_deg or q.shape[0] % batch_deg:
                raise ValueError(
                    "cp_impl='pallas' shards kv heads over 'model' and batch "
                    f"over data×fsdp explicitly: n_kv_heads {cfg.n_kv_heads} "
                    f"must divide by model={model_deg} and batch {q.shape[0]} "
                    f"by data×fsdp={batch_deg} (cp_impl='xla' has no such "
                    "constraint)"
                )
            qspec = P(BATCH_AXES, "model", "context", None)
            if segment_ids is not None:
                ring = shard_map(
                    partial(
                        ring_attention_pallas_seg, axis_name="context",
                        causal=True, window=cfg.sliding_window,
                    ),
                    mesh=mesh,
                    in_specs=(qspec, qspec, qspec, P(BATCH_AXES, "context")),
                    out_specs=qspec,
                    axis_names=set(mesh.axis_names),
                    check_vma=False,
                )
                return ring(q, k, v, segment_ids)
            ring = shard_map(
                partial(
                    ring_attention_pallas, axis_name="context", causal=True,
                    window=cfg.sliding_window,
                ),
                mesh=mesh,
                in_specs=(qspec, qspec, qspec),
                out_specs=qspec,
                axis_names=set(mesh.axis_names),
                check_vma=False,
            )
            return ring(q, k, v)
        n_rep = cfg.n_heads // cfg.n_kv_heads
        spec = P(None, None, "context", None)
        if cfg.cp_impl == "ulysses":
            # all-to-all seq↔head reshard: cheaper collectives than the ring
            # when n_heads >= context degree (docs/parallelism.md). KV stays
            # at Hkv width on the wire when it divides the context degree
            # (mha's GQA aliasing then applies); otherwise broadcast first.
            from tony_tpu.parallel.context import ulysses_attention

            cp = mesh.shape["context"]
            if cfg.n_heads % cp:
                raise ValueError(
                    f"cp_impl='ulysses' needs n_heads {cfg.n_heads} divisible "
                    f"by the context degree {cp} (use 'xla'/'pallas' ring)"
                )
            if cfg.n_kv_heads % cp:
                k = attn_ops.repeat_kv(k, n_rep)
                v = attn_ops.repeat_kv(v, n_rep)
            fn = partial(
                ulysses_attention, axis_name="context",
                attn_fn=partial(attn_ops.mha, causal=True, impl=cfg.attn_impl),
            )
        else:
            k = attn_ops.repeat_kv(k, n_rep)
            v = attn_ops.repeat_kv(v, n_rep)
            fn = partial(ring_attention, axis_name="context", causal=True)
        ring = shard_map(
            fn,
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            axis_names={"context"},
            check_vma=False,
        )
        return ring(q, k, v)
    return attn_ops.mha(
        q, k, v, causal=True, impl=cfg.attn_impl, segment_ids=segment_ids,
        window=cfg.sliding_window,
    )


def mask_packed_targets(tokens: jax.Array, seg: jax.Array | None):
    """Shared packed-batch target masking (llama + mixtral): next-token
    pairs must stay within one segment, and segment 0 (padding) never
    contributes loss. Returns (targets [B, T], seg_in [B, T] or None)."""
    targets = tokens[:, 1:]
    if seg is None:
        return targets, None
    ok = (seg[:, 1:] == seg[:, :-1]) & (seg[:, 1:] != 0)
    return jnp.where(ok, targets, -100), seg[:, :-1]


def embed_lookup(embed: jax.Array, tokens: jax.Array, mesh=None) -> jax.Array:
    """Embedding lookup that compiles cleanly on every mesh.

    Whenever the activation sharding spans two or more mesh axes (hybrid
    data×fsdp, or fsdp×tp×cp), XLA's gather-op sharding cannot move the
    take's output between the table's layout and the batch layout and
    falls back to "involuntary full rematerialization"
    (replicate-then-reshard) in fwd AND bwd. A one-hot dot has native
    GSPMD sharding rules — vocab contraction over the 'model' shards, D
    stays on fsdp, batch stays put — at the FLOP cost of one extra
    lm-head-sized matmul, so it's used ONLY on those multi-axis meshes; a
    single sharded axis (e.g. the pure-FSDP 8B plan) and the unsharded
    case keep the plain take, whose transition XLA handles cleanly.
    """
    if mesh is not None:
        active = sum(
            1 for a in ("data", "fsdp", "model", "context") if mesh.shape.get(a, 1) > 1
        )
        if active >= 2:
            onehot = jax.nn.one_hot(tokens, embed.shape[0], dtype=embed.dtype)
            return jnp.einsum("btv,vd->btd", onehot, embed)
    return jnp.take(embed, tokens, axis=0)


def segment_positions(segment_ids: jax.Array) -> jax.Array:
    """[B, T] per-segment positions (0-based, restarting at each segment
    boundary) for RoPE on packed batches."""
    B, T = segment_ids.shape
    idx = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
    is_start = jnp.concatenate(
        [jnp.ones((B, 1), bool), segment_ids[:, 1:] != segment_ids[:, :-1]], axis=1
    )
    start = jax.lax.cummax(jnp.where(is_start, idx, 0), axis=1)
    return idx - start


def _block(
    x: jax.Array, lp: dict, cos, sin, cfg: LlamaConfig, mesh,
    segment_ids=None, positions=None,
) -> tuple[jax.Array, None]:
    """One decoder block (pre-norm attention + SwiGLU), scan-compatible.
    Shared by the flat layer scan (hidden_states) and the pipeline stage
    body (pp_loss_fn, where mesh is None — stages run per-device)."""
    B, T = x.shape[0], x.shape[1]
    Dh, H, Hkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    act_spec = P(BATCH_AXES, "context", None)
    h = L.rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    q = jnp.einsum("btd,dh->bth", h, lp["wq"]).reshape(B, T, H, Dh).transpose(0, 2, 1, 3)
    k = jnp.einsum("btd,dh->bth", h, lp["wk"]).reshape(B, T, Hkv, Dh).transpose(0, 2, 1, 3)
    v = jnp.einsum("btd,dh->bth", h, lp["wv"]).reshape(B, T, Hkv, Dh).transpose(0, 2, 1, 3)
    q = L.apply_rope(q, cos, sin, positions=positions)
    k = L.apply_rope(k, cos, sin, positions=positions)
    o = _attention(q, k, v, cfg, mesh, segment_ids=segment_ids)
    o = o.transpose(0, 2, 1, 3).reshape(B, T, H * Dh)
    x = x + jnp.einsum("bth,hd->btd", o, lp["wo"])
    if mesh is not None:
        x = constrain(x, mesh, act_spec)
    h = L.rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
    x = x + L.swiglu(h, lp["w_gate"], lp["w_up"], lp["w_down"])
    if mesh is not None:
        x = constrain(x, mesh, act_spec)
    return x, None


def hidden_states(
    params: dict, tokens: jax.Array, cfg: LlamaConfig, mesh=None, segment_ids=None
) -> jax.Array:
    """tokens [B, T] int32 → final-norm hidden states [B, T, D].

    ``segment_ids`` [B, T] enables packed-sequence training: attention is
    confined within segments (flash-kernel-native masking) and RoPE
    positions restart at every segment boundary."""
    T = tokens.shape[1]
    cos, sin = L.rope_frequencies(cfg.head_dim, T, cfg.rope_theta, cfg.rope_scaling)
    positions = segment_positions(segment_ids) if segment_ids is not None else None

    x = embed_lookup(params["embed"], tokens, mesh)
    if mesh is not None:
        x = constrain(x, mesh, P(BATCH_AXES, "context", None))

    block_fn = attn_ops.remat_block(
        partial(_block, cos=cos, sin=sin, cfg=cfg, mesh=mesh,
                segment_ids=segment_ids, positions=positions),
        cfg.remat, cfg.remat_policy,
    )
    x, _ = jax.lax.scan(block_fn, x, params["layers"])

    return L.rms_norm(x, params["final_norm"], cfg.norm_eps)


def pp_loss_fn(
    params: dict, batch: dict, cfg: LlamaConfig, mesh, num_microbatches: int = 2
) -> tuple[jax.Array, dict]:
    """TEACHING-PATH pipeline loss (GPipe schedule + autodiff): the stacked
    layer dim splits into equal-depth stages over the mesh's ``stage`` axis
    (parallel/pipeline.spmd_pipeline); embedding and the (chunked) CE head
    run outside the pipeline, replicated over stages.

    Production training uses ``pp_value_and_grad`` (1F1B) — the train loop
    only ever routes there. This path stays as the independently-verifiable
    spec the 1F1B parity tests compare against: microbatches enter
    REPLICATED along data/fsdp (no DP speedup), the output bank broadcasts
    to every stage, and neither packing nor a context axis composes.
    """
    from tony_tpu.parallel.pipeline import spmd_pipeline, split_layers_into_stages

    S = mesh.shape.get("stage", 1)
    if S <= 1:
        return loss_fn(params, batch, cfg, mesh)
    if mesh.shape.get("context", 1) > 1:
        raise ValueError("pp_loss_fn does not compose with a context axis")
    if "segment_ids" in batch:
        raise ValueError(
            "pp_loss_fn does not support packed batches (segment_ids) yet — "
            "silently ignoring them would train across document boundaries"
        )
    tokens = batch["tokens"]
    T = tokens.shape[1] - 1
    cos, sin = L.rope_frequencies(cfg.head_dim, T, cfg.rope_theta, cfg.rope_scaling)
    x = jnp.take(params["embed"], tokens[:, :-1], axis=0)

    block_fn = attn_ops.remat_block(
        partial(_block, cos=cos, sin=sin, cfg=cfg, mesh=None),
        cfg.remat, cfg.remat_policy,
    )

    def stage_fn(stage_lp, h):
        h, _ = jax.lax.scan(block_fn, h, stage_lp)
        return h

    stages = split_layers_into_stages(params["layers"], S)
    x = spmd_pipeline(stage_fn, stages, x, mesh=mesh, num_microbatches=num_microbatches)
    x = L.rms_norm(x, params["final_norm"], cfg.norm_eps)
    # always the fused CE (ce_chunk<=0 → one full-length chunk in the
    # callee): the PP path never materializes [B, T, V] logits
    loss, n = L.chunked_cross_entropy_loss(
        x, params["lm_head"], tokens[:, 1:], chunk=cfg.ce_chunk
    )
    return loss, {"loss": loss, "tokens": n}


def pp_value_and_grad(
    params: dict, batch: dict, cfg: LlamaConfig, mesh, num_microbatches: int = 2,
    wire_dtype=jnp.bfloat16, num_chunks: int = 1,
) -> tuple[jax.Array, dict, dict]:
    """1F1B pipeline train-step core: ``(loss, metrics, grads)`` with grads
    shaped exactly like ``params``.

    The hand-scheduled backward (parallel/pipeline.spmd_pipeline_1f1b)
    interleaves each microbatch's backward with later microbatches' forwards,
    bounding live activations per stage at O(S) microbatches instead of the
    GPipe path's O(M); the CE head runs inside the last stage's tick behind
    a ``lax.cond`` (other stages pay none of its FLOPs), and the microbatch
    batch dim shards over data/fsdp. Packed batches (segment_ids) are
    supported: attention confinement, per-segment RoPE, and boundary target
    masking all apply per microbatch. Use via ``make_pp_train_step``
    (train/trainer.py).
    """
    from tony_tpu.parallel.pipeline import spmd_pipeline_1f1b, split_layers_into_stages

    S = mesh.shape.get("stage", 1)
    if S <= 1:
        loss_and_grads = jax.value_and_grad(
            lambda p: loss_fn(p, batch, cfg, mesh), has_aux=True
        )(params)
        (loss, metrics), grads = loss_and_grads
        return loss, metrics, grads
    if mesh.shape.get("context", 1) > 1:
        raise ValueError("pipeline parallelism does not compose with a context axis")
    tokens = batch["tokens"]
    T = tokens.shape[1] - 1
    cos, sin = L.rope_frequencies(cfg.head_dim, T, cfg.rope_theta, cfg.rope_scaling)

    def _mb_ctx(mb):
        seg = mb.get("segment_ids")
        seg_in = seg[:, :-1] if seg is not None else None
        positions = segment_positions(seg_in) if seg_in is not None else None
        return seg_in, positions

    def stage_fn(stage_lp, h, mb):
        seg_in, positions = _mb_ctx(mb)
        block_fn = attn_ops.remat_block(
            partial(_block, cos=cos, sin=sin, cfg=cfg, mesh=None,
                    segment_ids=seg_in, positions=positions),
            cfg.remat, cfg.remat_policy,
        )
        h, _ = jax.lax.scan(block_fn, h, stage_lp)
        return h

    def embed_fn(embed_p, mb):
        return jnp.take(embed_p, mb["tokens"][:, :-1], axis=0)

    def loss_head_fn(head_p, y, mb):
        targets, _ = mask_packed_targets(mb["tokens"], mb.get("segment_ids"))
        x = L.rms_norm(y, head_p["final_norm"], cfg.norm_eps)
        mean, n = L.chunked_cross_entropy_loss(
            x, head_p["lm_head"], targets, chunk=cfg.ce_chunk
        )
        # mean * n == the exact nll SUM even when n is the CE's >=1 clamp
        # (0/1 * 1 = 0); report the TRUE count so an all-pad microbatch
        # doesn't inflate the token total the grads divide by
        return mean * n, jnp.sum(targets != -100)

    pp_batch = {"tokens": tokens}
    if "segment_ids" in batch:
        pp_batch["segment_ids"] = batch["segment_ids"]
    head_params = {"final_norm": params["final_norm"], "lm_head": params["lm_head"]}
    if num_chunks > 1:
        from tony_tpu.parallel.pipeline import (
            spmd_pipeline_1f1b_interleaved,
            split_layers_into_chunks,
        )

        chunks = split_layers_into_chunks(params["layers"], S, num_chunks)
        nll, ntok, (dchunk, dembed, dhead) = spmd_pipeline_1f1b_interleaved(
            stage_fn, chunks, pp_batch, params["embed"], head_params,
            embed_fn, loss_head_fn,
            mesh=mesh, num_microbatches=num_microbatches, num_chunks=num_chunks,
            wire_dtype=wire_dtype, compute_dtype=cfg.jdtype,
        )
        loss = nll / jnp.maximum(ntok, 1.0)
        inv = 1.0 / jnp.maximum(ntok, 1.0)

        def unsplit(g, p):
            # [S, V, Lc, ...] grads → [L, ...] matching the stacked layout
            V = num_chunks
            r = g.reshape(S, V, -1, *p.shape[1:])
            r = r.transpose(1, 0, *range(2, r.ndim))  # [V, S, Lc, ...]
            return (r.reshape(cfg.n_layers, *p.shape[1:]) * inv).astype(p.dtype)

        d_layers = jax.tree.map(unsplit, dchunk, params["layers"])
        grads = {
            "embed": (dembed * inv).astype(params["embed"].dtype),
            "layers": d_layers,
            "final_norm": (dhead["final_norm"] * inv).astype(params["final_norm"].dtype),
            "lm_head": (dhead["lm_head"] * inv).astype(params["lm_head"].dtype),
        }
        return loss, {"loss": loss, "tokens": ntok}, grads
    stages = split_layers_into_stages(params["layers"], S)
    nll, ntok, _, (dstage, dembed, dhead) = spmd_pipeline_1f1b(
        stage_fn, stages, pp_batch, params["embed"], head_params,
        embed_fn, loss_head_fn,
        mesh=mesh, num_microbatches=num_microbatches, wire_dtype=wire_dtype,
        compute_dtype=cfg.jdtype,
    )
    loss = nll / jnp.maximum(ntok, 1.0)
    inv = 1.0 / jnp.maximum(ntok, 1.0)
    d_layers = jax.tree.map(
        lambda g, p: (g.reshape(cfg.n_layers, *g.shape[2:]) * inv).astype(p.dtype),
        dstage, params["layers"],
    )
    grads = {
        "embed": (dembed * inv).astype(params["embed"].dtype),
        "layers": d_layers,
        "final_norm": (dhead["final_norm"] * inv).astype(params["final_norm"].dtype),
        "lm_head": (dhead["lm_head"] * inv).astype(params["lm_head"].dtype),
    }
    return loss, {"loss": loss, "tokens": ntok}, grads


def forward(
    params: dict, tokens: jax.Array, cfg: LlamaConfig, mesh=None, segment_ids=None
) -> jax.Array:
    """tokens [B, T] int32 → logits [B, T, V]."""
    x = hidden_states(params, tokens, cfg, mesh, segment_ids=segment_ids)
    logits = jnp.einsum("btd,dv->btv", x, params["lm_head"])
    if mesh is not None:
        logits = constrain(logits, mesh, P(BATCH_AXES, "context", None))
    return logits


def loss_fn(params: dict, batch: dict, cfg: LlamaConfig, mesh=None) -> tuple[jax.Array, dict]:
    """batch: {"tokens": [B, T+1], optional "segment_ids": [B, T+1]} →
    next-token CE loss.

    With ``cfg.ce_chunk > 0`` the lm-head matmul and CE are fused per
    sequence chunk (ops/layers.chunked_cross_entropy_loss) so the [B, T, V]
    logits never exist — the activation that otherwise bounds batch size.

    With ``segment_ids`` (packed sequences), attention and RoPE respect
    segment boundaries and the cross-boundary targets (a segment's last
    token predicting the NEXT segment's first) are masked out of the loss.
    """
    tokens = batch["tokens"]
    targets, seg_in = mask_packed_targets(tokens, batch.get("segment_ids"))
    if cfg.ce_chunk > 0:
        x = hidden_states(params, tokens[:, :-1], cfg, mesh, segment_ids=seg_in)
        loss, n = L.chunked_cross_entropy_loss(
            x, params["lm_head"], targets, chunk=cfg.ce_chunk
        )
    else:
        logits = forward(params, tokens[:, :-1], cfg, mesh, segment_ids=seg_in)
        loss, n = L.cross_entropy_loss(logits, targets)
    return loss, {"loss": loss, "tokens": n}


def synthetic_batch(key: jax.Array, batch_size: int, seq_len: int, cfg: LlamaConfig) -> dict:
    return {
        "tokens": jax.random.randint(key, (batch_size, seq_len + 1), 0, cfg.vocab_size, jnp.int32)
    }


def config_from_dict(d: dict) -> LlamaConfig:
    if isinstance(d, str):
        return PRESETS[d]
    fields = {f.name for f in dataclasses.fields(LlamaConfig)}
    return dataclasses.replace(
        PRESETS.get(d.get("preset", ""), LlamaConfig()),
        **{k: v for k, v in d.items() if k in fields},
    )
