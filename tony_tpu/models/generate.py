"""Autoregressive generation with a KV cache for the Llama family.

The reference orchestrates training jobs only — serving/eval is new
capability (SURVEY.md §2.5 "absent" rows). TPU-first shape discipline:
the cache is a static [L, B, Hkv, max_len, Dh] ring of bf16 K/V, decode
steps are one jitted token step with `lax.scan` over positions (no Python
loop, no dynamic shapes), and attention against the cache is masked
full-length so XLA compiles one kernel for every step.

Numerical parity with training: reuses the same rms_norm/rope/swiglu ops
and the params pytree from models/llama.py — `tests/test_generate.py`
asserts greedy decode reproduces teacher-forced forward argmaxes.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from tony_tpu.models.llama import LlamaConfig
from tony_tpu.ops import layers as L
from tony_tpu.ops import quant as Q


def _mm(x, w):
    """x @ w where w may be an int8 QTensor (weight-only quantized serving:
    quant.quantize_tree(params) then pass the tree here unchanged)."""
    if isinstance(w, Q.QTensor):
        return Q.int8_matmul(x, w).astype(x.dtype)
    return jnp.einsum("...d,dh->...h", x, w)


def _embed_lookup(embed, tokens, dtype):
    if isinstance(embed, Q.QTensor):
        rows = jnp.take(embed.q, tokens, axis=0).astype(jnp.float32)
        return (rows * embed.scale).astype(dtype)
    return jnp.take(embed, tokens, axis=0)


class KVCache(NamedTuple):
    """Static-shape decode state. k/v: [L, B, Hkv, max_len, Dh]."""

    k: jax.Array
    v: jax.Array
    length: jax.Array  # [] int32 — tokens already in the cache


def init_cache(cfg: LlamaConfig, batch: int, max_len: int) -> KVCache:
    shape = (cfg.n_layers, batch, cfg.n_kv_heads, max_len, cfg.head_dim)
    return KVCache(
        k=jnp.zeros(shape, cfg.jdtype),
        v=jnp.zeros(shape, cfg.jdtype),
        length=jnp.zeros((), jnp.int32),
    )


def _cached_attention(q, ck, cv, length, n_rep, window: int = 0):
    """q: [B, H, Tq, Dh]; ck/cv: [B, Hkv, maxT, Dh]; positions < length+Tq.

    Masked full-length attention: rows attend to cache slots [0, length+row]
    (causal within the new tokens, everything before them unconditionally);
    with ``window`` > 0 the band narrows to the last ``window`` positions —
    decode then matches the training-side sliding-window semantics instead
    of silently widening beyond it.
    """
    from tony_tpu.ops.attention import repeat_kv

    B, H, Tq, Dh = q.shape
    maxT = ck.shape[2]
    ck = repeat_kv(ck, n_rep)
    cv = repeat_kv(cv, n_rep)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, ck, preferred_element_type=jnp.float32)
    s = s * (Dh ** -0.5)
    slot = jax.lax.broadcasted_iota(jnp.int32, (Tq, maxT), 1)
    row_end = length + jax.lax.broadcasted_iota(jnp.int32, (Tq, maxT), 0)
    ok = slot <= row_end
    if window > 0:
        ok = jnp.logical_and(ok, slot > row_end - window)
    s = jnp.where(ok, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(cv.dtype), cv)


def _masked_slot_attention(q1, ck, cv, lengths, n_rep, window: int = 0,
                           *, cur_k, cur_v):
    """Single-token decode attention over read-only caches (shared by the
    serving engine's bucketed path and ``generate()``'s decode steps — ONE
    implementation, so the two paths cannot diverge in attention MATH;
    note bf16 projections can still differ by 1 ulp between batch sizes
    from XLA tiling, which is why MoE greedy-parity tests run f32).

    q1 [S, H, Dh] vs per-slot caches [S, Hkv, maxT, Dh]. ``lengths`` counts
    CACHE positions only; the current token's K/V arrive separately
    (``cur_k``/``cur_v`` [S, Hkv, Dh]) and its score is appended before the
    softmax — the big cache is READ-ONLY here, so callers write it once per
    step with a tiny scatter instead of carrying a full cache copy through
    their layer scans (the r3-cont serving fix: the copy cost −32% decode
    tok/s at 64 slots). Slot s attends cache positions
    [max(0, len_s + 1 - window), len_s) plus itself (always in-window)."""
    from tony_tpu.ops.attention import repeat_kv

    S, H, Dh = q1.shape
    maxT = ck.shape[2]
    ckr = repeat_kv(ck, n_rep)
    cvr = repeat_kv(cv, n_rep)
    s = jnp.einsum("shd,shkd->shk", q1, ckr, preferred_element_type=jnp.float32)
    s = s * (Dh ** -0.5)
    idx = jax.lax.broadcasted_iota(jnp.int32, (S, 1, maxT), 2)
    hi = lengths[:, None, None]
    ok = idx < hi
    if window > 0:
        ok = jnp.logical_and(ok, idx >= hi + 1 - window)
    s = jnp.where(ok, s, -1e30)
    ckr1 = repeat_kv(cur_k[:, :, None], n_rep)[:, :, 0]          # [S, H, Dh]
    cvr1 = repeat_kv(cur_v[:, :, None], n_rep)[:, :, 0]
    s_self = jnp.einsum(
        "shd,shd->sh", q1, ckr1, preferred_element_type=jnp.float32
    )[..., None] * (Dh ** -0.5)
    p = jax.nn.softmax(jnp.concatenate([s, s_self], axis=-1), axis=-1)
    o = jnp.einsum("shk,shkd->shd", p[..., :maxT].astype(cvr.dtype), cvr)
    return o + p[..., maxT:].astype(cvr1.dtype) * cvr1


def _ffn_with_cache(h, lp, cfg: LlamaConfig):
    """Decode-side FFN: dense SwiGLU, or the MoE mixture when the layer
    params carry a router (Mixtral family).

    The MoE DECODE path (short Tq) computes ALL experts and combines with
    the top-k one-hot gates — at decode batch sizes (a handful of tokens)
    the step is weight-bandwidth-bound and B·K distinct expert picks touch
    most experts anyway, so dense-expert compute costs ~nothing extra
    while avoiding per-token weight gathers; gates renormalize over top-k
    exactly like training (parallel/expert._gating). PREFILL (long Tq)
    routes through the training dispatch instead — all-expert compute
    over a whole prompt would pay E/top_k× the FFN FLOPs and materialize
    [B, T, E, F] banks."""
    if "router" not in lp:
        g = jax.nn.silu(_mm(h, lp["w_gate"]))
        u = _mm(h, lp["w_up"])
        return _mm(g * u, lp["w_down"])
    if h.shape[1] > 16:  # prefill: routed dispatch, same math, top-k FLOPs
        from tony_tpu.parallel.expert import moe_ffn

        y, _ = moe_ffn(
            h, lp["router"], lp["we_gate"], lp["we_up"], lp["we_down"], cfg.moe, None
        )
        return y
    from tony_tpu.parallel.expert import _gating

    E = lp["router"].shape[-1]
    # ONE copy of the gating convention: the training-side _gating
    # (softmax → top-k → renormalize) drives decode too
    gate_vals, gate_idx, _, _ = _gating(h, lp["router"], cfg.moe, None)
    w = jnp.sum(jax.nn.one_hot(gate_idx, E) * gate_vals[..., None], axis=-2)  # [B,T,E]
    ge = jnp.einsum("btd,edf->btef", h, lp["we_gate"])
    ue = jnp.einsum("btd,edf->btef", h, lp["we_up"])
    ye = jnp.einsum("btef,efd->bted", jax.nn.silu(ge) * ue, lp["we_down"])
    return jnp.einsum("bted,bte->btd", ye, w.astype(ye.dtype))


def _block_with_cache(x, lp, ck, cv, length, cos, sin, cfg: LlamaConfig):
    """One decoder block over Tq new tokens at positions [length, length+Tq).

    Returns (x, new_k, new_v) where new_k/v are this step's K/V slabs
    [B, Hkv, Tq, Dh] for the caller to write into the cache.
    """
    B, Tq = x.shape[0], x.shape[1]
    Dh, H, Hkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    positions = length + jnp.arange(Tq)

    h = L.rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    q = _mm(h, lp["wq"]).reshape(B, Tq, H, Dh).transpose(0, 2, 1, 3)
    k = _mm(h, lp["wk"]).reshape(B, Tq, Hkv, Dh).transpose(0, 2, 1, 3)
    v = _mm(h, lp["wv"]).reshape(B, Tq, Hkv, Dh).transpose(0, 2, 1, 3)
    q = L.apply_rope(q, cos, sin, positions=positions)
    k = L.apply_rope(k, cos, sin, positions=positions)

    if Tq == 1:
        # decode: the cache stays read-only (same split attention math as
        # the serving engine — shared _masked_slot_attention) and the
        # caller's post-scan dynamic_update_slice is the only cache write
        o = _masked_slot_attention(
            q[:, :, 0], ck, cv, jnp.broadcast_to(length, (B,)), H // Hkv,
            window=cfg.sliding_window,
            cur_k=k[:, :, 0].astype(ck.dtype), cur_v=v[:, :, 0].astype(cv.dtype),
        )[:, :, None]
    else:
        ck = jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype), (0, 0, length, 0))
        cv = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype), (0, 0, length, 0))
        o = _cached_attention(q, ck, cv, length, H // Hkv, window=cfg.sliding_window)
    o = o.transpose(0, 2, 1, 3).reshape(B, Tq, H * Dh)
    x = x + _mm(o, lp["wo"])
    h = L.rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
    x = x + _ffn_with_cache(h, lp, cfg)
    return x, k, v


def _forward_with_cache(params, tokens, cache: KVCache, cfg: LlamaConfig):
    """tokens [B, Tq] (new tokens only) → (logits [B, Tq, V], cache')."""
    maxT = cache.k.shape[3]
    cos, sin = L.rope_frequencies(cfg.head_dim, maxT, cfg.rope_theta, cfg.rope_scaling)
    x = _embed_lookup(params["embed"], tokens, cfg.jdtype)

    def layer(x, inputs):
        lp, ck, cv = inputs
        x, new_k, new_v = _block_with_cache(x, lp, ck, cv, cache.length, cos, sin, cfg)
        return x, (new_k, new_v)

    x, (new_ks, new_vs) = jax.lax.scan(layer, x, (params["layers"], cache.k, cache.v))
    Tq = tokens.shape[1]
    k = jax.lax.dynamic_update_slice(cache.k, new_ks, (0, 0, 0, cache.length, 0))
    v = jax.lax.dynamic_update_slice(cache.v, new_vs, (0, 0, 0, cache.length, 0))
    x = L.rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = _mm(x, params["lm_head"]).astype(jnp.float32)
    return logits, KVCache(k, v, cache.length + Tq)


def prefill(params, tokens, cache: KVCache, cfg: LlamaConfig):
    """Run the prompt through the model, filling the cache.

    Returns (last-position logits [B, V], cache')."""
    logits, cache = _forward_with_cache(params, tokens, cache, cfg)
    return logits[:, -1], cache


# module-level jits: generate() is called per serving request, so the traced
# functions must be cached across calls (keys/prompt/cache are arguments,
# never closure constants — a closure would retrace every request)
_prefill_jit = jax.jit(prefill, static_argnames=("cfg",))


@functools.partial(jax.jit, static_argnames=("cfg", "temperature", "top_k"))
def _decode_all(params, cache, first, keys, cfg, temperature, top_k):
    def step(carry, k_step):
        cache, tok = carry
        logits, cache = _forward_with_cache(params, tok[:, None], cache, cfg)
        nxt = _sample(logits[:, -1], k_step, temperature, top_k)
        return (cache, nxt), nxt

    (_, _), rest = jax.lax.scan(step, (cache, first), keys)
    return rest


def _sample(logits, key, temperature: float, top_k: int):
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def sample_logits(logits, key, temperature, top_k, top_p):
    """PER-ROW sampling with temperature / top-k / top-p (nucleus), all
    DEVICE arrays [B] — one compiled variant serves every mixture of
    per-request params (the serving engine's per-slot path; the static
    ``_sample`` stays the cheap batch path when every row shares params).

    Row semantics: temperature 0 → greedy (argmax; the key is unused for
    that row); top_k 0 → no k-cut; top_p outside (0, 1) → no nucleus cut.
    One descending sort powers both cuts.
    """
    V = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    desc = jnp.sort(scaled, axis=-1)[:, ::-1]                     # [B, V]
    # top-k: threshold at the k-th largest (k<=0 → keep all)
    k_idx = jnp.clip(top_k - 1, 0, V - 1)
    kth = jnp.take_along_axis(desc, k_idx[:, None], axis=1)       # [B, 1]
    keep_k = (top_k[:, None] <= 0) | (scaled >= kth)
    # top-p: smallest prefix of the sorted probs with mass >= p; the
    # threshold is the logit of the LAST kept rank
    probs = jax.nn.softmax(desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    p = top_p[:, None]
    nucleus = (cum - probs) < p                                    # keep-while mask
    last_rank = jnp.maximum(nucleus.sum(axis=-1) - 1, 0)           # [B]
    pth = jnp.take_along_axis(desc, last_rank[:, None], axis=1)    # [B, 1]
    keep_p = (p <= 0) | (p >= 1) | (scaled >= pth)
    masked = jnp.where(keep_k & keep_p, scaled, -1e30)
    sampled = jax.random.categorical(key, masked, axis=-1).astype(jnp.int32)
    return jnp.where(temperature <= 0.0, greedy, sampled)


def generate(
    params,
    prompt: jax.Array,
    cfg: LlamaConfig,
    *,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int = 0,
    key: jax.Array | None = None,
    max_len: int | None = None,
) -> jax.Array:
    """prompt [B, Tp] int32 → generated tokens [B, max_new_tokens].

    Greedy when temperature == 0, else top-k/temperature sampling. One jit
    for prefill, one for the scanned decode loop.
    """
    B, Tp = prompt.shape
    max_len = max_len or (Tp + max_new_tokens)
    assert max_len >= Tp + max_new_tokens, "cache too small for requested tokens"
    key = key if key is not None else jax.random.PRNGKey(0)
    keys = jax.random.split(key, max_new_tokens)

    cache = init_cache(cfg, B, max_len)
    logits, cache = _prefill_jit(params, prompt, cache, cfg)
    first = _sample(logits, keys[0], temperature, top_k)

    if max_new_tokens == 1:
        return first[:, None]
    rest = _decode_all(params, cache, first, keys[1:], cfg, temperature, top_k)  # [N-1, B]
    return jnp.concatenate([first[:, None], rest.T], axis=1)
