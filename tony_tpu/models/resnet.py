"""ResNet-v1.5 image classifier (BASELINE.json config #3 — the PyTorch-DDP →
torch-xla analog workload, here pure JAX with data-parallel sharding).

Convs via lax.conv_general_dilated in NHWC (the TPU-native layout — channels
on the 128-lane minor dim feeds the MXU without relayout). BatchNorm is
functional: batch statistics computed in-step; running stats carried in a
separate ``state`` pytree updated as an aux output (no hidden mutation).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tony_tpu.parallel.sharding import ShardingRules

STAGE_BLOCKS = {18: (2, 2, 2, 2), 34: (3, 4, 6, 3), 50: (3, 4, 6, 3), 101: (3, 4, 23, 3)}
BOTTLENECK = {50: True, 101: True, 18: False, 34: False}


@dataclass(frozen=True)
class ResNetConfig:
    depth: int = 50
    num_classes: int = 1000
    width: int = 64
    image_size: int = 224
    bn_momentum: float = 0.9
    dtype: str = "bfloat16"
    # space-to-depth stem (same math, 4× MXU lane occupancy on the 3-channel
    # stem conv). Off by default: measured NEUTRAL-to-slightly-slower on the
    # axon v5e backend (371 vs 358 ms/step @ b512 — its conv emulation isn't
    # lane-bound on the stem); the standard MLPerf-TPU win may still apply
    # on other TPU generations, so the exact transform is kept selectable.
    stem_s2d: bool = False

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)

    @property
    def blocks(self) -> tuple[int, ...]:
        return STAGE_BLOCKS[self.depth]

    @property
    def bottleneck(self) -> bool:
        return BOTTLENECK[self.depth]


RESNET50 = ResNetConfig()
RESNET_TINY = ResNetConfig(depth=18, num_classes=10, width=8, image_size=32, dtype="float32")
PRESETS = {"resnet50": RESNET50, "tiny": RESNET_TINY}


def _conv_init(key, kh, kw, cin, cout, dtype):
    fan_in = kh * kw * cin
    return (jax.random.truncated_normal(key, -2, 2, (kh, kw, cin, cout), jnp.float32)
            * (2.0 / fan_in) ** 0.5).astype(dtype)


def _bn_params(c, dtype):
    return {"scale": jnp.ones((c,), dtype), "bias": jnp.zeros((c,), dtype)}


def _bn_state(c):
    return {"mean": jnp.zeros((c,), jnp.float32), "var": jnp.ones((c,), jnp.float32)}


def init(key: jax.Array, cfg: ResNetConfig) -> tuple[dict, dict]:
    """Returns (params, state) — state carries BatchNorm running stats."""
    dt = cfg.jdtype
    keys = iter(jax.random.split(key, 256))
    params: dict[str, Any] = {"stem": {"conv": _conv_init(next(keys), 7, 7, 3, cfg.width, dt),
                                       "bn": _bn_params(cfg.width, dt)}}
    state: dict[str, Any] = {"stem": {"bn": _bn_state(cfg.width)}}

    expansion = 4 if cfg.bottleneck else 1
    cin = cfg.width
    for stage, n_blocks in enumerate(cfg.blocks):
        cmid = cfg.width * (2**stage)
        cout = cmid * expansion
        for b in range(n_blocks):
            name = f"stage{stage}_block{b}"
            stride = 2 if (b == 0 and stage > 0) else 1
            blk_p: dict[str, Any] = {}
            blk_s: dict[str, Any] = {}
            if cfg.bottleneck:
                shapes = [(1, 1, cin, cmid, 1), (3, 3, cmid, cmid, stride), (1, 1, cmid, cout, 1)]
            else:
                shapes = [(3, 3, cin, cmid, stride), (3, 3, cmid, cout, 1)]
            for i, (kh, kw, ci, co, _s) in enumerate(shapes):
                blk_p[f"conv{i}"] = _conv_init(next(keys), kh, kw, ci, co, dt)
                blk_p[f"bn{i}"] = _bn_params(co, dt)
                blk_s[f"bn{i}"] = _bn_state(co)
            if cin != cout or stride != 1:
                blk_p["proj"] = _conv_init(next(keys), 1, 1, cin, cout, dt)
                blk_p["proj_bn"] = _bn_params(cout, dt)
                blk_s["proj_bn"] = _bn_state(cout)
            params[name] = blk_p
            state[name] = blk_s
            cin = cout
    params["head"] = {"w": (jax.random.normal(next(keys), (cin, cfg.num_classes)) * cin**-0.5).astype(dt),
                      "b": jnp.zeros((cfg.num_classes,), dt)}
    return params, state


def sharding_rules(cfg: ResNetConfig) -> ShardingRules:
    # convs are small: replicate weights, shard only the batch (pure DP);
    # the head's [C, classes] can shard over model for very wide variants.
    return ShardingRules([(r"head/w", P("fsdp", "model")), (r".*", P())])


def _conv(x, w, stride):
    return jax.lax.conv_general_dilated(
        x, w, (stride, stride), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _stem_conv_s2d(images, w):
    """The 7×7/2 stem conv as a space-to-depth 4×4/1 conv — numerically
    identical, but 12 input channels instead of 3, which quadruples MXU
    lane occupancy on the layer that otherwise runs at 3/128 efficiency
    (the standard MLPerf-TPU ResNet stem transform).

    images [B, S, S, 3] with even S; w [7, 7, 3, C].
    """
    B, S, _, _ = images.shape
    C = w.shape[-1]
    # SAME padding for k=7/s=2 is (2, 3); one extra trailing row/col of
    # zeros (total 2+S+4) keeps the length even for the 2×2 blocking and
    # only ever multiplies the zero-padded kernel tap
    x = jnp.pad(images, ((0, 0), (2, 4), (2, 4), (0, 0)))
    Sp = (S + 6) // 2
    x = x.reshape(B, Sp, 2, Sp, 2, 3).transpose(0, 1, 3, 2, 4, 5).reshape(B, Sp, Sp, 12)
    w8 = jnp.pad(w, ((0, 1), (0, 1), (0, 0), (0, 0)))                 # [8,8,3,C]
    ws = w8.reshape(4, 2, 4, 2, 3, C).transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 12, C)
    return jax.lax.conv_general_dilated(
        x, ws, (1, 1), "VALID", dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _bn(x, p, s, momentum, train):
    xf = x.astype(jnp.float32)
    if train:
        mean = jnp.mean(xf, axis=(0, 1, 2))
        var = jnp.var(xf, axis=(0, 1, 2))
        new_s = {"mean": momentum * s["mean"] + (1 - momentum) * mean,
                 "var": momentum * s["var"] + (1 - momentum) * var}
    else:
        mean, var = s["mean"], s["var"]
        new_s = s
    out = (xf - mean) * jax.lax.rsqrt(var + 1e-5)
    return (out.astype(x.dtype) * p["scale"] + p["bias"]), new_s


def forward(params: dict, state: dict, images: jax.Array, cfg: ResNetConfig,
            train: bool = True, mesh=None) -> tuple[jax.Array, dict]:
    """images [B, H, W, 3] → (logits [B, classes], new_state)."""
    new_state: dict[str, Any] = {}
    images = images.astype(cfg.jdtype)
    if cfg.stem_s2d and images.shape[1] == images.shape[2] and images.shape[1] % 2 == 0:
        x = _stem_conv_s2d(images, params["stem"]["conv"])
    else:
        x = _conv(images, params["stem"]["conv"], 2)
    x, bn_s = _bn(x, params["stem"]["bn"], state["stem"]["bn"], cfg.bn_momentum, train)
    new_state["stem"] = {"bn": bn_s}
    x = jax.nn.relu(x)
    x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME")

    expansion = 4 if cfg.bottleneck else 1
    cin = cfg.width
    for stage, n_blocks in enumerate(cfg.blocks):
        cmid = cfg.width * (2**stage)
        cout = cmid * expansion
        for b in range(n_blocks):
            name = f"stage{stage}_block{b}"
            blk_p, blk_s = params[name], state[name]
            new_blk_s: dict[str, Any] = {}
            stride = 2 if (b == 0 and stage > 0) else 1
            shortcut = x
            strides = ([1, stride, 1] if cfg.bottleneck else [stride, 1])
            h = x
            for i, s_i in enumerate(strides):
                h = _conv(h, blk_p[f"conv{i}"], s_i)
                h, bn_s = _bn(h, blk_p[f"bn{i}"], blk_s[f"bn{i}"], cfg.bn_momentum, train)
                new_blk_s[f"bn{i}"] = bn_s
                if i < len(strides) - 1:
                    h = jax.nn.relu(h)
            if "proj" in blk_p:
                shortcut = _conv(shortcut, blk_p["proj"], stride)
                shortcut, bn_s = _bn(shortcut, blk_p["proj_bn"], blk_s["proj_bn"], cfg.bn_momentum, train)
                new_blk_s["proj_bn"] = bn_s
            x = jax.nn.relu(h + shortcut)
            new_state[name] = new_blk_s
            cin = cout

    x = jnp.mean(x, axis=(1, 2))
    logits = x @ params["head"]["w"] + params["head"]["b"]
    return logits, new_state


def loss_fn(params: dict, batch: dict, cfg: ResNetConfig, mesh=None,
            state: dict | None = None) -> tuple[jax.Array, dict]:
    logits, new_state = forward(params, state if state is not None else batch["bn_state"],
                                batch["image"], cfg, train=True, mesh=mesh)
    labels = batch["label"]
    loss = jnp.mean(
        -jax.nn.log_softmax(logits.astype(jnp.float32))[jnp.arange(labels.shape[0]), labels])
    acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
    return loss, {"loss": loss, "accuracy": acc, "bn_state": new_state}


def synthetic_batch(key: jax.Array, batch_size: int, cfg: ResNetConfig) -> dict:
    k1, k2 = jax.random.split(key)
    return {
        "image": jax.random.uniform(k1, (batch_size, cfg.image_size, cfg.image_size, 3), jnp.float32),
        "label": jax.random.randint(k2, (batch_size,), 0, cfg.num_classes, jnp.int32),
    }


def config_from_dict(d: dict | str) -> ResNetConfig:
    if isinstance(d, str):
        return PRESETS[d]
    fields = {f.name for f in dataclasses.fields(ResNetConfig)}
    return dataclasses.replace(
        PRESETS.get(d.get("preset", ""), ResNetConfig()),
        **{k: v for k, v in d.items() if k in fields},
    )
