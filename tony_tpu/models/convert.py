"""Weight import from Hugging Face checkpoints (LlamaForCausalLM family).

The reference orchestrates user-supplied training programs; users arriving
from that ecosystem hold HF/PyTorch checkpoints. This converter maps an HF
Llama state dict onto models/llama.py's pytree (and config), verified to
logit-level parity in tests/test_convert.py — the rope convention
(rotate-half, non-interleaved), GQA head layout, and un-tied lm head all
line up, so only transposes are needed (HF nn.Linear stores [out, in]; our
einsums consume [in, out]).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from tony_tpu.models.llama import LlamaConfig


def config_from_hf(hf_config, dtype: str = "bfloat16", **overrides) -> LlamaConfig:
    """transformers LlamaConfig → LlamaConfig (ours). Rejects checkpoint
    features the native model does not implement, rather than importing
    something that silently diverges."""
    if getattr(hf_config, "rope_scaling", None):
        raise NotImplementedError(
            "rope_scaling (Llama 3.1+ long-context scaling) is not implemented "
            "in ops/layers.rope_frequencies — importing would silently diverge "
            "from the HF forward at long positions"
        )
    explicit_hd = getattr(hf_config, "head_dim", None)
    derived_hd = hf_config.hidden_size // hf_config.num_attention_heads
    if explicit_hd is not None and explicit_hd != derived_hd:
        raise NotImplementedError(
            f"checkpoint head_dim {explicit_hd} != hidden_size/num_heads "
            f"{derived_hd}; the native LlamaConfig derives head_dim"
        )
    if getattr(hf_config, "attention_bias", False) or getattr(hf_config, "mlp_bias", False):
        raise NotImplementedError(
            "attention_bias/mlp_bias checkpoints are not supported (the native "
            "block has no bias terms)"
        )
    base = LlamaConfig(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=getattr(hf_config, "num_key_value_heads", hf_config.num_attention_heads),
        d_ff=hf_config.intermediate_size,
        max_seq=hf_config.max_position_embeddings,
        rope_theta=getattr(hf_config, "rope_theta", 10_000.0),
        norm_eps=hf_config.rms_norm_eps,
        dtype=dtype,
    )
    return dataclasses.replace(base, **overrides) if overrides else base


def _to_np(t) -> np.ndarray:
    if hasattr(t, "detach"):  # torch tensor
        return t.detach().to("cpu").float().numpy()
    return np.asarray(t, np.float32)


# non-parameter buffers some transformers versions persist in state dicts
_IGNORABLE_SUFFIXES = ("rotary_emb.inv_freq",)


def params_from_hf_state_dict(state_dict: dict, cfg: LlamaConfig) -> dict:
    """HF LlamaForCausalLM state dict → stacked-layer params pytree.

    Accepts torch tensors or numpy arrays; each tensor converts lazily at
    consumption (no second full-precision copy of the whole checkpoint).
    Missing ``lm_head.weight`` means a tied-embedding checkpoint: the
    embedding row matrix is reused. Any key this mapping does not consume
    (e.g. bias terms) raises — silently dropping weights would produce a
    model that runs but diverges.
    """
    dt = cfg.jdtype
    consumed: set[str] = set()

    def take(key: str, transpose: bool) -> np.ndarray:
        consumed.add(key)
        w = _to_np(state_dict[key])
        return w.T if transpose else w

    def stack(fmt: str, transpose: bool = True):
        return jnp.asarray(
            np.stack([take(fmt.format(i=i), transpose) for i in range(cfg.n_layers)]), dt
        )

    embed = take("model.embed_tokens.weight", transpose=False)
    params = {
        "embed": jnp.asarray(embed, dt),
        "layers": {
            "attn_norm": stack("model.layers.{i}.input_layernorm.weight", transpose=False),
            "wq": stack("model.layers.{i}.self_attn.q_proj.weight"),
            "wk": stack("model.layers.{i}.self_attn.k_proj.weight"),
            "wv": stack("model.layers.{i}.self_attn.v_proj.weight"),
            "wo": stack("model.layers.{i}.self_attn.o_proj.weight"),
            "mlp_norm": stack("model.layers.{i}.post_attention_layernorm.weight", transpose=False),
            "w_gate": stack("model.layers.{i}.mlp.gate_proj.weight"),
            "w_up": stack("model.layers.{i}.mlp.up_proj.weight"),
            "w_down": stack("model.layers.{i}.mlp.down_proj.weight"),
        },
        "final_norm": jnp.asarray(take("model.norm.weight", transpose=False), dt),
    }
    if "lm_head.weight" in state_dict:
        params["lm_head"] = jnp.asarray(take("lm_head.weight", transpose=True), dt)
    else:  # tied embeddings
        params["lm_head"] = jnp.asarray(embed.T, dt)

    leftover = [
        k for k in state_dict
        if k not in consumed and not k.endswith(_IGNORABLE_SUFFIXES)
    ]
    if leftover:
        raise ValueError(
            f"state dict has {len(leftover)} unconsumed tensors (e.g. "
            f"{sorted(leftover)[:4]}): this checkpoint carries weights the "
            "native Llama has no slot for — refusing a silently-wrong import"
        )
    return params


def from_hf(model, dtype: str = "bfloat16", **overrides):
    """One-call import: (params, cfg) from a transformers LlamaForCausalLM.
    For a bare state dict, build the config yourself (``config_from_hf`` or
    a native LlamaConfig) and call ``params_from_hf_state_dict``."""
    if hasattr(model, "state_dict") and hasattr(model, "config"):
        cfg = config_from_hf(model.config, dtype=dtype, **overrides)
        return params_from_hf_state_dict(model.state_dict(), cfg), cfg
    raise TypeError(
        "pass a transformers LlamaForCausalLM; for a bare state dict use "
        "params_from_hf_state_dict with an explicit config"
    )
