"""Weight import from Hugging Face checkpoints (LlamaForCausalLM family).

The reference orchestrates user-supplied training programs; users arriving
from that ecosystem hold HF/PyTorch checkpoints. This converter maps an HF
Llama state dict onto models/llama.py's pytree (and config), verified to
logit-level parity in tests/test_convert.py — the rope convention
(rotate-half, non-interleaved), GQA head layout, and un-tied lm head all
line up, so only transposes are needed (HF nn.Linear stores [out, in]; our
einsums consume [in, out]).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from tony_tpu.models.llama import LlamaConfig


def _reject_unsupported(hf_config) -> None:
    """Checkpoint features the native models do not implement raise here,
    rather than importing something that silently diverges."""
    scaling = getattr(hf_config, "rope_scaling", None)
    if scaling:
        kind = scaling.get("rope_type", scaling.get("type"))
        if kind not in ("llama3", "linear"):
            raise NotImplementedError(
                f"rope_scaling type {kind!r} is not implemented (llama3 and "
                "linear are; yarn/dynamic would silently diverge)"
            )
    explicit_hd = getattr(hf_config, "head_dim", None)
    derived_hd = hf_config.hidden_size // hf_config.num_attention_heads
    if explicit_hd is not None and explicit_hd != derived_hd:
        raise NotImplementedError(
            f"checkpoint head_dim {explicit_hd} != hidden_size/num_heads "
            f"{derived_hd}; the native configs derive head_dim"
        )
    if getattr(hf_config, "attention_bias", False) or getattr(hf_config, "mlp_bias", False):
        raise NotImplementedError(
            "attention_bias/mlp_bias checkpoints are not supported (the native "
            "block has no bias terms)"
        )


def _rope_scaling_tuple(hf_config) -> tuple:
    """HF rope_scaling dict → the hashable tuple ops/layers expects."""
    scaling = getattr(hf_config, "rope_scaling", None)
    if not scaling:
        return ()
    kind = scaling.get("rope_type", scaling.get("type"))
    if kind == "linear":
        return ("linear", float(scaling["factor"]))
    if kind == "llama3":
        return (
            "llama3",
            float(scaling["factor"]),
            float(scaling["low_freq_factor"]),
            float(scaling["high_freq_factor"]),
            float(scaling["original_max_position_embeddings"]),
        )
    raise NotImplementedError(f"rope_scaling type {kind!r}")


def config_from_hf(hf_config, dtype: str = "bfloat16", **overrides) -> LlamaConfig:
    """transformers LlamaConfig → LlamaConfig (ours)."""
    _reject_unsupported(hf_config)
    base = LlamaConfig(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=getattr(hf_config, "num_key_value_heads", hf_config.num_attention_heads),
        d_ff=hf_config.intermediate_size,
        max_seq=hf_config.max_position_embeddings,
        rope_theta=getattr(hf_config, "rope_theta", 10_000.0),
        norm_eps=hf_config.rms_norm_eps,
        dtype=dtype,
        sliding_window=int(getattr(hf_config, "sliding_window", None) or 0),
        rope_scaling=_rope_scaling_tuple(hf_config),
    )
    return dataclasses.replace(base, **overrides) if overrides else base


def _to_np(t) -> np.ndarray:
    if hasattr(t, "detach"):  # torch tensor
        return t.detach().to("cpu").float().numpy()
    return np.asarray(t, np.float32)


# non-parameter buffers some transformers versions persist in state dicts
_IGNORABLE_SUFFIXES = ("rotary_emb.inv_freq",)


class _Consumer:
    """Tracks which state-dict keys the mapping consumed; converts each
    tensor lazily at consumption (no second full-precision copy of the
    whole checkpoint) and refuses to finish while any weight tensor is
    left unconsumed — silently dropping weights would produce a model
    that runs but diverges."""

    def __init__(self, state_dict: dict, cfg):
        self.sd = state_dict
        self.cfg = cfg
        self.dt = cfg.jdtype
        self.consumed: set[str] = set()

    def take(self, key: str, transpose: bool) -> np.ndarray:
        self.consumed.add(key)
        w = _to_np(self.sd[key])
        return w.T if transpose else w

    def stack(self, fmt: str, transpose: bool = True, dtype=None):
        # per-layer dtype conversion bounds the f32 peak to one layer
        return jnp.stack([
            jnp.asarray(self.take(fmt.format(i=i), transpose), dtype or self.dt)
            for i in range(self.cfg.n_layers)
        ])

    def common(self) -> tuple[dict, dict]:
        """The embedding/attention/norm/lm-head mapping every Llama-family
        architecture shares. Returns (params, layer dict to extend)."""
        embed = self.take("model.embed_tokens.weight", transpose=False)
        layers = {
            "attn_norm": self.stack("model.layers.{i}.input_layernorm.weight", transpose=False),
            "wq": self.stack("model.layers.{i}.self_attn.q_proj.weight"),
            "wk": self.stack("model.layers.{i}.self_attn.k_proj.weight"),
            "wv": self.stack("model.layers.{i}.self_attn.v_proj.weight"),
            "wo": self.stack("model.layers.{i}.self_attn.o_proj.weight"),
            "mlp_norm": self.stack("model.layers.{i}.post_attention_layernorm.weight", transpose=False),
        }
        params = {
            "embed": jnp.asarray(embed, self.dt),
            "layers": layers,
            "final_norm": jnp.asarray(self.take("model.norm.weight", transpose=False), self.dt),
        }
        if "lm_head.weight" in self.sd:
            params["lm_head"] = jnp.asarray(self.take("lm_head.weight", transpose=True), self.dt)
        else:  # tied embeddings
            params["lm_head"] = jnp.asarray(embed.T, self.dt)
        return params, layers

    def finish(self, params: dict) -> dict:
        leftover = [
            k for k in self.sd
            if k not in self.consumed and not k.endswith(_IGNORABLE_SUFFIXES)
        ]
        if leftover:
            raise ValueError(
                f"state dict has {len(leftover)} unconsumed tensors (e.g. "
                f"{sorted(leftover)[:4]}): this checkpoint carries weights the "
                "native model has no slot for — refusing a silently-wrong import"
            )
        return params


def params_from_hf_state_dict(state_dict: dict, cfg: LlamaConfig) -> dict:
    """HF LlamaForCausalLM state dict → stacked-layer params pytree.
    Missing ``lm_head.weight`` means a tied-embedding checkpoint: the
    embedding row matrix is reused."""
    c = _Consumer(state_dict, cfg)
    params, layers = c.common()
    layers.update(
        w_gate=c.stack("model.layers.{i}.mlp.gate_proj.weight"),
        w_up=c.stack("model.layers.{i}.mlp.up_proj.weight"),
        w_down=c.stack("model.layers.{i}.mlp.down_proj.weight"),
    )
    return c.finish(params)


def config_from_hf_mixtral(hf_config, dtype: str = "bfloat16", **overrides):
    """transformers MixtralConfig → MixtralConfig (ours).

    capacity_factor defaults to num_experts/top_k — the lossless setting
    (HF's reference routing has no capacity and drops nothing; any smaller
    factor would make imported logits diverge when routing is imbalanced).
    """
    from tony_tpu.models.mixtral import MixtralConfig

    _reject_unsupported(hf_config)
    base = MixtralConfig(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=hf_config.num_key_value_heads,
        d_ff=hf_config.intermediate_size,
        max_seq=hf_config.max_position_embeddings,
        rope_theta=getattr(hf_config, "rope_theta", 1e6),
        norm_eps=hf_config.rms_norm_eps,
        dtype=dtype,
        num_experts=hf_config.num_local_experts,
        top_k=hf_config.num_experts_per_tok,
        capacity_factor=hf_config.num_local_experts / hf_config.num_experts_per_tok,
    )
    return dataclasses.replace(base, **overrides) if overrides else base


def params_from_hf_mixtral_state_dict(state_dict: dict, cfg) -> dict:
    """HF MixtralForCausalLM state dict → native Mixtral pytree.

    Expert naming: HF w1 = gate, w3 = up, w2 = down; the per-expert matrices
    stack into [L, E, ...] tensors. The router imports directly in f32
    (never rounded through the model dtype — bf16-quantized routing logits
    could flip near-tie expert selections versus the HF forward).
    """
    c = _Consumer(state_dict, cfg)
    params, layers = c.common()

    def stack_experts(which: str):
        # per-layer conversion: the f32 staging peak is one layer's experts
        return jnp.stack([
            jnp.asarray(
                np.stack([
                    c.take(f"model.layers.{i}.block_sparse_moe.experts.{e}.{which}.weight", True)
                    for e in range(cfg.num_experts)
                ]),
                c.dt,
            )
            for i in range(cfg.n_layers)
        ])

    layers.update(
        router=c.stack("model.layers.{i}.block_sparse_moe.gate.weight", dtype=jnp.float32),
        we_gate=stack_experts("w1"),
        we_up=stack_experts("w3"),
        we_down=stack_experts("w2"),
    )
    return c.finish(params)


def from_hf(model, dtype: str = "bfloat16", **overrides):
    """One-call import: (params, cfg) from a transformers LlamaForCausalLM
    or MixtralForCausalLM (dispatch on config.model_type). For a bare state
    dict, build the config yourself (``config_from_hf`` /
    ``config_from_hf_mixtral``) and call the matching
    ``params_from_hf*_state_dict``."""
    if hasattr(model, "state_dict") and hasattr(model, "config"):
        kind = getattr(model.config, "model_type", "llama")
        if kind == "mixtral":
            cfg = config_from_hf_mixtral(model.config, dtype=dtype, **overrides)
            return params_from_hf_mixtral_state_dict(model.state_dict(), cfg), cfg
        cfg = config_from_hf(model.config, dtype=dtype, **overrides)
        return params_from_hf_state_dict(model.state_dict(), cfg), cfg
    raise TypeError(
        "pass a transformers LlamaForCausalLM/MixtralForCausalLM; for a bare "
        "state dict use the params_from_hf*_state_dict functions"
    )
