"""BERT-style bidirectional encoder with an MLM head (BASELINE.json config #2
— the MultiWorkerMirrored-analog workload, here data/fsdp/tensor-parallel).

Same functional conventions as llama.py: stacked scanned layers, rule-based
sharding, f32 norm/softmax accumulation.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tony_tpu.ops import attention as attn_ops
from tony_tpu.ops import layers as L
from tony_tpu.parallel.sharding import ShardingRules, constrain

BATCH_AXES = ("data", "fsdp")


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30_522
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    max_seq: int = 512
    type_vocab: int = 2
    norm_eps: float = 1e-12
    dtype: str = "bfloat16"
    remat: bool = False
    attn_impl: str = "auto"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)

    def num_params(self) -> int:
        D, F, V = self.d_model, self.d_ff, self.vocab_size
        per_layer = 4 * D * D + 4 * D + 2 * D * F + D + F + 4 * D
        return (V + self.max_seq + self.type_vocab) * D + 2 * D + self.n_layers * per_layer + D * V + V

    def flops_per_token(self, masked_frac: float | None = None) -> int:
        """Training FLOPs/token (PaLM convention, as train/metrics.py);
        the attention term is NOT halved — bidirectional, no causal mask.
        With ``masked_frac``, the MLM-head matmul is counted only at the
        masked positions actually projected (the gathered-positions path)."""
        attn = 12 * self.n_layers * self.d_model * self.max_seq
        flops = 6 * self.num_params() + attn
        if masked_frac is not None:
            head = self.d_model * self.vocab_size
            flops -= int(6 * head * (1.0 - masked_frac))
        return flops


BERT_BASE = BertConfig()
BERT_TINY = BertConfig(
    vocab_size=256, d_model=64, n_layers=2, n_heads=4, d_ff=128, max_seq=64,
    attn_impl="reference",
)
PRESETS = {"bert-base": BERT_BASE, "tiny": BERT_TINY}


def init(key: jax.Array, cfg: BertConfig) -> dict:
    D, F, V = cfg.d_model, cfg.d_ff, cfg.vocab_size
    Lyr = cfg.n_layers
    dt = cfg.jdtype
    ks = jax.random.split(key, 10)

    def dense(k, *shape, fan_in):
        return (jax.random.truncated_normal(k, -2, 2, shape, jnp.float32) * fan_in**-0.5).astype(dt)

    return {
        "tok_embed": dense(ks[0], V, D, fan_in=1.0),
        "pos_embed": dense(ks[1], cfg.max_seq, D, fan_in=1.0),
        "type_embed": dense(ks[2], cfg.type_vocab, D, fan_in=1.0),
        "embed_norm": {"w": jnp.ones((D,), dt), "b": jnp.zeros((D,), dt)},
        "layers": {
            "wqkv": dense(ks[3], Lyr, D, 3 * D, fan_in=D),
            "bqkv": jnp.zeros((Lyr, 3 * D), dt),
            "wo": dense(ks[4], Lyr, D, D, fan_in=D),
            "bo": jnp.zeros((Lyr, D), dt),
            "attn_norm": {"w": jnp.ones((Lyr, D), dt), "b": jnp.zeros((Lyr, D), dt)},
            "w_in": dense(ks[5], Lyr, D, F, fan_in=D),
            "b_in": jnp.zeros((Lyr, F), dt),
            "w_out": dense(ks[6], Lyr, F, D, fan_in=F),
            "b_out": jnp.zeros((Lyr, D), dt),
            "mlp_norm": {"w": jnp.ones((Lyr, D), dt), "b": jnp.zeros((Lyr, D), dt)},
        },
        "mlm_head": dense(ks[7], D, V, fan_in=D),
        "mlm_bias": jnp.zeros((V,), dt),
    }


def sharding_rules(cfg: BertConfig) -> ShardingRules:
    return ShardingRules([
        (r"tok_embed", P("model", "fsdp")),
        (r"(pos|type)_embed", P(None, "fsdp")),
        (r"layers/(wqkv|w_in)", P(None, "fsdp", "model")),
        (r"layers/(bqkv|b_in)", P(None, "model")),
        (r"layers/(wo|w_out)", P(None, "model", "fsdp")),
        (r"mlm_head", P("fsdp", "model")),
        (r".*", P()),
    ])


def hidden_states(params: dict, tokens: jax.Array, cfg: BertConfig, mesh=None,
                  type_ids: jax.Array | None = None,
                  segment_ids: jax.Array | None = None) -> jax.Array:
    """Encoder output [B, T, D] without the MLM head.

    ``segment_ids`` [B, T] (packed batches, data.pack_sequences layout):
    attention is confined within segments (flash-kernel segment masking,
    bidirectional) and the learned absolute positions restart at every
    segment boundary — the packing r2 built for the decoder models, applied
    to the padded-512 MLM batches it was built for (SURVEY §5.7 / VERDICT
    r2 weak #7). Pad tokens (segment 0) attend only among themselves and
    must simply carry no masked positions.
    """
    B, T = tokens.shape
    H, Dh = cfg.n_heads, cfg.head_dim
    act_spec = P(BATCH_AXES, None, None)

    if segment_ids is not None:
        from tony_tpu.models.llama import segment_positions

        pos_e = jnp.take(params["pos_embed"], segment_positions(segment_ids), axis=0)
    else:
        pos_e = params["pos_embed"][:T]
    x = (
        jnp.take(params["tok_embed"], tokens, axis=0)
        + pos_e
        + jnp.take(params["type_embed"], type_ids if type_ids is not None else jnp.zeros_like(tokens), axis=0)
    )
    x = L.layer_norm(x, params["embed_norm"]["w"], params["embed_norm"]["b"], cfg.norm_eps)
    if mesh is not None:
        x = constrain(x, mesh, act_spec)

    def block(x, lp):
        qkv = jnp.einsum("btd,dh->bth", x, lp["wqkv"]) + lp["bqkv"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, H, Dh).transpose(0, 2, 1, 3)
        k = k.reshape(B, T, H, Dh).transpose(0, 2, 1, 3)
        v = v.reshape(B, T, H, Dh).transpose(0, 2, 1, 3)
        o = attn_ops.mha(
            q, k, v, causal=False, impl=cfg.attn_impl, segment_ids=segment_ids
        )
        o = o.transpose(0, 2, 1, 3).reshape(B, T, H * Dh)
        x = L.layer_norm(
            x + jnp.einsum("bth,hd->btd", o, lp["wo"]) + lp["bo"],
            lp["attn_norm"]["w"], lp["attn_norm"]["b"], cfg.norm_eps,
        )
        x = L.layer_norm(
            x + L.gelu_mlp(x, lp["w_in"], lp["b_in"], lp["w_out"], lp["b_out"]),
            lp["mlp_norm"]["w"], lp["mlp_norm"]["b"], cfg.norm_eps,
        )
        if mesh is not None:
            x = constrain(x, mesh, act_spec)
        return x, None

    block_fn = jax.checkpoint(block) if cfg.remat else block
    x, _ = jax.lax.scan(block_fn, x, params["layers"])
    return x


def forward(params: dict, tokens: jax.Array, cfg: BertConfig, mesh=None,
            type_ids: jax.Array | None = None,
            segment_ids: jax.Array | None = None) -> jax.Array:
    """Full-vocab MLM logits [B, T, V] at every position."""
    x = hidden_states(params, tokens, cfg, mesh, type_ids, segment_ids=segment_ids)
    return jnp.einsum("btd,dv->btv", x, params["mlm_head"]) + params["mlm_bias"]


def loss_fn(params: dict, batch: dict, cfg: BertConfig, mesh=None) -> tuple[jax.Array, dict]:
    """MLM loss.

    Two batch layouts:
    - gathered (preferred): ``masked_pos`` [B, M] + ``masked_targets``
      [B, M] — the MLM head projects ONLY the masked positions (as original
      BERT does), skipping ~85% of the head matmul and never materializing
      the [B, T, V] logits.
    - dense: ``targets`` [B, T] with -100 = unmasked; full-logits path.
    """
    if "masked_pos" in batch:
        x = hidden_states(params, batch["tokens"], cfg, mesh,
                          segment_ids=batch.get("segment_ids"))
        pos = batch["masked_pos"]                                     # [B, M]
        xm = jnp.take_along_axis(x, pos[..., None], axis=1)           # [B, M, D]
        logits = jnp.einsum("bmd,dv->bmv", xm, params["mlm_head"]) + params["mlm_bias"]
        loss, n = L.cross_entropy_loss(logits, batch["masked_targets"])
        return loss, {"loss": loss, "tokens": n}
    logits = forward(params, batch["tokens"], cfg, mesh,
                     segment_ids=batch.get("segment_ids"))
    targets = batch["targets"]
    if "segment_ids" in batch:
        # packed rows: never score padding, whatever the caller put there
        targets = jnp.where(batch["segment_ids"] != 0, targets, -100)
    loss, n = L.cross_entropy_loss(logits, targets)
    return loss, {"loss": loss, "tokens": n}


def synthetic_batch(key: jax.Array, batch_size: int, seq_len: int, cfg: BertConfig,
                    mask_frac: float = 0.15) -> dict:
    """Gathered MLM layout: exactly M = round(mask_frac·T) masked positions
    per row (fixed count = static shapes for the gathered-head loss path;
    this is also how production BERT pipelines batch MLM)."""
    k1, k2 = jax.random.split(key)
    tokens = jax.random.randint(k1, (batch_size, seq_len), 0, cfg.vocab_size, jnp.int32)
    M = max(1, round(seq_len * mask_frac))
    # top-M of uniform noise = M distinct positions, sorted for locality
    noise = jax.random.uniform(k2, (batch_size, seq_len))
    pos = jnp.sort(jnp.argsort(noise, axis=-1)[:, :M], axis=-1).astype(jnp.int32)
    targets = jnp.take_along_axis(tokens, pos, axis=1)
    return {"tokens": tokens, "masked_pos": pos, "masked_targets": targets}


def dense_synthetic_batch(key: jax.Array, batch_size: int, seq_len: int, cfg: BertConfig,
                         mask_frac: float = 0.15) -> dict:
    """Dense [B, T] targets layout (-100 = unmasked) for the full-logits path."""
    k1, k2 = jax.random.split(key)
    tokens = jax.random.randint(k1, (batch_size, seq_len), 0, cfg.vocab_size, jnp.int32)
    masked = jax.random.uniform(k2, (batch_size, seq_len)) < mask_frac
    return {"tokens": tokens, "targets": jnp.where(masked, tokens, -100)}


def config_from_dict(d: dict | str) -> BertConfig:
    if isinstance(d, str):
        return PRESETS[d]
    fields = {f.name for f in dataclasses.fields(BertConfig)}
    return dataclasses.replace(
        PRESETS.get(d.get("preset", ""), BertConfig()),
        **{k: v for k, v in d.items() if k in fields},
    )
