"""MNIST-scale MLP classifier (BASELINE.json config #1: the mnist example
analog — the smallest end-to-end workload `tony submit` runs)."""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tony_tpu.parallel.sharding import ShardingRules


@dataclass(frozen=True)
class MLPConfig:
    input_dim: int = 784
    hidden_dim: int = 512
    num_classes: int = 10
    n_layers: int = 2
    dtype: str = "float32"

    def num_params(self) -> int:
        dims = [self.input_dim] + [self.hidden_dim] * self.n_layers + [self.num_classes]
        return sum(dims[i] * dims[i + 1] + dims[i + 1] for i in range(len(dims) - 1))


def init(key: jax.Array, cfg: MLPConfig) -> dict:
    dims = [cfg.input_dim] + [cfg.hidden_dim] * cfg.n_layers + [cfg.num_classes]
    dt = jnp.dtype(cfg.dtype)
    params = {}
    for i, (d_in, d_out) in enumerate(zip(dims[:-1], dims[1:])):
        k = jax.random.fold_in(key, i)
        params[f"layer_{i}"] = {
            "w": (jax.random.normal(k, (d_in, d_out)) * d_in**-0.5).astype(dt),
            "b": jnp.zeros((d_out,), dt),
        }
    return params


def sharding_rules(cfg: MLPConfig) -> ShardingRules:
    return ShardingRules([(r"layer_\d+/w", P("fsdp", "model")), (r".*", P())])


def forward(params: dict, x: jax.Array, cfg: MLPConfig, mesh=None) -> jax.Array:
    n = cfg.n_layers + 1
    for i in range(n):
        lp = params[f"layer_{i}"]
        x = x @ lp["w"] + lp["b"]
        if i < n - 1:
            x = jax.nn.relu(x)
    return x


def loss_fn(params: dict, batch: dict, cfg: MLPConfig, mesh=None) -> tuple[jax.Array, dict]:
    logits = forward(params, batch["image"], cfg, mesh)
    labels = batch["label"]
    loss = jnp.mean(
        -jax.nn.log_softmax(logits.astype(jnp.float32))[jnp.arange(labels.shape[0]), labels]
    )
    acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
    return loss, {"loss": loss, "accuracy": acc}


def synthetic_batch(key: jax.Array, batch_size: int, cfg: MLPConfig) -> dict:
    k1, k2 = jax.random.split(key)
    return {
        "image": jax.random.uniform(k1, (batch_size, cfg.input_dim), jnp.float32),
        "label": jax.random.randint(k2, (batch_size,), 0, cfg.num_classes, jnp.int32),
    }
