"""Mixtral-style sparse-MoE decoder (BASELINE.json config #5).

Llama backbone (RMSNorm / RoPE / GQA attention, scanned stacked layers) with
the dense FFN replaced by a top-2-of-E SwiGLU mixture routed per token
(parallel/expert.py); expert weights shard over the ``expert`` mesh axis.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tony_tpu.models import llama as llama_mod
from tony_tpu.ops import attention as attn_ops
from tony_tpu.ops import layers as L
from tony_tpu.parallel.expert import MoEConfig, moe_ffn
from tony_tpu.parallel.sharding import ShardingRules, constrain

BATCH_AXES = llama_mod.BATCH_AXES


@dataclass(frozen=True)
class MixtralConfig(llama_mod.LlamaConfig):
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    moe_dispatch: str = "ragged"  # ragged (grouped GEMM / fused kernel) | ragged_xla | gather | dense
    aux_loss_coef: float = 1e-2   # load-balance loss weight
    router_z_coef: float = 1e-3   # router z-loss weight

    @property
    def moe(self) -> MoEConfig:
        return MoEConfig(
            self.num_experts, self.top_k, self.capacity_factor,
            router_z_coef=self.router_z_coef, aux_loss_coef=self.aux_loss_coef,
            dispatch=self.moe_dispatch,
        )

    def num_params(self) -> int:
        base = super().num_params()
        D, F = self.d_model, self.d_ff
        dense_ffn = self.n_layers * 3 * D * F
        moe_ffn_params = self.n_layers * (self.num_experts * 3 * D * F + D * self.num_experts)
        return base - dense_ffn + moe_ffn_params

    def active_params(self) -> int:
        """Params touched per token (top-k of E experts) — the MFU basis."""
        D, F = self.d_model, self.d_ff
        dense_ffn = self.n_layers * 3 * D * F
        active_ffn = self.n_layers * (self.top_k * 3 * D * F + D * self.num_experts)
        return super().num_params() - dense_ffn + active_ffn

    def flops_per_token(self) -> int:
        from tony_tpu.train.metrics import transformer_flops_per_token

        return transformer_flops_per_token(
            self.active_params(), self.n_layers, self.d_model, self.max_seq, training=True
        )


MIXTRAL_8X7B = MixtralConfig(
    vocab_size=32_000, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8,
    d_ff=14_336, max_seq=8192, rope_theta=1e6, num_experts=8, top_k=2,
    # NO sliding window: released Mixtral-8x7B checkpoints set
    # sliding_window=null (fully dense over 32k ctx); only Mistral-7B uses
    # the 4096 SWA band. SWA stays available via config / convert for
    # Mistral-style checkpoints.
    sliding_window=0,
)
MIXTRAL_TINY = MixtralConfig(
    vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=128,
    max_seq=128, num_experts=4, top_k=2, remat=False, attn_impl="reference",
)
PRESETS = {"mixtral-8x7b": MIXTRAL_8X7B, "tiny": MIXTRAL_TINY}


def init(key: jax.Array, cfg: MixtralConfig) -> dict:
    D, F, E, Lyr = cfg.d_model, cfg.d_ff, cfg.num_experts, cfg.n_layers
    dt = cfg.jdtype
    base = llama_mod.init(key, cfg)
    ks = jax.random.split(jax.random.fold_in(key, 1), 4)

    def dense(k, *shape, fan_in):
        return (jax.random.truncated_normal(k, -2, 2, shape, jnp.float32) * fan_in**-0.5).astype(dt)

    layers = dict(base["layers"])
    for gone in ("w_gate", "w_up", "w_down"):
        del layers[gone]
    layers.update(
        router=dense(ks[0], Lyr, D, E, fan_in=D).astype(jnp.float32),
        we_gate=dense(ks[1], Lyr, E, D, F, fan_in=D),
        we_up=dense(ks[2], Lyr, E, D, F, fan_in=D),
        we_down=dense(ks[3], Lyr, E, F, D, fan_in=F),
    )
    base["layers"] = layers
    return base


def sharding_rules(cfg: MixtralConfig) -> ShardingRules:
    return ShardingRules([
        (r"embed", P("model", "fsdp")),
        (r"layers/(wq|wk|wv)", P(None, "fsdp", "model")),
        (r"layers/wo", P(None, "model", "fsdp")),
        (r"layers/router", P(None, None, None)),
        (r"layers/(we_gate|we_up)", P(None, "expert", "fsdp", "model")),
        (r"layers/we_down", P(None, "expert", "model", "fsdp")),
        (r"layers/.*norm", P(None, None)),
        (r"final_norm", P(None)),
        (r"lm_head", P("fsdp", "model")),
    ])


def _layer(
    x: jax.Array, lp: dict, cos, sin, cfg: MixtralConfig, mesh,
    segment_ids=None, positions=None, token_mask=None,
) -> tuple[jax.Array, dict]:
    """One Mixtral decoder layer (pre-norm GQA attention + MoE FFN) →
    (x, per-layer aux dict). Shared by the flat layer scan (hidden_states)
    and the 1F1B pipeline stage body (pp_value_and_grad, mesh=None)."""
    B, T = x.shape[0], x.shape[1]
    Dh, H, Hkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    act_spec = P(BATCH_AXES, "context", None)
    h = L.rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    q = jnp.einsum("btd,dh->bth", h, lp["wq"]).reshape(B, T, H, Dh).transpose(0, 2, 1, 3)
    k = jnp.einsum("btd,dh->bth", h, lp["wk"]).reshape(B, T, Hkv, Dh).transpose(0, 2, 1, 3)
    v = jnp.einsum("btd,dh->bth", h, lp["wv"]).reshape(B, T, Hkv, Dh).transpose(0, 2, 1, 3)
    q = L.apply_rope(q, cos, sin, positions=positions)
    k = L.apply_rope(k, cos, sin, positions=positions)
    o = llama_mod._attention(q, k, v, cfg, mesh, segment_ids=segment_ids)
    o = o.transpose(0, 2, 1, 3).reshape(B, T, H * Dh)
    x = x + jnp.einsum("bth,hd->btd", o, lp["wo"])
    if mesh is not None:
        x = constrain(x, mesh, act_spec)
    h = L.rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
    y, aux = moe_ffn(
        h, lp["router"], lp["we_gate"], lp["we_up"], lp["we_down"], cfg.moe,
        mesh, token_mask=token_mask,
    )
    x = x + y
    if mesh is not None:
        x = constrain(x, mesh, act_spec)
    return x, aux


def hidden_states(
    params: dict, tokens: jax.Array, cfg: MixtralConfig, mesh=None, segment_ids=None
) -> tuple[jax.Array, dict]:
    """tokens [B, T] → (final-norm hidden states [B, T, D], moe aux losses).

    ``segment_ids`` [B, T] (packed sequences): segment-confined attention +
    per-segment RoPE positions, same contract as llama.hidden_states."""
    T = tokens.shape[1]
    cos, sin = L.rope_frequencies(cfg.head_dim, T, cfg.rope_theta, cfg.rope_scaling)
    positions = (
        llama_mod.segment_positions(segment_ids) if segment_ids is not None else None
    )
    token_mask = (segment_ids != 0) if segment_ids is not None else None

    x = llama_mod.embed_lookup(params["embed"], tokens, mesh)
    if mesh is not None:
        x = constrain(x, mesh, P(BATCH_AXES, "context", None))

    def block(carry, lp):
        x, aux_acc = carry
        x, aux = _layer(
            x, lp, cos, sin, cfg, mesh,
            segment_ids=segment_ids, positions=positions, token_mask=token_mask,
        )
        aux_acc = {
            "moe_balance_loss": aux_acc["moe_balance_loss"] + aux["moe_balance_loss"],
            "moe_z_loss": aux_acc["moe_z_loss"] + aux["moe_z_loss"],
            "moe_dropped_frac": aux_acc["moe_dropped_frac"] + aux["moe_dropped_frac"] / cfg.n_layers,
        }
        return (x, aux_acc), None

    aux0 = {k: jnp.zeros((), jnp.float32) for k in ("moe_balance_loss", "moe_z_loss", "moe_dropped_frac")}
    block_fn = attn_ops.remat_block(block, cfg.remat, cfg.remat_policy)
    (x, aux), _ = jax.lax.scan(block_fn, (x, aux0), params["layers"])

    return L.rms_norm(x, params["final_norm"], cfg.norm_eps), aux


def forward(
    params: dict, tokens: jax.Array, cfg: MixtralConfig, mesh=None, segment_ids=None
) -> tuple[jax.Array, dict]:
    """tokens [B, T] → (logits [B, T, V], moe aux losses summed over layers)."""
    x, aux = hidden_states(params, tokens, cfg, mesh, segment_ids=segment_ids)
    logits = jnp.einsum("btd,dv->btv", x, params["lm_head"])
    return logits, aux


def loss_fn(params: dict, batch: dict, cfg: MixtralConfig, mesh=None) -> tuple[jax.Array, dict]:
    """With ``cfg.ce_chunk > 0`` the lm-head + CE fuse per sequence chunk so
    the [B, T, V] logits never materialize; packed batches (segment_ids)
    get segment-confined attention and boundary/pad target masking (same
    scheme as llama.loss_fn)."""
    tokens = batch["tokens"]
    targets, seg_in = llama_mod.mask_packed_targets(tokens, batch.get("segment_ids"))
    if cfg.ce_chunk > 0:
        x, aux = hidden_states(params, tokens[:, :-1], cfg, mesh, segment_ids=seg_in)
        ce, n = L.chunked_cross_entropy_loss(
            x, params["lm_head"], targets, chunk=cfg.ce_chunk
        )
    else:
        logits, aux = forward(params, tokens[:, :-1], cfg, mesh, segment_ids=seg_in)
        ce, n = L.cross_entropy_loss(logits, targets)
    loss = ce + aux["moe_balance_loss"] + aux["moe_z_loss"]
    return loss, {"loss": loss, "ce_loss": ce, "tokens": n, **aux}


def pp_value_and_grad(
    params: dict, batch: dict, cfg: MixtralConfig, mesh, num_microbatches: int = 2,
    wire_dtype=jnp.bfloat16,
) -> tuple[jax.Array, dict, dict]:
    """1F1B pipeline train-step core for the MoE model: ``(loss, metrics,
    grads)``, grads shaped like ``params`` — the PP×EP deployment shape of
    an 8×7B (SURVEY.md §2.5 PP row; experts stay stage-local, so the ragged
    grouped-GEMM dispatch runs unsharded inside each stage).

    MoE aux losses (balance + z) thread through the hand-scheduled backward
    as a per-stage scalar with a matching cotangent seed
    (parallel/pipeline.spmd_pipeline_1f1b ``stage_has_aux``): the objective
    is ``CE_mean + aux_mean`` where aux is averaged over microbatches — the
    standard per-group approximation of the full-batch balance statistic.
    Packed batches (segment_ids) compose: confinement, per-segment RoPE,
    pad-aware routing, and boundary target masking all apply per microbatch.

    Wire-dtype note: the default bf16 wire quantizes each stage's input
    activations, which can flip near-tie top-k routing choices relative to
    an unpipelined f32 run — bounded routing jitter (equivalent to the
    bf16 activations every stage>0 layer already sees), not an error; pass
    ``wire_dtype=jnp.float32`` when bitwise routing stability matters.
    """
    from tony_tpu.parallel.pipeline import spmd_pipeline_1f1b, split_layers_into_stages

    S = mesh.shape.get("stage", 1)
    if S <= 1:
        (loss, metrics), grads = jax.value_and_grad(
            lambda p: loss_fn(p, batch, cfg, mesh), has_aux=True
        )(params)
        return loss, metrics, grads
    if mesh.shape.get("context", 1) > 1:
        raise ValueError("pipeline parallelism does not compose with a context axis")
    if mesh.shape.get("expert", 1) > 1:
        raise ValueError(
            "stage_axis > 1 keeps experts stage-local (ragged dispatch inside "
            "each stage) — use an expert axis of 1 with pipeline parallelism"
        )
    tokens = batch["tokens"]
    T = tokens.shape[1] - 1
    cos, sin = L.rope_frequencies(cfg.head_dim, T, cfg.rope_theta, cfg.rope_scaling)

    def stage_fn(stage_lp, h, mb):
        seg = mb.get("segment_ids")
        seg_in = seg[:, :-1] if seg is not None else None
        positions = llama_mod.segment_positions(seg_in) if seg_in is not None else None
        token_mask = (seg_in != 0) if seg_in is not None else None

        def block(carry, lp):
            x, aux_acc = carry
            x, aux = _layer(
                x, lp, cos, sin, cfg, None,
                segment_ids=seg_in, positions=positions, token_mask=token_mask,
            )
            return (x, aux_acc + aux["moe_balance_loss"] + aux["moe_z_loss"]), None

        block_fn = attn_ops.remat_block(block, cfg.remat, cfg.remat_policy)
        (h, aux), _ = jax.lax.scan(block_fn, (h, jnp.zeros((), jnp.float32)), stage_lp)
        return h, aux

    def embed_fn(embed_p, mb):
        return jnp.take(embed_p, mb["tokens"][:, :-1], axis=0)

    def loss_head_fn(head_p, y, mb):
        targets, _ = llama_mod.mask_packed_targets(mb["tokens"], mb.get("segment_ids"))
        x = L.rms_norm(y, head_p["final_norm"], cfg.norm_eps)
        mean, n = L.chunked_cross_entropy_loss(
            x, head_p["lm_head"], targets, chunk=cfg.ce_chunk
        )
        # true count, not the CE's >=1 clamp: keeps ntok == ntok_pre so the
        # aux cotangent lands at exactly unit scale (see seed note below)
        return mean * n, jnp.sum(targets != -100)

    # the valid-target count is computable before the schedule runs; seeding
    # the aux cotangent with it makes the post-hoc /ntok division land the
    # aux gradients at exactly unit scale (see spmd_pipeline_1f1b docstring)
    targets_all, _ = llama_mod.mask_packed_targets(tokens, batch.get("segment_ids"))
    ntok_pre = jnp.sum(targets_all != -100).astype(jnp.float32)

    pp_batch = {"tokens": tokens}
    if "segment_ids" in batch:
        pp_batch["segment_ids"] = batch["segment_ids"]
    stages = split_layers_into_stages(params["layers"], S)
    head_params = {"final_norm": params["final_norm"], "lm_head": params["lm_head"]}
    nll, ntok, aux_total, (dstage, dembed, dhead) = spmd_pipeline_1f1b(
        stage_fn, stages, pp_batch, params["embed"], head_params,
        embed_fn, loss_head_fn,
        mesh=mesh, num_microbatches=num_microbatches, wire_dtype=wire_dtype,
        compute_dtype=cfg.jdtype, stage_has_aux=True, aux_seed_scale=ntok_pre,
    )
    ce = nll / jnp.maximum(ntok, 1.0)
    loss = ce + aux_total
    inv = 1.0 / jnp.maximum(ntok, 1.0)
    d_layers = jax.tree.map(
        lambda g, p: (g.reshape(cfg.n_layers, *g.shape[2:]) * inv).astype(p.dtype),
        dstage, params["layers"],
    )
    grads = {
        "embed": (dembed * inv).astype(params["embed"].dtype),
        "layers": d_layers,
        "final_norm": (dhead["final_norm"] * inv).astype(params["final_norm"].dtype),
        "lm_head": (dhead["lm_head"] * inv).astype(params["lm_head"].dtype),
    }
    metrics = {"loss": loss, "ce_loss": ce, "tokens": ntok, "moe_aux_loss": aux_total}
    return loss, metrics, grads


synthetic_batch = llama_mod.synthetic_batch


def config_from_dict(d: dict | str) -> MixtralConfig:
    if isinstance(d, str):
        return PRESETS[d]
    fields = {f.name for f in dataclasses.fields(MixtralConfig)}
    return dataclasses.replace(
        PRESETS.get(d.get("preset", ""), MixtralConfig()),
        **{k: v for k, v in d.items() if k in fields},
    )
