"""Mixtral-style sparse-MoE decoder (BASELINE.json config #5).

Llama backbone (RMSNorm / RoPE / GQA attention, scanned stacked layers) with
the dense FFN replaced by a top-2-of-E SwiGLU mixture routed per token
(parallel/expert.py); expert weights shard over the ``expert`` mesh axis.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tony_tpu.models import llama as llama_mod
from tony_tpu.ops import attention as attn_ops
from tony_tpu.ops import layers as L
from tony_tpu.parallel.expert import MoEConfig, moe_ffn
from tony_tpu.parallel.sharding import ShardingRules, constrain

BATCH_AXES = llama_mod.BATCH_AXES


@dataclass(frozen=True)
class MixtralConfig(llama_mod.LlamaConfig):
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    moe_dispatch: str = "gather"  # gather (indexed) | dense (GShard einsum)

    @property
    def moe(self) -> MoEConfig:
        return MoEConfig(
            self.num_experts, self.top_k, self.capacity_factor,
            dispatch=self.moe_dispatch,
        )

    def num_params(self) -> int:
        base = super().num_params()
        D, F = self.d_model, self.d_ff
        dense_ffn = self.n_layers * 3 * D * F
        moe_ffn_params = self.n_layers * (self.num_experts * 3 * D * F + D * self.num_experts)
        return base - dense_ffn + moe_ffn_params

    def active_params(self) -> int:
        """Params touched per token (top-k of E experts) — the MFU basis."""
        D, F = self.d_model, self.d_ff
        dense_ffn = self.n_layers * 3 * D * F
        active_ffn = self.n_layers * (self.top_k * 3 * D * F + D * self.num_experts)
        return super().num_params() - dense_ffn + active_ffn

    def flops_per_token(self) -> int:
        from tony_tpu.train.metrics import transformer_flops_per_token

        return transformer_flops_per_token(
            self.active_params(), self.n_layers, self.d_model, self.max_seq, training=True
        )


MIXTRAL_8X7B = MixtralConfig(
    vocab_size=32_000, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8,
    d_ff=14_336, max_seq=8192, rope_theta=1e6, num_experts=8, top_k=2,
    sliding_window=4096,  # real Mixtral-8x7B (v0.1) uses a 4096 SWA band
)
MIXTRAL_TINY = MixtralConfig(
    vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=128,
    max_seq=128, num_experts=4, top_k=2, remat=False, attn_impl="reference",
)
PRESETS = {"mixtral-8x7b": MIXTRAL_8X7B, "tiny": MIXTRAL_TINY}


def init(key: jax.Array, cfg: MixtralConfig) -> dict:
    D, F, E, Lyr = cfg.d_model, cfg.d_ff, cfg.num_experts, cfg.n_layers
    dt = cfg.jdtype
    base = llama_mod.init(key, cfg)
    ks = jax.random.split(jax.random.fold_in(key, 1), 4)

    def dense(k, *shape, fan_in):
        return (jax.random.truncated_normal(k, -2, 2, shape, jnp.float32) * fan_in**-0.5).astype(dt)

    layers = dict(base["layers"])
    for gone in ("w_gate", "w_up", "w_down"):
        del layers[gone]
    layers.update(
        router=dense(ks[0], Lyr, D, E, fan_in=D).astype(jnp.float32),
        we_gate=dense(ks[1], Lyr, E, D, F, fan_in=D),
        we_up=dense(ks[2], Lyr, E, D, F, fan_in=D),
        we_down=dense(ks[3], Lyr, E, F, D, fan_in=F),
    )
    base["layers"] = layers
    return base


def sharding_rules(cfg: MixtralConfig) -> ShardingRules:
    return ShardingRules([
        (r"embed", P("model", "fsdp")),
        (r"layers/(wq|wk|wv)", P(None, "fsdp", "model")),
        (r"layers/wo", P(None, "model", "fsdp")),
        (r"layers/router", P(None, None, None)),
        (r"layers/(we_gate|we_up)", P(None, "expert", "fsdp", "model")),
        (r"layers/we_down", P(None, "expert", "model", "fsdp")),
        (r"layers/.*norm", P(None, None)),
        (r"final_norm", P(None)),
        (r"lm_head", P("fsdp", "model")),
    ])


def hidden_states(
    params: dict, tokens: jax.Array, cfg: MixtralConfig, mesh=None, segment_ids=None
) -> tuple[jax.Array, dict]:
    """tokens [B, T] → (final-norm hidden states [B, T, D], moe aux losses).

    ``segment_ids`` [B, T] (packed sequences): segment-confined attention +
    per-segment RoPE positions, same contract as llama.hidden_states."""
    B, T = tokens.shape
    Dh, H, Hkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    cos, sin = L.rope_frequencies(Dh, T, cfg.rope_theta, cfg.rope_scaling)
    positions = (
        llama_mod.segment_positions(segment_ids) if segment_ids is not None else None
    )
    token_mask = (segment_ids != 0) if segment_ids is not None else None
    act_spec = P(BATCH_AXES, "context", None)

    x = jnp.take(params["embed"], tokens, axis=0)
    if mesh is not None:
        x = constrain(x, mesh, act_spec)

    def block(carry, lp):
        x, aux_acc = carry
        h = L.rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q = jnp.einsum("btd,dh->bth", h, lp["wq"]).reshape(B, T, H, Dh).transpose(0, 2, 1, 3)
        k = jnp.einsum("btd,dh->bth", h, lp["wk"]).reshape(B, T, Hkv, Dh).transpose(0, 2, 1, 3)
        v = jnp.einsum("btd,dh->bth", h, lp["wv"]).reshape(B, T, Hkv, Dh).transpose(0, 2, 1, 3)
        q = L.apply_rope(q, cos, sin, positions=positions)
        k = L.apply_rope(k, cos, sin, positions=positions)
        o = llama_mod._attention(q, k, v, cfg, mesh, segment_ids=segment_ids)
        o = o.transpose(0, 2, 1, 3).reshape(B, T, H * Dh)
        x = x + jnp.einsum("bth,hd->btd", o, lp["wo"])
        if mesh is not None:
            x = constrain(x, mesh, act_spec)
        h = L.rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        y, aux = moe_ffn(
            h, lp["router"], lp["we_gate"], lp["we_up"], lp["we_down"], cfg.moe,
            mesh, token_mask=token_mask,
        )
        x = x + y
        if mesh is not None:
            x = constrain(x, mesh, act_spec)
        aux_acc = {
            "moe_balance_loss": aux_acc["moe_balance_loss"] + aux["moe_balance_loss"],
            "moe_z_loss": aux_acc["moe_z_loss"] + aux["moe_z_loss"],
            "moe_dropped_frac": aux_acc["moe_dropped_frac"] + aux["moe_dropped_frac"] / cfg.n_layers,
        }
        return (x, aux_acc), None

    aux0 = {k: jnp.zeros((), jnp.float32) for k in ("moe_balance_loss", "moe_z_loss", "moe_dropped_frac")}
    block_fn = attn_ops.remat_block(block, cfg.remat, cfg.remat_policy)
    (x, aux), _ = jax.lax.scan(block_fn, (x, aux0), params["layers"])

    return L.rms_norm(x, params["final_norm"], cfg.norm_eps), aux


def forward(
    params: dict, tokens: jax.Array, cfg: MixtralConfig, mesh=None, segment_ids=None
) -> tuple[jax.Array, dict]:
    """tokens [B, T] → (logits [B, T, V], moe aux losses summed over layers)."""
    x, aux = hidden_states(params, tokens, cfg, mesh, segment_ids=segment_ids)
    logits = jnp.einsum("btd,dv->btv", x, params["lm_head"])
    return logits, aux


def loss_fn(params: dict, batch: dict, cfg: MixtralConfig, mesh=None) -> tuple[jax.Array, dict]:
    """With ``cfg.ce_chunk > 0`` the lm-head + CE fuse per sequence chunk so
    the [B, T, V] logits never materialize; packed batches (segment_ids)
    get segment-confined attention and boundary/pad target masking (same
    scheme as llama.loss_fn)."""
    tokens = batch["tokens"]
    targets, seg_in = llama_mod.mask_packed_targets(tokens, batch.get("segment_ids"))
    if cfg.ce_chunk > 0:
        x, aux = hidden_states(params, tokens[:, :-1], cfg, mesh, segment_ids=seg_in)
        ce, n = L.chunked_cross_entropy_loss(
            x, params["lm_head"], targets, chunk=cfg.ce_chunk
        )
    else:
        logits, aux = forward(params, tokens[:, :-1], cfg, mesh, segment_ids=seg_in)
        ce, n = L.cross_entropy_loss(logits, targets)
    loss = ce + aux["moe_balance_loss"] + aux["moe_z_loss"]
    return loss, {"loss": loss, "ce_loss": ce, "tokens": n, **aux}


synthetic_batch = llama_mod.synthetic_batch


def config_from_dict(d: dict | str) -> MixtralConfig:
    if isinstance(d, str):
        return PRESETS[d]
    fields = {f.name for f in dataclasses.fields(MixtralConfig)}
    return dataclasses.replace(
        PRESETS.get(d.get("preset", ""), MixtralConfig()),
        **{k: v for k, v in d.items() if k in fields},
    )
