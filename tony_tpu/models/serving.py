"""Continuous-batching decode engine (Llama + Mixtral families).

The reference orchestrates training jobs only — serving is new capability
(SURVEY.md §2.5 "absent" rows); this is the slot-based engine layer above
models/generate.py. TPU shape discipline: one compiled decode step serves a
FIXED number of slots against a FIXED-length KV cache; requests of any
length flow through by admission into free slots (prefill, padded to
power-of-two buckets so the jit cache stays small) and per-slot position
masking — no dynamic shapes ever reach XLA.

The decode step is SLOT-NATIVE (r3 rewrite): one layer scan over a
[L, S, Hkv, maxT, Dh] cache runs every slot's token through batched
projections and FFN (so the Mixtral mixture runs once over all slots, not
vmapped per slot), with per-slot cache positions. Attention picks one of
two implementations:

- ``ragged`` (TPU): the Pallas per-slot-length kernel
  (ops/decode_attention.py) — each slot streams only ITS OWN cache length
  (and only the window for SWA models), so step cost follows Σ len_s and a
  single long-lived request no longer taxes every slot (r2 weak #3);
- ``bucketed`` (portable XLA): masked attention over the shortest
  power-of-two cache prefix covering every active slot — the r2 scheme,
  kept as the CPU/test path and fallback.

Host/device split: admission, queueing, EOS/termination bookkeeping run on
the host between steps (microseconds, overlapped with the device step);
everything per-token is one jitted call over all slots. Weights may be an
int8-quantized tree (ops/quant.py) for the dense family — the same ``_mm``
dispatch as generate.py serves both.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from tony_tpu.models.generate import (
    KVCache,
    _embed_lookup,
    _ffn_with_cache,
    _forward_with_cache,
    _masked_slot_attention,
    _mm,
    _sample,
    init_cache,
)
from tony_tpu.models.llama import LlamaConfig
from tony_tpu.ops import layers as L


class SlotCache(NamedTuple):
    """Decode state for S slots. k/v: [L, S, Hkv, maxT, Dh]; lengths: [S]."""

    k: jax.Array
    v: jax.Array
    lengths: jax.Array  # int32 [S] — tokens already cached per slot


def init_slot_cache(cfg: LlamaConfig, num_slots: int, max_len: int) -> SlotCache:
    shape = (cfg.n_layers, num_slots, cfg.n_kv_heads, max_len, cfg.head_dim)
    return SlotCache(
        k=jnp.zeros(shape, cfg.jdtype),
        v=jnp.zeros(shape, cfg.jdtype),
        lengths=jnp.zeros((num_slots,), jnp.int32),
    )


# decode attention lives in generate.py (_masked_slot_attention) — ONE
# implementation shared with generate()'s decode steps, so the two paths
# cannot diverge in attention math


def _decode_one(
    params, cache: SlotCache, tokens: jax.Array, key: jax.Array,
    cfg: LlamaConfig, temperature: float = 0.0, top_k: int = 0, attn: str = "bucketed",
):
    """One token for every slot, slot-native: (next tokens [S], cache').

    Each slot runs at its own position (cache.lengths[s], clamped at
    maxT-1). Inactive slots decode garbage harmlessly; the host ignores
    them. Projections and the FFN (dense SwiGLU or the Mixtral mixture —
    generate._ffn_with_cache) run batched over the slot dim.
    """
    S = tokens.shape[0]
    Dh, H, Hkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    maxT = cache.k.shape[3]
    cos, sin = L.rope_frequencies(Dh, maxT, cfg.rope_theta, cfg.rope_scaling)
    # KERNEL PRECONDITION: active slots have lengths < maxT (enforced by
    # submit()'s prompt+budget <= max_len check). A slot clamped AT maxT
    # would attend both the stale cached entry at maxT-1 and the current
    # token (double-counting one position) in the read-only-cache split —
    # only retired-not-yet-flushed slots decoding discarded overshoot
    # tokens can reach that state, and their output is never read.
    pos = jnp.minimum(cache.lengths, maxT - 1)                      # write position
    x = _embed_lookup(params["embed"], tokens[:, None], cfg.jdtype)  # [S, 1, D]

    # The big cache tensors are scan XS (read-only): attention sees the OLD
    # cache plus the current token's K/V explicitly, and the scan emits only
    # the tiny [S, Hkv, Dh] new K/V per layer. Carrying the updated cache
    # through the scan instead (the first r3 design) stacked a full cache
    # copy as scan ys EVERY token — measured −32% decode tok/s at 64 slots.
    def layer(x, inputs):
        lp, ck, cv = inputs  # ck/cv [S, Hkv, maxT, Dh], read-only
        h = L.rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q = _mm(h, lp["wq"]).reshape(S, 1, H, Dh).transpose(0, 2, 1, 3)
        k = _mm(h, lp["wk"]).reshape(S, 1, Hkv, Dh).transpose(0, 2, 1, 3)
        v = _mm(h, lp["wv"]).reshape(S, 1, Hkv, Dh).transpose(0, 2, 1, 3)
        q = L.apply_rope(q, cos, sin, positions=pos[:, None])
        k = L.apply_rope(k, cos, sin, positions=pos[:, None])
        k1 = k[:, :, 0].astype(ck.dtype)                             # [S, Hkv, Dh]
        v1 = v[:, :, 0].astype(cv.dtype)
        if attn == "ragged":
            from tony_tpu.ops.decode_attention import ragged_decode_attention

            o = ragged_decode_attention(
                q[:, :, 0], ck, cv, pos, cur_k=k1, cur_v=v1,
                window=cfg.sliding_window,
            )
        else:
            o = _masked_slot_attention(
                q[:, :, 0], ck, cv, pos, H // Hkv, window=cfg.sliding_window,
                cur_k=k1, cur_v=v1,
            )
        x = x + _mm(o.reshape(S, 1, H * Dh), lp["wo"])
        h = L.rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        x = x + _ffn_with_cache(h, lp, cfg)
        return x, (k1, v1)

    x, (ks_new, vs_new) = jax.lax.scan(layer, x, (params["layers"], cache.k, cache.v))
    x = L.rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = _mm(x[:, 0], params["lm_head"]).astype(jnp.float32)     # [S, V]
    nxt = _sample(logits, key, temperature, top_k)

    # single write: scatter each slot's [L, Hkv, Dh] column at its position
    # (the donated cache updates in place — no full-cache copy per token)
    def write_slot(c, kv, p):
        # c [L, Hkv, maxT, Dh]; kv [L, Hkv, Dh]
        return jax.lax.dynamic_update_slice(c, kv[:, :, None], (0, 0, p, 0))

    ks = jax.vmap(write_slot, in_axes=(1, 1, 0), out_axes=1)(cache.k, ks_new, pos)
    vs = jax.vmap(write_slot, in_axes=(1, 1, 0), out_axes=1)(cache.v, vs_new, pos)
    # idle slots (length 0 — flushed retirements / never admitted) stay at 0
    # instead of regrowing +1 per step: their stale cache never re-enters
    # the ragged kernel's Σ len_s (active slots always have length ≥ 1)
    new_len = jnp.where(
        cache.lengths > 0, jnp.minimum(cache.lengths + 1, maxT), 0
    )
    return nxt, SlotCache(ks, vs, new_len)


decode_step = functools.partial(
    jax.jit, static_argnames=("cfg", "temperature", "top_k", "attn"), donate_argnums=(1,)
)(_decode_one)


@functools.partial(
    jax.jit, static_argnames=("cfg", "n", "temperature", "top_k", "attn"),
    donate_argnums=(1,),
)
def decode_steps(
    params, cache: SlotCache, tokens: jax.Array, key: jax.Array,
    cfg: LlamaConfig, n: int, temperature: float = 0.0, top_k: int = 0,
    attn: str = "ragged",
):
    """``n`` decode steps in ONE compiled call (lax.scan): (tokens [S],
    all tokens [n, S], cache'). Amortizes per-dispatch host overhead —
    the dominant cost of single-token steps on remote/tunneled backends.
    With ``attn='ragged'`` the Pallas kernel reads each slot's own cache
    length, so no bucketing is needed (or helpful)."""

    def body(carry, k_step):
        cache, toks = carry
        nxt, cache = _decode_one(params, cache, toks, k_step, cfg, temperature, top_k, attn)
        return (cache, nxt), nxt

    (cache, toks), seq = jax.lax.scan(body, (cache, tokens), jax.random.split(key, n))
    return toks, seq, cache


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "n", "bucket", "temperature", "top_k"),
    donate_argnums=(1,),
)
def decode_steps_bucketed(
    params, cache: SlotCache, tokens: jax.Array, key: jax.Array,
    cfg: LlamaConfig, n: int, bucket: int, temperature: float = 0.0, top_k: int = 0,
):
    """``decode_steps`` over a LENGTH-BUCKETED cache view (XLA fallback):
    attention reads only the first ``bucket`` cache positions (a power of
    two ≥ the longest active slot + n, chosen by the host), then the grown
    view is written back into the full cache. Portable but global — one
    long slot drags every slot to its bucket; the ragged path doesn't.
    One jit variant per bucket (powers of two → log(max_len) variants)."""
    sub = SlotCache(cache.k[:, :, :, :bucket], cache.v[:, :, :, :bucket], cache.lengths)

    def body(carry, k_step):
        c, toks = carry
        nxt, c = _decode_one(params, c, toks, k_step, cfg, temperature, top_k, "bucketed")
        return (c, nxt), nxt

    (sub, toks), seq = jax.lax.scan(body, (sub, tokens), jax.random.split(key, n))
    k = jax.lax.dynamic_update_slice(cache.k, sub.k, (0, 0, 0, 0, 0))
    v = jax.lax.dynamic_update_slice(cache.v, sub.v, (0, 0, 0, 0, 0))
    return toks, seq, SlotCache(k, v, sub.lengths)


def _bucket(n: int, lo: int = 16) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


# one jit variant per (prompt bucket, cache length) — buckets are powers of
# two so the variant count stays logarithmic in max_len
_prefill_padded = jax.jit(_forward_with_cache, static_argnames=("cfg",))


@functools.partial(jax.jit, donate_argnums=(0,))
def _insert_prefill(cache: SlotCache, pre: KVCache, slot: jax.Array, true_len: jax.Array):
    """Copy a 1-request prefill cache [L, 1, Hkv, maxT, Dh] into ``slot``."""
    k = jax.lax.dynamic_update_slice(cache.k, pre.k, (0, slot, 0, 0, 0))
    v = jax.lax.dynamic_update_slice(cache.v, pre.v, (0, slot, 0, 0, 0))
    lengths = cache.lengths.at[slot].set(true_len)
    return SlotCache(k, v, lengths)


@dataclass
class _Request:
    rid: int
    prompt: list[int]
    max_new_tokens: int
    out: list[int] = field(default_factory=list)
    slot: int = -1

    def is_done(self, eos_id: int) -> bool:
        """THE termination predicate — budget spent or EOS emitted. Both the
        chunk-drain loop and retirement consult this one method."""
        return len(self.out) >= self.max_new_tokens or (
            eos_id >= 0 and bool(self.out) and self.out[-1] == eos_id
        )


class ContinuousBatcher:
    """Slot-based continuous batching: admit → decode → retire, every step.

    One engine instance owns S slots over a shared static KV cache. Requests
    are admitted into free slots as they arrive (prefill padded to a bucket
    so prompt-length jit variants stay bounded) and retire independently on
    EOS or their token budget — the running batch never drains to admit new
    work, which is the throughput property batch-of-one ``generate()`` lacks.

    ``attn``: "auto" (CPU: always bucketed; TPU: bucketed while every
    active slot fits a short bucket, the ragged Pallas kernel once the
    needed bucket crosses ``ragged_threshold`` — short regimes are
    XLA-batched-einsum-friendly, long/straggler regimes are where per-slot
    reads pay), or force "ragged"/"bucketed". Works for Llama and Mixtral
    param trees — the decode step dispatches the FFN on the layer keys.
    """

    #: needed-bucket size above which "auto" switches to the ragged kernel
    RAGGED_THRESHOLD = 512

    def __init__(
        self, params, cfg: LlamaConfig, *, num_slots: int = 8, max_len: int = 512,
        eos_id: int = -1, temperature: float = 0.0, top_k: int = 0,
        key: jax.Array | None = None, decode_chunk: int = 8, attn: str = "auto",
        prefill_chunk: int = 0,
    ):
        if num_slots < 1 or max_len < 1:
            raise ValueError(f"need num_slots>=1 and max_len>=1, got {num_slots}/{max_len}")
        if attn == "auto" and jax.default_backend() == "cpu":
            attn = "bucketed"
        if attn not in ("auto", "ragged", "bucketed"):
            raise ValueError(f"attn must be auto|ragged|bucketed, got {attn!r}")
        if attn == "auto" and max_len <= self.RAGGED_THRESHOLD:
            attn = "bucketed"  # ragged could never engage at this max_len
        if attn in ("auto", "ragged") and max_len % 128:
            raise ValueError(f"attn={attn!r} needs max_len % 128 == 0, got {max_len}")
        self.params, self.cfg = params, cfg
        self.S, self.max_len, self.eos_id = num_slots, max_len, eos_id
        self.temperature, self.top_k = temperature, top_k
        self.attn = attn
        # decode this many tokens per compiled call; requests finishing
        # mid-chunk simply DISCARD their overshoot tokens (see step()). >1
        # amortizes host dispatch overhead at the cost of admission latency
        self.decode_chunk = max(1, decode_chunk)
        # >0: long prompts prefill in chunks of this many tokens, ONE chunk
        # per engine step, so a long admission can't stall running decodes
        # for more than ~one chunk's compute. Middle chunks are EXACT
        # length (cache positions must be true); only the final partial
        # chunk pads to a bucket (garbage K/V past the prompt is masked by
        # the slot length, as in the unchunked path).
        self.prefill_chunk = prefill_chunk
        self.cache = init_slot_cache(cfg, num_slots, max_len)
        self.tokens = jnp.zeros((num_slots,), jnp.int32)  # last token per slot
        self.key = key if key is not None else jax.random.PRNGKey(0)
        self.pending: list[_Request] = []
        self.running: dict[int, _Request] = {}   # slot → request
        self.done: dict[int, list[int]] = {}
        # slots retired since the last flush: their device-side lengths are
        # zeroed in ONE batched update per step — a per-retirement
        # ``lengths.at[slot].set(0)`` dispatch costs this backend's ~10 ms
        # dispatch floor EACH, which measured as a −25% tok/s engine tax
        # when a whole batch retires together (r3-cont)
        self._retired_slots: list[int] = []
        self._next_rid = 0
        # streaming cursor per request: drain_stream() hands out tokens
        # appended since the last drain (serving_http's SSE path)
        self._stream_pos: dict[int, int] = {}
        self._stream_done: set[int] = set()
        # prefill state machine entries, dispatched ahead of slot
        # availability (overlap with the in-flight decode chunk):
        # [request, prefill cache, tokens prefilled, first token | None]
        self._staged: list[list] = []
        self._slot_len = [0] * num_slots  # host mirror of cache.lengths

    def submit(self, prompt, max_new_tokens: int) -> int:
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("prompt must be non-empty")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt {len(prompt)} + max_new_tokens {max_new_tokens} "
                f"exceeds engine max_len {self.max_len}"
            )
        rid = self._next_rid
        self._next_rid += 1
        self.pending.append(_Request(rid, prompt, max_new_tokens))
        return rid

    # -- engine internals ---------------------------------------------------

    def _free_slots(self) -> list[int]:
        return [s for s in range(self.S) if s not in self.running]

    def _stage_prefills(self, budget: int, advance: bool = True):
        """Stage up to ``budget`` pending requests and (when ``advance``)
        run prefill work for every staged entry. The advancing call site is
        AFTER the decode chunk is dispatched, so prefill compute queues
        behind it instead of delaying it; admission-time staging passes
        ``advance=False`` (unless nothing is decoding) to keep the
        one-chunk-per-step stall bound honest."""
        while self.pending and len(self._staged) < budget:
            req = self.pending.pop(0)
            self._staged.append([req, init_cache(self.cfg, 1, self.max_len), 0, None])
        if advance:
            for entry in self._staged:
                self._advance_prefill(entry)

    def _advance_prefill(self, entry) -> None:
        """Run one prefill chunk (or the whole prompt when unchunked)."""
        req, pre, pos, first = entry
        if first is not None:
            return
        Tp = len(req.prompt)
        step = self.prefill_chunk if self.prefill_chunk > 0 else Tp
        while first is None:
            take = min(step, Tp - pos)
            last = pos + take >= Tp
            if last:
                # cap the pad so the padded write NEVER runs past max_len —
                # dynamic_update_slice would clamp the start and silently
                # shift real prompt K/V (caught by review repro: prompt 59,
                # chunk 8, max_len 64 corrupted positions 48..59)
                pad = min(_bucket(take), self.max_len - pos) - take
            else:
                pad = 0  # middle chunks are exact: cache positions stay true
            toks = jnp.array(
                req.prompt[pos:pos + take] + [0] * pad, jnp.int32
            )[None, :]
            # padded positions write garbage K/V past Tp; decode masks them
            # out via lengths[slot] = Tp, and causality protects the prefix
            logits, pre = _prefill_padded(self.params, toks, pre, self.cfg)
            pos += take
            if last:
                first = _sample(
                    logits[:, take - 1].astype(jnp.float32), self._split(),
                    self.temperature, self.top_k,
                )
            entry[1], entry[2], entry[3] = pre, pos, first
            if self.prefill_chunk > 0:
                break  # one chunk per engine step — decode interleaves

    def _admit(self):
        free = self._free_slots()
        # only compute prefills here when nothing is decoding (startup /
        # drain); otherwise they advance after the decode chunk dispatches
        self._stage_prefills(len(free), advance=not self.running)
        while self._staged and free and self._staged[0][3] is not None:
            req, pre, _, first = self._staged.pop(0)
            slot = free.pop(0)
            Tp = len(req.prompt)
            self.cache = _insert_prefill(
                self.cache, pre, jnp.int32(slot), jnp.int32(Tp)
            )
            self.tokens = self.tokens.at[slot].set(first[0])
            self._slot_len[slot] = Tp
            req.slot = slot
            req.out.append(int(first[0]))
            self.running[slot] = req
            self._retire_if_done(req)  # 1-token requests finish at admission

    def _split(self):
        if self.temperature == 0.0:
            return self.key  # greedy sampling never consumes the key
        self.key, sub = jax.random.split(self.key)
        return sub

    def _retire_if_done(self, req: _Request):
        if req.slot in self.running and req.is_done(self.eos_id):
            del self.running[req.slot]
            self.done[req.rid] = req.out
            self._retired_slots.append(req.slot)
            self._slot_len[req.slot] = 0

    def _flush_retired(self):
        """Zero retired slots' device-side lengths in ONE update (idle slots
        would otherwise keep advancing, clamped at maxT, and the ragged
        kernel would stream their stale cache every step). Slots re-admitted
        since retirement are skipped — their length is live again."""
        idle = [s for s in self._retired_slots if s not in self.running]
        self._retired_slots = []
        if idle:
            self.cache = SlotCache(
                self.cache.k, self.cache.v,
                self.cache.lengths.at[jnp.asarray(idle, jnp.int32)].set(0),
            )

    def step(self) -> bool:
        """Admit + one decode chunk. Returns True while work remains."""
        self._admit()
        self._flush_retired()
        if not self.running:
            return bool(self.pending or self._staged)
        # constant chunk height = ONE compiled decode variant; slots whose
        # request finishes mid-chunk simply discard the overshoot tokens
        # (their cache writes clamp at the view's end and the slot is fully
        # overwritten at its next admission)
        h = self.decode_chunk
        needed = max(self._slot_len[s] for s in self.running) + h
        bucket = min(_bucket(max(needed, 1)), self.max_len)
        use_ragged = self.attn == "ragged" or (
            self.attn == "auto" and bucket > self.RAGGED_THRESHOLD
        )
        if use_ragged:
            toks, seq, self.cache = decode_steps(
                self.params, self.cache, self.tokens, self._split(), self.cfg, h,
                self.temperature, self.top_k, "ragged",
            )
        else:
            # length bucket: attention reads only the shortest power-of-two
            # cache prefix covering every active slot through this chunk
            toks, seq, self.cache = decode_steps_bucketed(
                self.params, self.cache, self.tokens, self._split(), self.cfg, h,
                bucket, self.temperature, self.top_k,
            )
        self.tokens = toks
        # overlap: queue prefills for the next admissions while the chunk
        # (already dispatched, still in flight) computes; one speculative
        # stage beyond the currently-free slots covers mid-chunk retirement
        self._stage_prefills(max(len(self._free_slots()), 1))
        seq_host = np.asarray(seq)  # [h, S]: ONE device→host transfer
        for slot in self.running:
            self._slot_len[slot] = min(self._slot_len[slot] + h, self.max_len)
        for slot, req in list(self.running.items()):
            for i in range(h):
                req.out.append(int(seq_host[i, slot]))
                if req.is_done(self.eos_id):
                    break  # post-budget/post-EOS chunk tokens are discarded
            self._retire_if_done(req)
        more = bool(self.running or self.pending or self._staged)
        if not more:
            # drained: zero the final chunk's retirees now — cache.lengths is
            # externally observable and must agree with _slot_len between runs
            self._flush_retired()
        return more

    def drain_stream(self) -> dict[int, tuple[list[int], bool]]:
        """Tokens appended per request since the last drain:
        {rid: (new_tokens, finished)}. Pure host-side bookkeeping (reads
        ``req.out`` cursors) — call between ``step()``s to stream
        incrementally; a finished request is reported exactly once with its
        final tokens and then forgotten."""
        out: dict[int, tuple[list[int], bool]] = {}
        # prune: once a finished request is popped from ``done`` by the
        # caller, its dedup entry has no further use — without this the set
        # grows with every request a long-lived server ever finishes
        self._stream_done &= self.done.keys()
        for rid, toks in self.done.items():
            if rid not in self._stream_done:
                pos = self._stream_pos.pop(rid, 0)
                out[rid] = (list(toks[pos:]), True)
                self._stream_done.add(rid)
        live = [e[0] for e in self._staged] + list(self.pending) + list(self.running.values())
        for req in live:
            if req.rid in self._stream_done or req.rid in out:
                continue
            pos = self._stream_pos.get(req.rid, 0)
            if len(req.out) > pos:
                out[req.rid] = (list(req.out[pos:]), False)
                self._stream_pos[req.rid] = len(req.out)
        return out

    def run(self) -> dict[int, list[int]]:
        """Drain all submitted requests; returns {request_id: tokens}."""
        while self.step():
            pass
        return dict(self.done)
