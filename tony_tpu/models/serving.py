"""Continuous-batching decode engine (Llama + Mixtral families).

The reference orchestrates training jobs only — serving is new capability
(SURVEY.md §2.5 "absent" rows); this is the slot-based engine layer above
models/generate.py. TPU shape discipline: one compiled decode step serves a
FIXED number of slots against a FIXED-length KV cache; requests of any
length flow through by admission into free slots (prefill, padded to
power-of-two buckets so the jit cache stays small) and per-slot position
masking — no dynamic shapes ever reach XLA.

The decode step is SLOT-NATIVE (r3 rewrite): one layer scan over a
[L, S, Hkv, maxT, Dh] cache runs every slot's token through batched
projections and FFN (so the Mixtral mixture runs once over all slots, not
vmapped per slot), with per-slot cache positions. Attention picks one of
two implementations:

- ``ragged`` (TPU): the Pallas per-slot-length kernel
  (ops/decode_attention.py) — each slot streams only ITS OWN cache length
  (and only the window for SWA models), so step cost follows Σ len_s and a
  single long-lived request no longer taxes every slot (r2 weak #3);
- ``bucketed`` (portable XLA): masked attention over the shortest
  power-of-two cache prefix covering every active slot — the r2 scheme,
  kept as the CPU/test path and fallback.

Host/device split: admission, queueing, EOS/termination bookkeeping run on
the host between steps (microseconds, overlapped with the device step);
everything per-token is one jitted call over all slots. Weights may be an
int8-quantized tree (ops/quant.py) for the dense family — the same ``_mm``
dispatch as generate.py serves both.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from tony_tpu.models.generate import (
    KVCache,
    _embed_lookup,
    _ffn_with_cache,
    _forward_with_cache,
    _masked_slot_attention,
    _mm,
    _sample,
    init_cache,
    sample_logits,
)
from tony_tpu.models.llama import LlamaConfig
from tony_tpu.ops import layers as L


class SlotCache(NamedTuple):
    """Decode state for S slots. k/v: [L, S, Hkv, maxT, Dh]; lengths: [S]."""

    k: jax.Array
    v: jax.Array
    lengths: jax.Array  # int32 [S] — tokens already cached per slot


def init_slot_cache(cfg: LlamaConfig, num_slots: int, max_len: int) -> SlotCache:
    shape = (cfg.n_layers, num_slots, cfg.n_kv_heads, max_len, cfg.head_dim)
    return SlotCache(
        k=jnp.zeros(shape, cfg.jdtype),
        v=jnp.zeros(shape, cfg.jdtype),
        lengths=jnp.zeros((num_slots,), jnp.int32),
    )


# decode attention lives in generate.py (_masked_slot_attention) — ONE
# implementation shared with generate()'s decode steps, so the two paths
# cannot diverge in attention math


def _decode_one(
    params, cache, tokens: jax.Array, key: jax.Array,
    cfg: LlamaConfig, temperature: float = 0.0, top_k: int = 0, attn: str = "bucketed",
    samp=None, staged=None,
):
    """One token for every slot, slot-native: (next tokens [S], cache').

    Each slot runs at its own position (cache.lengths[s], clamped at
    maxT-1). Inactive slots decode garbage harmlessly; the host ignores
    them. Projections and the FFN (dense SwiGLU or the Mixtral mixture —
    generate._ffn_with_cache) run batched over the slot dim.

    ``cache`` is a SlotCache (dense per-slot slabs) or a PagedCache (page
    pool + per-slot page tables, models/paged_cache.py): the trace-time
    branch picks the attention read (per-slot slab DMA vs page-indirected
    DMA — same kernel body) and the write (per-slot column scatter vs
    (page, offset) scatter). Everything else — projections, RoPE, FFN,
    sampling — is identical, so the two cache layouts cannot drift.
    """
    from tony_tpu.models.paged_cache import PagedCache

    paged = isinstance(cache, PagedCache)
    S = tokens.shape[0]
    Dh, H, Hkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    maxT = (cache.page_table.shape[1] * cache.k.shape[3]) if paged else cache.k.shape[3]
    cos, sin = L.rope_frequencies(Dh, maxT, cfg.rope_theta, cfg.rope_scaling)
    # KERNEL PRECONDITION: active slots have lengths < maxT (enforced by
    # submit()'s prompt+budget <= max_len check). A slot clamped AT maxT
    # would attend both the stale cached entry at maxT-1 and the current
    # token (double-counting one position) in the read-only-cache split —
    # only retired-not-yet-flushed slots decoding discarded overshoot
    # tokens can reach that state, and their output is never read.
    pos = jnp.minimum(cache.lengths, maxT - 1)                      # write position
    x = _embed_lookup(params["embed"], tokens[:, None], cfg.jdtype)  # [S, 1, D]

    # The big cache tensors are scan XS (read-only): attention sees the OLD
    # cache plus the current token's K/V explicitly, and the scan emits only
    # the tiny [S, Hkv, Dh] new K/V per layer. Carrying the updated cache
    # through the scan instead (the first r3 design) stacked a full cache
    # copy as scan ys EVERY token — measured −32% decode tok/s at 64 slots.
    def layer(x, inputs):
        if staged is not None:
            lp, ck, cv, skl, svl = inputs  # + this layer's staged window
        else:
            lp, ck, cv = inputs  # dense: ck/cv [S, Hkv, maxT, Dh]; paged: [P, Hkv, page_len, Dh]
        h = L.rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q = _mm(h, lp["wq"]).reshape(S, 1, H, Dh).transpose(0, 2, 1, 3)
        k = _mm(h, lp["wk"]).reshape(S, 1, Hkv, Dh).transpose(0, 2, 1, 3)
        v = _mm(h, lp["wv"]).reshape(S, 1, Hkv, Dh).transpose(0, 2, 1, 3)
        q = L.apply_rope(q, cos, sin, positions=pos[:, None])
        k = L.apply_rope(k, cos, sin, positions=pos[:, None])
        k1 = k[:, :, 0].astype(ck.dtype)                             # [S, Hkv, Dh]
        v1 = v[:, :, 0].astype(cv.dtype)
        if paged:
            from tony_tpu.ops.decode_attention import paged_decode_attention

            extra = {}
            if staged is not None:
                extra = dict(
                    staged_k=skl, staged_v=svl,
                    staged_count=jnp.broadcast_to(staged[2], (S,)),
                )
            o = paged_decode_attention(
                q[:, :, 0], ck, cv, pos, cache.page_table, cur_k=k1, cur_v=v1,
                window=cfg.sliding_window, **extra,
            )
        elif attn == "ragged":
            from tony_tpu.ops.decode_attention import ragged_decode_attention

            o = ragged_decode_attention(
                q[:, :, 0], ck, cv, pos, cur_k=k1, cur_v=v1,
                window=cfg.sliding_window,
            )
        else:
            o = _masked_slot_attention(
                q[:, :, 0], ck, cv, pos, H // Hkv, window=cfg.sliding_window,
                cur_k=k1, cur_v=v1,
            )
        x = x + _mm(o.reshape(S, 1, H * Dh), lp["wo"])
        h = L.rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        x = x + _ffn_with_cache(h, lp, cfg)
        return x, (k1, v1)

    xs = (params["layers"], cache.k, cache.v)
    if staged is not None:
        xs = xs + (staged[0], staged[1])  # per-layer staged windows
    x, (ks_new, vs_new) = jax.lax.scan(layer, x, xs)
    x = L.rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = _mm(x[:, 0], params["lm_head"]).astype(jnp.float32)     # [S, V]
    if samp is not None:
        nxt = sample_logits(logits, key, *samp)  # per-slot temp/top_k/top_p
    else:
        nxt = _sample(logits, key, temperature, top_k)

    # idle slots (length 0 — flushed retirements / never admitted) stay at 0
    # instead of regrowing +1 per step: their stale cache never re-enters
    # the ragged kernel's Σ len_s (active slots always have length ≥ 1)
    new_len = jnp.where(
        cache.lengths > 0, jnp.minimum(cache.lengths + 1, maxT), 0
    )
    if staged is not None:
        # deferred-write mode (decode_steps' paged chunk): this step's
        # columns go to the chunk staging, the POOL is untouched — the
        # per-token page write measured −24%/chunk as 2·S serial dus
        from tony_tpu.models.paged_cache import PagedCache as _PC

        return nxt, _PC(cache.k, cache.v, new_len, cache.page_table), ks_new, vs_new
    if paged:
        # write each slot's [L, Hkv, Dh] column at its (physical page,
        # in-page offset) via a fori chain of dynamic_update_slice — XLA
        # keeps these in-place on the donated pool, where the equivalent
        # two-index-array scatter measured +24% on the whole decode chunk
        # (it materializes gather/scatter traffic instead of aliasing)
        page_len = cache.k.shape[3]
        pages = cache.page_table[jnp.arange(S), pos // page_len]     # [S]
        offs = pos % page_len

        def write_slot_page(s, kv):
            ks, vs = kv
            kcol = jax.lax.dynamic_slice_in_dim(ks_new, s, 1, axis=1)  # [L,1,Hkv,Dh]
            vcol = jax.lax.dynamic_slice_in_dim(vs_new, s, 1, axis=1)
            idx = (0, pages[s], 0, offs[s], 0)
            ks = jax.lax.dynamic_update_slice(ks, kcol[:, 0][:, None, :, None, :], idx)
            vs = jax.lax.dynamic_update_slice(vs, vcol[:, 0][:, None, :, None, :], idx)
            return ks, vs

        ks, vs = jax.lax.fori_loop(0, S, write_slot_page, (cache.k, cache.v))
        from tony_tpu.models.paged_cache import PagedCache as _PC

        return nxt, _PC(ks, vs, new_len, cache.page_table)

    # single write: scatter each slot's [L, Hkv, Dh] column at its position
    # (the donated cache updates in place — no full-cache copy per token)
    def write_slot(c, kv, p):
        # c [L, Hkv, maxT, Dh]; kv [L, Hkv, Dh]
        return jax.lax.dynamic_update_slice(c, kv[:, :, None], (0, 0, p, 0))

    ks = jax.vmap(write_slot, in_axes=(1, 1, 0), out_axes=1)(cache.k, ks_new, pos)
    vs = jax.vmap(write_slot, in_axes=(1, 1, 0), out_axes=1)(cache.v, vs_new, pos)
    return nxt, SlotCache(ks, vs, new_len)


decode_step = functools.partial(
    jax.jit, static_argnames=("cfg", "temperature", "top_k", "attn"), donate_argnums=(1,)
)(_decode_one)


@functools.partial(
    jax.jit, static_argnames=("cfg", "n", "temperature", "top_k", "attn"),
    donate_argnums=(1,),
)
def decode_steps(
    params, cache: SlotCache, tokens: jax.Array, key: jax.Array,
    cfg: LlamaConfig, n: int, temperature: float = 0.0, top_k: int = 0,
    attn: str = "ragged", samp=None,
):
    """``n`` decode steps in ONE compiled call (lax.scan): (tokens [S],
    all tokens [n, S], cache'). Amortizes per-dispatch host overhead —
    the dominant cost of single-token steps on remote/tunneled backends.
    With ``attn='ragged'`` the Pallas kernel reads each slot's own cache
    length, so no bucketing is needed (or helpful). ``samp``: per-slot
    (temperature, top_k, top_p) device arrays — overrides the static
    sampling params when present.

    PAGED caches decode in DEFERRED-WRITE mode: each step's K/V columns
    land in a chunk staging buffer (one contiguous write per step), the
    kernel folds the staged window from VMEM, and the page pool is written
    ONCE per chunk — the per-token page scatter (2·S serial updates into
    dynamic (page, offset) targets) measured −24% on the whole chunk."""
    from tony_tpu.models.paged_cache import PagedCache

    if not isinstance(cache, PagedCache):

        def body(carry, k_step):
            cache, toks = carry
            nxt, cache = _decode_one(
                params, cache, toks, k_step, cfg, temperature, top_k, attn, samp
            )
            return (cache, nxt), nxt

        (cache, toks), seq = jax.lax.scan(body, (cache, tokens), jax.random.split(key, n))
        return toks, seq, cache

    Lc, _, Hkv, page_len, Dh = cache.k.shape
    S = tokens.shape[0]
    maxT = cache.page_table.shape[1] * page_len
    len0 = cache.lengths
    stage_k = jnp.zeros((Lc, S, n, Hkv, Dh), cache.k.dtype)
    stage_v = jnp.zeros((Lc, S, n, Hkv, Dh), cache.v.dtype)

    def body(carry, k_step):
        cache, toks, sk, sv, i = carry
        nxt, cache, cols_k, cols_v = _decode_one(
            params, cache, toks, k_step, cfg, temperature, top_k, attn, samp,
            staged=(sk, sv, i),
        )
        # cols [L, S, Hkv, Dh] → staging[:, :, i] (one contiguous write)
        sk = jax.lax.dynamic_update_slice(sk, cols_k[:, :, None], (0, 0, i, 0, 0))
        sv = jax.lax.dynamic_update_slice(sv, cols_v[:, :, None], (0, 0, i, 0, 0))
        return (cache, nxt, sk, sv, i + 1), nxt

    (cache, toks, stage_k, stage_v, _), seq = jax.lax.scan(
        body, (cache, tokens, stage_k, stage_v, jnp.int32(0)),
        jax.random.split(key, n),
    )
    # ONE pool write for the whole chunk: position of (slot s, step j) is
    # len0[s]+j (idle slots pin to the sacrificial page; overshoot clamps
    # to maxT-1 — duplicate targets there hold garbage nothing reads)
    steps = jnp.arange(n, dtype=jnp.int32)[None, :]
    pos = jnp.where(
        len0[:, None] > 0, jnp.minimum(len0[:, None] + steps, maxT - 1), 0
    )                                                                # [S, n]
    pages = jnp.take_along_axis(cache.page_table, pos // page_len, axis=1)
    offs = (pos % page_len).reshape(-1)
    pages = pages.reshape(-1)
    cols_k = stage_k.transpose(1, 2, 0, 3, 4).reshape(S * n, Lc, Hkv, Dh)
    cols_v = stage_v.transpose(1, 2, 0, 3, 4).reshape(S * n, Lc, Hkv, Dh)
    k = cache.k.at[:, pages, :, offs, :].set(cols_k)
    v = cache.v.at[:, pages, :, offs, :].set(cols_v)
    return toks, seq, PagedCache(k, v, cache.lengths, cache.page_table)


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "n", "bucket", "temperature", "top_k"),
    donate_argnums=(1,),
)
def decode_steps_bucketed(
    params, cache: SlotCache, tokens: jax.Array, key: jax.Array,
    cfg: LlamaConfig, n: int, bucket: int, temperature: float = 0.0, top_k: int = 0,
    samp=None,
):
    """``decode_steps`` over a LENGTH-BUCKETED cache view (XLA fallback):
    attention reads only the first ``bucket`` cache positions (a power of
    two ≥ the longest active slot + n, chosen by the host), then the grown
    view is written back into the full cache. Portable but global — one
    long slot drags every slot to its bucket; the ragged path doesn't.
    One jit variant per bucket (powers of two → log(max_len) variants)."""
    sub = SlotCache(cache.k[:, :, :, :bucket], cache.v[:, :, :, :bucket], cache.lengths)

    def body(carry, k_step):
        c, toks = carry
        nxt, c = _decode_one(
            params, c, toks, k_step, cfg, temperature, top_k, "bucketed", samp
        )
        return (c, nxt), nxt

    (sub, toks), seq = jax.lax.scan(body, (sub, tokens), jax.random.split(key, n))
    k = jax.lax.dynamic_update_slice(cache.k, sub.k, (0, 0, 0, 0, 0))
    v = jax.lax.dynamic_update_slice(cache.v, sub.v, (0, 0, 0, 0, 0))
    return toks, seq, SlotCache(k, v, sub.lengths)


# host-loop cache/token updates MUST be shape-stable jitted calls: an eager
# `.at[idx].set()` whose index list length (or constant-folded position)
# varies re-lowers and RE-COMPILES per distinct pattern — ~50 ms per tiny
# executable on a local backend, >1 s through a remote-compile tunnel. The
# r5 probe caught retirement flushes + per-admission token writes costing
# 13.7 s of an 18 s serving pass this way (decode itself: 0.6 s); with the
# fixed-shape forms below each helper compiles exactly once per engine.
@functools.partial(jax.jit, donate_argnums=(0,))
def _set_slot_token(tokens, slot, val):
    return tokens.at[slot].set(val[0])  # val [1]: indexed inside the jit


@functools.partial(jax.jit, donate_argnums=(0,))
def _mask_zero(lengths, mask):
    return jnp.where(mask, 0, lengths)


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _mask_zero_paged(lengths, page_table, mask):
    return jnp.where(mask, 0, lengths), jnp.where(mask[:, None], 0, page_table)


def _bucket(n: int, lo: int = 16) -> int:
    b = lo
    while b < n:
        b *= 2
    return b


# one jit variant per (prompt bucket, cache length) — buckets are powers of
# two so the variant count stays logarithmic in max_len
_prefill_padded = jax.jit(_forward_with_cache, static_argnames=("cfg",))


@functools.partial(jax.jit, donate_argnums=(0,))
def _insert_prefill(cache: SlotCache, pre: KVCache, slot: jax.Array, true_len: jax.Array):
    """Copy a 1-request prefill cache [L, 1, Hkv, maxT, Dh] into ``slot``."""
    k = jax.lax.dynamic_update_slice(cache.k, pre.k, (0, slot, 0, 0, 0))
    v = jax.lax.dynamic_update_slice(cache.v, pre.v, (0, slot, 0, 0, 0))
    lengths = cache.lengths.at[slot].set(true_len)
    return SlotCache(k, v, lengths)


@dataclass
class _Request:
    rid: int
    prompt: list[int]
    max_new_tokens: int
    out: list[int] = field(default_factory=list)
    slot: int = -1
    # per-request sampling overrides (None → the engine's defaults)
    temperature: float | None = None
    top_k: int | None = None
    top_p: float | None = None
    cancelled: bool = False           # client gone: retire at the next chunk

    def is_done(self, eos_id: int) -> bool:
        """THE termination predicate — budget spent, EOS emitted, or the
        request cancelled. Both the chunk-drain loop and retirement consult
        this one method, so a cancelled slot frees within one decode chunk."""
        return self.cancelled or len(self.out) >= self.max_new_tokens or (
            eos_id >= 0 and bool(self.out) and self.out[-1] == eos_id
        )


@dataclass
class _Staged:
    """A request mid-prefill, staged ahead of slot availability."""

    req: _Request
    pre: KVCache                      # per-request dense staging cache
    pos: int = 0                      # prompt tokens prefilled so far
    first: object = None              # sampled first output token (None → prefilling)
    matched: list[int] = field(default_factory=list)  # pinned shared-prefix pages
    keys: list[tuple] = field(default_factory=list)   # cumulative prefix keys (paged)


class ContinuousBatcher:
    """Slot-based continuous batching: admit → decode → retire, every step.

    One engine instance owns S slots over a shared static KV cache. Requests
    are admitted into free slots as they arrive (prefill padded to a bucket
    so prompt-length jit variants stay bounded) and retire independently on
    EOS or their token budget — the running batch never drains to admit new
    work, which is the throughput property batch-of-one ``generate()`` lacks.

    ``attn``: "auto" (CPU: always bucketed; TPU: bucketed while every
    active slot fits a short bucket, the ragged Pallas kernel once the
    needed bucket crosses ``ragged_threshold`` — short regimes are
    XLA-batched-einsum-friendly, long/straggler regimes are where per-slot
    reads pay), or force "ragged"/"bucketed". Works for Llama and Mixtral
    param trees — the decode step dispatches the FFN on the layer keys.
    """

    #: needed-bucket size above which "auto" switches to the ragged kernel
    RAGGED_THRESHOLD = 512

    def __init__(
        self, params, cfg: LlamaConfig, *, num_slots: int = 8, max_len: int = 512,
        eos_id: int = -1, temperature: float = 0.0, top_k: int = 0,
        key: jax.Array | None = None, decode_chunk: int = 8, attn: str = "auto",
        prefill_chunk: int = 0, kv: str = "dense", page_len: int = 256,
        num_pages: int | None = None, mesh=None,
    ):
        if num_slots < 1 or max_len < 1:
            raise ValueError(f"need num_slots>=1 and max_len>=1, got {num_slots}/{max_len}")
        if kv not in ("dense", "paged"):
            raise ValueError(f"kv must be dense|paged, got {kv!r}")
        self.kv = kv
        if kv == "paged":
            # paged mode always decodes through the paged Pallas kernel; the
            # attn policy knob only governs the dense engine
            if page_len < 8 or page_len % 8:
                raise ValueError(f"page_len must be a multiple of 8 >= 8, got {page_len}")
            if max_len % page_len:
                raise ValueError(f"max_len {max_len} must be a multiple of page_len {page_len}")
        # model-axis tensor parallelism (VERDICT r4 #3): the TRAINING
        # column/row rules (models/llama.py sharding_rules) shard the decode
        # projections unchanged, the KV cache shards over its head dim, and
        # the host loop stays identical — admission/retirement/sampling
        # bookkeeping never sees the mesh. GSPMD inserts the row-parallel
        # psums; attention is embarrassingly parallel over heads. TP=1 with
        # a mesh (or mesh=None) is byte-for-byte the single-device program.
        self.mesh = mesh
        self.tp = int(mesh.shape.get("model", 1)) if mesh is not None else 1
        if self.tp > 1:
            if kv == "paged":
                raise ValueError(
                    "model-axis TP serving currently requires kv='dense' "
                    "(the paged pool's page indirection is per-device)"
                )
            if cfg.n_kv_heads % self.tp or cfg.n_heads % self.tp:
                raise ValueError(
                    f"n_heads {cfg.n_heads} and n_kv_heads {cfg.n_kv_heads} "
                    f"must divide the model axis ({self.tp})"
                )
            # the Pallas ragged kernel is not GSPMD-partitionable; the
            # pure-XLA bucketed path shards cleanly over the head dim.
            # An EXPLICIT ragged ask under TP is an error (silently running
            # a different kernel would hide a perf cliff); "auto" coerces.
            if attn == "ragged":
                raise ValueError(
                    "attn='ragged' is incompatible with model-axis TP (the "
                    "Pallas kernel is not GSPMD-partitionable); use attn='auto'"
                )
            attn = "bucketed"
        if attn == "auto" and jax.default_backend() == "cpu":
            attn = "bucketed"
        if attn not in ("auto", "ragged", "bucketed"):
            raise ValueError(f"attn must be auto|ragged|bucketed, got {attn!r}")
        if attn == "auto" and max_len <= self.RAGGED_THRESHOLD:
            attn = "bucketed"  # ragged could never engage at this max_len
        if kv == "dense" and attn in ("auto", "ragged") and max_len % 128:
            raise ValueError(f"attn={attn!r} needs max_len % 128 == 0, got {max_len}")
        self.params, self.cfg = params, cfg
        self.S, self.max_len, self.eos_id = num_slots, max_len, eos_id
        self.temperature, self.top_k = temperature, top_k
        self.attn = attn
        # per-slot sampling state (host mirrors, shipped per decode chunk):
        # engine defaults until a request overrides them. The first override
        # latches _per_slot and switches the decode step to the dynamic
        # sampler (one-time recompile; greedy/static engines never pay it)
        self._samp_temp = np.full((num_slots,), temperature, np.float32)
        self._samp_topk = np.full((num_slots,), top_k, np.int32)
        self._samp_topp = np.zeros((num_slots,), np.float32)
        self._per_slot = False
        self._samp_dev = None  # cached device copies; refreshed when dirty
        self._samp_dirty = True
        # decode this many tokens per compiled call; requests finishing
        # mid-chunk simply DISCARD their overshoot tokens (see step()). >1
        # amortizes host dispatch overhead at the cost of admission latency
        self.decode_chunk = max(1, decode_chunk)
        # >0: long prompts prefill in chunks of this many tokens, ONE chunk
        # per engine step, so a long admission can't stall running decodes
        # for more than ~one chunk's compute. Middle chunks are EXACT
        # length (cache positions must be true); only the final partial
        # chunk pads to a bucket (garbage K/V past the prompt is masked by
        # the slot length, as in the unchunked path).
        self.prefill_chunk = prefill_chunk
        if kv == "paged":
            from tony_tpu.models.paged_cache import PageAllocator, init_paged_cache

            self.page_len = page_len
            self.max_pages = max_len // page_len
            # default pool = dense-equivalent (every slot fully backed) + the
            # sacrificial page; the capacity win comes from running MORE
            # slots against the same pool (or a smaller pool) — HBM then
            # tracks reserved tokens, not slots × max_len
            self.num_pages = (
                num_pages if num_pages is not None else num_slots * self.max_pages + 1
            )
            self.allocator = PageAllocator(self.num_pages)
            self.cache = init_paged_cache(cfg, num_slots, max_len, page_len, self.num_pages)
            self._slot_pages: dict[int, list[int]] = {}  # slot → reserved pages
            #: cumulative count of prompt tokens whose prefill compute was
            #: skipped via prefix-cache hits (the sharing win, observable)
            self.prefix_hit_tokens = 0
        else:
            self.cache = init_slot_cache(cfg, num_slots, max_len)
        self.tokens = jnp.zeros((num_slots,), jnp.int32)  # last token per slot
        if self.tp > 1:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            from tony_tpu.models import llama as _llama
            from tony_tpu.models import mixtral as _mixtral

            rules = (
                _mixtral.sharding_rules(cfg)
                if isinstance(cfg, _mixtral.MixtralConfig)
                else _llama.sharding_rules(cfg)
            )
            self.params = jax.device_put(params, rules.sharding_tree(params, mesh))
            repl = NamedSharding(mesh, P())
            heads = NamedSharding(mesh, P(None, None, "model"))  # [L,S,Hkv,T,Dh]
            self.cache = SlotCache(
                k=jax.device_put(self.cache.k, heads),
                v=jax.device_put(self.cache.v, heads),
                lengths=jax.device_put(self.cache.lengths, repl),
            )
            self.tokens = jax.device_put(self.tokens, repl)
        self.key = key if key is not None else jax.random.PRNGKey(0)
        self.pending: list[_Request] = []
        self.running: dict[int, _Request] = {}   # slot → request
        self.done: dict[int, list[int]] = {}
        # slots retired since the last flush: their device-side lengths are
        # zeroed in ONE batched update per step — a per-retirement
        # ``lengths.at[slot].set(0)`` dispatch costs this backend's ~10 ms
        # dispatch floor EACH, which measured as a −25% tok/s engine tax
        # when a whole batch retires together (r3-cont)
        self._retired_slots: list[int] = []
        self._next_rid = 0
        # streaming cursor per request: drain_stream() hands out tokens
        # appended since the last drain (serving_http's SSE path)
        self._stream_pos: dict[int, int] = {}
        self._stream_done: set[int] = set()
        # prefill state machine, dispatched ahead of slot availability
        # (overlap with the in-flight decode chunk)
        self._staged: list[_Staged] = []
        self._slot_len = [0] * num_slots  # host mirror of cache.lengths

    def submit(
        self, prompt, max_new_tokens: int, *,
        temperature: float | None = None, top_k: int | None = None,
        top_p: float | None = None,
    ) -> int:
        """``temperature``/``top_k``/``top_p`` override the engine defaults
        for THIS request only (per-slot sampling); None keeps the default."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("prompt must be non-empty")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if temperature is not None and temperature < 0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        if top_k is not None and top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        if top_p is not None and not 0 < top_p <= 1:
            # 0.0 is the internal "nucleus cut disabled" sentinel — a client
            # sending top_p=0 expecting near-greedy would silently get the
            # FULL distribution, so reject it (use temperature=0 for greedy)
            raise ValueError(
                f"top_p must be in (0, 1], got {top_p} "
                "(for greedy decoding use temperature=0)"
            )
        if len(prompt) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt {len(prompt)} + max_new_tokens {max_new_tokens} "
                f"exceeds engine max_len {self.max_len}"
            )
        if self.kv == "paged":
            need = self._pages_needed(len(prompt), max_new_tokens)
            if need > self.num_pages - 1:
                raise ValueError(
                    f"request needs {need} pages but the pool holds "
                    f"{self.num_pages - 1}: raise num_pages or shrink the request"
                )
        rid = self._next_rid
        self._next_rid += 1
        if temperature is not None or top_k is not None or top_p is not None:
            self._per_slot = True
        self.pending.append(_Request(
            rid, prompt, max_new_tokens,
            temperature=temperature, top_k=top_k, top_p=top_p,
        ))
        return rid

    def cancel(self, rid: int) -> bool:
        """Drop a request wherever it is (same-thread as step(), like all
        engine calls). Pending → removed; staged → removed with its prefix
        pins released; running → retires at the next chunk boundary (the
        slot and its pages free through the normal retirement flush — a
        dropped client stops costing TPU within one decode chunk). Returns
        False for unknown/already-finished rids. A cancelled request never
        lands in ``done``; its partial tokens are discarded."""
        for i, req in enumerate(self.pending):
            if req.rid == rid:
                self.pending.pop(i)
                self._stream_pos.pop(rid, None)
                return True
        for i, entry in enumerate(self._staged):
            if entry.req.rid == rid:
                if self.kv == "paged":
                    for p in entry.matched:
                        self.allocator.release(p)
                self._staged.pop(i)
                self._stream_pos.pop(rid, None)
                return True
        for slot, req in self.running.items():
            if req.rid == rid:
                req.cancelled = True  # is_done() now true → retires next chunk
                return True
        return False

    # -- engine internals ---------------------------------------------------

    def _free_slots(self) -> list[int]:
        return [s for s in range(self.S) if s not in self.running]

    def _pages_needed(self, Tp: int, max_new: int) -> int:
        """Worst-case page RESERVATION for a request: prompt + budget,
        rounded up to whole decode chunks — a request retiring mid-chunk
        keeps writing (discarded) tokens until the chunk ends, and those
        writes must land inside its own pages. Reserving up front means
        decode can never hit an empty pool mid-request: admission is the
        only wait point, exactly like waiting for a free slot."""
        h = self.decode_chunk
        hi = min(Tp + -(-max_new // h) * h, self.max_len)
        return -(-hi // self.page_len)

    def _stage_prefills(self, budget: int, advance: bool = True):
        """Stage up to ``budget`` pending requests and (when ``advance``)
        run prefill work for every staged entry. The advancing call site is
        AFTER the decode chunk is dispatched, so prefill compute queues
        behind it instead of delaying it; admission-time staging passes
        ``advance=False`` (unless nothing is decoding) to keep the
        one-chunk-per-step stall bound honest."""
        while self.pending and len(self._staged) < budget:
            req = self.pending.pop(0)
            entry = _Staged(req, init_cache(self.cfg, 1, self.max_len))
            if self.kv == "paged":
                from tony_tpu.models.paged_cache import prefix_keys

                entry.keys = prefix_keys(req.prompt, self.page_len)
                self._match_prefix_into(entry)
            self._staged.append(entry)
        if advance:
            # burst dedup: a staged entry whose FIRST full page matches ANY
            # earlier still-staged entry defers its prefill — the earlier
            # one admits and registers its pages, and this one re-matches
            # them (_advance_prefill) instead of recomputing. The leader
            # keeps claiming its key even after ITS prefill completes:
            # while it is page-blocked at admission nothing is registered
            # yet, and letting a follower through would burn a full
            # redundant prefill per blocked round.
            seen_first: set[tuple] = set()
            for entry in self._staged:
                fk = entry.keys[0] if entry.keys else None
                defer = (
                    fk is not None and fk in seen_first
                    and entry.first is None and entry.pos == 0 and not entry.matched
                    # once the leader REGISTERED the prefix, followers must
                    # all proceed this round (they re-match, not recompute) —
                    # deferring on the raw key would serialize the burst to
                    # one follower per engine step
                    and not self.allocator.has_key(fk)
                )
                if fk is not None:
                    seen_first.add(fk)
                if not defer:
                    self._advance_prefill(entry)

    def _match_prefix_into(self, entry: _Staged) -> bool:
        """Shared-prefix reuse (paged kv): pin the longest resident chain of
        FULL prompt pages, copy it into the entry's staging cache, and start
        prefill after it — N same-prefix requests run ~1 prefill. Capped at
        (Tp-1)//page_len: the LAST prompt token must always be prefilled
        (its logits sample the first output token). Only callable while the
        entry has no pins and no prefill progress."""
        from tony_tpu.models.paged_cache import gather_prefix_into_staging

        cap = (len(entry.req.prompt) - 1) // self.page_len
        matched = self.allocator.match_prefix(entry.keys[:cap])
        if not matched:
            return False
        entry.pre = gather_prefix_into_staging(
            entry.pre, self.cache.k, self.cache.v,
            jnp.asarray(matched, jnp.int32), n=len(matched),
        )
        entry.pos = len(matched) * self.page_len
        entry.matched = matched
        self.prefix_hit_tokens += entry.pos
        return True

    def _advance_prefill(self, entry: _Staged) -> None:
        """Run one prefill chunk (or the whole prompt when unchunked).
        ``pos`` starts past any shared-prefix pages (paged kv)."""
        req, pre, pos, first = entry.req, entry.pre, entry.pos, entry.first
        if first is not None:
            return
        Tp = len(req.prompt)
        if self.kv == "paged" and pos == 0 and not entry.matched:
            # the prefix chain may have grown since this entry was staged
            # (an earlier same-prefix request admitted) — re-match before
            # spending any prefill compute
            if self._match_prefix_into(entry):
                pre, pos = entry.pre, entry.pos
        step = self.prefill_chunk if self.prefill_chunk > 0 else Tp
        while first is None:
            take = min(step, Tp - pos)
            last = pos + take >= Tp
            if last:
                # cap the pad so the padded write NEVER runs past max_len —
                # dynamic_update_slice would clamp the start and silently
                # shift real prompt K/V (caught by review repro: prompt 59,
                # chunk 8, max_len 64 corrupted positions 48..59)
                pad = min(_bucket(take), self.max_len - pos) - take
            else:
                pad = 0  # middle chunks are exact: cache positions stay true
            toks = jnp.array(
                req.prompt[pos:pos + take] + [0] * pad, jnp.int32
            )[None, :]
            # padded positions write garbage K/V past Tp; decode masks them
            # out via lengths[slot] = Tp, and causality protects the prefix
            logits, pre = _prefill_padded(self.params, toks, pre, self.cfg)
            pos += take
            if last:
                last_logits = logits[:, take - 1].astype(jnp.float32)
                if (
                    req.temperature is not None or req.top_k is not None
                    or req.top_p is not None
                ):
                    first = sample_logits(
                        last_logits, self._split(),
                        jnp.full((1,), req.temperature if req.temperature is not None
                                 else self.temperature, jnp.float32),
                        jnp.full((1,), req.top_k if req.top_k is not None
                                 else self.top_k, jnp.int32),
                        jnp.full((1,), req.top_p if req.top_p is not None
                                 else 0.0, jnp.float32),
                    )
                else:
                    first = _sample(
                        last_logits, self._split(), self.temperature, self.top_k
                    )
            entry.pre, entry.pos, entry.first = pre, pos, first
            if first is not None:
                # start the device→host copy NOW, while the prefill is still
                # in flight: admission's int(first[0]) then finds the value
                # already local instead of paying a blocking round trip per
                # request (~165 ms/request of pure admission serialization
                # on a tunneled backend, r5 probe)
                try:
                    first.copy_to_host_async()
                except AttributeError:  # non-jax.Array stand-ins in tests
                    pass
            if self.prefill_chunk > 0:
                break  # one chunk per engine step — decode interleaves

    def _admit(self):
        free = self._free_slots()
        # only compute prefills here when nothing is decoding (startup /
        # drain); otherwise they advance after the decode chunk dispatches
        self._stage_prefills(len(free), advance=not self.running)
        while self._staged and free and self._staged[0].first is not None:
            head = self._staged[0]
            req, pre, first = head.req, head.pre, head.first
            slot = free[0]
            Tp = len(req.prompt)
            if self.kv == "paged":
                if not self._admit_paged(req, pre, head.matched, head.keys, slot, Tp):
                    break  # pages short: admission waits for retirements
            else:
                self.cache = _insert_prefill(
                    self.cache, pre, jnp.int32(slot), jnp.int32(Tp)
                )
            self._staged.pop(0)
            free.pop(0)
            self.tokens = _set_slot_token(self.tokens, jnp.int32(slot), first)
            self._samp_temp[slot] = (
                req.temperature if req.temperature is not None else self.temperature
            )
            self._samp_topk[slot] = req.top_k if req.top_k is not None else self.top_k
            self._samp_topp[slot] = req.top_p if req.top_p is not None else 0.0
            self._samp_dirty = True
            self._slot_len[slot] = Tp
            req.slot = slot
            req.out.append(int(np.asarray(first)[0]))  # host copy (async-warmed)
            self.running[slot] = req
            self._retire_if_done(req)  # 1-token requests finish at admission

    def _admit_paged(
        self, req, pre, matched: list[int], keys: list[tuple], slot: int, Tp: int
    ) -> bool:
        """Reserve pages, attach the shared prefix, copy the prefilled span,
        install the page-table row. False → pool short, caller waits."""
        import numpy as np

        from tony_tpu.models.paged_cache import insert_paged_prefill

        # a retired-but-unflushed slot being re-admitted still holds its old
        # reservation — release it BEFORE the availability check (the freed
        # pages may be exactly what covers this admission; checking first
        # would stall the request one needless chunk)
        for p in self._slot_pages.pop(slot, []):
            self.allocator.release(p)
        n_covered = self._pages_needed(Tp, req.max_new_tokens)
        n_fresh = n_covered - len(matched)
        if n_fresh > self.allocator.available():
            # nothing running means nothing will retire to free pages — the
            # only reclaimable capacity is OTHER staged entries' prefix pins.
            # Demoting a pin is free: its content was already COPIED into
            # that entry's staging cache, so insert simply copies instead of
            # attaching. Demote and retry once; still short → a true wait.
            if not self.running:
                for entry in self._staged:
                    if entry.req is not req and entry.matched:
                        for p in entry.matched:
                            self.allocator.release(p)
                        entry.matched = []
                if n_fresh > self.allocator.available():
                    return False
            else:
                return False
        fresh = self.allocator.alloc(n_fresh)
        row = list(matched) + fresh                      # logical page order
        n_prefill = -(-Tp // self.page_len)              # pages holding prompt K/V
        nc = n_prefill - len(matched)                    # pages to COPY from staging
        pt_row = np.zeros(self.max_pages, np.int32)
        pt_row[:n_covered] = row
        # fresh-page list padded to a FIXED [max_pages] width + traced copy
        # count: one compiled insert variant covers every page-count class
        # (a [nc]-shaped arg would re-compile per distinct nc)
        fp = np.zeros(self.max_pages, np.int32)
        fp[:nc] = fresh[:nc]
        self.cache = insert_paged_prefill(
            self.cache, pre.k, pre.v, fp, pt_row,
            jnp.int32(slot), jnp.int32(Tp), jnp.int32(len(matched)),
            n=jnp.int32(nc),
        )
        # content-address the request's FULL prompt pages so later
        # same-prefix requests reuse them (first writer wins)
        for j in range(Tp // self.page_len):
            if j >= len(matched):
                self.allocator.register(row[j], keys[j])
        self._slot_pages[slot] = row
        return True

    def _split(self):
        if self.temperature == 0.0 and not self._per_slot:
            return self.key  # greedy sampling never consumes the key
        self.key, sub = jax.random.split(self.key)
        return sub

    def _retire_if_done(self, req: _Request):
        if req.slot in self.running and req.is_done(self.eos_id):
            del self.running[req.slot]
            if req.cancelled:
                self._stream_pos.pop(req.rid, None)  # nobody drains it again
            else:
                self.done[req.rid] = req.out
            self._retired_slots.append(req.slot)
            self._slot_len[req.slot] = 0

    def _flush_retired(self):
        """Zero retired slots' device-side lengths in ONE update (idle slots
        would otherwise keep advancing, clamped at maxT, and the ragged
        kernel would stream their stale cache every step). Slots re-admitted
        since retirement are skipped — their length is live again."""
        idle = [s for s in self._retired_slots if s not in self.running]
        self._retired_slots = []
        if idle:
            mask = np.zeros(self.S, bool)
            mask[idle] = True
            mask = jnp.asarray(mask)  # [S] always — one compiled variant
            if self.kv == "paged":
                from tony_tpu.models.paged_cache import PagedCache

                # release the reservation (registered full-prompt pages park
                # in the allocator's reuse pool for future prefix hits) and
                # reset the page-table rows: an idle slot's garbage write
                # lands in the sacrificial page 0, never a live page
                for s in idle:
                    for p in self._slot_pages.pop(s, []):
                        self.allocator.release(p)
                lengths, page_table = _mask_zero_paged(
                    self.cache.lengths, self.cache.page_table, mask
                )
                self.cache = PagedCache(
                    self.cache.k, self.cache.v, lengths, page_table
                )
            else:
                self.cache = SlotCache(
                    self.cache.k, self.cache.v,
                    _mask_zero(self.cache.lengths, mask),
                )

    def step(self) -> bool:
        """Admit + one decode chunk. Returns True while work remains."""
        self._admit()
        self._flush_retired()
        if not self.running:
            return bool(self.pending or self._staged)
        # constant chunk height = ONE compiled decode variant; slots whose
        # request finishes mid-chunk simply discard the overshoot tokens
        # (their cache writes clamp at the view's end and the slot is fully
        # overwritten at its next admission)
        h = self.decode_chunk
        if self.kv == "paged":
            # paged decode has exactly one path: the page-indirected ragged
            # kernel ("ragged" below is ignored by _decode_one's paged branch)
            use_ragged, bucket = True, 0
        else:
            needed = max(self._slot_len[s] for s in self.running) + h
            bucket = min(_bucket(max(needed, 1)), self.max_len)
            use_ragged = self.attn == "ragged" or (
                self.attn == "auto" and bucket > self.RAGGED_THRESHOLD
            )
        samp = None
        if self._per_slot:
            # host→device upload only when an admission changed a slot's
            # params — not per chunk forever after the first override
            if self._samp_dirty or self._samp_dev is None:
                self._samp_dev = (
                    jnp.asarray(self._samp_temp),
                    jnp.asarray(self._samp_topk),
                    jnp.asarray(self._samp_topp),
                )
                self._samp_dirty = False
            samp = self._samp_dev
        if use_ragged:
            toks, seq, self.cache = decode_steps(
                self.params, self.cache, self.tokens, self._split(), self.cfg, h,
                self.temperature, self.top_k, "ragged", samp,
            )
        else:
            # length bucket: attention reads only the shortest power-of-two
            # cache prefix covering every active slot through this chunk
            toks, seq, self.cache = decode_steps_bucketed(
                self.params, self.cache, self.tokens, self._split(), self.cfg, h,
                bucket, self.temperature, self.top_k, samp,
            )
        self.tokens = toks
        # overlap: queue prefills for the next admissions while the chunk
        # (already dispatched, still in flight) computes; one speculative
        # stage beyond the currently-free slots covers mid-chunk retirement
        self._stage_prefills(max(len(self._free_slots()), 1))
        seq_host = np.asarray(seq)  # [h, S]: ONE device→host transfer
        for slot in self.running:
            self._slot_len[slot] = min(self._slot_len[slot] + h, self.max_len)
        for slot, req in list(self.running.items()):
            for i in range(h):
                req.out.append(int(seq_host[i, slot]))
                if req.is_done(self.eos_id):
                    break  # post-budget/post-EOS chunk tokens are discarded
            self._retire_if_done(req)
        more = bool(self.running or self.pending or self._staged)
        if not more:
            # drained: zero the final chunk's retirees now — cache.lengths is
            # externally observable and must agree with _slot_len between runs
            self._flush_retired()
        return more

    def drain_stream(self) -> dict[int, tuple[list[int], bool]]:
        """Tokens appended per request since the last drain:
        {rid: (new_tokens, finished)}. Pure host-side bookkeeping (reads
        ``req.out`` cursors) — call between ``step()``s to stream
        incrementally; a finished request is reported exactly once with its
        final tokens and then forgotten."""
        out: dict[int, tuple[list[int], bool]] = {}
        # prune: once a finished request is popped from ``done`` by the
        # caller, its dedup entry has no further use — without this the set
        # grows with every request a long-lived server ever finishes
        self._stream_done &= self.done.keys()
        for rid, toks in self.done.items():
            if rid not in self._stream_done:
                pos = self._stream_pos.pop(rid, 0)
                out[rid] = (list(toks[pos:]), True)
                self._stream_done.add(rid)
        live = [e.req for e in self._staged] + list(self.pending) + list(self.running.values())
        for req in live:
            if req.rid in self._stream_done or req.rid in out:
                continue
            pos = self._stream_pos.get(req.rid, 0)
            if len(req.out) > pos:
                out[req.rid] = (list(req.out[pos:]), False)
                self._stream_pos[req.rid] = len(req.out)
        return out

    def run(self) -> dict[int, list[int]]:
        """Drain all submitted requests; returns {request_id: tokens}."""
        while self.step():
            pass
        return dict(self.done)
