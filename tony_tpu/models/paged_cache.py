"""Block-paged KV cache + shared-prefix reuse for the serving engine.

Dense slot caches cost HBM O(slots × max_len) regardless of occupancy, and
N requests with the same prompt prefix (the dominant production pattern)
prefill and store it N times. This module replaces the per-slot slab with a
PAGE POOL:

- storage: ``[L, P, Hkv, page_len, Dh]`` — P fixed-size pages shared by all
  slots; a slot's logical positions map through a per-slot page table. HBM
  tracks allocated pages, so mixed-length workloads fit ~max_len/avg_len
  more slots in the same footprint. The ragged decode kernel reads pages
  directly (ops/decode_attention.paged_decode_attention — same slab-DMA
  pipeline, one indirection).
- prefix cache: FULL prompt pages are content-addressed (the exact token
  prefix is the key). A new request reuses every matching full page —
  refcounted, never written after prefill (decode writes always land past
  the prompt), so sharing needs no copy-on-write — and prefills only the
  remainder. N same-prefix requests cost ~1 prefill.
- reservation: a request's worst-case pages (prompt + budget + chunk
  overshoot) are reserved at admission, so decode can never hit an empty
  pool mid-request; admission simply waits when pages are short, exactly
  like it waits for a free slot.

Host/device split follows the engine's: the allocator (free list,
refcounts, prefix chain, LRU reuse pool) is pure host bookkeeping between
steps; everything per-token stays in the jitted decode step.

No reference counterpart (the reference does not serve); the engine-level
contract is tested against the dense-cache engine for parity and against
HBM/prefill accounting for the capacity and sharing wins.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import NamedTuple

import jax
import jax.numpy as jnp

from tony_tpu.models.llama import LlamaConfig


class PagedCache(NamedTuple):
    """Device state: page pools + per-slot views.

    k/v: [L, P, Hkv, page_len, Dh]; lengths: [S] cache positions;
    page_table: [S, max_pages] int32 — logical page j of slot s lives in
    physical page page_table[s, j]. Entries beyond a slot's live pages are
    never read (kernel loop bounds come from lengths)."""

    k: jax.Array
    v: jax.Array
    lengths: jax.Array
    page_table: jax.Array


def init_paged_cache(
    cfg: LlamaConfig, num_slots: int, max_len: int, page_len: int, num_pages: int
) -> PagedCache:
    if max_len % page_len:
        raise ValueError(f"max_len {max_len} must be a multiple of page_len {page_len}")
    max_pages = max_len // page_len
    return PagedCache(
        k=jnp.zeros((cfg.n_layers, num_pages, cfg.n_kv_heads, page_len, cfg.head_dim),
                    cfg.jdtype),
        v=jnp.zeros((cfg.n_layers, num_pages, cfg.n_kv_heads, page_len, cfg.head_dim),
                    cfg.jdtype),
        lengths=jnp.zeros((num_slots,), jnp.int32),
        page_table=jnp.zeros((num_slots, max_pages), jnp.int32),
    )


class PageAllocator:
    """Host-side page accounting: free list, refcounts, prefix chain.

    Pages move free → live (ref ≥ 1) → on release either back to free
    (unregistered) or into the REUSE POOL (registered full prompt pages,
    ref 0 but content valid — future prefix hits resurrect them; the pool
    is evicted LRU when fresh allocations outrun the free list)."""

    #: physical page 0 is SACRIFICIAL — never allocated. Idle slots (length
    #: 0, or retired-and-flushed with their page-table row reset to zeros)
    #: still run the decode step and write one garbage column per step;
    #: in the dense engine that lands in their own slab, here it must land
    #: somewhere that can never be another slot's live page.
    GARBAGE_PAGE = 0

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError(f"need >= 2 pages (page 0 is sacrificial), got {num_pages}")
        self.num_pages = num_pages
        self._free: list[int] = list(range(num_pages - 1, 0, -1))
        self._ref = [0] * num_pages
        self._chain: dict[tuple, int] = {}       # prefix key → page
        self._key_of: dict[int, tuple] = {}      # page → its chain key
        self._reusable: "OrderedDict[int, None]" = OrderedDict()  # ref==0, keyed

    # -- capacity ----------------------------------------------------------
    def available(self) -> int:
        return len(self._free) + len(self._reusable)

    def live_pages(self) -> int:
        return self.num_pages - 1 - self.available()  # page 0 never counts

    # -- allocation --------------------------------------------------------
    def alloc(self, n: int) -> list[int]:
        """n fresh pages (ref 1 each), evicting LRU reuse-pool pages as
        needed. Raises if the pool genuinely cannot supply them — callers
        check available() first (admission waits instead)."""
        if n > self.available():
            raise RuntimeError(
                f"page pool exhausted: want {n}, have {self.available()}"
            )
        out = []
        for _ in range(n):
            if self._free:
                p = self._free.pop()
            else:
                p, _ = self._reusable.popitem(last=False)  # LRU eviction
                del self._chain[self._key_of.pop(p)]
            self._ref[p] = 1
            out.append(p)
        return out

    def release(self, page: int) -> None:
        self._ref[page] -= 1
        if self._ref[page] > 0:
            return
        if page in self._key_of:
            self._reusable[page] = None      # content stays valid for reuse
            self._reusable.move_to_end(page)
        else:
            self._free.append(page)

    # -- prefix chain ------------------------------------------------------
    def match_prefix(self, keys: list[tuple]) -> list[int]:
        """Longest chain of resident pages for cumulative prefix ``keys``;
        each matched page's refcount is taken (pinned) before returning."""
        got: list[int] = []
        for key in keys:
            p = self._chain.get(key)
            if p is None:
                break
            if self._ref[p] == 0:
                self._reusable.pop(p, None)  # resurrect from the reuse pool
            self._ref[p] += 1
            got.append(p)
        return got

    def has_key(self, key: tuple) -> bool:
        """Is this prefix page resident (live or reusable)? Cheap host
        lookup — the engine's burst dedup stops deferring followers the
        moment their leader registers."""
        return key in self._chain

    def register(self, page: int, key: tuple) -> None:
        """Content-address a LIVE full prompt page. First writer wins — a
        concurrent duplicate simply stays unregistered and frees normally."""
        if key not in self._chain and page not in self._key_of:
            self._chain[key] = page
            self._key_of[page] = key


def prefix_keys(prompt: list[int], page_len: int) -> list[tuple]:
    """Cumulative content keys for the prompt's FULL pages; page j's key
    covers tokens [0, (j+1)·page_len). Keys are (page_index, sha256-of-
    prefix) built INCREMENTALLY — one O(Tp) pass total, O(1) hashing per
    dict lookup — instead of materializing O(Tp²/page_len) token tuples
    (a 32k-token shared prefix is the stated workload). A 256-bit digest
    collision (~2⁻¹²⁸) is the standard paged-cache tradeoff."""
    import hashlib

    h = hashlib.sha256()
    out: list[tuple] = []
    for j in range(len(prompt) // page_len):
        page = prompt[j * page_len:(j + 1) * page_len]
        h.update(b"".join(t.to_bytes(8, "little", signed=True) for t in page))
        out.append((j, h.digest()))
    return out


# -- jitted device plumbing -------------------------------------------------

import functools


@functools.partial(jax.jit, donate_argnums=(0,), static_argnames=("n",))
def gather_prefix_into_staging(
    staging,                             # KVCache [L, 1, Hkv, maxT, Dh] (donated)
    pk: jax.Array, pv: jax.Array,        # pools [L, P, Hkv, page_len, Dh]
    pages: jax.Array,                    # [n] matched physical pages
    n: int = 0,
):
    """Copy matched prefix pages into a request's dense staging cache (and
    set its length) so the remainder prefill writes at the right positions
    and attends the shared prefix. One HBM copy — negligible next to the
    prefill FLOPs it saves."""
    L, _, Hkv, page_len, Dh = pk.shape
    got_k = pk[:, pages]                 # [L, n, Hkv, page_len, Dh]
    got_v = pv[:, pages]
    flat_k = got_k.transpose(0, 2, 1, 3, 4).reshape(L, 1, Hkv, n * page_len, Dh)
    flat_v = got_v.transpose(0, 2, 1, 3, 4).reshape(L, 1, Hkv, n * page_len, Dh)
    sk = jax.lax.dynamic_update_slice(staging.k, flat_k, (0, 0, 0, 0, 0))
    sv = jax.lax.dynamic_update_slice(staging.v, flat_v, (0, 0, 0, 0, 0))
    return staging._replace(k=sk, v=sv, length=jnp.int32(n * page_len))


@functools.partial(jax.jit, static_argnames=("n",))
def gather_pages(pk: jax.Array, pv: jax.Array, pages: jax.Array, n: int = 0):
    """Read ``n`` physical pages out of the pools — the EXPORT half of the
    disaggregated KV handoff (serve/disagg.py): a prefill replica gathers
    its finished full-prompt pages into one [L, n, Hkv, page_len, Dh] pair
    to serialize toward the decode replica. One device gather, host copy at
    the caller (jax.device_get)."""
    return pk[:, pages], pv[:, pages]


@functools.partial(jax.jit, donate_argnums=(0,), static_argnames=("n",))
def scatter_pages(
    cache: PagedCache,
    pages: jax.Array,                    # [n] destination physical pages
    vals_k: jax.Array, vals_v: jax.Array,  # [L, n, Hkv, page_len, Dh]
    n: int = 0,
):
    """Write ``n`` received pages into the pools in place (donated) — the
    ADOPT half of the KV handoff. The caller (engine thread) has already
    alloc()'d the destination pages, so nothing live is overwritten; a
    fori_loop of per-page dynamic_update_slice keeps the update aliasing
    the donated pool, same shape discipline as insert_paged_prefill."""
    L, _, Hkv, page_len, Dh = cache.k.shape

    def body(j, kv):
        k, v = kv
        k = jax.lax.dynamic_update_slice(
            k, jax.lax.dynamic_slice(vals_k, (0, j, 0, 0, 0),
                                     (L, 1, Hkv, page_len, Dh)),
            (0, pages[j], 0, 0, 0))
        v = jax.lax.dynamic_update_slice(
            v, jax.lax.dynamic_slice(vals_v, (0, j, 0, 0, 0),
                                     (L, 1, Hkv, page_len, Dh)),
            (0, pages[j], 0, 0, 0))
        return k, v

    k, v = jax.lax.fori_loop(0, n, body, (cache.k, cache.v))
    return cache._replace(k=k, v=v)


@functools.partial(jax.jit, donate_argnums=(0,))
def insert_paged_prefill(
    cache: PagedCache,
    sk: jax.Array, sv: jax.Array,        # staging [L, 1, Hkv, maxT, Dh]
    fresh_pages: jax.Array,              # [max_pages] physical pages, first n live
    pt_row: jax.Array,                   # [max_pages] the slot's full page table row
    slot: jax.Array, true_len: jax.Array,
    j0: jax.Array,                       # [] int32 — first NON-shared logical page
    n: jax.Array | int = 0,              # [] int32 — pages to copy (dynamic)
):
    """Admission commit: copy the slot's NON-shared prefill span (logical
    pages j0..j0+n) from staging into its fresh physical pages, and install
    the page-table row + length. Shared prefix pages (j < j0) are already
    resident — installing the row is all it takes to attach them.

    The copy is a dynamic-trip fori_loop of per-page dynamic_update_slice
    ops: the staging slice [L, 1, Hkv, page_len, Dh] is axis-for-axis the
    pool's per-page layout, so each dus aliases the DONATED pool in place
    with no transpose, and the traced trip count + fixed-width
    ``fresh_pages`` mean ONE compiled variant covers every page-count
    class. The previous one-shot index-array scatter
    (`.at[:, fresh_pages].set(span)`) materialized a pool-sized copy per
    admission — the entirety of the paged engine's admission-side deficit
    vs dense (BASELINE.md r5)."""
    L, _, Hkv, page_len, Dh = cache.k.shape

    def body(j, kv):
        k, v = kv
        pk = jax.lax.dynamic_slice(
            sk, (0, 0, 0, (j0 + j) * page_len, 0), (L, 1, Hkv, page_len, Dh)
        )
        pv = jax.lax.dynamic_slice(
            sv, (0, 0, 0, (j0 + j) * page_len, 0), (L, 1, Hkv, page_len, Dh)
        )
        k = jax.lax.dynamic_update_slice(k, pk, (0, fresh_pages[j], 0, 0, 0))
        v = jax.lax.dynamic_update_slice(v, pv, (0, fresh_pages[j], 0, 0, 0))
        return k, v

    k, v = jax.lax.fori_loop(0, n, body, (cache.k, cache.v))
    return PagedCache(
        k=k, v=v,
        lengths=cache.lengths.at[slot].set(true_len),
        page_table=cache.page_table.at[slot].set(pt_row),
    )
