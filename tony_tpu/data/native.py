"""ctypes bindings to the native loader/sampler (native/tonyio.cc, tonymon.cc).

The shared library is built lazily with ``make -C native`` the first time it
is needed (cached thereafter); when no C++ toolchain is available every entry
point falls back to a pure-Python implementation with identical semantics —
the same batches in the same order (both sides implement the same
splitmix-hash window draw), just without the off-GIL prefetch.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from queue import Queue

import numpy as np

from tony_tpu.data.dataset import open_shard

_NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"
_LIB_PATH = _NATIVE_DIR / "libtonyio.so"
_lib = None
_lib_err: str | None = None
_build_lock = threading.Lock()


def _load_library():
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return _lib
    with _build_lock:
        if _lib is not None or _lib_err is not None:
            return _lib
        try:
            if os.environ.get("TONY_NATIVE_BUILD", "1") == "1":
                # Always invoke make: its prerequisites are the staleness
                # cache, so an up-to-date .so costs milliseconds while an
                # edited .cc actually rebuilds. Build failure only matters
                # when no previously built library exists to load.
                try:
                    subprocess.run(  # lint: disable=blocking-under-lock — build-once serializer: concurrent first callers MUST wait for the one make
                        ["make", "-C", str(_NATIVE_DIR)],
                        check=True,
                        capture_output=True,
                        timeout=120,
                    )
                except Exception:
                    if not _LIB_PATH.exists():
                        raise
            elif not _LIB_PATH.exists():
                raise RuntimeError("native build disabled (TONY_NATIVE_BUILD=0)")
            lib = ctypes.CDLL(str(_LIB_PATH))
            lib.tony_loader_open.restype = ctypes.c_int
            lib.tony_loader_open.argtypes = [
                ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint32,
                ctypes.c_uint32, ctypes.c_uint64, ctypes.c_uint32, ctypes.c_uint32,
                ctypes.POINTER(ctypes.c_void_p),
            ]
            # open_at (resume replay) — a stale .so without the symbol drops
            # the whole native path to the Python fallback, never misbinds
            lib.tony_loader_open_at.restype = ctypes.c_int
            lib.tony_loader_open_at.argtypes = [
                ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint32,
                ctypes.c_uint32, ctypes.c_uint64, ctypes.c_uint32, ctypes.c_uint32,
                ctypes.c_uint64, ctypes.POINTER(ctypes.c_void_p),
            ]
            lib.tony_loader_next.restype = ctypes.c_int
            lib.tony_loader_next.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_uint64),
            ]
            lib.tony_loader_total_tokens.restype = ctypes.c_uint64
            lib.tony_loader_total_tokens.argtypes = [ctypes.c_void_p]
            lib.tony_loader_num_windows.restype = ctypes.c_uint64
            lib.tony_loader_num_windows.argtypes = [ctypes.c_void_p]
            lib.tony_loader_close.restype = None
            lib.tony_loader_close.argtypes = [ctypes.c_void_p]
            lib.tony_mon_sample.restype = ctypes.c_int
            lib.tony_mon_sample.argtypes = [ctypes.POINTER(ctypes.c_double)]
            _lib = lib
        except Exception as e:  # noqa: BLE001 — any failure → Python fallback
            _lib_err = f"{type(e).__name__}: {e}"
        return _lib


def native_available() -> bool:
    """True iff the C++ library is (or can be) loaded; may build it."""
    return _load_library() is not None


def _splitmix(x: int) -> int:
    x = (x + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return x ^ (x >> 31)


class TokenLoader:
    """Batched (seq+1)-token window sampler over TONYTOK shards.

    Native path: C++ mmap + prefetch threads (off-GIL). Fallback: numpy with
    a single Python prefetch thread. Both draw windows with the same
    splitmix hash of (seed, GLOBAL slot).

    GLOBAL-ORDER CONTRACT (the elastic-replay spec): the stream is ONE
    global sequence of samples, a pure function of (seed, global slot);
    shard ``k`` of ``K`` produces rows ``[k*batch, (k+1)*batch)`` of each
    global batch of ``G = batch * num_shards`` rows — i.e. local batch
    ``t``, row ``i`` is global slot ``t*G + k*batch + i``. Consequences:
    - concatenating the K shards' local batches (in shard order)
      reconstructs the K=1 stream with batch ``G`` exactly;
    - replay after a RESHARD (K -> K') is exact provided the global batch
      ``G`` is held constant (per-shard batch adapts to ``G / K'``) and the
      resumed loaders start at ``start_index`` = global batch index —
      no sample is repeated or skipped across the shape change.
    """

    def __init__(
        self,
        shard_paths: list[str | Path],
        batch: int,
        seq: int,
        *,
        shard_id: int = 0,
        num_shards: int = 1,
        seed: int = 0,
        prefetch_depth: int = 4,
        num_threads: int = 2,
        start_index: int = 0,
    ):
        """``start_index``: first GLOBAL batch index to produce. The window
        draw is a pure function of (seed, global slot), so a resumed run
        that keeps its seed and global batch size and starts the loader at
        its step counter replays the exact uninterrupted stream — no
        repeated, no skipped samples — even across a shard-count change."""
        if not shard_paths:
            raise ValueError("no shard paths")
        if num_shards < 1 or not 0 <= shard_id < num_shards:
            raise ValueError(f"shard_id {shard_id} out of range for num_shards {num_shards}")
        if start_index < 0:
            raise ValueError(f"start_index must be >= 0, got {start_index}")
        self.batch, self.seq = batch, seq
        self.shard_id, self.num_shards, self.seed = shard_id, num_shards, seed
        self._handle = None
        self._out = np.empty((batch, seq + 1), np.int32)
        lib = _load_library()
        if lib is not None:
            blob = b"".join(str(Path(p)).encode() + b"\0" for p in shard_paths) + b"\0"
            handle = ctypes.c_void_p()
            rc = lib.tony_loader_open_at(
                blob, batch, seq, shard_id, num_shards, seed,
                prefetch_depth, num_threads, start_index, ctypes.byref(handle),
            )
            if rc != 0:
                raise ValueError(f"tony_loader_open failed (rc={rc}) for {shard_paths}")
            self._handle = handle
            self._lib = lib
            self.total_tokens = int(lib.tony_loader_total_tokens(handle))
            self.num_windows = int(lib.tony_loader_num_windows(handle))
        else:
            self._shards = [open_shard(p) for p in shard_paths]  # mmapped, stored dtype
            self.total_tokens = int(sum(s.size for s in self._shards))
            self.num_windows = int(sum(s.size // (seq + 1) for s in self._shards))
            if self.num_windows < 1:
                raise ValueError("not enough data for a single (seq+1)-token window")
            self._queue: Queue = Queue(maxsize=prefetch_depth)
            self._index = start_index
            self._stop = threading.Event()
            self._thread = threading.Thread(target=self._py_prefetch, daemon=True)
            self._thread.start()

    # -- python fallback ----------------------------------------------------
    def _py_window(self, window: int) -> np.ndarray:
        stride = self.seq + 1
        for s in self._shards:
            here = s.size // stride
            if window < here:
                # per-window int32 conversion: only seq+1 tokens leave the mmap
                return np.asarray(s[window * stride:(window + 1) * stride], np.int32)
            window -= here
        raise IndexError(window)

    def _py_batch(self, index: int) -> np.ndarray:
        out = np.empty((self.batch, self.seq + 1), np.int32)
        gbatch = self.batch * self.num_shards
        nw = self.num_windows
        for i in range(self.batch):
            # global slot: this shard owns rows [k*batch, (k+1)*batch) of
            # global batch `index` — the elastic-replay contract above
            g = index * gbatch + self.shard_id * self.batch + i
            epoch, pos = divmod(g, nw)
            r = _splitmix(_splitmix(self.seed ^ _splitmix(epoch)) ^ pos)
            out[i] = self._py_window(r % nw)
        return out

    def _py_prefetch(self) -> None:
        # Exceptions are shipped through the queue — a silent producer death
        # would otherwise hang the consumer forever on an empty queue.
        try:
            while not self._stop.is_set():
                b = self._py_batch(self._index)
                self._index += 1
                self._queue.put(b)
        except Exception as e:  # noqa: BLE001
            self._queue.put(e)

    # -- public -------------------------------------------------------------
    @property
    def is_native(self) -> bool:
        return self._handle is not None

    def next(self) -> np.ndarray:
        """Next [batch, seq+1] int32 batch (tokens + shifted targets)."""
        if self._handle is not None:
            idx = ctypes.c_uint64()
            rc = self._lib.tony_loader_next(
                self._handle,
                self._out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                ctypes.byref(idx),
            )
            if rc != 0:
                raise RuntimeError(f"tony_loader_next failed (rc={rc})")
            return self._out.copy()
        item = self._queue.get()
        if isinstance(item, Exception):
            raise RuntimeError("data loader producer failed") from item
        return item

    def __iter__(self):
        while True:
            yield self.next()

    def close(self) -> None:
        if self._handle is not None:
            self._lib.tony_loader_close(self._handle)
            self._handle = None
        elif hasattr(self, "_stop"):
            self._stop.set()
            try:  # unblock the producer if it is waiting on a full queue
                self._queue.get_nowait()
            except Exception:  # noqa: BLE001
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class HostMetricsSampler:
    """CPU/mem utilization snapshot: native /proc sampler, /proc-free fallback."""

    def __init__(self):
        self._lib = _load_library()
        self._last: tuple[int, int] | None = None

    def sample(self) -> dict:
        if self._lib is not None:
            out = (ctypes.c_double * 5)()
            if self._lib.tony_mon_sample(out) == 0:
                return {
                    "cpu_util_pct": round(out[0], 2),
                    "mem_used_pct": round(out[1], 2),
                    "mem_total_mb": round(out[2], 1),
                    "rss_mb": round(out[3], 1),
                    "ncpus": int(out[4]),
                }
        return self._py_sample()

    def _py_sample(self) -> dict:
        try:
            with open("/proc/stat") as f:
                parts = [int(x) for x in f.readline().split()[1:9]]
            total, idle = sum(parts), parts[3] + parts[4]
            util = 0.0
            if self._last and total > self._last[0]:
                util = 100.0 * (1 - (idle - self._last[1]) / (total - self._last[0]))
            self._last = (total, idle)
            mem = {}
            with open("/proc/meminfo") as f:
                for line in f:
                    k, v = line.split(":", 1)
                    mem[k] = int(v.split()[0])
            total_kb = mem.get("MemTotal", 0)
            avail_kb = mem.get("MemAvailable", 0)
            return {
                "cpu_util_pct": round(util, 2),
                "mem_used_pct": round(100.0 * (1 - avail_kb / total_kb), 2) if total_kb else 0.0,
                "mem_total_mb": round(total_kb / 1024, 1),
                "rss_mb": 0.0,
                "ncpus": os.cpu_count() or 1,
            }
        except OSError:
            return {"cpu_util_pct": 0.0, "mem_used_pct": 0.0, "mem_total_mb": 0.0,
                    "rss_mb": 0.0, "ncpus": os.cpu_count() or 1}
