"""Corpus preparation: text files → TONYTOK token shards.

Completes the data plane (dataset.py writes/reads shards; native.py streams
them into training): one command takes raw text to the shard format the
C++ loader mmaps. Tokenizers:

- ``bytes`` (default): UTF-8 byte-level, vocab 256, streamed in fixed-size
  chunks (flat memory for arbitrarily large files) — dependency-free and
  works offline. NUL bytes are stripped so token 0 is unambiguously the
  end-of-document marker; all other bytes round-trip exactly.
- ``hf:<path>``: a local HuggingFace tokenizer directory, loaded with
  ``local_files_only`` (no network fetch is attempted). Requires the
  optional ``transformers`` package; a clear error tells the user if it
  is absent.
"""

from __future__ import annotations

import sys
from pathlib import Path

import numpy as np

from tony_tpu.data.dataset import TokenShardWriter

EOD = 0  # byte-level end-of-document marker (NUL bytes are stripped on encode)
_CHUNK_BYTES = 1 << 20


def _encode_bytes(data: bytes) -> np.ndarray:
    tokens = np.frombuffer(data, dtype=np.uint8)
    return tokens[tokens != EOD].astype(np.uint16)  # keep token 0 = EOD only


def _load_hf_tokenizer(path: str):
    try:
        from transformers import AutoTokenizer
    except ImportError as e:
        raise RuntimeError(
            "tokenizer 'hf:<path>' needs the optional `transformers` package "
            "(pip install transformers), or use the built-in 'bytes' tokenizer"
        ) from e

    return AutoTokenizer.from_pretrained(path, local_files_only=True)


def prepare_corpus(
    inputs: list[str | Path],
    out_dir: str | Path,
    *,
    tokenizer: str = "bytes",
    shard_tokens: int = 1 << 24,
    append_eod: bool = True,
) -> dict:
    """Tokenize text files into shards; returns a manifest dict."""
    hf = _load_hf_tokenizer(tokenizer[3:]) if tokenizer.startswith("hf:") else None
    writer = TokenShardWriter(out_dir, shard_tokens=shard_tokens)
    n_docs = total = 0
    for p in inputs:
        if hf is not None:
            # HF tokenizers need document context; per-file memory here
            text = Path(p).read_text(encoding="utf-8", errors="replace")
            tokens = np.asarray(hf.encode(text), dtype=np.int32)
            writer.append(tokens)
            total += int(tokens.size)
            eod_dtype = tokens.dtype
        else:
            # byte-level is position-independent → stream in flat memory
            eod_dtype = np.uint16
            with open(p, "rb") as f:
                while chunk := f.read(_CHUNK_BYTES):
                    tokens = _encode_bytes(chunk)
                    writer.append(tokens)
                    total += int(tokens.size)
        if append_eod:
            eod = hf.eos_token_id if hf is not None and hf.eos_token_id is not None else EOD
            writer.append(np.asarray([eod], eod_dtype))
            total += 1
        n_docs += 1
    shards = writer.close()
    return {
        "shards": [str(s) for s in shards],
        "n_docs": n_docs,
        "total_tokens": total,
        "vocab_size": (len(hf) if hf is not None else 256),
        "tokenizer": tokenizer,
    }


def main(argv: list[str] | None = None) -> int:
    import argparse
    import json

    p = argparse.ArgumentParser(
        prog="tony data-prep", description="tokenize text files into TONYTOK shards"
    )
    p.add_argument("inputs", nargs="+", help="text files")
    p.add_argument("--out", required=True, help="output shard directory")
    p.add_argument("--tokenizer", default="bytes", help="'bytes' or 'hf:<local dir>'")
    p.add_argument("--shard_tokens", type=int, default=1 << 24)
    args = p.parse_args(argv if argv is not None else sys.argv[1:])
    manifest = prepare_corpus(
        args.inputs, args.out, tokenizer=args.tokenizer, shard_tokens=args.shard_tokens
    )
    print(json.dumps(manifest))  # lint: disable=print-discipline — the manifest on stdout IS the output
    return 0


if __name__ == "__main__":
    sys.exit(main())
