"""Data plane: tokenized shard datasets + native prefetching loader.

The reference left the input pipeline to the user's framework (tf.data /
torch DataLoader inside the user process — SURVEY.md §2.4); tony-tpu owns it:
- ``dataset``: the TONYTOK shard format (writer + pure-Python reader),
- ``native``: ctypes bindings to the C++ loader (native/tonyio.cc) with
  mmap + background prefetch; transparently falls back to Python.
"""

from tony_tpu.data.dataset import TokenShardWriter, read_shard, write_token_shard
from tony_tpu.data.native import HostMetricsSampler, TokenLoader, native_available

__all__ = [
    "TokenShardWriter",
    "read_shard",
    "write_token_shard",
    "TokenLoader",
    "HostMetricsSampler",
    "native_available",
]
