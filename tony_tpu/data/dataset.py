"""TONYTOK shard format: flat token streams for LM pretraining.

Layout (little-endian): 8-byte magic ``TONYTOK1``, u32 dtype (0=uint16,
1=int32), u64 token count, then the flat token payload. uint16 covers
vocabularies <= 65535 (2 bytes/token on disk); int32 covers the rest.
The C++ loader (native/tonyio.cc) mmaps the same format.

Elastic-replay primitives (docs/fault-tolerance.md "Elastic training"):
:func:`global_slots` is the single definition of which GLOBAL sample slots a
rank owns in a global batch, and :class:`ConsumptionCursor` persists how far
the stream has been consumed — together they make "no sample dropped or
double-consumed across a live resize of the data axis" a checkable property
instead of a hope.
"""

from __future__ import annotations

import json
import os
import struct
from dataclasses import asdict, dataclass
from pathlib import Path

import numpy as np

MAGIC = b"TONYTOK1"
HEADER_SIZE = 20  # 8-byte magic + u32 dtype + u64 count

_DTYPES = {0: np.dtype("<u2"), 1: np.dtype("<i4")}


def write_token_shard(path: str | Path, tokens: np.ndarray) -> Path:
    """Write one shard; dtype picked from the token range."""
    path = Path(path)
    tokens = np.asarray(tokens).ravel()
    if tokens.size and int(tokens.min()) < 0:
        raise ValueError("negative token ids")
    code = 0 if (tokens.size == 0 or int(tokens.max()) <= 0xFFFF) else 1
    payload = tokens.astype(_DTYPES[code])
    with open(path, "wb") as f:
        f.write(MAGIC)
        f.write(struct.pack("<IQ", code, payload.size))
        f.write(payload.tobytes())
    return path


class TokenShardWriter:
    """Streaming writer: append token arrays, roll shards at ``shard_tokens``."""

    def __init__(self, out_dir: str | Path, prefix: str = "shard", shard_tokens: int = 1 << 24):
        self.out_dir = Path(out_dir)
        self.out_dir.mkdir(parents=True, exist_ok=True)
        self.prefix = prefix
        self.shard_tokens = shard_tokens
        self._buf: list[np.ndarray] = []
        self._buffered = 0
        self._shards: list[Path] = []

    def append(self, tokens: np.ndarray) -> None:
        tokens = np.asarray(tokens).ravel()
        self._buf.append(tokens)
        self._buffered += tokens.size
        if self._buffered >= self.shard_tokens:
            self._flush()

    def _flush(self) -> None:
        if not self._buffered:
            return
        path = self.out_dir / f"{self.prefix}-{len(self._shards):05d}.tonytok"
        write_token_shard(path, np.concatenate(self._buf))
        self._shards.append(path)
        self._buf, self._buffered = [], 0

    def close(self) -> list[Path]:
        self._flush()
        return self._shards


def open_shard(path: str | Path) -> np.memmap:
    """Memory-map a shard's payload in its stored dtype (u16 or i32) —
    no copy; slices convert to int32 at use (TokenLoader fallback does
    this per window so a large corpus never materializes in RAM)."""
    path = Path(path)
    with open(path, "rb") as f:
        head = f.read(HEADER_SIZE)
    if len(head) < HEADER_SIZE or head[:8] != MAGIC:
        raise ValueError(f"{path}: not a TONYTOK1 shard")
    code, count = struct.unpack_from("<IQ", head, 8)
    if code not in _DTYPES:
        raise ValueError(f"{path}: unknown dtype code {code}")
    return np.memmap(path, dtype=_DTYPES[code], mode="r", offset=HEADER_SIZE, shape=(count,))


def read_shard(path: str | Path) -> np.ndarray:
    """Read a whole shard as int32 (materializes; fine for tools/tests —
    streaming consumers should use open_shard / TokenLoader)."""
    return np.asarray(open_shard(path), dtype=np.int32)


def global_slots(batch_index: int, global_batch: int, shard_id: int, num_shards: int) -> range:
    """The GLOBAL sample slots rank ``shard_id`` of ``num_shards`` consumes
    in global batch ``batch_index`` — the deterministic repartition rule the
    elastic resize relies on (TokenLoader's global-order contract,
    data/native.py): rank ``k`` owns the contiguous rows
    ``[t*G + k*b, t*G + (k+1)*b)`` where ``G = global_batch`` and
    ``b = G / num_shards``.

    Because the rule is a pure function of (batch index, world size), the
    union of every rank's slots over any world-size history that covers
    global batches ``[0, T)`` with a constant ``G`` is exactly
    ``range(0, T*G)`` — each slot once. Tests and the chaos determinism
    assertion recompute consumption with this function rather than
    instrumenting the hot loop."""
    if num_shards < 1 or not 0 <= shard_id < num_shards:
        raise ValueError(f"shard_id {shard_id} out of range for num_shards {num_shards}")
    if global_batch % num_shards:
        raise ValueError(
            f"global batch {global_batch} must divide by num_shards {num_shards}"
        )
    b = global_batch // num_shards
    start = batch_index * global_batch + shard_id * b
    return range(start, start + b)


@dataclass
class ConsumptionCursor:
    """Persisted data-consumption position, written next to each checkpoint.

    One global batch is consumed per training step, so ``global_batch_index``
    (the next global batch to draw) equals the checkpoint step it was saved
    with. The cursor pins the two knobs the exact-replay contract depends on
    — the draw ``seed`` and the GLOBAL batch size — so a resumed run at a
    DIFFERENT world size can prove it is continuing the same stream (and a
    run that silently changed either fails loudly instead of silently
    double-consuming or skipping samples). ``world_size`` records who wrote
    it, for forensics only — it is exactly the thing allowed to change.
    """

    global_batch_index: int
    global_batch_size: int
    seed: int
    world_size: int = 1

    def save(self, ckpt_dir: str | Path) -> Path:
        """Atomic write to ``<ckpt_dir>/cursor-<index>.json`` (one file per
        checkpointed step, so a quarantined/garbage-collected checkpoint
        never strands the stream position of a surviving one)."""
        path = Path(ckpt_dir) / f"cursor-{self.global_batch_index}.json"
        tmp = str(path) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(asdict(self), f)
        os.replace(tmp, path)
        return path

    @classmethod
    def load(cls, ckpt_dir: str | Path, global_batch_index: int) -> "ConsumptionCursor | None":
        """The cursor saved with checkpoint step ``global_batch_index``, or
        None (pre-cursor checkpoint / no data loader in that run)."""
        path = Path(ckpt_dir) / f"cursor-{global_batch_index}.json"
        try:
            with open(path) as f:
                d = json.load(f)
            return cls(
                global_batch_index=int(d["global_batch_index"]),
                global_batch_size=int(d["global_batch_size"]),
                seed=int(d["seed"]),
                world_size=int(d.get("world_size", 1)),
            )
        except (OSError, ValueError, KeyError):
            return None

    def validate_resume(self, global_batch_size: int, seed: int, start_index: int) -> None:
        """The exactly-once gate for a (possibly resized) resume: the GLOBAL
        batch and seed must match what the stream was consumed under, and
        the loader must restart at the recorded position. A violation means
        samples would repeat or vanish — fail the resume loudly."""
        if global_batch_size != self.global_batch_size:
            raise ValueError(
                f"global batch changed across resume: checkpointed stream "
                f"consumed {self.global_batch_size} rows/step, resuming with "
                f"{global_batch_size} — the replay contract requires a "
                "constant GLOBAL batch (per-rank batch adapts instead)"
            )
        if seed != self.seed:
            raise ValueError(
                f"data seed changed across resume: {self.seed} → {seed} — "
                "the resumed draw would be a different stream"
            )
        if start_index != self.global_batch_index:
            raise ValueError(
                f"loader resume position {start_index} disagrees with the "
                f"checkpoint's consumption cursor {self.global_batch_index}"
            )


def pack_sequences(
    sequences: list[np.ndarray] | list[list[int]],
    seq_len: int,
    pad_id: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """First-fit pack variable-length sequences into [N, seq_len] rows.

    Returns (tokens, segment_ids), both [N, seq_len] int32. Each row holds
    one or more whole sequences back to back; segment_ids number them 1, 2,
    ... within the row, with 0 marking trailing padding. Feed both to
    ``llama.loss_fn`` (as ``tokens``/``segment_ids``): attention and RoPE
    stay confined per segment and cross-boundary/pad targets are masked.
    Sequences longer than seq_len are split into seq_len-sized pieces.
    """
    rows: list[tuple[list[int], list[int]]] = []  # (tokens, segs), mutable fill
    for seq in sequences:
        seq = list(np.asarray(seq, dtype=np.int32))
        for off in range(0, len(seq), seq_len):
            piece = seq[off:off + seq_len]
            for toks, segs in rows:
                if len(toks) + len(piece) <= seq_len:
                    seg_id = segs[-1] + 1 if segs else 1
                    toks.extend(int(t) for t in piece)
                    segs.extend([seg_id] * len(piece))
                    break
            else:
                rows.append(([int(t) for t in piece], [1] * len(piece)))
    tokens = np.full((len(rows), seq_len), pad_id, dtype=np.int32)
    segment_ids = np.zeros((len(rows), seq_len), dtype=np.int32)
    for i, (toks, segs) in enumerate(rows):
        tokens[i, : len(toks)] = toks
        segment_ids[i, : len(segs)] = segs
    return tokens, segment_ids
