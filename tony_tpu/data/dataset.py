"""TONYTOK shard format: flat token streams for LM pretraining.

Layout (little-endian): 8-byte magic ``TONYTOK1``, u32 dtype (0=uint16,
1=int32), u64 token count, then the flat token payload. uint16 covers
vocabularies <= 65535 (2 bytes/token on disk); int32 covers the rest.
The C++ loader (native/tonyio.cc) mmaps the same format.
"""

from __future__ import annotations

import struct
from pathlib import Path

import numpy as np

MAGIC = b"TONYTOK1"
HEADER_SIZE = 20  # 8-byte magic + u32 dtype + u64 count

_DTYPES = {0: np.dtype("<u2"), 1: np.dtype("<i4")}


def write_token_shard(path: str | Path, tokens: np.ndarray) -> Path:
    """Write one shard; dtype picked from the token range."""
    path = Path(path)
    tokens = np.asarray(tokens).ravel()
    if tokens.size and int(tokens.min()) < 0:
        raise ValueError("negative token ids")
    code = 0 if (tokens.size == 0 or int(tokens.max()) <= 0xFFFF) else 1
    payload = tokens.astype(_DTYPES[code])
    with open(path, "wb") as f:
        f.write(MAGIC)
        f.write(struct.pack("<IQ", code, payload.size))
        f.write(payload.tobytes())
    return path


class TokenShardWriter:
    """Streaming writer: append token arrays, roll shards at ``shard_tokens``."""

    def __init__(self, out_dir: str | Path, prefix: str = "shard", shard_tokens: int = 1 << 24):
        self.out_dir = Path(out_dir)
        self.out_dir.mkdir(parents=True, exist_ok=True)
        self.prefix = prefix
        self.shard_tokens = shard_tokens
        self._buf: list[np.ndarray] = []
        self._buffered = 0
        self._shards: list[Path] = []

    def append(self, tokens: np.ndarray) -> None:
        tokens = np.asarray(tokens).ravel()
        self._buf.append(tokens)
        self._buffered += tokens.size
        if self._buffered >= self.shard_tokens:
            self._flush()

    def _flush(self) -> None:
        if not self._buffered:
            return
        path = self.out_dir / f"{self.prefix}-{len(self._shards):05d}.tonytok"
        write_token_shard(path, np.concatenate(self._buf))
        self._shards.append(path)
        self._buf, self._buffered = [], 0

    def close(self) -> list[Path]:
        self._flush()
        return self._shards


def open_shard(path: str | Path) -> np.memmap:
    """Memory-map a shard's payload in its stored dtype (u16 or i32) —
    no copy; slices convert to int32 at use (TokenLoader fallback does
    this per window so a large corpus never materializes in RAM)."""
    path = Path(path)
    with open(path, "rb") as f:
        head = f.read(HEADER_SIZE)
    if len(head) < HEADER_SIZE or head[:8] != MAGIC:
        raise ValueError(f"{path}: not a TONYTOK1 shard")
    code, count = struct.unpack_from("<IQ", head, 8)
    if code not in _DTYPES:
        raise ValueError(f"{path}: unknown dtype code {code}")
    return np.memmap(path, dtype=_DTYPES[code], mode="r", offset=HEADER_SIZE, shape=(count,))


def read_shard(path: str | Path) -> np.ndarray:
    """Read a whole shard as int32 (materializes; fine for tools/tests —
    streaming consumers should use open_shard / TokenLoader)."""
    return np.asarray(open_shard(path), dtype=np.int32)


def pack_sequences(
    sequences: list[np.ndarray] | list[list[int]],
    seq_len: int,
    pad_id: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """First-fit pack variable-length sequences into [N, seq_len] rows.

    Returns (tokens, segment_ids), both [N, seq_len] int32. Each row holds
    one or more whole sequences back to back; segment_ids number them 1, 2,
    ... within the row, with 0 marking trailing padding. Feed both to
    ``llama.loss_fn`` (as ``tokens``/``segment_ids``): attention and RoPE
    stay confined per segment and cross-boundary/pad targets are masked.
    Sequences longer than seq_len are split into seq_len-sized pieces.
    """
    rows: list[tuple[list[int], list[int]]] = []  # (tokens, segs), mutable fill
    for seq in sequences:
        seq = list(np.asarray(seq, dtype=np.int32))
        for off in range(0, len(seq), seq_len):
            piece = seq[off:off + seq_len]
            for toks, segs in rows:
                if len(toks) + len(piece) <= seq_len:
                    seg_id = segs[-1] + 1 if segs else 1
                    toks.extend(int(t) for t in piece)
                    segs.extend([seg_id] * len(piece))
                    break
            else:
                rows.append(([int(t) for t in piece], [1] * len(piece)))
    tokens = np.full((len(rows), seq_len), pad_id, dtype=np.int32)
    segment_ids = np.zeros((len(rows), seq_len), dtype=np.int32)
    for i, (toks, segs) in enumerate(rows):
        tokens[i, : len(toks)] = toks
        segment_ids[i, : len(segs)] = segs
    return tokens, segment_ids
