"""Horovod runtime adapter: AM-side driver plan + worker rank env.

Analog of the reference's ``runtime/HorovodRuntime.java`` (SURVEY.md §2.2,
§3.3) — the one adapter where the AM participates in rendezvous: it builds the
host/slot plan from all registrations (AM-side hook), then hands each worker
its rank/local-rank/cross-rank coordinates plus the rendezvous address via the
cluster-spec response. In the reference the ring then forms worker-to-worker
over Gloo/NCCL; here the "ring" is the ICI mesh and the rendezvous collapses
into ``jax.distributed`` bootstrap, so we export BOTH env families:
``HOROVOD_*`` (drop-in for horovod-style user scripts) and the jax coordinator
contract (what a TPU job actually consumes).
"""

from __future__ import annotations

from collections import defaultdict

from tony_tpu import constants
from tony_tpu.runtime.base import FrameworkRuntime
from tony_tpu.runtime.jax_runtime import canonical_task_order, coordinator_address

if False:  # typing only
    from tony_tpu.cluster.session import Session


class HorovodRuntime(FrameworkRuntime):
    def __init__(self, config):
        super().__init__(config)
        self._plan: dict[tuple[str, int], dict[str, str]] = {}

    # -- AM side: the driver's slot plan ----------------------------------
    def on_gang_complete(self, session: "Session") -> None:
        spec = session.cluster_spec()
        assert spec is not None
        order = canonical_task_order(spec, self.config.untracked_types())
        size = len(order)

        # group ranks by host → local ranks; hosts in first-seen order → cross ranks
        host_of: dict[tuple[str, int], str] = {}
        by_host: dict[str, list[tuple[str, int]]] = defaultdict(list)
        for t, i in order:
            host = spec[t][i].rsplit(":", 1)[0]
            host_of[(t, i)] = host
            by_host[host].append((t, i))
        hosts = list(by_host.keys())

        rendezvous = coordinator_address(spec, self.config.untracked_types())
        rdv_host, _, rdv_port = rendezvous.rpartition(":")
        for rank, (t, i) in enumerate(order):
            host = host_of[(t, i)]
            self._plan[(t, i)] = {
                constants.ENV_HOROVOD_CONTROLLER: "gloo",
                constants.ENV_HOROVOD_CPU_OPERATIONS: "gloo",
                constants.ENV_HOROVOD_GLOO_RENDEZVOUS_ADDR: rdv_host,
                constants.ENV_HOROVOD_GLOO_RENDEZVOUS_PORT: rdv_port,
                constants.ENV_HOROVOD_RANK: str(rank),
                constants.ENV_HOROVOD_SIZE: str(size),
                constants.ENV_HOROVOD_LOCAL_RANK: str(by_host[host].index((t, i))),
                constants.ENV_HOROVOD_LOCAL_SIZE: str(len(by_host[host])),
                constants.ENV_HOROVOD_CROSS_RANK: str(hosts.index(host)),
                constants.ENV_HOROVOD_CROSS_SIZE: str(len(hosts)),
            }

    def am_extra_env(self, session: "Session", job_name: str, index: int) -> dict[str, str]:
        return dict(self._plan.get((job_name, index), {}))

    # -- executor side -----------------------------------------------------
    def executor_env(self, cluster_spec: dict[str, list[str]], job_name: str, index: int) -> dict[str, str]:
        env = super().executor_env(cluster_spec, job_name, index)
        exclude = self.config.untracked_types()
        order = canonical_task_order(cluster_spec, exclude)
        if (job_name, index) not in order:
            return env
        env[constants.ENV_JAX_COORDINATOR] = coordinator_address(cluster_spec, exclude)
        env[constants.ENV_JAX_PROCESS_ID] = str(order.index((job_name, index)))
        env[constants.ENV_JAX_NUM_PROCESSES] = str(len(order))
        return env
