"""PyTorch / torch-xla runtime adapter: torch.distributed rendezvous env.

Analog of the reference's ``runtime/PyTorchRuntime.java`` (SURVEY.md §2.2):
coordinator = the rank-0 task's address; exports MASTER_ADDR / MASTER_PORT /
RANK / WORLD_SIZE / LOCAL_RANK and a tcp:// INIT_METHOD. On TPU hosts,
torch-xla's PJRT picks the device; DDP-style jobs map their all-reduce onto
XLA collectives instead of NCCL (BASELINE.json config #3).
"""

from __future__ import annotations

from tony_tpu import constants
from tony_tpu.runtime.base import FrameworkRuntime
from tony_tpu.runtime.jax_runtime import canonical_task_order, coordinator_address


class TorchRuntime(FrameworkRuntime):
    def executor_env(self, cluster_spec: dict[str, list[str]], job_name: str, index: int) -> dict[str, str]:
        env = super().executor_env(cluster_spec, job_name, index)
        exclude = self.config.untracked_types()
        order = canonical_task_order(cluster_spec, exclude)
        if (job_name, index) not in order:
            return env  # sidecar task: not a torch.distributed member
        coord = coordinator_address(cluster_spec, exclude)
        host, _, port = coord.rpartition(":")
        env[constants.ENV_MASTER_ADDR] = host
        env[constants.ENV_MASTER_PORT] = port
        env[constants.ENV_RANK] = str(order.index((job_name, index)))
        env[constants.ENV_WORLD_SIZE] = str(len(order))
        env[constants.ENV_LOCAL_RANK] = "0"  # one task per container
        env[constants.ENV_INIT_METHOD] = f"tcp://{coord}"
        return env
