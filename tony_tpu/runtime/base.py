"""Framework runtime adapter interface.

Analog of the reference's ``tony-core/.../tony/runtime/`` (``Framework`` enum,
``FrameworkRuntime`` factory/interface, ``MLGenericRuntime`` base —
SURVEY.md §2.2). An adapter has hooks on **both sides** of the control plane,
exactly like the reference:

- AM side: validate the job conf, observe registrations, and contribute
  per-task extra env once the gang is complete (the Horovod driver's
  slot-plan/rendezvous is the reference case for this hook).
- Executor side: turn (cluster spec, my identity) into the env contract the
  user process expects (TF_CONFIG / torch rendezvous / jax.distributed ...).
"""

from __future__ import annotations

import enum
from typing import TYPE_CHECKING

from tony_tpu import constants
from tony_tpu.config import TonyConfig

if TYPE_CHECKING:
    from tony_tpu.cluster.session import Session


class Framework(enum.Enum):
    JAX = "jax"
    TENSORFLOW = "tensorflow"
    PYTORCH = "pytorch"
    HOROVOD = "horovod"
    MXNET = "mxnet"
    GENERIC = "generic"

    @classmethod
    def from_config(cls, config: TonyConfig) -> "Framework":
        from tony_tpu.config import keys

        name = (config.get(keys.APPLICATION_FRAMEWORK) or "generic").strip().lower()
        try:
            return cls(name)
        except ValueError:
            raise ValueError(
                f"unknown tony.application.framework {name!r}; "
                f"expected one of {[f.value for f in cls]}"
            ) from None


class FrameworkRuntime:
    """Base adapter = the MLGenericRuntime analog: generic env only."""

    def __init__(self, config: TonyConfig):
        self.config = config

    # -- AM-side hooks -----------------------------------------------------
    def validate(self) -> None:
        """Raise on an invalid conf for this framework (AM prepare-time).

        Base checks apply to every framework; subclasses extend."""
        from tony_tpu.config import keys

        interval = self.config.get(keys.CHECKPOINT_INTERVAL_STEPS)
        if interval:
            try:
                int(interval)
            except ValueError:
                raise ValueError(
                    f"{keys.CHECKPOINT_INTERVAL_STEPS} must be an integer, "
                    f"got {interval!r}"
                ) from None

    def on_gang_complete(self, session: "Session") -> None:
        """Called once when every task has registered (spec is complete)."""

    def am_extra_env(self, session: "Session", job_name: str, index: int) -> dict[str, str]:
        """Per-task env contributed by the AM side (e.g. Horovod rank plan)."""
        return {}

    # -- executor-side hooks ----------------------------------------------
    def executor_env(
        self,
        cluster_spec: dict[str, list[str]],
        job_name: str,
        index: int,
    ) -> dict[str, str]:
        """Env for the user process, built from the complete cluster spec.

        Base contract (every adapter inherits it): JOB_NAME / TASK_INDEX /
        TASK_NUM / DISTRIBUTED_MODE / CLUSTER_SPEC.
        """
        import json

        from tony_tpu.config import keys

        total = sum(len(v) for v in cluster_spec.values())
        env = {
            constants.ENV_JOB_NAME: job_name,
            constants.ENV_TASK_INDEX: str(index),
            constants.ENV_TASK_NUM: str(len(cluster_spec.get(job_name, []))),
            constants.ENV_DISTRIBUTED_MODE: (
                constants.DISTRIBUTED_MODE_SINGLE_NODE if total <= 1 else constants.DISTRIBUTED_MODE_GANG
            ),
            constants.ENV_CLUSTER_SPEC: json.dumps(cluster_spec),
        }
        # checkpoint contract: the frozen job conf is the whole-job truth
        # (SURVEY.md §5.6), so tony.checkpoint.* reaches the user process as
        # env that train.loop's arg parser defaults from — the job config
        # configures resume without touching the training script's CLI
        ckpt_dir = self.config.get(keys.CHECKPOINT_DIR)
        if ckpt_dir:
            env[constants.ENV_CHECKPOINT_DIR] = ckpt_dir
        interval = self.config.get(keys.CHECKPOINT_INTERVAL_STEPS)
        if interval and interval != "0":
            # independent of the dir: the training command may pass its own
            # --checkpoint_dir while the job conf owns the cadence
            env[constants.ENV_CHECKPOINT_INTERVAL] = interval
        return env


def get_runtime(config: TonyConfig) -> FrameworkRuntime:
    """Factory (the reference's Framework enum → runtime selection)."""
    fw = Framework.from_config(config)
    if fw == Framework.JAX:
        from tony_tpu.runtime.jax_runtime import JaxRuntime

        return JaxRuntime(config)
    if fw == Framework.TENSORFLOW:
        from tony_tpu.runtime.tf_runtime import TFRuntime

        return TFRuntime(config)
    if fw == Framework.PYTORCH:
        from tony_tpu.runtime.torch_runtime import TorchRuntime

        return TorchRuntime(config)
    if fw == Framework.HOROVOD:
        from tony_tpu.runtime.horovod_runtime import HorovodRuntime

        return HorovodRuntime(config)
    if fw == Framework.MXNET:
        from tony_tpu.runtime.mxnet_runtime import MXNetRuntime

        return MXNetRuntime(config)
    return FrameworkRuntime(config)
