"""Framework runtime adapters (reference tony-core runtime/ analog).

``get_runtime(config)`` selects the adapter from
``tony.application.framework``; ``init_distributed()`` is the user-side helper
that consumes the env contract the JaxRuntime injects.
"""

from __future__ import annotations

import os

from tony_tpu import constants
from tony_tpu.runtime.base import Framework, FrameworkRuntime, get_runtime  # noqa: F401


def init_distributed() -> None:
    """Join the job's jax.distributed process group from injected env.

    Called at the top of TPU-native user programs (the analog of user TF code
    reading TF_CONFIG). No-op for single-process jobs or when the contract env
    is absent, so the same script runs under `tony submit` and bare python.
    """
    coord = os.environ.get(constants.ENV_JAX_COORDINATOR)
    n = int(os.environ.get(constants.ENV_JAX_NUM_PROCESSES, "1"))
    if not coord or n <= 1:
        return
    import jax

    jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=n,
        process_id=int(os.environ[constants.ENV_JAX_PROCESS_ID]),
    )
