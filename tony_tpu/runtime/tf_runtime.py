"""TensorFlow runtime adapter: the TF_CONFIG contract.

Analog of the reference's ``runtime/TFRuntime.java`` (SURVEY.md §2.2, §3.2):
renders ``TF_CONFIG = {"cluster": {type: ["h:p", ...]}, "task": {"type": t,
"index": i}}`` plus the legacy ``CLUSTER_SPEC`` env (inherited from the base
contract), and surfaces the tensorboard task type.
"""

from __future__ import annotations

import json

from tony_tpu import constants
from tony_tpu.runtime.base import FrameworkRuntime


class TFRuntime(FrameworkRuntime):
    def executor_env(self, cluster_spec: dict[str, list[str]], job_name: str, index: int) -> dict[str, str]:
        env = super().executor_env(cluster_spec, job_name, index)
        # tensorboard is an observer, not a TF_CONFIG cluster member
        cluster = {t: a for t, a in cluster_spec.items() if t != constants.TENSORBOARD_JOB_NAME}
        env[constants.ENV_TF_CONFIG] = json.dumps(
            {"cluster": cluster, "task": {"type": job_name, "index": index}}
        )
        return env
