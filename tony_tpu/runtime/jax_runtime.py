"""JAX runtime adapter — the TPU-native first-class runtime.

This is the adapter the reference never had (its closest analogs are
TFRuntime/HorovodRuntime rendezvous — SURVEY.md §2.2): it injects the
``jax.distributed.initialize`` contract so every task joins one JAX process
group whose collectives ride ICI/DCN via XLA:

- coordinator = the rank-0 task's registered address (chief if declared,
  else the first task in canonical order),
- ``JAX_PROCESS_ID`` = canonical global rank, ``JAX_NUM_PROCESSES`` = gang size.

User code then just calls ``tony_tpu.runtime.init_distributed()`` (or plain
``jax.distributed.initialize()`` reading these env vars).
"""

from __future__ import annotations

from tony_tpu import constants
from tony_tpu.runtime.base import FrameworkRuntime


def canonical_task_order(
    cluster_spec: dict[str, list[str]], exclude: frozenset[str] = frozenset()
) -> list[tuple[str, int]]:
    """Deterministic global rank order: chief first, then remaining types
    alphabetically, each type by index. Every adapter that needs a global
    rank (jax, pytorch, horovod) uses this one ordering. ``exclude`` drops
    sidecar types (tensorboard, notebook, ...) that must not join the
    training process group."""
    order: list[tuple[str, int]] = []
    types = sorted(t for t in cluster_spec if t not in exclude)
    if constants.CHIEF_JOB_NAME in types:
        types.remove(constants.CHIEF_JOB_NAME)
        types.insert(0, constants.CHIEF_JOB_NAME)
    for t in types:
        order.extend((t, i) for i in range(len(cluster_spec[t])))
    return order


def global_rank(
    cluster_spec: dict[str, list[str]], job_name: str, index: int,
    exclude: frozenset[str] = frozenset(),
) -> int:
    return canonical_task_order(cluster_spec, exclude).index((job_name, index))


def coordinator_address(
    cluster_spec: dict[str, list[str]], exclude: frozenset[str] = frozenset()
) -> str:
    t, i = canonical_task_order(cluster_spec, exclude)[0]
    return cluster_spec[t][i]


class JaxRuntime(FrameworkRuntime):
    def executor_env(self, cluster_spec: dict[str, list[str]], job_name: str, index: int) -> dict[str, str]:
        env = super().executor_env(cluster_spec, job_name, index)
        # untracked sidecars (tensorboard, notebook, ps-as-observer) never join
        # the jax.distributed group — and must not become its coordinator.
        exclude = self.config.untracked_types()
        order = canonical_task_order(cluster_spec, exclude)
        if (job_name, index) not in order:
            return env  # sidecar task: no process-group contract
        env[constants.ENV_JAX_COORDINATOR] = coordinator_address(cluster_spec, exclude)
        env[constants.ENV_JAX_PROCESS_ID] = str(order.index((job_name, index)))
        env[constants.ENV_JAX_NUM_PROCESSES] = str(len(order))
        return env
