"""MXNet runtime adapter: the DMLC PS-Lite env contract.

Analog of the reference's ``runtime/MXNetRuntime.java`` (SURVEY.md §2.2,
confidence [L] there — details follow the DMLC convention): the ``ps`` job
type plays the DMLC server role, ``worker`` the worker role, and the root URI
points at the first ps (or a dedicated ``scheduler`` type if declared).
"""

from __future__ import annotations

from tony_tpu import constants
from tony_tpu.runtime.base import FrameworkRuntime


class MXNetRuntime(FrameworkRuntime):
    _ROLE_MAP = {constants.PS_JOB_NAME: "server", "scheduler": "scheduler"}

    def executor_env(self, cluster_spec: dict[str, list[str]], job_name: str, index: int) -> dict[str, str]:
        env = super().executor_env(cluster_spec, job_name, index)
        root_type = "scheduler" if "scheduler" in cluster_spec else constants.PS_JOB_NAME
        root = cluster_spec.get(root_type, [None])[0] or next(iter(cluster_spec.values()))[0]
        host, _, port = root.rpartition(":")
        env[constants.ENV_DMLC_PS_ROOT_URI] = host
        env[constants.ENV_DMLC_PS_ROOT_PORT] = port
        env[constants.ENV_DMLC_ROLE] = self._ROLE_MAP.get(job_name, "worker")
        env[constants.ENV_DMLC_NUM_SERVER] = str(len(cluster_spec.get(constants.PS_JOB_NAME, [])))
        env[constants.ENV_DMLC_NUM_WORKER] = str(len(cluster_spec.get(constants.WORKER_JOB_NAME, [])))
        return env
