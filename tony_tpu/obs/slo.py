"""SLO objectives, error-budget ledgers, and multi-window burn-rate alerting.

The judgement layer over the telemetry the stack already collects: operators
declare *objectives* as plain ``tony.slo.*`` config keys and this module
turns the raw counters (serve TTFT histograms, request-outcome counters, the
train goodput ledger) into

- an **error-budget ledger** per objective — exact good/bad accounting over
  a trailing compliance window, bucketed at ``tony.slo.bucket-ms`` grain,
  reset-safe against replica restarts (the same exactness contract as
  goodput's wall-time partition, property-tested the same way);
- **multi-window multi-burn-rate alert rules** (SRE-workbook shape: a
  fast-burn page and a slow-burn warn, each confirmed by a short secondary
  window so rules resolve promptly once the burn actually stops) compiled
  into the AM's edge-triggered :class:`~tony_tpu.obs.alerts.AlertEngine`,
  with rule names prefixed ``slo-`` so the emit loop publishes them as
  ``SLO_BURN_ALERT`` / ``SLO_BURN_RESOLVED`` events;
- ``tony_slo_budget_remaining`` / ``tony_slo_burn_rate`` gauges, a status
  document for ``tony slo`` / the portal ``/slo`` page, and per-bucket
  JSONL window rows (``<staging>/<app>/slo.jsonl``) the history server
  ingests into ``slo_series`` so verdicts survive the AM.

================================  ============================================
``tony.slo.serve-ttft-target``    fraction of requests whose TTFT must land
                                  under ``serve-ttft-threshold-ms`` (empty
                                  threshold inherits the capacity market's
                                  ``tony.serve.market.slo-ttft-ms``)
``tony.slo.serve-availability-target``  fraction of requests finishing
                                  without server error
``tony.slo.train-goodput-target``  windowed goodput-ms floor (unit is
                                  milliseconds, not requests — the ledger
                                  partition feeds it)
================================  ============================================

Exactness matters here the same way it does for goodput: the serve TTFT
histogram grows a bucket edge aligned to the configured threshold
(:meth:`~tony_tpu.obs.metrics.Histogram.ensure_bucket`), so good/bad counts
come straight off cumulative bucket counts — never interpolated — and the
``tony slo verdict`` read from history is count-exact, not estimated.
"""

from __future__ import annotations

import json
import math
import threading
from dataclasses import dataclass
from typing import Any, Iterable, Mapping

from tony_tpu.obs import alerts as obs_alerts
from tony_tpu.obs import logging as obs_logging
from tony_tpu.obs import metrics as obs_metrics

_BUDGET_REMAINING = obs_metrics.gauge(
    "tony_slo_budget_remaining",
    "fraction of the compliance-window error budget left, per objective",
    labelnames=("objective",))
_BURN_RATE = obs_metrics.gauge(
    "tony_slo_burn_rate",
    "error-budget burn rate per objective over the fast/slow alert windows",
    labelnames=("objective", "window"))

#: Alert-rule name prefix the AM's emit loop branches on to publish SLO
#: transitions as SLO_BURN_ALERT/SLO_BURN_RESOLVED instead of ALERT_*.
RULE_PREFIX = "slo-"

#: objective vocabulary: name → unit of its good/bad counts.
OBJECTIVES: dict[str, str] = {
    "serve-ttft": "requests",
    "serve-availability": "requests",
    "train-goodput": "ms",
}


@dataclass(frozen=True)
class Objective:
    name: str                  # one of OBJECTIVES
    target: float              # good fraction promised, 0 < target < 1
    unit: str = "requests"
    threshold_ms: float | None = None   # serve-ttft: the aligned bucket edge

    @property
    def allowed_bad_fraction(self) -> float:
        return 1.0 - self.target


def objectives_from_config(config) -> list[Objective]:
    """Parse ``tony.slo.*`` into objectives; empty targets are disabled,
    unparseable values a loud no (mirrors alerts.rules_from_config)."""
    from tony_tpu.config import keys

    def target_of(key: str) -> float | None:
        raw = config.get(key)
        if raw in (None, ""):
            return None
        try:
            t = float(raw)
        except ValueError as e:
            raise ValueError(f"{key}={raw!r} is not a number") from e
        if not 0.0 < t < 1.0:
            raise ValueError(f"{key}={raw!r} must be a fraction in (0, 1)")
        return t

    out: list[Objective] = []
    t = target_of(keys.SLO_SERVE_TTFT_TARGET)
    if t is not None:
        raw_thr = config.get(keys.SLO_SERVE_TTFT_THRESHOLD_MS)
        if raw_thr in (None, ""):
            raw_thr = config.get(keys.SERVE_MARKET_SLO_TTFT_MS) or "2000"
        thr = float(raw_thr)
        if not (math.isfinite(thr) and thr > 0):
            raise ValueError(f"slo serve-ttft threshold {raw_thr!r} must be > 0 ms")
        out.append(Objective("serve-ttft", t, "requests", thr))
    t = target_of(keys.SLO_SERVE_AVAILABILITY_TARGET)
    if t is not None:
        out.append(Objective("serve-availability", t, "requests"))
    t = target_of(keys.SLO_TRAIN_GOODPUT_TARGET)
    if t is not None:
        out.append(Objective("train-goodput", t, "ms"))
    return out


class BudgetLedger:
    """Exact good/bad accounting for one objective over a trailing window.

    Ingests **cumulative** (good_total, bad_total) counter samples — the
    shape registry snapshots give us — per source (task identity), deltas
    them against a watermark, and banks the deltas into fixed-width time
    buckets. A counter running backwards is a process restart: the fresh
    totals ARE the delta (nothing is lost, nothing double-counted).

    The exactness contract (property-tested like goodput's partition):
    ``total ingested == expired out the window + still banked in buckets``
    at every point in time, for any interleaving of ingests, advances,
    window boundaries, and counter resets.
    """

    def __init__(self, objective: Objective, window_ms: int, bucket_ms: int):
        window_ms, bucket_ms = int(window_ms), int(bucket_ms)
        if window_ms <= 0 or bucket_ms <= 0 or bucket_ms > window_ms:
            raise ValueError(
                f"slo {objective.name}: need 0 < bucket-ms ({bucket_ms}) "
                f"<= window-ms ({window_ms})")
        self.objective = objective
        self.window_ms = window_ms
        self.bucket_ms = bucket_ms
        self._buckets: dict[int, list[int]] = {}       # bucket start → [good, bad]
        self._last: dict[str, tuple[int, int]] = {}    # source → cumulative watermark
        self.total_good = 0
        self.total_bad = 0
        self.expired_good = 0
        self.expired_bad = 0

    def ingest(self, source: str, good_total: int, bad_total: int,
               now_ms: int) -> tuple[int, int]:
        """Account one cumulative sample; returns the (good, bad) delta banked."""
        g, b = int(good_total), int(bad_total)
        last = self._last.get(source)
        if last is None:
            dg, db = g, b
        else:
            dg, db = g - last[0], b - last[1]
            if dg < 0 or db < 0:   # counter reset: restarted source starts fresh
                dg, db = g, b
        self._last[source] = (g, b)
        if dg or db:
            start = (int(now_ms) // self.bucket_ms) * self.bucket_ms
            cell = self._buckets.get(start)
            if cell is None:
                cell = self._buckets[start] = [0, 0]
            cell[0] += dg
            cell[1] += db
            self.total_good += dg
            self.total_bad += db
        return dg, db

    def forget(self, source: str) -> None:
        """Drop a source's watermark (task gone); its banked history stays."""
        self._last.pop(source, None)

    def advance(self, now_ms: int) -> None:
        """Expire buckets that fell wholly out of the compliance window."""
        edge = int(now_ms) - self.window_ms
        for start in [s for s in self._buckets if s + self.bucket_ms <= edge]:
            g, b = self._buckets.pop(start)
            self.expired_good += g
            self.expired_bad += b

    def window_counts(self, now_ms: int,
                      window_ms: int | None = None) -> tuple[int, int]:
        """(good, bad) banked within the trailing ``window_ms`` (≤ the
        compliance window; buckets overlapping the edge count whole — the
        grain of truth is the bucket, never a fraction of one)."""
        now = int(now_ms)
        w = self.window_ms if window_ms is None else min(int(window_ms), self.window_ms)
        lo = now - w
        good = bad = 0
        for start, (g, b) in self._buckets.items():
            if start + self.bucket_ms > lo and start <= now:
                good += g
                bad += b
        return good, bad

    def bucket_counts(self, now_ms: int) -> tuple[int, int, int]:
        """(bucket_start_ms, good, bad) for the bucket ``now_ms`` lands in."""
        start = (int(now_ms) // self.bucket_ms) * self.bucket_ms
        cell = self._buckets.get(start) or (0, 0)
        return start, int(cell[0]), int(cell[1])

    def burn_rate(self, now_ms: int, window_ms: int | None = None) -> float | None:
        """bad-fraction / allowed-bad-fraction over the window; 1.0 burns the
        budget in exactly one compliance window. None = no traffic (no data
        must neither fire nor resolve, same contract as AlertEngine)."""
        good, bad = self.window_counts(now_ms, window_ms)
        total = good + bad
        if total == 0:
            return None
        allowed = self.objective.allowed_bad_fraction
        if allowed <= 0.0:
            return math.inf if bad else 0.0
        return (bad / total) / allowed

    def budget_remaining(self, now_ms: int) -> float:
        """Fraction of the window's error budget left (budget = allowed bad
        count given the observed volume); clamped at 0."""
        good, bad = self.window_counts(now_ms)
        allowed = self.objective.allowed_bad_fraction * (good + bad)
        if allowed <= 0.0:
            return 1.0 if bad == 0 else 0.0
        return max(0.0, 1.0 - bad / allowed)


# --------------------------------------------------------------- extraction
def _snapshot_metric(snapshot: Iterable[Mapping[str, Any]],
                     name: str) -> Mapping[str, Any] | None:
    for m in snapshot or ():
        if m.get("name") == name:
            return m
    return None


def ttft_good_bad(snapshot: Iterable[Mapping[str, Any]],
                  threshold_ms: float,
                  name: str = "tony_serve_ttft_seconds") -> tuple[int, int] | None:
    """Cumulative (good, bad) request counts from a TTFT histogram snapshot:
    good = cumulative count at the largest bucket edge ≤ threshold. Exact
    when the engine inserted the SLO-aligned edge (ensure_bucket)."""
    m = _snapshot_metric(snapshot, name)
    if m is None:
        return None
    thr_s = float(threshold_ms) / 1000.0
    buckets = m.get("buckets") or []
    good = total = 0
    for sample in m.get("samples", []):
        cum = 0
        at_thr = 0
        for ub, n in zip(buckets, sample.get("counts", [])):
            cum += int(n)
            if float(ub) <= thr_s + 1e-9:
                at_thr = cum
            else:
                break
        good += at_thr
        total += int(sample.get("count", 0))
    return good, max(total - good, 0)


def availability_good_bad(
        snapshot: Iterable[Mapping[str, Any]],
        name: str = "tony_serve_requests_total") -> tuple[int, int] | None:
    """(non-error, error) finished-request counts by outcome label. A client
    cancel is not a server error — it spends no availability budget."""
    m = _snapshot_metric(snapshot, name)
    if m is None:
        return None
    good = bad = 0
    for sample in m.get("samples", []):
        v = int(sample.get("value", 0))
        if sample.get("labels", {}).get("outcome") == "error":
            bad += v
        else:
            good += v
    return good, bad


def ttft_exemplars(snapshot: Iterable[Mapping[str, Any]],
                   name: str = "tony_serve_ttft_seconds") -> list[tuple[float, str]]:
    """Worst-offender (ttft_seconds, request_id) exemplars from a snapshot."""
    m = _snapshot_metric(snapshot, name)
    if m is None:
        return []
    out: list[tuple[float, str]] = []
    for sample in m.get("samples", []):
        for e in sample.get("exemplars", ()):
            try:
                out.append((float(e[0]), str(e[1])))
            except (TypeError, ValueError, IndexError):
                continue
    out.sort(key=lambda t: -t[0])
    return out[:obs_metrics.EXEMPLAR_K]


# ------------------------------------------------------------------- engine
class SloEngine:
    """Objectives + ledgers + burn rules + gauges + the slo.jsonl stream.

    Owned by the AM; fed from the goodput tick (serve registry snapshots per
    task, the train ledger) and read by the ``get_slo`` RPC. All public
    methods take the caller's clock so tests drive time deterministically.
    """

    def __init__(self, config, app_id: str = "", sink_path: str | None = None):
        from tony_tpu.config import keys

        self.app_id = app_id
        self.objectives = objectives_from_config(config)
        self.window_ms = int(config.get(keys.SLO_WINDOW_MS) or "3600000")
        self.bucket_ms = int(config.get(keys.SLO_BUCKET_MS) or "5000")
        self.fast_burn = float(config.get(keys.SLO_FAST_BURN) or "14.4")
        self.fast_window_ms = int(config.get(keys.SLO_FAST_WINDOW_MS) or "300000")
        self.slow_burn = float(config.get(keys.SLO_SLOW_BURN) or "6.0")
        self.slow_window_ms = int(config.get(keys.SLO_SLOW_WINDOW_MS) or "1800000")
        self.sink_path = sink_path or None
        self.ledgers = {
            o.name: BudgetLedger(o, self.window_ms, self.bucket_ms)
            for o in self.objectives
        }
        self._exemplars: dict[str, list[tuple[float, str]]] = {}
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return bool(self.objectives)

    def ttft_threshold_ms(self) -> float | None:
        for o in self.objectives:
            if o.name == "serve-ttft":
                return o.threshold_ms
        return None

    def burn_rules(self) -> list[obs_alerts.AlertRule]:
        """The rules to append to the AM's AlertEngine: per objective, a
        fast-burn page and a slow-burn warn (burn rate is unitless ×)."""
        rules: list[obs_alerts.AlertRule] = []
        for o in self.objectives:
            rules.append(obs_alerts.AlertRule(
                f"{RULE_PREFIX}{o.name}-fast-burn", self.fast_burn, "above", "x"))
            rules.append(obs_alerts.AlertRule(
                f"{RULE_PREFIX}{o.name}-slow-burn", self.slow_burn, "above", "x"))
        return rules

    # ---------------------------------------------------------- ingestion
    def observe_serve(self, source: str, snapshot: Iterable[Mapping[str, Any]],
                      now_ms: int) -> None:
        """Account one serve task's registry snapshot (from task_obs)."""
        with self._lock:
            for o in self.objectives:
                if o.name == "serve-ttft":
                    gb = ttft_good_bad(snapshot, o.threshold_ms or 0.0)
                elif o.name == "serve-availability":
                    gb = availability_good_bad(snapshot)
                else:
                    continue
                if gb is not None:
                    self.ledgers[o.name].ingest(source, gb[0], gb[1], now_ms)
            if any(o.name == "serve-ttft" for o in self.objectives):
                fresh = ttft_exemplars(snapshot)
                if fresh:
                    merged = {rid: v for v, rid in self._exemplars.get("serve-ttft", [])}
                    merged.update({rid: v for v, rid in fresh})
                    top = sorted(((v, rid) for rid, v in merged.items()),
                                 key=lambda t: -t[0])
                    self._exemplars["serve-ttft"] = top[:obs_metrics.EXEMPLAR_K]

    def observe_train(self, source: str, ledger, now_ms: int) -> None:
        """Account the goodput ledger's exact wall partition: good =
        productive ms, bad = everything else (cumulative, reset-safe)."""
        if "train-goodput" not in self.ledgers or ledger is None:
            return
        wall = int(ledger.wall_ms)
        good = int(ledger.phases_ms.get("productive", 0))
        with self._lock:
            self.ledgers["train-goodput"].ingest(
                source, good, max(wall - good, 0), now_ms)

    # --------------------------------------------------------- evaluation
    def _rule_burn(self, led: BudgetLedger, window_ms: int,
                   now_ms: int) -> float | None:
        """Multi-window burn: the long window supplies the sustained signal,
        a short confirmation window (window/12, floored at one bucket) makes
        the rule resolve promptly once the burn actually stops. No data in
        the short window means no *current* burn (min with 0)."""
        long_burn = led.burn_rate(now_ms, window_ms)
        if long_burn is None:
            return None
        short_w = max(self.bucket_ms, int(window_ms) // 12)
        short_burn = led.burn_rate(now_ms, short_w)
        return min(long_burn, short_burn if short_burn is not None else 0.0)

    def tick(self, now_ms: int) -> dict[str, float | None]:
        """Advance ledgers, refresh the gauges, and return the value per
        burn rule for AlertEngine.evaluate (None = no data, state holds)."""
        values: dict[str, float | None] = {}
        with self._lock:
            for o in self.objectives:
                led = self.ledgers[o.name]
                led.advance(now_ms)
                fast = self._rule_burn(led, self.fast_window_ms, now_ms)
                slow = self._rule_burn(led, self.slow_window_ms, now_ms)
                values[f"{RULE_PREFIX}{o.name}-fast-burn"] = fast
                values[f"{RULE_PREFIX}{o.name}-slow-burn"] = slow
                if fast is not None:
                    _BURN_RATE.set(fast, objective=o.name, window="fast")
                if slow is not None:
                    _BURN_RATE.set(slow, objective=o.name, window="slow")
                _BUDGET_REMAINING.set(led.budget_remaining(now_ms), objective=o.name)
        return values

    # ----------------------------------------------------------- surfaces
    def status(self, now_ms: int) -> dict[str, Any]:
        """The ``tony slo`` / portal document: per objective, the window
        counts, budget, burn rates, and worst-offender exemplars."""
        out: dict[str, Any] = {
            "app_id": self.app_id,
            "enabled": self.enabled,
            "window_ms": self.window_ms,
            "bucket_ms": self.bucket_ms,
            "fast_burn": self.fast_burn,
            "fast_window_ms": self.fast_window_ms,
            "slow_burn": self.slow_burn,
            "slow_window_ms": self.slow_window_ms,
            "ts_ms": int(now_ms),
            "objectives": {},
        }
        with self._lock:
            for o in self.objectives:
                led = self.ledgers[o.name]
                good, bad = led.window_counts(now_ms)
                out["objectives"][o.name] = {
                    "target": o.target,
                    "unit": o.unit,
                    "threshold_ms": o.threshold_ms,
                    "good": good,
                    "bad": bad,
                    "budget_remaining": led.budget_remaining(now_ms),
                    "burn_fast": self._rule_burn(led, self.fast_window_ms, now_ms),
                    "burn_slow": self._rule_burn(led, self.slow_window_ms, now_ms),
                    "exemplars": [
                        {"value_s": v, "request_id": rid}
                        for v, rid in self._exemplars.get(o.name, [])
                    ],
                }
        return out

    def window_rows(self, now_ms: int) -> list[dict[str, Any]]:
        """One row per objective for the bucket ``now_ms`` lands in — the
        slo.jsonl / slo_series shape. Rewriting the same bucket as it fills
        is fine: the store keys on (source, objective, window_start_ms) and
        REPLACEs, so the last write (the fullest) wins."""
        rows: list[dict[str, Any]] = []
        with self._lock:
            for o in self.objectives:
                led = self.ledgers[o.name]
                start, good, bad = led.bucket_counts(now_ms)
                rows.append({
                    "app_id": self.app_id,
                    "objective": o.name,
                    "target": o.target,
                    "unit": o.unit,
                    "window_start_ms": start,
                    "window_end_ms": start + self.bucket_ms,
                    "good": good,
                    "bad": bad,
                    "burn_fast": self._rule_burn(led, self.fast_window_ms, now_ms),
                    "burn_slow": self._rule_burn(led, self.slow_window_ms, now_ms),
                    "budget_remaining": led.budget_remaining(now_ms),
                })
        return rows

    def append_windows(self, now_ms: int) -> None:
        """Best-effort slo.jsonl append (same torn-tail discipline as every
        other artifact; a full disk must never take down the AM)."""
        if not self.sink_path or not self.enabled:
            return
        try:
            rows = self.window_rows(now_ms)
            with open(self.sink_path, "a") as f:
                for row in rows:
                    f.write(json.dumps(row) + "\n")
        except OSError as e:
            obs_logging.warning(f"[tony-slo] sink write failed: {e}")


# ------------------------------------------------------------------ verdict
def verdict_from_rows(rows: Iterable[Mapping[str, Any]], window_ms: int,
                      now_ms: int) -> dict[str, Any]:
    """The machine-readable pass/fail over persisted ``slo_series`` rows
    (history store or slo.jsonl) — deliberately NOT in-process state, so the
    verdict survives the AM. Counts are summed per objective over the
    trailing window; an objective passes when its achieved good fraction
    meets the target it recorded (rows are self-describing). Overall:
    PASS = every objective with data passes; NO_DATA = nothing in window.
    """
    lo = int(now_ms) - int(window_ms)
    agg: dict[str, dict[str, Any]] = {}
    for r in rows:
        try:
            start = int(r["window_start_ms"])
            name = str(r["objective"])
        except (KeyError, TypeError, ValueError):
            continue
        if start + 1 <= lo or start > now_ms:
            continue
        a = agg.setdefault(name, {
            "good": 0, "bad": 0, "target": float(r.get("target") or 0.0),
            "unit": str(r.get("unit") or ""), "rows": 0,
        })
        a["good"] += int(r.get("good") or 0)
        a["bad"] += int(r.get("bad") or 0)
        a["target"] = max(a["target"], float(r.get("target") or 0.0))
        a["rows"] += 1
    objectives: dict[str, Any] = {}
    all_pass = True
    for name, a in sorted(agg.items()):
        total = a["good"] + a["bad"]
        achieved = a["good"] / total if total else None
        allowed = (1.0 - a["target"]) * total
        if allowed > 0.0:
            burned_pct = 100.0 * a["bad"] / allowed
        else:
            burned_pct = 0.0 if a["bad"] == 0 else math.inf
        passed = (achieved is not None
                  and achieved + 1e-12 >= a["target"])
        if total and not passed:
            all_pass = False
        objectives[name] = {
            "target": a["target"],
            "unit": a["unit"],
            "good": a["good"],
            "bad": a["bad"],
            "achieved": achieved,
            "budget_burned_pct": burned_pct,
            "rows": a["rows"],
            "passed": passed if total else None,
        }
    with_data = [o for o in objectives.values() if (o["good"] + o["bad"])]
    verdict = "NO_DATA" if not with_data else ("PASS" if all_pass else "FAIL")
    return {
        "verdict": verdict,
        "window_ms": int(window_ms),
        "ts_ms": int(now_ms),
        "objectives": objectives,
    }
