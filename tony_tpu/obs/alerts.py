"""Declarative alert rules over the live goodput/health signals.

The decision layer on top of three generations of telemetry: operators
declare thresholds as plain ``tony.alerts.*`` config keys and the engine
turns signal crossings into ``ALERT_FIRED`` / ``ALERT_RESOLVED`` events, a
``tony_alerts_active`` gauge, and a pluggable sink (JSONL file + optional
webhook). Rules are **per job** — they ride the frozen config like every
other ``tony.*`` knob:

=================================  ==========================================
``tony.alerts.goodput-floor``      fires while the trailing-window goodput
                                   fraction (obs/goodput.py,
                                   ``tony.goodput.window-ms``) is BELOW this
``tony.alerts.step-time-p99-ms``   fires while the gang's step-time p99
                                   (merged ``tony_train_step_seconds``
                                   histograms) is ABOVE this
``tony.alerts.heartbeat-age-ms``   fires while any live task's last
                                   heartbeat is older than this
``tony.alerts.queue-depth``        fires while any serve replica's admission
                                   queue is deeper than this
=================================  ==========================================

Empty (the default) disables a rule. The engine is deliberately edge-
triggered state, not a stream processor: :meth:`AlertEngine.evaluate` takes
the current value per rule (None = no data, state unchanged) and returns
only the TRANSITIONS — the caller (the AM's goodput tick, the history
server's finalized-job sweep) owns when to sample and what to do with a
transition. The sink is best-effort by contract: a full disk or a dead
webhook must never take down the control plane.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass
from typing import Any, Mapping

from tony_tpu.obs import logging as obs_logging
from tony_tpu.obs import metrics as obs_metrics

_ACTIVE = obs_metrics.gauge(
    "tony_alerts_active", "alert rules currently firing for this job")
_TRANSITIONS = obs_metrics.counter(
    "tony_alerts_transitions_total",
    "alert state transitions by rule and action (fired, resolved)",
    labelnames=("rule", "action"))

#: rule vocabulary: name → (direction, unit). ``below`` fires when
#: value < threshold; ``above`` when value > threshold.
RULES: dict[str, tuple[str, str]] = {
    "goodput-floor": ("below", "fraction"),
    "step-time-p99-ms": ("above", "ms"),
    "heartbeat-age-ms": ("above", "ms"),
    "queue-depth": ("above", "requests"),
}


@dataclass(frozen=True)
class AlertRule:
    name: str          # one of RULES
    threshold: float
    direction: str     # "below" | "above"
    unit: str = ""

    def breached(self, value: float) -> bool:
        return value < self.threshold if self.direction == "below" else value > self.threshold


def rules_from_config(config) -> list[AlertRule]:
    """Parse the ``tony.alerts.*`` keys into rules; unset/empty keys are
    disabled, unparseable values are a loud no (config mistakes must not
    silently disable monitoring)."""
    from tony_tpu.config import keys

    declared = {
        "goodput-floor": keys.ALERTS_GOODPUT_FLOOR,
        "step-time-p99-ms": keys.ALERTS_STEP_TIME_P99_MS,
        "heartbeat-age-ms": keys.ALERTS_HEARTBEAT_AGE_MS,
        "queue-depth": keys.ALERTS_QUEUE_DEPTH,
    }
    out: list[AlertRule] = []
    for name, (direction, unit) in RULES.items():
        raw = config.get(declared[name])
        if raw in (None, ""):
            continue
        try:
            threshold = float(raw)
        except ValueError as e:
            raise ValueError(f"tony.alerts.{name}={raw!r} is not a number") from e
        out.append(AlertRule(name, threshold, direction, unit))
    return out


class AlertSink:
    """Where transitions go besides the event stream: an append-only JSONL
    file (same torn-tail discipline as every other artifact) and an optional
    webhook POSTing each transition as JSON. Both best-effort."""

    def __init__(self, jsonl_path: str | None = None,
                 webhook_url: str | None = None, timeout_s: float = 2.0):
        self.jsonl_path = jsonl_path or None
        self.webhook_url = webhook_url or None
        self.timeout_s = timeout_s

    def deliver(self, record: Mapping[str, Any]) -> None:
        if self.jsonl_path:
            try:
                with open(self.jsonl_path, "a") as f:
                    f.write(json.dumps(record) + "\n")
            except OSError as e:
                obs_logging.warning(f"[tony-alerts] sink write failed: {e}")
        if self.webhook_url:
            try:
                import urllib.request

                req = urllib.request.Request(
                    self.webhook_url,
                    data=json.dumps(record).encode(),
                    headers={"Content-Type": "application/json"},
                )
                urllib.request.urlopen(req, timeout=self.timeout_s).close()
            except Exception as e:  # noqa: BLE001 — a dead webhook is not our outage
                obs_logging.warning(f"[tony-alerts] webhook delivery failed: {e}")


class AlertEngine:
    """Edge-triggered rule evaluation: tracks which rules are firing and
    reports only the transitions."""

    def __init__(self, rules: list[AlertRule], sink: AlertSink | None = None,
                 app_id: str = ""):
        self.rules = list(rules)
        self.sink = sink
        self.app_id = app_id
        self._active: dict[str, dict[str, Any]] = {}   # rule name → fired record

    def active(self) -> list[dict[str, Any]]:
        """Currently-firing alerts (fired record + last observed value)."""
        return [dict(rec) for _, rec in sorted(self._active.items())]

    def evaluate(
        self, values: Mapping[str, float | None], now_ms: int | None = None
    ) -> list[dict[str, Any]]:
        """One sample per rule name (None = no data this tick: state holds —
        a scrape gap must neither fire nor resolve anything). Returns the
        transition records, each already delivered to the sink."""
        now = int(now_ms if now_ms is not None else time.time() * 1000)
        transitions: list[dict[str, Any]] = []
        for rule in self.rules:
            value = values.get(rule.name)
            if value is None:
                continue
            firing = rule.breached(float(value))
            was = rule.name in self._active
            if firing and not was:
                rec = {
                    "app_id": self.app_id,
                    "rule": rule.name,
                    "state": "fired",
                    "value": float(value),
                    "threshold": rule.threshold,
                    "direction": rule.direction,
                    "unit": rule.unit,
                    "ts_ms": now,
                }
                self._active[rule.name] = dict(rec, state="firing")
                transitions.append(rec)
                _TRANSITIONS.inc(rule=rule.name, action="fired")
            elif not firing and was:
                fired = self._active.pop(rule.name)
                rec = {
                    "app_id": self.app_id,
                    "rule": rule.name,
                    "state": "resolved",
                    "value": float(value),
                    "threshold": rule.threshold,
                    "direction": rule.direction,
                    "unit": rule.unit,
                    "ts_ms": now,
                    "active_ms": max(now - int(fired.get("ts_ms", now)), 0),
                }
                transitions.append(rec)
                _TRANSITIONS.inc(rule=rule.name, action="resolved")
            elif firing:
                self._active[rule.name]["value"] = float(value)
        _ACTIVE.set(len(self._active))
        if self.sink is not None:
            for rec in transitions:
                self.sink.deliver(rec)
        return transitions

    def resolve_all(self, reason: str, now_ms: int | None = None) -> list[dict[str, Any]]:
        """Finalization: a finished job's alerts are no longer actionable —
        resolve them loudly rather than leaving ghosts in the sink."""
        now = int(now_ms if now_ms is not None else time.time() * 1000)
        transitions = []
        for name, fired in sorted(self._active.items()):
            rec = {
                "app_id": self.app_id,
                "rule": name,
                "state": "resolved",
                "reason": reason,
                "threshold": fired.get("threshold"),
                "direction": fired.get("direction"),
                "unit": fired.get("unit"),
                "ts_ms": now,
                "active_ms": max(now - int(fired.get("ts_ms", now)), 0),
            }
            transitions.append(rec)
            _TRANSITIONS.inc(rule=name, action="resolved")
        self._active.clear()
        _ACTIVE.set(0)
        if self.sink is not None:
            for rec in transitions:
                self.sink.deliver(rec)
        return transitions
