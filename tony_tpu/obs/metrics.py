"""Process-wide metrics registry with Prometheus text exposition.

The MetricsRpc analog grown up: instead of ad-hoc dicts pushed to the AM,
every process owns one :data:`REGISTRY` of named counters / gauges /
fixed-bucket histograms. Instrumented paths (RPC client/server latency,
``call_with_retry`` attempts/backoff, heartbeat RTT, scheduler queue wait,
checkpoint durations, sampled train-step time) record into it; exposition is

- ``GET /metrics`` on the portal (Prometheus text format 0.0.4), which merges
  its own registry with every running AM's via the ``get_metrics`` RPC, and
- the AM's ``get_metrics`` RPC returning :meth:`MetricsRegistry.snapshot`.

Snapshots are plain JSON (they ride the framed-JSON RPC), and
:func:`render_merged` turns any set of (snapshot, extra-labels) groups into
one valid exposition — the portal labels each AM's group with ``app=<id>``.

Everything is stdlib + threads; recording is a dict update under a per-metric
lock (the instrumented paths are control-plane rate, not the train step).
``set_enabled(False)`` (``tony.metrics.enabled=false``) turns every recording
call into an early return.
"""

from __future__ import annotations

import math
import threading
from typing import Any, Iterable, Mapping, Sequence

_INF = float("inf")

#: Default latency buckets (seconds): sub-ms RPC dispatch up to multi-second
#: checkpoint/compile work.
DEFAULT_BUCKETS: tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0,
)

#: Wider buckets for waits measured in seconds-to-minutes (queue admission,
#: gang registration, restarts).
WAIT_BUCKETS: tuple[float, ...] = (
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0,
)

_enabled = True


def set_enabled(on: bool) -> None:
    """Gate all recording (tony.metrics.enabled); registration still works."""
    global _enabled
    _enabled = bool(on)


class _Metric:
    kind = ""

    def __init__(self, name: str, help_: str, labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help_
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: dict[tuple[str, ...], Any] = {}

    def _key(self, labels: Mapping[str, Any]) -> tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != declared {sorted(self.labelnames)}"
            )
        return tuple(str(labels[n]) for n in self.labelnames)

    def remove(self, **labels: Any) -> None:
        """Drop one label child from the exposition. For bounded-lifetime
        label values (e.g. the portal's per-app scrape-age gauge): without
        removal, every value ever labeled stays a frozen series forever —
        unbounded cardinality and permanently stale samples."""
        key = self._key(labels)
        with self._lock:
            self._children.pop(key, None)

    def _label_dicts(self) -> "list[tuple[tuple[str, ...], Any]]":
        with self._lock:
            # deep-copy histogram children: observe() mutates them under
            # this same lock, and a live reference would let a concurrent
            # observe tear the snapshot (counts summing to N+1, count N →
            # a non-monotone exposition scrapers reject)
            return [
                (k, dict(v, counts=list(v["counts"])) if isinstance(v, dict) else v)
                for k, v in self._children.items()
            ]


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels: Any) -> None:
        if not _enabled:
            return
        key = self._key(labels)
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + amount

    def value(self, **labels: Any) -> float:
        key = self._key(labels)
        with self._lock:
            return float(self._children.get(key, 0.0))


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels: Any) -> None:
        if not _enabled:
            return
        key = self._key(labels)
        with self._lock:
            self._children[key] = float(value)

    def value(self, **labels: Any) -> float:
        key = self._key(labels)
        with self._lock:
            return float(self._children.get(key, 0.0))


#: Worst-offender exemplars kept per histogram label child (highest values).
EXEMPLAR_K = 5


class Histogram(_Metric):
    """Fixed-bucket histogram (per-bucket increments; cumulated at render)."""

    kind = "histogram"

    def __init__(self, name: str, help_: str, labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help_, labelnames)
        bs = sorted(float(b) for b in buckets)
        if not bs or any(not math.isfinite(b) for b in bs):
            raise ValueError(f"{name}: buckets must be finite and non-empty")
        self.buckets = tuple(bs)

    def ensure_bucket(self, bound: float) -> None:
        """Insert a bucket boundary (idempotent) — e.g. the configured SLO
        TTFT threshold, so good/bad request counts are exact from cumulative
        bucket counts rather than interpolated. Call at process startup:
        observations recorded before the insert stay in their original
        (coarser) bucket, so a mid-stream insert undercounts at the new edge.
        """
        b = float(bound)
        if not math.isfinite(b) or b <= 0:
            raise ValueError(f"{self.name}: SLO bucket bound must be finite and > 0")
        with self._lock:
            if b in self.buckets:
                return
            merged = sorted(self.buckets + (b,))
            idx = merged.index(b)
            self.buckets = tuple(merged)
            for child in self._children.values():
                child["counts"].insert(idx, 0)

    def observe(self, value: float, exemplar: Any = None, **labels: Any) -> None:
        if not _enabled:
            return
        key = self._key(labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                # [per-bucket counts..., overflow], sum, count
                child = self._children[key] = {
                    "counts": [0] * (len(self.buckets) + 1), "sum": 0.0, "count": 0,
                    "exemplars": [],
                }
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    child["counts"][i] += 1
                    break
            else:
                child["counts"][-1] += 1
            child["sum"] += value
            child["count"] += 1
            if exemplar is not None:
                # worst-K by value: lets an operator jump from a burning
                # latency SLO straight to the offending request ids
                ex = child["exemplars"]
                ex.append((float(value), str(exemplar)))
                ex.sort(key=lambda t: -t[0])
                del ex[EXEMPLAR_K:]

    def _snapshot_children(self) -> "tuple[list[float], list[tuple[tuple[str, ...], dict]]]":
        # buckets + children under ONE lock: ensure_bucket resizes counts in
        # place, and reading them separately could tear bucket/count lengths
        with self._lock:
            return list(self.buckets), [
                (k, {
                    "counts": list(v["counts"]), "sum": v["sum"], "count": v["count"],
                    "exemplars": [list(e) for e in v.get("exemplars", ())],
                })
                for k, v in self._children.items()
            ]


class MetricsRegistry:
    """Name → metric map; re-registering a name returns the existing metric
    (modules declare their instruments at import time, in any order)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def _register(self, cls, name: str, help_: str, labelnames: Sequence[str],
                  **kwargs: Any) -> Any:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help_, labelnames, **kwargs)
            elif not isinstance(m, cls) or m.labelnames != tuple(labelnames):
                raise ValueError(f"metric {name!r} re-registered with a different shape")
            return m

    def counter(self, name: str, help_: str = "", labelnames: Sequence[str] = ()) -> Counter:
        return self._register(Counter, name, help_, labelnames)

    def gauge(self, name: str, help_: str = "", labelnames: Sequence[str] = ()) -> Gauge:
        return self._register(Gauge, name, help_, labelnames)

    def histogram(self, name: str, help_: str = "", labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._register(Histogram, name, help_, labelnames, buckets=buckets)

    def reset(self) -> None:
        """Drop all recorded values AND registrations (tests only)."""
        with self._lock:
            self._metrics.clear()

    # ---------------------------------------------------------- exposition
    def snapshot(self) -> list[dict[str, Any]]:
        """JSON-able view of every metric — the ``get_metrics`` RPC payload."""
        with self._lock:
            metrics = list(self._metrics.values())
        out: list[dict[str, Any]] = []
        for m in metrics:
            entry: dict[str, Any] = {
                "name": m.name, "type": m.kind, "help": m.help,
                "labelnames": list(m.labelnames), "samples": [],
            }
            if isinstance(m, Histogram):
                buckets, children = m._snapshot_children()
                entry["buckets"] = buckets
                for key, child in children:
                    entry["samples"].append({
                        "labels": dict(zip(m.labelnames, key)),
                        "counts": child["counts"],
                        "sum": child["sum"],
                        "count": child["count"],
                        "exemplars": child["exemplars"],
                    })
            else:
                for key, value in m._label_dicts():
                    entry["samples"].append({
                        "labels": dict(zip(m.labelnames, key)), "value": value,
                    })
            out.append(entry)
        return out

    def render(self) -> str:
        """This process's registry as Prometheus text format."""
        return render_merged([(self.snapshot(), {})])


#: The process-wide default registry every instrumented module records into.
REGISTRY = MetricsRegistry()


def counter(name: str, help_: str = "", labelnames: Sequence[str] = ()) -> Counter:
    return REGISTRY.counter(name, help_, labelnames)


def gauge(name: str, help_: str = "", labelnames: Sequence[str] = ()) -> Gauge:
    return REGISTRY.gauge(name, help_, labelnames)


def histogram(name: str, help_: str = "", labelnames: Sequence[str] = (),
              buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
    return REGISTRY.histogram(name, help_, labelnames, buckets=buckets)


# ------------------------------------------------------- Prometheus text
def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(labels: Mapping[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(str(v))}"' for k, v in labels.items())
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    f = float(v)
    if f == math.inf:
        return "+Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return f"{f:.10g}"


def render_merged(
    groups: Iterable[tuple[list[dict[str, Any]], Mapping[str, str]]],
) -> str:
    """Merge (snapshot, extra_labels) groups into one Prometheus exposition.

    Metrics sharing a name across groups (the portal's own registry + each
    AM's) are emitted under a single HELP/TYPE header, their samples
    distinguished by the group's extra labels (e.g. ``app="application_…"``).
    """
    by_name: dict[str, list[tuple[dict[str, Any], Mapping[str, str]]]] = {}
    order: list[str] = []
    for snapshot, extra in groups:
        for metric in snapshot:
            name = metric["name"]
            if name not in by_name:
                by_name[name] = []
                order.append(name)
            by_name[name].append((metric, extra))
    lines: list[str] = []
    for name in order:
        entries = by_name[name]
        mtype = entries[0][0].get("type", "untyped")
        help_ = entries[0][0].get("help", "")
        if help_:
            lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} {mtype}")
        for metric, extra in entries:
            for sample in metric.get("samples", []):
                labels = {**sample.get("labels", {}), **extra}
                if mtype == "histogram":
                    cum = 0
                    for ub, n in zip(metric.get("buckets", []), sample["counts"]):
                        cum += n
                        blabels = {**labels, "le": _fmt_value(ub)}
                        lines.append(f"{name}_bucket{_fmt_labels(blabels)} {cum}")
                    blabels = {**labels, "le": "+Inf"}
                    lines.append(f"{name}_bucket{_fmt_labels(blabels)} {sample['count']}")
                    lines.append(f"{name}_sum{_fmt_labels(labels)} {_fmt_value(sample['sum'])}")
                    lines.append(f"{name}_count{_fmt_labels(labels)} {sample['count']}")
                else:
                    lines.append(f"{name}{_fmt_labels(labels)} {_fmt_value(sample['value'])}")
    return "\n".join(lines) + ("\n" if lines else "")
