"""Structured per-process logging for the control plane and training child.

The other half of crash forensics after tracing (obs/trace.py): every job
process — submitting client, AM, each executor, each training child — owns
one process-global :class:`JsonLogger` that appends one JSON object per
record to ``<staging>/logs/<identity>.log.jsonl``. ``tony logs <app_id>``
(cli/introspect.py) merges and tails those files in timestamp order, so a
dead gang's story is one command instead of a per-file scavenger hunt.

Records carry correlation for free: the process identity, the gang restart
``epoch``, and — when tracing is on — the ``span`` id currently open on the
logging thread, so a log line can be placed on the ``tony trace`` timeline.

The module-level helpers (:func:`debug` … :func:`error`) are the library's
print replacement. Contract:

- **below the active level is free**: the level compare happens before any
  record dict, JSON, or I/O exists (``debug()`` at the default ``info``
  level allocates nothing — asserted by tests/test_introspect.py);
- **at or above the level**, the record is written to the JSONL sink (when
  a logger is installed) AND echoed human-readably to stdout (stderr for
  warning/error), so container-captured logs and CLI output look exactly
  like the ``print`` calls they replaced;
- with **no logger installed** (library use outside a tony container) the
  helpers degrade to the echo alone.

A stdlib ``logging`` bridge forwards third-party records into the same sink
(no echo — stdlib handlers already own the console).
"""

from __future__ import annotations

import json
import logging as _stdlib_logging
import os
import sys
import threading
import time
from typing import Any, Iterator, Mapping

from tony_tpu import constants
from tony_tpu.obs import trace as _trace

DEBUG, INFO, WARNING, ERROR = 10, 20, 30, 40
OFF = 100  # above every level: the sink writes nothing

_LEVEL_NAMES = {DEBUG: "debug", INFO: "info", WARNING: "warning", ERROR: "error"}
_LEVELS_BY_NAME = {v: k for k, v in _LEVEL_NAMES.items()}
_LEVELS_BY_NAME["off"] = OFF

#: record keys the logger owns; extra fields never shadow them
_RESERVED = frozenset({"ts_ms", "level", "identity", "msg", "epoch", "span"})

_logger: "JsonLogger | None" = None
#: echo threshold when no logger is installed (library use outside tony)
_DEFAULT_LEVEL = INFO

LOG_SUFFIX = ".log.jsonl"


def level_from_name(name: str | None, default: int = INFO) -> int:
    return _LEVELS_BY_NAME.get((name or "").strip().lower(), default)


def get() -> "JsonLogger | None":
    """The process-global logger, or None (echo-only fallback)."""
    return _logger


def _safe_identity(identity: str) -> str:
    return identity.replace(":", "_").replace(os.sep, "_")


class JsonLogger:
    """Per-process JSONL sink (one file per process identity).

    Line-buffered append like the span sink: an ``os._exit`` or SIGKILL
    loses at most the record being formatted. Restart attempts of the same
    identity append to the same file; the gang epoch rides in each record.
    """

    def __init__(self, identity: str, log_dir: str, level: int = INFO,
                 epoch: int = 0, echo: bool = True):
        self.identity = identity
        self.level = level
        #: gang restart attempt stamped on every record (the AM bumps its
        #: own on each whole-gang restart)
        self.epoch = epoch
        self.echo = echo
        self.log_dir = log_dir
        self._lock = threading.Lock()
        os.makedirs(log_dir, exist_ok=True)
        self.path = os.path.join(log_dir, _safe_identity(identity) + LOG_SUFFIX)
        self._file = open(self.path, "a", buffering=1)

    def log(self, level: int, msg: str, fields: Mapping[str, Any] | None = None) -> None:
        if level < self.level:
            return
        self._emit(level, msg, fields)

    def _emit(self, level: int, msg: str, fields: Mapping[str, Any] | None) -> None:
        rec: dict[str, Any] = {
            "ts_ms": round(time.time() * 1000.0, 3),
            "level": _LEVEL_NAMES.get(level, str(level)),
            "identity": self.identity,
            "msg": str(msg),
        }
        if self.epoch:
            rec["epoch"] = self.epoch
        span = _trace.current_span()
        if span is not None:
            rec["span"] = span.span_id
        if fields:
            for k, v in fields.items():
                if k not in _RESERVED:
                    rec[k] = v
        try:
            line = json.dumps(rec, default=str)
        except (TypeError, ValueError):
            return  # a log record must never take the process down
        with self._lock:
            try:
                self._file.write(line + "\n")  # lint: disable=blocking-under-lock — the logger lock IS the log-line serializer (leaf; line already serialized outside it)
            except (OSError, ValueError):
                # disk full / IO error / closed mid-teardown: logging is
                # best-effort by contract and must never take the process down
                pass

    def close(self) -> None:
        with self._lock:
            try:
                self._file.close()
            except OSError:
                pass


# ------------------------------------------------------------- module API
def _log(level: int, msg: str, fields: dict[str, Any]) -> None:
    # The echo threshold is FIXED at info: console behavior is always
    # exactly the print calls these helpers replaced, regardless of
    # ``tony.log.level`` — that knob governs only the JSONL sink. (A
    # level=error job still prints its submit/monitor lines; a level=debug
    # job does not spam the console with sink-only debug records.)
    lg = _logger
    sink = lg is not None and level >= lg.level
    echo = level >= _DEFAULT_LEVEL and (lg is None or lg.echo)
    if not sink and not echo:
        return  # the free path: sub-threshold calls build nothing
    if sink:
        lg._emit(level, msg, fields)
    if echo:
        stream = sys.stdout if level < WARNING else sys.stderr
        print(msg, file=stream, flush=True)  # lint: disable=print-discipline — the echo sink IS the logger


def debug(msg: str, **fields: Any) -> None:
    _log(DEBUG, msg, fields)


def info(msg: str, **fields: Any) -> None:
    _log(INFO, msg, fields)


def warning(msg: str, **fields: Any) -> None:
    _log(WARNING, msg, fields)


def error(msg: str, **fields: Any) -> None:
    _log(ERROR, msg, fields)


# ---------------------------------------------------------- stdlib bridge
class _StdlibBridge(_stdlib_logging.Handler):
    """Forwards stdlib-logging records into the tony sink (no echo: stdlib
    handlers already own the console for those records)."""

    def emit(self, record: _stdlib_logging.LogRecord) -> None:
        lg = _logger
        if lg is None:
            return
        level = (record.levelno // 10) * 10
        level = min(max(level, DEBUG), ERROR)
        if level < lg.level:
            return
        try:
            lg._emit(level, record.getMessage(), {"logger": record.name})
        except Exception:  # noqa: BLE001 — logging must never raise into user code
            pass


_bridge: _StdlibBridge | None = None


def _install_bridge() -> None:
    global _bridge
    if _bridge is None:
        _bridge = _StdlibBridge()
        _stdlib_logging.getLogger().addHandler(_bridge)


def _remove_bridge() -> None:
    global _bridge
    if _bridge is not None:
        _stdlib_logging.getLogger().removeHandler(_bridge)
        _bridge = None


# -------------------------------------------------------------- factories
def init_logging(identity: str, log_dir: str, level: int = INFO,
                 epoch: int = 0, echo: bool = True) -> JsonLogger:
    """Install the process-global logger (replacing any previous one) and
    the stdlib bridge."""
    global _logger
    if _logger is not None:
        _logger.close()
    _logger = JsonLogger(identity, log_dir, level=level, epoch=epoch, echo=echo)
    _install_bridge()
    return _logger


def init_from_config(config, identity: str, staging_dir: str,
                     epoch: int = 0) -> JsonLogger | None:
    """Control-plane processes (client, AM, executor): sink + level from the
    frozen job config. ``tony.log.level=off`` skips the sink entirely (the
    echo fallback keeps console output identical)."""
    from tony_tpu.config import keys

    level = level_from_name(config.get(keys.LOG_LEVEL))
    if level >= OFF:
        return None
    log_dir = config.get(keys.LOG_DIR) or os.path.join(staging_dir, "logs")
    return init_logging(identity, log_dir, level=level, epoch=epoch)


def init_from_env(env: Mapping[str, str] | None = None,
                  role: str = "train") -> JsonLogger | None:
    """The executor-launched child's contract: the executor exports
    TONY_LOG_DIR / TONY_LOG_LEVEL. None — and echo-only behavior — otherwise
    (also the library path outside a tony container). ``role`` is the
    identity suffix distinguishing co-scheduled child kinds in the aggregate
    (the training loop keeps the default; a serve engine passes "serve")."""
    env = os.environ if env is None else env
    log_dir = env.get(constants.ENV_LOG_DIR, "")
    if not log_dir:
        return None
    level = level_from_name(env.get(constants.ENV_LOG_LEVEL))
    if level >= OFF:
        return None
    job = env.get(constants.ENV_JOB_NAME)
    idx = env.get(constants.ENV_TASK_INDEX)
    identity = f"{job}:{idx}:{role}" if job and idx is not None else "proc"
    epoch = int(env.get("TONY_RESTART_ATTEMPT", "0") or 0)
    return init_logging(identity, log_dir, level=level, epoch=epoch)


def shutdown() -> None:
    """Close and uninstall the process-global logger (idempotent)."""
    global _logger
    _remove_bridge()
    if _logger is not None:
        _logger.close()
        _logger = None


# ------------------------------------------------------------ aggregation
def resolve_log_dir(staging: str, app_id: str) -> str:
    """Where the job's aggregate lives: the ``tony.log.dir`` override from
    its frozen config when set, else ``<staging>/<app_id>/logs``. Shared by
    every reader surface (`tony logs`, the portal pages) so they never
    disagree with the writers."""
    conf_path = os.path.join(staging, app_id, constants.TONY_FINAL_CONF)
    try:
        from tony_tpu.config import TonyConfig, keys

        override = TonyConfig.load_final(conf_path).get(keys.LOG_DIR)
    except (OSError, ValueError):
        override = None
    return override or os.path.join(staging, app_id, "logs")


def read_records(log_dir: str) -> list[dict[str, Any]]:
    """Every record from every ``*.log.jsonl`` under ``log_dir``, merged and
    sorted by timestamp. Malformed lines (a process killed mid-write) are
    skipped — same tolerance as the span reader."""
    records: list[dict[str, Any]] = []
    if not os.path.isdir(log_dir):
        return records
    for fn in sorted(os.listdir(log_dir)):
        if not fn.endswith(LOG_SUFFIX):
            continue
        with open(os.path.join(log_dir, fn), errors="replace") as f:
            for line in f:
                rec = _parse_record(line)
                if rec is not None:
                    records.append(rec)
    records.sort(key=lambda r: r.get("ts_ms", 0.0))
    return records


def tail_records(log_dir: str, limit: int = 500,
                 max_bytes_per_file: int = 1 << 20) -> list[dict[str, Any]]:
    """The newest ``limit`` records across the aggregate, reading at most
    ``max_bytes_per_file`` from the tail of each file — bounded work however
    large a long-running job's logs grow (the portal pages use this;
    ``tony logs`` without ``-f`` still reads everything by design)."""
    records: list[dict[str, Any]] = []
    if not os.path.isdir(log_dir):
        return records
    for fn in sorted(os.listdir(log_dir)):
        if not fn.endswith(LOG_SUFFIX):
            continue
        path = os.path.join(log_dir, fn)
        try:
            size = os.path.getsize(path)
            with open(path, errors="replace") as f:
                if size > max_bytes_per_file:
                    f.seek(size - max_bytes_per_file)
                    f.readline()  # drop the partial line the seek landed in
                lines = f.readlines()
        except OSError:
            continue
        parsed = (_parse_record(line) for line in lines[-limit:])
        records.extend(r for r in parsed if r is not None)
    records.sort(key=lambda r: r.get("ts_ms", 0.0))
    return records[-limit:] if limit else records


def _parse_record(line: str) -> dict[str, Any] | None:
    line = line.strip()
    if not line:
        return None
    try:
        d = json.loads(line)
    except ValueError:
        return None
    return d if isinstance(d, dict) and "msg" in d else None


class LogFollower:
    """Incremental reader for ``tony logs -f``: remembers per-file offsets,
    discovers files that appear later (a restarted task's first record), and
    yields each poll's new records sorted by timestamp."""

    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        self._offsets: dict[str, int] = {}
        self._partial: dict[str, str] = {}

    def poll(self) -> list[dict[str, Any]]:
        records: list[dict[str, Any]] = []
        if not os.path.isdir(self.log_dir):
            return records
        for fn in sorted(os.listdir(self.log_dir)):
            if not fn.endswith(LOG_SUFFIX):
                continue
            path = os.path.join(self.log_dir, fn)
            try:
                with open(path, errors="replace") as f:
                    f.seek(self._offsets.get(fn, 0))
                    chunk = f.read()
                    self._offsets[fn] = f.tell()
            except OSError:
                continue
            if not chunk:
                continue
            buf = self._partial.pop(fn, "") + chunk
            lines = buf.split("\n")
            if buf and not buf.endswith("\n"):
                self._partial[fn] = lines.pop()  # torn tail: wait for the rest
            else:
                lines = lines[:-1] if lines and lines[-1] == "" else lines
            for line in lines:
                rec = _parse_record(line)
                if rec is not None:
                    records.append(rec)
        records.sort(key=lambda r: r.get("ts_ms", 0.0))
        return records


def format_record(rec: Mapping[str, Any]) -> str:
    """One human line: ``HH:MM:SS.mmm [identity] LEVEL msg k=v ...``."""
    ts_ms = float(rec.get("ts_ms", 0.0))
    hhmmss = time.strftime("%H:%M:%S", time.localtime(ts_ms / 1000.0))
    frac = int(ts_ms % 1000)
    extras = " ".join(
        f"{k}={v}" for k, v in rec.items() if k not in _RESERVED
    )
    level = str(rec.get("level", "info")).upper()
    line = (f"{hhmmss}.{frac:03d} [{rec.get('identity', '?')}] "
            f"{level:<7s} {rec.get('msg', '')}")
    return f"{line}  {extras}" if extras else line


def iter_formatted(records: list[dict[str, Any]]) -> Iterator[str]:
    for rec in records:
        yield format_record(rec)
