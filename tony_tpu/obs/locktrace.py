"""Opt-in traced locks: the runtime witness that keeps the static
concurrency model honest (docs/static-analysis.md).

Every control-plane lock the lint's lock-order graph models is created
through :func:`make_lock` with the SAME string id the static analysis
derives (``<module-stem>.<Class>.<attr>``, e.g. ``pool.PoolService._lock``).
Off (the default — ``tony.debug.locktrace`` unset, ``TONY_LOCKTRACE``
unset), ``make_lock`` returns a plain ``threading.Lock``/``RLock``: zero
overhead, byte-identical behavior, nothing recorded. On, it returns a
:class:`_TracedLock` that observes, per thread, the real acquisition
order (every ``held -> acquired`` edge), per-lock hold times (the
``tony_lock_hold_seconds`` histogram), and contention (acquirer had to
wait). The tier-1 witness test drives representative pool/AM/store
workloads under it and asserts every witnessed edge embeds into the
static graph — an inversion the lint did not model fails the build.

The witness state is process-global (locks cross object boundaries);
tests snapshot it with :func:`witness` and clear it with
:func:`reset_witness`.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any

from tony_tpu import constants
from tony_tpu.obs import metrics as _metrics

#: sub-microsecond grabs up to multi-second stalls — a control-plane lock
#: held past ~100ms is exactly the cliff blocking-under-lock hunts
HOLD_BUCKETS: tuple[float, ...] = (
    0.000001, 0.00001, 0.0001, 0.001, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 5.0,
)

_HOLD = _metrics.histogram(
    "tony_lock_hold_seconds",
    "traced control-plane lock hold time (tony.debug.locktrace only)",
    labelnames=("lock",), buckets=HOLD_BUCKETS)

_enabled = os.environ.get(constants.ENV_LOCKTRACE, "").lower() in (
    "1", "true", "yes")


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> None:
    """Flip tracing for locks created AFTER this call (daemon mains read
    ``tony.debug.locktrace`` before constructing their services; tests
    flip it around service construction). Existing locks keep whatever
    they are — a plain Lock cannot retroactively grow tracing."""
    global _enabled
    _enabled = bool(on)


class _Witness:
    """Process-global record of what traced locks actually did."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        #: (held_name, acquired_name) -> count
        self.edges: dict[tuple[str, str], int] = {}
        #: name -> acquisition count
        self.acquires: dict[str, int] = {}
        #: name -> times the acquirer found the lock taken
        self.contended: dict[str, int] = {}

    def record(self, stack: list[str], name: str, waited: bool) -> None:
        with self._lock:
            self.acquires[name] = self.acquires.get(name, 0) + 1
            if waited:
                self.contended[name] = self.contended.get(name, 0) + 1
            for held in stack:
                if held != name:  # reentrant re-acquire is not an edge
                    key = (held, name)
                    self.edges[key] = self.edges.get(key, 0) + 1

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            return {
                "edges": dict(self.edges),
                "acquires": dict(self.acquires),
                "contended": dict(self.contended),
            }

    def reset(self) -> None:
        with self._lock:
            self.edges.clear()
            self.acquires.clear()
            self.contended.clear()


_WITNESS = _Witness()
_held_stack = threading.local()


def witness() -> dict[str, Any]:
    """Snapshot of the witnessed order edges / acquire / contention counts."""
    return _WITNESS.snapshot()


def reset_witness() -> None:
    _WITNESS.reset()


class _TracedLock:
    """Wraps a real Lock/RLock; context-manager protocol plus the
    acquire/release methods the wrapped code already uses."""

    __slots__ = ("name", "_inner", "_t0")

    def __init__(self, name: str, reentrant: bool):
        self.name = name
        self._inner = threading.RLock() if reentrant else threading.Lock()
        # per-acquisition start times, a stack for reentrant locks
        self._t0: list[float] = []

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        waited = not self._inner.acquire(blocking=False)
        if waited:
            if not blocking:
                return False
            if not self._inner.acquire(True, timeout):
                return False
        stack = getattr(_held_stack, "names", None)
        if stack is None:
            stack = _held_stack.names = []
        _WITNESS.record(stack, self.name, waited)
        stack.append(self.name)
        self._t0.append(time.perf_counter())
        return True

    def release(self) -> None:
        t0 = self._t0.pop() if self._t0 else None
        stack = getattr(_held_stack, "names", None)
        if stack and self.name in stack:
            # remove the innermost occurrence (reentrant-safe)
            for i in range(len(stack) - 1, -1, -1):
                if stack[i] == self.name:
                    del stack[i]
                    break
        self._inner.release()
        # observe AFTER releasing: the histogram's own lock must never
        # extend this lock's critical section
        if t0 is not None:
            _HOLD.observe(time.perf_counter() - t0, lock=self.name)

    def __enter__(self) -> "_TracedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def locked(self) -> bool:
        probe = getattr(self._inner, "locked", None)  # RLock lacks it pre-3.12
        return bool(probe()) if probe else bool(self._t0)


def make_lock(name: str, reentrant: bool = False):
    """A lock named with its static-analysis id. Plain (untraced, zero
    overhead) unless locktrace is enabled at creation time."""
    if not _enabled:
        return threading.RLock() if reentrant else threading.Lock()
    return _TracedLock(name, reentrant)
