"""The one artifact index: where every per-job on-disk artifact lives.

Analog of the reference history server's ``HistoryFileUtils`` path logic
grown into a shared index (SURVEY.md §2.1): given a staging root and an
application id, this module — and only this module — knows where the job's
``.jhist`` (intermediate or finished), frozen config, ``am_info.json`` /
``am_status.json``, structured-log JSONL aggregate, span JSONL trace dir,
profiler captures, and train-metrics drops live, and whether the job has
finalized. Portal scrape, ``tony trace``, ``tony logs``/``tony top``, and
the history server's ingestion all resolve artifacts through it; a consumer
re-implementing its own discovery walk is a regression (asserted by a
grep-style test in tests/test_history_server.py).

Per-job overrides (``tony.history.location``, ``tony.log.dir``,
``tony.trace.dir``) come from the job's frozen config snapshot, so readers
never disagree with the writers that honored the same keys.

``read_history_events`` applies the journal reader discipline
(cluster/journal.py) to ``.jhist`` files: a job killed mid-write can only
tear the tail of an append-only JSONL stream, so the intact prefix is
returned and the torn/truncated state is reported as ``complete=False``
instead of raising — the history server ingests such jobs as ``incomplete``.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import TYPE_CHECKING, Any

from tony_tpu import constants
from tony_tpu.cluster import history
from tony_tpu.cluster.events import Event

if TYPE_CHECKING:
    from tony_tpu.cluster.rpc import RpcClient


@dataclass
class JobArtifacts:
    """Every artifact location for one application, resolved once."""

    app_id: str
    staging_root: str
    staging_dir: str            # <staging_root>/<app_id>
    history_root: str           # tony.history.location or <staging_root>/history
    frozen_config_path: str     # <staging_dir>/tony-final.json (client-written)
    am_info_path: str           # live AM advertisement (host/port/secret)
    am_status_path: str         # final verdict (written once, atomically)
    log_dir: str                # structured-log JSONL aggregate (tony.log.dir override)
    trace_dir: str              # span JSONL sink (tony.trace.dir override)
    profile_dir: str            # jax.profiler captures (static + on-demand)
    metrics_dir: str            # executor train-metrics drops (+ .obs snapshots)
    jhist_path: str | None      # finished .jhist if finalized, else intermediate, else None
    finalized: bool             # a finished .jhist exists for this app
    history_file: "history.HistoryFileName | None"  # parsed finished-filename fields
    config_snapshot_path: str | None  # finished-dir config.json (finalized only)

    # -- live/terminal state -------------------------------------------------

    def am_status(self) -> dict[str, Any] | None:
        """The final ``am_status.json`` verdict, or None (job still running
        or never started)."""
        try:
            with open(self.am_status_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def am_client(self, timeout_s: float = 5.0) -> "RpcClient | None":
        """RpcClient for the job's live AM from its ``am_info.json``
        advertisement, or None (no AM / unreadable advertisement). A
        work-preserving takeover republishes the file with a fresh
        port+secret — callers re-resolving through this method reach the
        adopting AM."""
        try:
            with open(self.am_info_path) as f:
                info = json.load(f)
            from tony_tpu.cluster.rpc import RpcClient

            return RpcClient(info["host"], info["port"],
                             secret=info.get("secret", ""), timeout_s=timeout_s)
        except (OSError, ValueError, KeyError):
            return None

    # -- event stream --------------------------------------------------------

    def read_events(self) -> tuple[list[Event], bool]:
        """The job's ``.jhist`` event stream with torn-file tolerance:
        ``(events, complete)`` where ``complete`` is False when the file is
        missing, truncated, or torn (see :func:`read_history_events`)."""
        if self.jhist_path is None:
            return [], False
        return read_history_events(self.jhist_path)

    # -- profiler artifacts --------------------------------------------------

    def profile_listing(self) -> list[dict[str, Any]]:
        """Profiler artifacts flattened to ``{path (relative), size}``
        entries — both the submit-time window's and on-demand captures'."""
        out: list[dict[str, Any]] = []
        for dirpath, _, files in os.walk(self.profile_dir):
            for fn in sorted(files):
                full = os.path.join(dirpath, fn)
                try:
                    size = os.path.getsize(full)
                except OSError:
                    continue
                out.append({"path": os.path.relpath(full, self.profile_dir), "size": size})
        out.sort(key=lambda e: e["path"])
        return out


def _frozen_config(staging_dir: str):
    """The job's frozen config, or None (not submitted through the client,
    or the snapshot is unreadable)."""
    path = os.path.join(staging_dir, constants.TONY_FINAL_CONF)
    try:
        from tony_tpu.config import TonyConfig

        return TonyConfig.load_final(path)
    except (OSError, ValueError):
        return None


def _find_finished(history_root: str, app_id: str) -> tuple[str, "history.HistoryFileName"] | None:
    """The finished ``.jhist`` (path, parsed filename) for one app, or None.

    Walks only ``finished/`` subtrees whose leaf directory is the app id —
    the yyyy/MM/dd layout means one terminal directory per app. Bulk
    consumers (the ingestion sweep) should walk once via
    :func:`finished_index` and pass entries through ``index(...,
    finished=...)`` instead of paying this walk per job.
    """
    root = os.path.join(history_root, constants.HISTORY_FINISHED_DIR)
    for dirpath, dirnames, filenames in os.walk(root):
        if os.path.basename(dirpath) != app_id:
            continue
        dirnames.clear()  # app dirs are leaves
        for fn in filenames:
            if fn.endswith(constants.HISTORY_SUFFIX):
                try:
                    return os.path.join(dirpath, fn), history.HistoryFileName.parse(fn)
                except ValueError:
                    continue
    return None


def finished_index(history_root: str) -> dict[str, tuple[str, "history.HistoryFileName"]]:
    """One walk of ``finished/`` → ``app_id → (jhist_path, parsed name)``.

    The sweep-side complement of :func:`_find_finished`: resolving N jobs
    against a shared history tree costs one tree walk, not N.
    """
    out: dict[str, tuple[str, "history.HistoryFileName"]] = {}
    root = os.path.join(history_root, constants.HISTORY_FINISHED_DIR)
    for dirpath, _dirnames, filenames in os.walk(root):
        for fn in filenames:
            if fn.endswith(constants.HISTORY_SUFFIX):
                try:
                    parsed = history.HistoryFileName.parse(fn)
                except ValueError:
                    continue
                out[parsed.app_id] = (os.path.join(dirpath, fn), parsed)
    return out


def index(
    staging_root: str,
    app_id: str,
    history_root: str | None = None,
    finished: tuple[str, "history.HistoryFileName"] | None = None,
) -> JobArtifacts:
    """Resolve every artifact location for ``app_id`` under ``staging_root``.

    ``history_root`` overrides the resolution (a portal serving one history
    tree for many staging roots); by default it comes from the job's frozen
    config (``tony.history.location``) with the AM's fallback of
    ``<staging_root>/history``. ``finished`` short-circuits the finished-
    tree lookup with a :func:`finished_index` entry (bulk callers).
    """
    staging_root = staging_root.rstrip("/") if staging_root else staging_root
    staging_dir = os.path.join(staging_root, app_id)
    cfg = _frozen_config(staging_dir)

    log_dir = os.path.join(staging_dir, constants.TASK_LOG_DIRNAME)
    trace_dir = os.path.join(staging_dir, "trace")
    resolved_history = history_root
    if cfg is not None:
        from tony_tpu.config import keys

        log_dir = cfg.get(keys.LOG_DIR) or log_dir
        trace_dir = cfg.get(keys.TRACE_DIR) or trace_dir
        if resolved_history is None:
            resolved_history = cfg.get(keys.HISTORY_LOCATION) or None
    if resolved_history is None:
        resolved_history = os.path.join(staging_root, "history")

    if finished is None:
        finished = _find_finished(resolved_history, app_id)
    if finished is not None:
        jhist_path: str | None = finished[0]
        hist_file: "history.HistoryFileName | None" = finished[1]
        config_snapshot: str | None = os.path.join(
            os.path.dirname(finished[0]), constants.CONFIG_SNAPSHOT_FILE)
        finalized = True
    else:
        hist_file, config_snapshot, finalized = None, None, False
        inter = os.path.join(resolved_history, constants.HISTORY_INTERMEDIATE_DIR,
                             app_id + constants.HISTORY_SUFFIX)
        jhist_path = inter if os.path.exists(inter) else None

    return JobArtifacts(
        app_id=app_id,
        staging_root=staging_root,
        staging_dir=staging_dir,
        history_root=resolved_history,
        frozen_config_path=os.path.join(staging_dir, constants.TONY_FINAL_CONF),
        am_info_path=os.path.join(staging_dir, constants.AM_INFO_FILE),
        am_status_path=os.path.join(staging_dir, "am_status.json"),
        log_dir=log_dir,
        trace_dir=trace_dir,
        profile_dir=os.path.join(staging_dir, "profile"),
        metrics_dir=os.path.join(staging_dir, "metrics"),
        jhist_path=jhist_path,
        finalized=finalized,
        history_file=hist_file,
        config_snapshot_path=config_snapshot,
    )


def am_info_path(staging_root: str, app_id: str) -> str:
    """The live-AM advertisement path WITHOUT full artifact resolution —
    for hot per-scrape freshness checks (the portal's O(changed) cache keys
    on this file's identity for every running app on every exposition;
    paying :func:`index`'s config reads per app per scrape would be the
    overhead the cache exists to avoid)."""
    return os.path.join(staging_root.rstrip("/"), app_id, constants.AM_INFO_FILE)


# ---------------------------------------------------------------- listings
def running_ids(history_root: str) -> list[str]:
    """Applications with an intermediate ``.jhist`` (the AM streams events
    there until finalization) — the portal's RUNNING list."""
    d = os.path.join(history_root, constants.HISTORY_INTERMEDIATE_DIR)
    if not os.path.isdir(d):
        return []
    suf = constants.HISTORY_SUFFIX
    return sorted(f[: -len(suf)] for f in os.listdir(d) if f.endswith(suf))


def finished_jobs(history_root: str) -> list["history.HistoryFileName"]:
    """Finished jobs under ``history_root``, newest first (codec in
    cluster/history.py)."""
    return history.list_finished_jobs(history_root)


def staged_ids(staging_root: str) -> list[str]:
    """Application ids with a staging directory under ``staging_root`` —
    the ingestion sweep's discovery surface (jobs whose staging dir was
    already GC'd are found through :func:`finished_jobs` instead)."""
    try:
        entries = os.listdir(staging_root)
    except OSError:
        return []
    out = []
    for name in sorted(entries):
        d = os.path.join(staging_root, name)
        if not os.path.isdir(d):
            continue
        # a staging dir is recognizable by the client/AM artifacts in it
        if (os.path.exists(os.path.join(d, constants.TONY_FINAL_CONF))
                or os.path.exists(os.path.join(d, constants.AM_INFO_FILE))
                or os.path.exists(os.path.join(d, "am_status.json"))):
            out.append(name)
    return out


# ---------------------------------------------------------- event reading
def read_history_events(path: str) -> tuple[list[Event], bool]:
    """Every intact event from a ``.jhist``, plus a completeness verdict.

    Journal-reader discipline (cluster/journal.py): the writer appends
    sequentially, so a SIGKILL mid-write can only tear the FINAL line — an
    unparseable or truncated tail is dropped and reported as incomplete, not
    raised. Garbage anywhere before the tail would mean the file was
    corrupted some other way; the intact PREFIX is still returned (history
    is forensics — partial evidence beats none) with ``complete=False``.
    ``complete`` also requires a terminal ``APPLICATION_FINISHED`` event:
    a job killed between steps never tore a line, yet its history is still
    missing its verdict.
    """
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            lines = f.read().split("\n")
    except OSError:
        return [], False
    events: list[Event] = []
    torn = False
    for line in lines:
        if not line.strip():
            continue
        try:
            ev = Event.from_json(line)
        except (ValueError, AttributeError, TypeError):
            torn = True
            break  # keep the intact prefix; everything after is suspect
        events.append(ev)
    finished = any(e.type.value == "APPLICATION_FINISHED" for e in events)
    return events, (not torn) and finished


def load_spans(trace_dir: str) -> list[dict[str, Any]]:
    """All spans from every ``*.spans.jsonl`` under ``trace_dir``, sorted by
    start time. Malformed lines (a process killed mid-write) are skipped —
    the span-file analog of :func:`read_history_events`'s tolerance."""
    spans: list[dict[str, Any]] = []
    if not os.path.isdir(trace_dir):
        return spans
    for fn in sorted(os.listdir(trace_dir)):
        if not fn.endswith(".spans.jsonl"):
            continue
        with open(os.path.join(trace_dir, fn), errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    d = json.loads(line)
                except ValueError:
                    continue
                if isinstance(d, dict) and "span_id" in d and "start_ms" in d:
                    spans.append(d)
    spans.sort(key=lambda s: s.get("start_ms", 0.0))
    return spans
