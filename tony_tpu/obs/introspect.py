"""Live job introspection: the plumbing behind ``tony profile`` / ``tony top``.

The reference's only answer to "what is this job doing right now?" was the
TensorBoard sidecar (SURVEY.md §5.1). This module turns the existing
AM↔executor↔training-child plumbing into an on-demand introspection plane:

- **AM side** — :class:`ProfileCoordinator` owns the single in-flight
  capture request: ``start_profile`` creates it (a second concurrent request
  raises the typed :class:`AlreadyProfilingError`), the heartbeat RPC
  piggybacks it out to each targeted executor, and
  ``report_profile_status`` folds per-task delivery/capture results back in.
- **Executor side** — :class:`ProfileCourier` relays a piggybacked request
  to the training child by atomically writing a **control file** next to the
  ``<train-metrics-file>`` drop (the established executor↔child piggyback
  contract), then watches for the child's **done file** and reports the
  capture result (artifacts + step-time summary) back over RPC.
- **Child side** — ``StepProfiler`` (train/profiling.py) polls the control
  file at step boundaries and runs the actual ``jax.profiler`` capture.
- **`tony top`** — helpers that synthesize one status row per task from the
  AM's ``get_task_infos`` + ``get_metrics`` payloads (step rate from the
  piggybacked step-time histogram, queue depth / TTFT for serve replicas,
  heartbeat age).

File contract next to ``<train-metrics-file>``:

========================  ====================================================
``<metrics>.profile``      control file the executor writes:
                           ``{"req_id", "num_steps", "memory", "dir"}``
``<metrics>.profile.done`` result the child writes after ``stop_trace``:
                           ``{"req_id", "ok", "dir", "artifacts",
                           "steps_captured", "step_times_ms", "truncated",
                           "error"}``
========================  ====================================================
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Mapping

CONTROL_SUFFIX = ".profile"
DONE_SUFFIX = ".profile.done"
#: cooperative-preemption urgent-checkpoint relay (docs/scheduling.md): the
#: executor drops the control file next to the train-metrics path, the child
#: force-saves at the next step boundary and answers with the done file
DRAIN_CONTROL_SUFFIX = ".drain"
DRAIN_DONE_SUFFIX = ".drain.done"

#: per-task capture states, in lifecycle order
PENDING, DELIVERED, CAPTURED, FAILED = "pending", "delivered", "captured", "error"
_TERMINAL = (CAPTURED, FAILED)


class AlreadyProfilingError(RuntimeError):
    """A capture request is already in flight for this application.

    Raised by the AM's ``start_profile`` handler; the name crosses the RPC
    boundary in the error frame (``"AlreadyProfilingError: ..."``) so the
    CLI — and tests — can distinguish it from transport failures.
    """


def write_json_atomic(path: str, obj: Any) -> None:
    """tmp + rename so a reader never sees a torn file (same discipline as
    the train-metrics drop)."""
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


def read_json(path: str) -> dict[str, Any] | None:
    """The JSON object at ``path``, or None (missing / torn / not a dict)."""
    try:
        with open(path) as f:
            d = json.load(f)
    except (OSError, ValueError):
        return None
    return d if isinstance(d, dict) else None


# --------------------------------------------------------------- AM side
class ProfileCoordinator:
    """The AM's single-slot capture request state machine.

    One request may be in flight at a time (``jax.profiler`` cannot nest
    traces, and overlapping windows would make the artifacts lie); a second
    ``start`` while one is live raises :class:`AlreadyProfilingError`. A
    request whose tasks never report — a target without a ``StepProfiler``
    in its child (a raw shell command, a serve replica), or a child that
    died without its executor noticing the done file — would otherwise wedge
    the slot for the job's lifetime, so an in-flight request older than
    ``stale_after_s`` is auto-failed by the next ``start``. All mutation
    happens under the internal lock — the RPC handler threads and the
    monitor loop race on this object.
    """

    def __init__(self, stale_after_s: float = 600.0) -> None:
        self._lock = threading.Lock()
        self._req: dict[str, Any] | None = None  # current/last request
        self.stale_after_s = stale_after_s

    def start(self, task_ids: list[str], num_steps: int, memory: bool) -> dict[str, Any]:
        if num_steps < 1:
            raise ValueError(f"num_steps must be >= 1, got {num_steps}")
        if not task_ids:
            raise RuntimeError("no running tracked tasks to profile")
        with self._lock:
            if self._req is not None and not self._req["complete"]:
                age_s = (time.time() * 1000 - self._req["started_ms"]) / 1000
                if age_s <= self.stale_after_s:
                    raise AlreadyProfilingError(
                        f"capture {self._req['req_id']} still in flight "
                        f"({self._progress_locked()}) — wait for it or re-run "
                        f"later (unreported requests expire after "
                        f"{self.stale_after_s:.0f}s)"
                    )
                # expired: some target never reported (e.g. its child runs no
                # StepProfiler) — fail it rather than brick the slot forever
                self._abort_locked(
                    f"expired: no report within {self.stale_after_s:.0f}s"
                )
            req_id = os.urandom(4).hex()
            self._req = {
                "req_id": req_id,
                "num_steps": int(num_steps),
                "memory": bool(memory),
                "started_ms": int(time.time() * 1000),
                "complete": False,
                "tasks": {tid: {"status": PENDING} for tid in task_ids},
            }
            return {"req_id": req_id, "num_steps": int(num_steps), "tasks": list(task_ids)}

    def _progress_locked(self) -> str:
        assert self._req is not None
        done = sum(1 for t in self._req["tasks"].values() if t["status"] in _TERMINAL)
        return f"{done}/{len(self._req['tasks'])} tasks reported"

    def pending_for(self, task_id: str) -> dict[str, Any] | None:
        """The heartbeat piggyback: the request this task should (still) act
        on, or None. Re-sent until the task reports a terminal status — the
        courier dedups by req_id, so redelivery is idempotent."""
        with self._lock:
            req = self._req
            if req is None or req["complete"]:
                return None
            entry = req["tasks"].get(task_id)
            if entry is None or entry["status"] in _TERMINAL:
                return None
            return {
                "req_id": req["req_id"],
                "num_steps": req["num_steps"],
                "memory": req["memory"],
            }

    def report(self, task_id: str, req_id: str, status: str,
               **extra: Any) -> tuple[bool, bool]:
        """Fold one task's status in. Returns ``(acked, completed_now)`` —
        ``completed_now`` is True exactly once, when this report was the
        last outstanding one (the caller emits the PROFILE_FINISHED event
        outside the lock)."""
        if status not in (PENDING, DELIVERED, CAPTURED, FAILED):
            return False, False
        with self._lock:
            req = self._req
            if req is None or req["req_id"] != req_id:
                return False, False
            entry = req["tasks"].get(task_id)
            if entry is None:
                return False, False
            entry["status"] = status
            for k, v in extra.items():
                if v is not None:
                    entry[k] = v
            if status in _TERMINAL and not req["complete"] and all(
                t["status"] in _TERMINAL for t in req["tasks"].values()
            ):
                req["complete"] = True
                return True, True
            return True, False

    def abort(self, reason: str) -> None:
        """Fail every non-terminal task (gang restart: the children that
        would have captured are gone; their control files are cleared at
        relaunch). Unblocks the next ``start``."""
        with self._lock:
            self._abort_locked(reason)

    def _abort_locked(self, reason: str) -> None:
        req = self._req
        if req is None or req["complete"]:
            return
        for entry in req["tasks"].values():
            if entry["status"] not in _TERMINAL:
                entry["status"] = FAILED
                entry["error"] = reason
        req["complete"] = True

    def status(self, req_id: str = "") -> dict[str, Any] | None:
        """Deep-copied view of the current/last request (RPC payload)."""
        with self._lock:
            req = self._req
            if req is None or (req_id and req["req_id"] != req_id):
                return None
            return {
                **{k: v for k, v in req.items() if k != "tasks"},
                "tasks": {tid: dict(e) for tid, e in req["tasks"].items()},
            }


# ---------------------------------------------------------- executor side
class ProfileCourier:
    """Executor-side relay: control file out, done file in, status back.

    Driven from the heartbeat loop: ``handle(piggyback)`` is called with the
    ``profile`` field of each heartbeat response (or None). The executor's
    final sweep after child exit calls ``handle(None, ...)`` from the main
    thread — possibly concurrent with the heartbeat iteration already in
    flight when ``_stop`` was set — so ``handle`` is atomic under an internal
    lock (one caller reports a done record; the other sees it already
    cleared)."""

    def __init__(self, staging_dir: str, job_name: str, index: int,
                 report: Callable[..., Any]):
        self.staging_dir = staging_dir
        self.job_name = job_name
        self.index = index
        #: report(req_id=..., status=..., **extra) → AM (exceptions are the
        #: caller's problem; the heartbeat loop already tolerates RPC churn)
        self._report = report
        self._lock = threading.Lock()
        self._outstanding: dict[str, str] | None = None  # req being captured
        self._reported: set[str] = set()                 # req_ids fully reported

    def artifact_dir(self, req_id: str) -> str:
        return os.path.join(
            self.staging_dir, "profile", f"{self.job_name}_{self.index}", req_id
        )

    def handle(self, piggyback: Mapping[str, Any] | None,
               metrics_path: str | None) -> None:
        with self._lock:
            if self._outstanding is not None:
                self._check_done_locked()
            if not piggyback or not metrics_path:
                return  # nothing requested, or the child is not launched yet
            req_id = str(piggyback.get("req_id") or "")
            if (
                not req_id
                or req_id in self._reported
                or (self._outstanding is not None and self._outstanding["req_id"] == req_id)
            ):
                return
            out_dir = self.artifact_dir(req_id)
            write_json_atomic(metrics_path + CONTROL_SUFFIX, {  # lint: disable=blocking-under-lock — leaf lock; tiny local control file once per profile request, on the heartbeat cadence
                "req_id": req_id,
                "num_steps": int(piggyback.get("num_steps", 5) or 5),
                "memory": bool(piggyback.get("memory")),
                "dir": out_dir,
            })
            self._outstanding = {
                "req_id": req_id,
                "done": metrics_path + DONE_SUFFIX,
                "dir": out_dir,
            }
            self._report(req_id=req_id, status=DELIVERED)

    def _check_done_locked(self) -> None:
        assert self._outstanding is not None
        done = read_json(self._outstanding["done"])  # lint: disable=blocking-under-lock — leaf lock; tiny local done-file probe, heartbeat cadence
        if done is None or done.get("req_id") != self._outstanding["req_id"]:
            return
        req_id = self._outstanding["req_id"]
        self._report(
            req_id=req_id,
            status=CAPTURED if done.get("ok") else FAILED,
            dir=done.get("dir") or self._outstanding["dir"],
            artifacts=done.get("artifacts") or [],
            summary={
                k: done.get(k)
                for k in ("steps_captured", "step_times_ms", "truncated")
                if done.get(k) is not None
            },
            error=done.get("error") or "",
        )
        self._reported.add(req_id)
        self._outstanding = None


class DrainCourier:
    """Executor-side urgent-checkpoint relay for cooperative preemption.

    Mirrors :class:`ProfileCourier`'s control/done file contract, driven
    from the same heartbeat loop: when a heartbeat response piggybacks a
    ``drain`` request, the courier drops ``<metrics>.drain``
    (``{"req_id"}``) for the child's
    :class:`~tony_tpu.train.checkpoint.UrgentSaveSignal`; once the child
    answers with ``<metrics>.drain.done`` (``{"req_id", "step"}``) the
    courier reports the saved step back over RPC (``report_drain_saved``)
    exactly once. Tasks whose child runs no training loop (a raw shell
    command) simply never answer — the AM's yield deadline covers them."""

    def __init__(self, report: Callable[..., Any]):
        #: report(req_id=..., step=...) → AM (exceptions are the caller's
        #: problem; the heartbeat loop already tolerates RPC churn)
        self._report = report
        self._lock = threading.Lock()
        self._outstanding: str | None = None   # req_id written, awaiting done
        self._reported: set[str] = set()

    def handle(self, piggyback: Mapping[str, Any] | None,
               metrics_path: str | None) -> None:
        with self._lock:
            if self._outstanding is not None and metrics_path:
                done = read_json(metrics_path + DRAIN_DONE_SUFFIX)  # lint: disable=blocking-under-lock — leaf lock; tiny local done-file probe, heartbeat cadence
                if done is not None and done.get("req_id") == self._outstanding:
                    req_id = self._outstanding
                    self._report(req_id=req_id, step=int(done.get("step") or 0))
                    self._reported.add(req_id)
                    self._outstanding = None
            if not piggyback or not metrics_path:
                return
            req_id = str(piggyback.get("req_id") or "")
            if not req_id or req_id in self._reported or req_id == self._outstanding:
                return
            write_json_atomic(metrics_path + DRAIN_CONTROL_SUFFIX, {"req_id": req_id})
            self._outstanding = req_id


# ------------------------------------------------------- `tony top` rows
def metric_value(snapshot: list[dict[str, Any]] | None, name: str) -> float | None:
    """First sample value of a counter/gauge in a registry snapshot."""
    for m in snapshot or []:
        if m.get("name") == name:
            for s in m.get("samples", []):
                if "value" in s:
                    return float(s["value"])
    return None


def histogram_stats(snapshot: list[dict[str, Any]] | None,
                    name: str) -> tuple[int, float] | None:
    """Summed ``(count, sum)`` across a histogram's label children."""
    for m in snapshot or []:
        if m.get("name") == name and m.get("type") == "histogram":
            count, total = 0, 0.0
            for s in m.get("samples", []):
                count += int(s.get("count", 0))
                total += float(s.get("sum", 0.0))
            return (count, total) if count else None
    return None


def step_stats_by_task(infos: list[dict[str, Any]],
                       task_obs: Mapping[str, Any]) -> dict[str, tuple[int, float]]:
    """Per-task cumulative ``(count, sum)`` of ``tony_train_step_seconds`` —
    the state a refreshing caller keeps between frames so
    :func:`build_top_rows` can turn the cumulative histogram into a live
    rate."""
    out: dict[str, tuple[int, float]] = {}
    for t in infos:
        tid = f"{t['name']}:{t['index']}"
        stats = histogram_stats(task_obs.get(tid), "tony_train_step_seconds")
        if stats is not None:
            out[tid] = stats
    return out


def visible_task_infos(infos: list[dict[str, Any]],
                       instances: Mapping[str, int] | None,
                       ) -> list[dict[str, Any]]:
    """The single resized-away rule behind ``tony top`` and the portal task
    table: tasks an elastic shrink removed — ``index >= instances[name]``,
    with ``instances`` the effective per-type counts from
    ``get_application_status`` — are dropped once terminal (they are not
    dead tasks, the resize retired their slots) and relabeled
    ``resized-away`` while teardown is still finishing."""
    from tony_tpu.cluster.session import TaskStatus

    if not instances:
        return list(infos)
    terminal = {s.value for s in TaskStatus if s.terminal}
    visible: list[dict[str, Any]] = []
    for t in infos:
        n = instances.get(t["name"])
        if n is not None and int(t["index"]) >= int(n):
            if str(t.get("status", "")) in terminal:
                continue
            t = dict(t, status="resized-away")
        visible.append(t)
    return visible


def build_top_rows(infos: list[dict[str, Any]],
                   task_obs: Mapping[str, Any],
                   now_ms: float | None = None,
                   prev_step_stats: Mapping[str, tuple[int, float]] | None = None,
                   instances: Mapping[str, int] | None = None,
                   ) -> list[dict[str, Any]]:
    """One display row per task, synthesized from ``get_task_infos`` and the
    per-task registry snapshots of ``get_metrics``.

    - ``steps_per_s``: from the piggybacked ``tony_train_step_seconds``
      histogram. With ``prev_step_stats`` (the previous frame's
      :func:`step_stats_by_task`) the rate is the delta between snapshots —
      genuinely live, so a job that slows down shows the slowdown; on the
      first frame (or ``--once``) it falls back to the lifetime average;
    - ``queue_depth`` / ``ttft_s``: serve-replica instruments when present;
    - ``hb_age_s``: seconds since the last executor heartbeat;
    - ``instances``: the :func:`visible_task_infos` resized-away rule —
      tasks an elastic shrink removed are dropped instead of rendering as
      dead forever; a task the resize is still tearing down shows as
      ``resized-away`` until its row disappears.
    """
    now_ms = time.time() * 1000.0 if now_ms is None else now_ms
    rows: list[dict[str, Any]] = []
    for t in visible_task_infos(infos, instances):
        tid = f"{t['name']}:{t['index']}"
        train = (t.get("metrics") or {}).get("train") or {}
        obs = task_obs.get(tid)
        row: dict[str, Any] = {
            "task": tid,
            "state": t.get("status", "?"),
            "step": train.get("step"),
            "loss": train.get("loss"),
            "tokens_per_s": train.get("tokens_per_sec", train.get("tokens_per_s")),
            "mfu": train.get("mfu"),
            "steps_per_s": None,
            "queue_depth": metric_value(obs, "tony_serve_queue_depth"),
            "ttft_s": None,
            "hb_age_s": None,
        }
        stats = histogram_stats(obs, "tony_train_step_seconds")
        if stats is not None:
            prev = (prev_step_stats or {}).get(tid)
            if prev is not None and stats[0] >= prev[0]:
                dcount, dsum = stats[0] - prev[0], stats[1] - prev[1]
                # no new steps since the last frame IS the live answer: 0
                row["steps_per_s"] = dcount / dsum if dsum > 0 else 0.0
            elif stats[1] > 0:
                row["steps_per_s"] = stats[0] / stats[1]
        ttft = histogram_stats(obs, "tony_serve_ttft_seconds")
        if ttft is not None and ttft[0] > 0:
            row["ttft_s"] = ttft[1] / ttft[0]
        hb = t.get("last_heartbeat_ms") or 0
        if hb:
            row["hb_age_s"] = max(now_ms - float(hb), 0.0) / 1000.0
        rows.append(row)
    return rows
