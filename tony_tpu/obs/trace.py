"""Dapper-style distributed tracing for the control plane.

One job = one trace (``trace_id`` is the application id). Every process in
the job — submitting client, AM, each executor supervisor, each training
child — owns a process-global :class:`Tracer` (``init_*`` factories below)
that appends finished spans to ``<staging>/trace/<identity>.spans.jsonl``;
``tony trace <app_id>`` merges those files into a Chrome trace-event timeline
(cli/trace.py). Causality crosses process boundaries two ways:

- **in-band through RPC frames**: ``RpcClient`` injects ``{"t": trace_id,
  "s": span_id}`` into every request and ``RpcServer`` parents its handler
  span on it (cluster/rpc.py);
- **through the spawn env**: a parent process exports its root span id as
  ``TONY_TRACE_PARENT`` so the child's root span links under it
  (client → AM → executor → training child).

The current span travels in a :data:`contextvars.ContextVar`, so nested
``with tracer.span(...)`` blocks parent naturally and each thread gets its
own stack; spans opened on a thread with no current span fall back to the
tracer's ``root_parent`` (the process root span).

Disabled is the default and MUST stay free: ``get()`` returns ``None``, every
injection point guards on that single check, and :func:`maybe_span` hands out
a shared no-op context manager — no Span allocation, no I/O, nothing
(asserted by tests/test_obs.py).
"""

from __future__ import annotations

import contextvars
import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Iterator, Mapping

from tony_tpu import constants

_CURRENT: contextvars.ContextVar["Span | None"] = contextvars.ContextVar("tony_span", default=None)
_tracer: "Tracer | None" = None


def get() -> "Tracer | None":
    """The process-global tracer, or None (tracing disabled — the default)."""
    return _tracer


def current_span() -> "Span | None":
    """The span currently open on this thread, or None."""
    return _CURRENT.get() if _tracer is not None else None


def add_event(name: str, **attrs: Any) -> None:
    """Annotate the current span with a point-in-time event.

    Safe to call from anywhere (chaos injection points, retry loops): a no-op
    when tracing is off or no span is open on this thread.
    """
    if _tracer is None:
        return
    span = _CURRENT.get()
    if span is not None:
        span.add_event(name, **attrs)


class _NoopCtx:
    """Shared do-nothing context manager for the disabled fast path."""

    __slots__ = ()

    def __enter__(self) -> None:
        return None

    def __exit__(self, *exc: Any) -> bool:
        return False


_NOOP = _NoopCtx()


def maybe_span(name: str, kind: str = "internal", **attrs: Any):
    """A real span when tracing is on, else the shared no-op context."""
    tr = _tracer
    if tr is None:
        return _NOOP
    return tr.span(name, kind=kind, **attrs)


def start_manual(name: str, kind: str = "internal", parent_id: str | None = None,
                 **attrs: Any) -> "Span | None":
    """A span NOT bound to the thread's context — for lifecycles that cross
    event-loop iterations (one serve request's queue → prefill → decode
    chain lives across many engine steps). Returns None when tracing is off:
    the disabled hot path stays one None check, no Span allocation (same
    contract as :func:`maybe_span`). Pair with :func:`end_manual`."""
    tr = _tracer
    if tr is None:
        return None
    if parent_id is None:
        cur = _CURRENT.get()
        parent_id = cur.span_id if cur is not None else tr.root_parent
    span = Span(name, tr.trace_id, _new_span_id(), parent_id, kind, tr.identity)
    if attrs:
        span.attrs.update(attrs)
    return span


def end_manual(span: "Span | None", status: str = "ok", **attrs: Any) -> None:
    """Finish and sink a :func:`start_manual` span (no-op on None)."""
    tr = _tracer
    if tr is None or span is None:
        return
    if attrs:
        span.attrs.update(attrs)
    span.end_ms = time.time() * 1000.0
    span.status = status
    tr._write(span)


def _new_span_id() -> str:
    return os.urandom(8).hex()


def _safe_identity(identity: str) -> str:
    return identity.replace(":", "_").replace(os.sep, "_")


class Span:
    """One timed operation: name, causal links, attributes, point events."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "kind", "identity",
        "thread_id", "start_ms", "end_ms", "status", "attrs", "events",
    )

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: str | None, kind: str, identity: str):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.kind = kind
        self.identity = identity
        self.thread_id = threading.get_ident()
        self.start_ms = time.time() * 1000.0
        self.end_ms = 0.0
        self.status = "ok"
        self.attrs: dict[str, Any] = {}
        self.events: list[dict[str, Any]] = []

    def set(self, **attrs: Any) -> "Span":
        self.attrs.update(attrs)
        return self

    def add_event(self, name: str, **attrs: Any) -> None:
        ev: dict[str, Any] = {"name": name, "ts_ms": time.time() * 1000.0}
        if attrs:
            ev["attrs"] = attrs
        self.events.append(ev)

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "kind": self.kind,
            "identity": self.identity,
            "thread": self.thread_id,
            "start_ms": round(self.start_ms, 3),
            "end_ms": round(self.end_ms, 3),
            "status": self.status,
        }
        if self.attrs:
            d["attrs"] = self.attrs
        if self.events:
            d["events"] = self.events
        return d


class Tracer:
    """Per-process span factory + JSONL sink (one file per process identity).

    The sink is line-buffered append — finished spans hit disk immediately,
    so an ``os._exit`` (heartbeat-lost executor) or SIGKILL loses at most the
    spans still open. Restart attempts of the same identity append to the
    same file; the restart epoch rides in span attrs.
    """

    def __init__(self, trace_id: str, identity: str, trace_dir: str,
                 parent_id: str | None = None):
        self.trace_id = trace_id
        self.identity = identity
        self.trace_dir = trace_dir
        #: fallback parent for spans opened with no current span on the
        #: thread — processes point this at their root span so background
        #: threads (heartbeat, metrics push) still nest under it
        self.root_parent = parent_id
        self._lock = threading.Lock()
        os.makedirs(trace_dir, exist_ok=True)
        path = os.path.join(trace_dir, _safe_identity(identity) + ".spans.jsonl")
        self._file = open(path, "a", buffering=1)

    # ------------------------------------------------------------ span API
    def start_span(
        self, name: str, kind: str = "internal", parent_id: str | None = None,
    ) -> tuple[Span, contextvars.Token]:
        """Open a span and make it current on this thread; pair with
        :meth:`end_span`. Prefer the :meth:`span` context manager unless the
        span must outlive a lexical scope (process root spans)."""
        if parent_id is None:
            cur = _CURRENT.get()
            parent_id = cur.span_id if cur is not None else self.root_parent
        span = Span(name, self.trace_id, _new_span_id(), parent_id, kind, self.identity)
        token = _CURRENT.set(span)
        return span, token

    def end_span(self, span: Span, token: contextvars.Token, status: str = "ok") -> None:
        span.end_ms = time.time() * 1000.0
        span.status = status
        try:
            _CURRENT.reset(token)
        except ValueError:
            pass  # ended from a different context than it started in
        self._write(span)

    def discard_span(self, span: Span, token: contextvars.Token) -> None:
        """Close a span WITHOUT writing it — for expected control-flow
        aborts (e.g. a queued allocation retried every monitor tick) that
        would otherwise flood the sink with identical error spans."""
        try:
            _CURRENT.reset(token)
        except ValueError:
            pass

    @contextmanager
    def span(self, name: str, kind: str = "internal",
             parent_id: str | None = None, **attrs: Any) -> Iterator[Span]:
        sp, token = self.start_span(name, kind=kind, parent_id=parent_id)
        if attrs:
            sp.attrs.update(attrs)
        try:
            yield sp
        except BaseException:
            self.end_span(sp, token, status="error")
            raise
        self.end_span(sp, token)

    # (the RPC wire context {"t": trace_id, "s": span_id} is built by
    # RpcClient.call from the span it just opened — cluster/rpc.py)

    # ---------------------------------------------------------------- sink
    def _write(self, span: Span) -> None:
        line = json.dumps(span.to_dict())
        with self._lock:
            try:
                self._file.write(line + "\n")  # lint: disable=blocking-under-lock — the tracer lock IS the span-line serializer (leaf; span serialized outside it)
            except ValueError:
                pass  # closed mid-teardown: spans are best-effort by contract

    def close(self) -> None:
        with self._lock:
            try:
                self._file.close()
            except OSError:
                pass


# ---------------------------------------------------------------- factories
def init_tracing(trace_id: str, identity: str, trace_dir: str,
                 parent_id: str | None = None) -> Tracer:
    """Install the process-global tracer (replacing any previous one)."""
    global _tracer
    if _tracer is not None:
        _tracer.close()
    _tracer = Tracer(trace_id, identity, trace_dir, parent_id=parent_id)
    return _tracer


def init_from_config(config, identity: str, staging_dir: str, app_id: str,
                     parent_id: str | None = None) -> "Tracer | None":
    """Control-plane processes (client, AM, executor): enable from the frozen
    job config. None — and zero ongoing cost — unless ``tony.trace.enabled``."""
    from tony_tpu.config import keys

    if not config.get_bool(keys.TRACE_ENABLED):
        return None
    trace_dir = config.get(keys.TRACE_DIR) or os.path.join(staging_dir, "trace")
    return init_tracing(app_id, identity, trace_dir, parent_id=parent_id)


def init_from_env(env: Mapping[str, str] | None = None) -> "Tracer | None":
    """The training child's contract: the executor exports TONY_TRACE_ENABLED
    / TONY_TRACE_DIR / TONY_TRACE_PARENT when tracing is on. None otherwise
    (also the no-op path for library use outside a tony container)."""
    env = os.environ if env is None else env
    if env.get(constants.ENV_TRACE_ENABLED) != "1":
        return None
    trace_dir = env.get(constants.ENV_TRACE_DIR, "")
    if not trace_dir:
        return None
    job = env.get(constants.ENV_JOB_NAME)
    idx = env.get(constants.ENV_TASK_INDEX)
    identity = f"{job}:{idx}:train" if job and idx is not None else "proc"
    return init_tracing(
        env.get(constants.ENV_APP_ID, "trace"),
        identity,
        trace_dir,
        parent_id=env.get(constants.ENV_TRACE_PARENT) or None,
    )


def shutdown() -> None:
    """Close and uninstall the process-global tracer (idempotent)."""
    global _tracer
    if _tracer is not None:
        _tracer.close()
        _tracer = None
